// metrics.h — the native core's observability seam (≙ the reference's
// bvar self-instrumentation: every layer publishes its internals,
// task_control.h:120-130, socket.cpp bvars, baidu_rpc_protocol counters).
// ~All hot-path work happens in this library; these counters make it
// visible to /vars, /metrics (Prometheus) and /status through the Python
// bvar registry (brpc_tpu/metrics/bvar.py merges native_metrics_dump()).
//
// Write side: single atomic add/sub on already-dirty cache lines (the
// counters sit next to the code that owns the state).  Read side: one
// pass formatting every counter — called at human frequency only.
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>

namespace trpc {

struct NativeMetrics {
  // usercode pool (rpc.cc UsercodePool): Python-handler dispatch
  std::atomic<int64_t> usercode_queue_depth{0};  // submitted, not started
  std::atomic<uint64_t> usercode_submitted{0};
  std::atomic<int64_t> usercode_running{0};      // inside a handler now
  std::atomic<uint64_t> usercode_rejected{0};    // ELIMIT (UsercodeAdmit)

  // client correlation (rpc.cc PendingCall pool)
  std::atomic<int64_t> pending_calls{0};         // armed, awaiting response

  // socket write path (socket.cc)
  std::atomic<int64_t> write_requests_queued{0}; // WriteRequests in flight
  std::atomic<uint64_t> keepwrite_spawns{0};     // background drain fibers
  std::atomic<uint64_t> inline_write_completes{0};  // drained in Write()

  // sockets (socket.cc)
  std::atomic<int64_t> live_sockets{0};
  std::atomic<uint64_t> sockets_created{0};
  std::atomic<uint64_t> socket_failures{0};

  // server-side pipelining sequencer (rpc.cc ConnState): responses inside
  // the sequencer — parked out-of-order OR queued for the drain owner.
  // Sustained growth means handlers complete far out of request order.
  std::atomic<int64_t> sequencer_parked{0};

  // ingress fast path (rpc.cc ServerOnMessages): run-to-completion
  // dispatch.  hits = requests executed inline on the parse fiber;
  // fallbacks = inline-eligible requests routed to the spawned path
  // (budget tripped or the fast path is flagged off); budget_trips =
  // drains whose inline budget (requests or µs) ran out mid-batch.
  std::atomic<uint64_t> inline_dispatch_hits{0};
  std::atomic<uint64_t> inline_dispatch_fallbacks{0};
  std::atomic<uint64_t> inline_dispatch_budget_trips{0};

  // parse-batch response corking (socket.cc): while a parse drain holds
  // the cork, responses pile onto the write queue with the doorbell held;
  // the uncork flushes them as one writev/SEND_ZC chain.  responses =
  // writes enqueued while corked; flushes = uncorks that had held bytes.
  std::atomic<uint64_t> batch_cork_flushes{0};
  std::atomic<uint64_t> batch_cork_responses{0};

  // usercode arm-time accounting (rpc.cc CallCtx.arm_ns, stamped from the
  // per-drain coarse clock): nanoseconds requests spent queued before a
  // usercode worker picked them up
  std::atomic<uint64_t> usercode_queue_ns_total{0};

  // client egress fast path (rpc.cc channel_call / channel_fanout_call):
  // cork_windows = Cork/Uncork brackets held around client request writes
  // (TRPC_CLIENT_CORK); inline_completes = unary responses completed
  // run-to-completion on the client parse fiber (butex woken directly,
  // no trampoline fiber)
  std::atomic<uint64_t> client_cork_windows{0};
  std::atomic<uint64_t> client_inline_completes{0};
  // client drains whose per-drain budget ran out (the drain flushed its
  // cork and yielded once) — kept SEPARATE from the server's
  // inline_dispatch_budget_trips so the ingress A/B stays readable
  std::atomic<uint64_t> client_budget_yields{0};

  // serialize-once fan-out (rpc.cc channel_fanout_call): calls = fan-out
  // groups issued; subcalls = member RPCs those groups fanned into;
  // shared_serializations = request bodies serialized ONCE and shared as
  // refcounted IOBuf blocks across the group (1 per fan-out call — N
  // sub-calls previously cost N serializations)
  std::atomic<uint64_t> fanout_calls{0};
  std::atomic<uint64_t> fanout_subcalls{0};
  std::atomic<uint64_t> fanout_shared_serializations{0};

  // payload-codec rail (codec.cc): encodes/decodes = parts transcoded
  // (a fan-out group encodes ONCE — compare against fanout_subcalls for
  // the codec-once proof); bytes_in/bytes_out are ENCODER-side (plain
  // in, encoded out): out/in is the wire saving
  std::atomic<uint64_t> codec_encodes{0};
  std::atomic<uint64_t> codec_decodes{0};
  std::atomic<uint64_t> codec_bytes_in{0};
  std::atomic<uint64_t> codec_bytes_out{0};

  // stream RST frames (stream.cc): abortive close carrying an error code
  std::atomic<uint64_t> stream_rsts_sent{0};
  std::atomic<uint64_t> stream_rsts_received{0};
  // device-frame rail selection (stream.cc stream_write_device): local =
  // handle passed, both ends share one PJRT client; host = explicit d2h
  // landing zone rides the wire (the cross-host rail)
  std::atomic<uint64_t> stream_device_local_rail{0};
  std::atomic<uint64_t> stream_device_host_rail{0};

  // protocol errors observed on input (both sides)
  std::atomic<uint64_t> parse_errors{0};

  // h2 connections (h2.cc registry)
  std::atomic<int64_t> h2_connections{0};

  // fiber-mutex contention (fiber_sync.h ≙ the contention profiler's
  // counters): contended acquisitions and total nanoseconds spent
  // waiting — a rising wait/contended ratio is a lock convoy
  std::atomic<uint64_t> mutex_contended{0};
  std::atomic<uint64_t> mutex_wait_ns{0};

  // io_uring engine (uring.cc): ring-fed receive path
  std::atomic<uint64_t> uring_recv_completions{0};
  std::atomic<uint64_t> uring_recv_bytes{0};
  std::atomic<uint64_t> uring_accepts{0};
  std::atomic<uint64_t> uring_rearms{0};       // multishot re-issues
  std::atomic<int64_t> uring_active_recvs{0};  // armed connections

  // zero-copy egress rail (uring.cc SEND_ZC): submitted = SEND_ZC SQEs,
  // retired = their zerocopy-notification CQEs (kernel done with the
  // pages; block refs drop here), copied = notifications that reported a
  // forced kernel copy (flips the rail back to writev), fixed =
  // registered-buffer sends, fallbacks = rail-eligible batches that went
  // through writev instead
  std::atomic<uint64_t> uring_sendzc_submitted{0};
  std::atomic<uint64_t> uring_sendzc_retired{0};
  std::atomic<uint64_t> uring_sendzc_copied{0};
  std::atomic<uint64_t> uring_sendzc_fixed{0};
  std::atomic<uint64_t> uring_sendzc_batches{0};
  std::atomic<uint64_t> uring_sendzc_fallbacks{0};
  // registered landing-zone pool occupancy
  std::atomic<int64_t> uring_zc_pool_slots{0};
  std::atomic<int64_t> uring_zc_pool_in_use{0};

  // schedule perturbation (sched_perturb.cc, TRPC_SCHED_SEED): yields =
  // injected pauses/spins/budget truncations at instrumented seams;
  // steal_shuffles = seeded steal-victim + placement-detour draws;
  // wake_shuffles = butex wake-order shuffles + parking-lot wake
  // widenings.  All zero when perturbation is off (bench-of-record).
  std::atomic<uint64_t> sched_perturb_yields{0};
  std::atomic<uint64_t> sched_perturb_steal_shuffles{0};
  std::atomic<uint64_t> sched_perturb_wake_shuffles{0};
};

NativeMetrics& native_metrics();

// Write "name value\n" lines (plus the device-plane counters from tpu.h)
// into buf; returns bytes written (truncated at cap).
size_t native_metrics_dump(char* buf, size_t cap);

}  // namespace trpc
