// metrics.h — the native core's observability seam (≙ the reference's
// bvar self-instrumentation: every layer publishes its internals,
// task_control.h:120-130, socket.cpp bvars, baidu_rpc_protocol counters).
// ~All hot-path work happens in this library; these counters make it
// visible to /vars, /metrics (Prometheus) and /status through the Python
// bvar registry (brpc_tpu/metrics/bvar.py merges native_metrics_dump()).
//
// Write side: single atomic add/sub on already-dirty cache lines (the
// counters sit next to the code that owns the state).  Read side: one
// pass formatting every counter — called at human frequency only.
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>

namespace trpc {

struct NativeMetrics {
  // usercode pool (rpc.cc UsercodePool): Python-handler dispatch
  std::atomic<int64_t> usercode_queue_depth{0};  // submitted, not started
  std::atomic<uint64_t> usercode_submitted{0};
  std::atomic<int64_t> usercode_running{0};      // inside a handler now
  std::atomic<uint64_t> usercode_rejected{0};    // ELIMIT (UsercodeAdmit)

  // client correlation (rpc.cc PendingCall pool)
  std::atomic<int64_t> pending_calls{0};         // armed, awaiting response

  // socket write path (socket.cc)
  std::atomic<int64_t> write_requests_queued{0}; // WriteRequests in flight
  std::atomic<uint64_t> keepwrite_spawns{0};     // background drain fibers
  std::atomic<uint64_t> inline_write_completes{0};  // drained in Write()

  // sockets (socket.cc)
  std::atomic<int64_t> live_sockets{0};
  std::atomic<uint64_t> sockets_created{0};
  std::atomic<uint64_t> socket_failures{0};

  // accept path (rpc.cc OnNewConnections / uring.cc acceptor): fd/buffer
  // exhaustion pauses — the accept loop parked on a backoff timer instead
  // of hot-retrying EMFILE/ENFILE
  std::atomic<uint64_t> accept_backoffs{0};
  // accept-storm pacing (rpc.cc): loop parks because the token bucket ran
  // dry or the pending-handshake cap filled (re-kicked off the timer
  // plane / the first-bytes decrement)
  std::atomic<uint64_t> accept_paced{0};
  // connections refused at accept because the overload plane judged the
  // owning shard saturated (connection-level shedding, ISSUE 16)
  std::atomic<uint64_t> accept_sheds{0};
  // accepted connections that have not yet delivered their first ingress
  // bytes (gauge; the per-listener cap bounds these)
  std::atomic<int64_t> accept_pending_handshakes{0};

  // per-connection memory diet (socket.cc idle-kick + IOBuf::shrink):
  // idle heartbeats that found no ingress since the last beat, shrinks
  // that actually released memory, and the bytes they returned
  std::atomic<uint64_t> conn_idle_kicks{0};
  std::atomic<uint64_t> conn_shrinks{0};
  std::atomic<uint64_t> conn_shrunk_bytes{0};
  // materialized per-connection parser states (gauge): stays at 0 for
  // idle-accepted connections — ConnState is first-byte-lazy (rpc.cc)
  std::atomic<int64_t> conn_parse_states{0};

  // timer plane (timer_thread.cc per-shard hierarchical wheels)
  std::atomic<uint64_t> timer_arms{0};     // timer_add/_oneshot calls
  std::atomic<uint64_t> timer_cancels{0};  // cancels that prevented a fire
  std::atomic<uint64_t> timer_fires{0};    // callbacks actually run
  std::atomic<uint64_t> timer_cascades{0}; // tasks relinked level-down
  // arms that fell back to the global wheel (caller had no shard): the
  // zero-cross-shard-contention proof reads this — fiber-side arms at
  // TRPC_SHARDS>1 must not move it
  std::atomic<uint64_t> timer_foreign_arms{0};
  std::atomic<int64_t> timer_pending{0};   // linked timers (gauge)

  // server-side pipelining sequencer (rpc.cc ConnState): responses inside
  // the sequencer — parked out-of-order OR queued for the drain owner.
  // Sustained growth means handlers complete far out of request order.
  std::atomic<int64_t> sequencer_parked{0};

  // ingress fast path (rpc.cc ServerOnMessages): run-to-completion
  // dispatch.  hits = requests executed inline on the parse fiber;
  // fallbacks = inline-eligible requests routed to the spawned path
  // (budget tripped or the fast path is flagged off); budget_trips =
  // drains whose inline budget (requests or µs) ran out mid-batch.
  std::atomic<uint64_t> inline_dispatch_hits{0};
  std::atomic<uint64_t> inline_dispatch_fallbacks{0};
  std::atomic<uint64_t> inline_dispatch_budget_trips{0};

  // parse-batch response corking (socket.cc): while a parse drain holds
  // the cork, responses pile onto the write queue with the doorbell held;
  // the uncork flushes them as one writev/SEND_ZC chain.  responses =
  // writes enqueued while corked; flushes = uncorks that had held bytes.
  std::atomic<uint64_t> batch_cork_flushes{0};
  std::atomic<uint64_t> batch_cork_responses{0};

  // usercode arm-time accounting (rpc.cc CallCtx.arm_ns, stamped from the
  // per-drain coarse clock): nanoseconds requests spent queued before a
  // usercode worker picked them up
  std::atomic<uint64_t> usercode_queue_ns_total{0};

  // client egress fast path (rpc.cc channel_call / channel_fanout_call):
  // cork_windows = Cork/Uncork brackets held around client request writes
  // (TRPC_CLIENT_CORK); inline_completes = unary responses completed
  // run-to-completion on the client parse fiber (butex woken directly,
  // no trampoline fiber)
  std::atomic<uint64_t> client_cork_windows{0};
  std::atomic<uint64_t> client_inline_completes{0};
  // client drains whose per-drain budget ran out (the drain flushed its
  // cork and yielded once) — kept SEPARATE from the server's
  // inline_dispatch_budget_trips so the ingress A/B stays readable
  std::atomic<uint64_t> client_budget_yields{0};

  // serialize-once fan-out (rpc.cc channel_fanout_call): calls = fan-out
  // groups issued; subcalls = member RPCs those groups fanned into;
  // shared_serializations = request bodies serialized ONCE and shared as
  // refcounted IOBuf blocks across the group (1 per fan-out call — N
  // sub-calls previously cost N serializations)
  std::atomic<uint64_t> fanout_calls{0};
  std::atomic<uint64_t> fanout_subcalls{0};
  std::atomic<uint64_t> fanout_shared_serializations{0};

  // payload-codec rail (codec.cc): encodes/decodes = parts transcoded
  // (a fan-out group encodes ONCE — compare against fanout_subcalls for
  // the codec-once proof); bytes_in/bytes_out are ENCODER-side (plain
  // in, encoded out): out/in is the wire saving
  std::atomic<uint64_t> codec_encodes{0};
  std::atomic<uint64_t> codec_decodes{0};
  std::atomic<uint64_t> codec_bytes_in{0};
  std::atomic<uint64_t> codec_bytes_out{0};

  // stream RST frames (stream.cc): abortive close carrying an error code
  std::atomic<uint64_t> stream_rsts_sent{0};
  std::atomic<uint64_t> stream_rsts_received{0};
  // device-frame rail selection (stream.cc stream_write_device): local =
  // handle passed, both ends share one PJRT client; host = explicit d2h
  // landing zone rides the wire (the cross-host rail)
  std::atomic<uint64_t> stream_device_local_rail{0};
  std::atomic<uint64_t> stream_device_host_rail{0};

  // protocol errors observed on input (both sides)
  std::atomic<uint64_t> parse_errors{0};

  // h2 connections (h2.cc registry)
  std::atomic<int64_t> h2_connections{0};

  // fiber-mutex contention (fiber_sync.h ≙ the contention profiler's
  // counters): contended acquisitions and total nanoseconds spent
  // waiting — a rising wait/contended ratio is a lock convoy
  std::atomic<uint64_t> mutex_contended{0};
  std::atomic<uint64_t> mutex_wait_ns{0};

  // io_uring engine (uring.cc): ring-fed receive path
  std::atomic<uint64_t> uring_recv_completions{0};
  std::atomic<uint64_t> uring_recv_bytes{0};
  std::atomic<uint64_t> uring_accepts{0};
  std::atomic<uint64_t> uring_rearms{0};       // multishot re-issues
  std::atomic<int64_t> uring_active_recvs{0};  // armed connections

  // zero-copy egress rail (uring.cc SEND_ZC): submitted = SEND_ZC SQEs,
  // retired = their zerocopy-notification CQEs (kernel done with the
  // pages; block refs drop here), copied = notifications that reported a
  // forced kernel copy (flips the rail back to writev), fixed =
  // registered-buffer sends, fallbacks = rail-eligible batches that went
  // through writev instead
  std::atomic<uint64_t> uring_sendzc_submitted{0};
  std::atomic<uint64_t> uring_sendzc_retired{0};
  std::atomic<uint64_t> uring_sendzc_copied{0};
  std::atomic<uint64_t> uring_sendzc_fixed{0};
  std::atomic<uint64_t> uring_sendzc_batches{0};
  std::atomic<uint64_t> uring_sendzc_fallbacks{0};
  // registered landing-zone pool occupancy
  std::atomic<int64_t> uring_zc_pool_slots{0};
  std::atomic<int64_t> uring_zc_pool_in_use{0};

  // native rpcz span capture (metrics.cc rings): sampled = spans that
  // landed in a shard ring; dropped = spans lost to ring laps or torn
  // drain reads.  A sustained dropped climb means the Python drain is
  // not keeping up with the sampling budget.
  std::atomic<uint64_t> rpcz_spans_sampled{0};
  std::atomic<uint64_t> rpcz_spans_dropped{0};

  // native traffic capture (dump.cc rings): captured = wire frames that
  // landed in a shard ring; dropped = frames lost to claim contention,
  // ring laps, or a record bigger than the drain buffer; drained =
  // frames consumed by trpc_dump_drain into the Python recordio writer.
  std::atomic<uint64_t> dump_captured{0};
  std::atomic<uint64_t> dump_dropped{0};
  std::atomic<uint64_t> dump_drained{0};

  // deadline-budget propagation (ISSUE 19, rpc.cc tag-18 plane):
  // deadline_drops = requests shed on the parse fiber because their
  // propagated budget was already spent (EDEADLINE on the cork — no
  // decode, no fiber, no usercode spawn; the per-family split is
  // deadline_drop_note below).  deadline_queue_drops = usercode requests
  // whose budget expired while queued for a worker: answered EDEADLINE
  // at dequeue, the handler never ran.
  std::atomic<uint64_t> deadline_drops{0};
  std::atomic<uint64_t> deadline_queue_drops{0};

  // schedule perturbation (sched_perturb.cc, TRPC_SCHED_SEED): yields =
  // injected pauses/spins/budget truncations at instrumented seams;
  // steal_shuffles = seeded steal-victim + placement-detour draws;
  // wake_shuffles = butex wake-order shuffles + parking-lot wake
  // widenings.  All zero when perturbation is off (bench-of-record).
  std::atomic<uint64_t> sched_perturb_yields{0};
  std::atomic<uint64_t> sched_perturb_steal_shuffles{0};
  std::atomic<uint64_t> sched_perturb_wake_shuffles{0};
};

NativeMetrics& native_metrics();

// Write "name value\n" lines (plus the device-plane counters from tpu.h)
// into buf; returns bytes written (truncated at cap).
size_t native_metrics_dump(char* buf, size_t cap);

// ---------------------------------------------------------------------------
// Hot-path telemetry plane (ISSUE 9; ≙ the reference's per-method
// LatencyRecorder feeding /status, latency_recorder.h:32-75, and the
// bvar::Collector-throttled rpcz spans, span.h:47 + collector.h:41).
// The PR-3/5/7 fast paths execute run-to-completion on parse fibers and
// never touch the Python LatencyRecorder — these per-shard structures
// make exactly that traffic observable: lock-free relaxed-atomic writes
// on the owning shard, percentiles/fold at read time only.

// Native method families with their own latency histogram + inflight
// gauge (≙ per-method MethodStatus for the methods Python never sees).
enum TelemetryFamily {
  TF_INLINE_ECHO = 0,   // native echo (inline + spawned-fallback arms)
  TF_HBM_ECHO = 1,      // device-plane echo (tpu.h round trips)
  TF_REDIS_CACHE = 2,   // native redis-cache commands
  TF_USERCODE = 3,      // Python TRPC handlers (queue-inclusive)
  TF_CLIENT_UNARY = 4,  // channel_call, issue -> completion
  TF_FANOUT_GROUP = 5,  // channel_fanout_call whole-group latency
  TF_FAMILIES = 6,
};

// Log-bucket bounds: bucket i holds latencies in (2^(i-1), 2^i] µs for
// i in 0..kHistFiniteBuckets-1 (bucket 0 = [0,1]µs), one +Inf overflow.
constexpr int kHistFiniteBuckets = 26;  // le 1µs .. le 2^25µs (~33.5s)

// Reloadable master switch (TRPC_TELEMETRY env seeds the default; the
// `telemetry` flag pushes through capi).  Off = no histogram writes, no
// span capture, no extra clock reads — the bench A/B baseline.
void set_telemetry(int on);
bool telemetry_enabled();

const char* telemetry_family_name(int family);
// Deadline-budget drop accounting (ISSUE 19): one parse-fiber shed of a
// budget-spent request — bumps the native_deadline_drops total plus the
// family's split row (family < 0 = handler unresolved: total only).
void deadline_drop_note(int family);
uint64_t deadline_drops_by_family(int family);
// One histogram write: relaxed atomic adds on the shard's agent (negative
// shard / off-worker callers fold into shard 0's agent).
void telemetry_record(int family, int shard, int64_t lat_us);
void telemetry_inflight_add(int family, int shard, int64_t d);
// Read side (folds every shard agent): percentile by log-bucket walk with
// linear interpolation inside the bucket, total count, µs sum, inflight.
int64_t telemetry_percentile_us(int family, double q);
uint64_t telemetry_count(int family);
uint64_t telemetry_sum_us(int family);
int64_t telemetry_inflight(int family);
// Prometheus text exposition: real cumulative `_bucket{le=...}` series
// per family plus `_sum` / `_count` (appended to /metrics by the portal).
size_t telemetry_prom_dump(char* buf, size_t cap);

// --- native rpcz: sampled span capture for fast-path requests --------------

// Native half of the rpcz switch (TRPC_RPCZ env seeds the default; the
// Python `enable_rpcz` flag validator pushes through capi) plus the
// collector-style per-second sampling budget shared by all shards.
void rpcz_set_enabled(int on);
bool rpcz_native_enabled();
void rpcz_set_budget(int64_t per_second);
// One budget token (false = disabled or over budget this second).
bool rpcz_try_sample();
// Fresh nonzero span/trace id (SplitMix64 over a per-boot random base).
uint64_t rpcz_next_id();

struct NativeSpan {
  uint64_t trace_id = 0;
  uint64_t span_id = 0;
  uint64_t parent_span_id = 0;
  int32_t family = 0;      // TelemetryFamily
  int32_t error_code = 0;
  int32_t shard = 0;
  int64_t start_mono_ns = 0;  // CLOCK_MONOTONIC (Python rebases to wall)
  int64_t latency_us = 0;
  char annotations[96] = {};  // '|'-separated free text (≙ TRACEPRINTF)
};

// Publish a finished span into the capturing shard's ring (seqlock
// slots: writers never block, a drain racing a write skips that slot).
void rpcz_capture(const NativeSpan& s);
// Drain every shard's ring into tab-separated lines
//   trace span parent family error shard start_mono_ns latency_us annot\n
// consuming the spans (they surface once, through the Python Collector).
size_t rpcz_drain(char* buf, size_t cap);

// --- cross-hop trace context (fiber-local parent) --------------------------
// One context per executing thread: parse fibers run requests to
// completion without yielding and usercode handlers own their pthread
// for the handler's duration, so a thread_local carries the inbound
// trace across the dispatch exactly like the reference's tls_parent
// (span.h:115).  channel_call/channel_fanout_call read it into TLV tags
// 7/8; UsercodePool stamps/clears it around every Python handler.

struct TraceCtx {
  uint64_t trace_id = 0;
  uint64_t span_id = 0;   // the CURRENT span: downstream hops parent here
  // set by the Python layer when IT created the client span for the next
  // call — native must then not capture a duplicate client-unary span
  bool python_owned = false;
};

TraceCtx trace_current();
void trace_set_current(uint64_t trace_id, uint64_t span_id,
                       int python_owned);
// TRACEPRINTF twin: append free text to the calling thread's pending
// annotation buffer; the next native span captured on this thread
// carries it (no-op when rpcz is off — unsampled annotate is free).
void trace_annotate(const char* text);
// Move the pending annotations out (into a NativeSpan::annotations-sized
// buffer); returns bytes written and clears the thread's buffer.
size_t trace_take_annotations(char* buf, size_t cap);

}  // namespace trpc
