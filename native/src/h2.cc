#include "h2.h"

#include "metrics.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <deque>
#include <mutex>
#include <unordered_map>

#include "execution_queue.h"
#include "fd_util.h"
#include "h2_tables.h"
#include "heap_profiler.h"
#include "tls.h"

namespace trpc {

namespace {

constexpr char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
constexpr size_t kPrefaceLen = 24;
constexpr uint32_t kMaxFrameAccept = 1u << 20;   // 1MB per frame
constexpr size_t kMaxHeaderBlock = 256u * 1024;
constexpr uint32_t kDefaultWindow = 65535;
constexpr uint32_t kOurMaxFrameSize = 16384;

// Per-request body cap.  Hitting it is a PER-STREAM failure (413 +
// RST_STREAM, the connection and its other streams live on), not the
// old connection-wide GOAWAY.  Env-tunable so tests can exercise the
// early-response path without uploading half a gigabyte.
size_t max_body_bytes() {
  static const size_t v = [] {
    const char* e = getenv("TRPC_H2_MAX_BODY");
    if (e != nullptr && e[0] != '\0') {
      long long n = strtoll(e, nullptr, 10);
      if (n >= 4096) {
        return (size_t)n;
      }
    }
    return (size_t)512u * 1024 * 1024;
  }();
  return v;
}

enum FrameType : uint8_t {
  F_DATA = 0x0, F_HEADERS = 0x1, F_PRIORITY = 0x2, F_RST = 0x3,
  F_SETTINGS = 0x4, F_PUSH = 0x5, F_PING = 0x6, F_GOAWAY = 0x7,
  F_WINDOW_UPDATE = 0x8, F_CONTINUATION = 0x9,
};

enum Flags : uint8_t {
  FLAG_END_STREAM = 0x1, FLAG_ACK = 0x1, FLAG_END_HEADERS = 0x4,
  FLAG_PADDED = 0x8, FLAG_PRIORITY = 0x20,
};

// --- Huffman decode (RFC 7541 Appendix B) ---------------------------------

struct HuffNode {
  int16_t next[2] = {-1, -1};
  int16_t sym = -1;  // 0..256 when leaf
};

struct HuffTree {
  std::vector<HuffNode> nodes;
  HuffTree() {
    nodes.emplace_back();
    for (int sym = 0; sym < 257; ++sym) {
      uint32_t code = kHuffCodes[sym].code;
      int bits = kHuffCodes[sym].bits;
      int cur = 0;
      for (int i = bits - 1; i >= 0; --i) {
        int b = (code >> i) & 1;
        if (nodes[cur].next[b] < 0) {
          nodes[cur].next[b] = (int16_t)nodes.size();
          nodes.emplace_back();
        }
        cur = nodes[cur].next[b];
      }
      nodes[cur].sym = (int16_t)sym;
    }
  }
};

const HuffTree& huff_tree() {
  static const HuffTree* t = new HuffTree();
  return *t;
}

// Returns false on invalid coding (EOS symbol, bad padding).
bool HuffmanDecode(const uint8_t* p, size_t n, std::string* out) {
  const HuffTree& t = huff_tree();
  int cur = 0;
  int depth = 0;  // bits since last emitted symbol
  for (size_t i = 0; i < n; ++i) {
    for (int b = 7; b >= 0; --b) {
      int bit = (p[i] >> b) & 1;
      int nxt = t.nodes[cur].next[bit];
      if (nxt < 0) {
        return false;
      }
      cur = nxt;
      ++depth;
      if (t.nodes[cur].sym >= 0) {
        if (t.nodes[cur].sym == 256) {
          return false;  // EOS in stream is a coding error
        }
        out->push_back((char)t.nodes[cur].sym);
        cur = 0;
        depth = 0;
      }
    }
  }
  // padding must be a prefix of EOS (all 1s), strictly < 8 bits
  return depth < 8;
}

// --- HPACK decoder ---------------------------------------------------------

struct DynEntry {
  std::string name, value;
  size_t size() const { return name.size() + value.size() + 32; }
};

class Hpack {
 public:
  size_t max_size = 4096;

  bool decode_block(const uint8_t* p, size_t n,
                    std::vector<std::pair<std::string, std::string>>* out) {
    size_t i = 0;
    while (i < n) {
      uint8_t b = p[i];
      if (b & 0x80) {  // indexed
        uint64_t idx;
        if (!read_int(p, n, &i, 7, &idx) || idx == 0) return false;
        std::string name, value;
        if (!lookup(idx, &name, &value)) return false;
        out->emplace_back(std::move(name), std::move(value));
      } else if (b & 0x40) {  // literal with incremental indexing
        uint64_t idx;
        if (!read_int(p, n, &i, 6, &idx)) return false;
        std::string name, value;
        if (!read_name(p, n, &i, idx, &name)) return false;
        if (!read_str(p, n, &i, &value)) return false;
        add_entry(name, value);
        out->emplace_back(std::move(name), std::move(value));
      } else if (b & 0x20) {  // dynamic table size update
        uint64_t sz;
        if (!read_int(p, n, &i, 5, &sz)) return false;
        if (sz > 65536) return false;
        max_size = (size_t)sz;
        evict();
      } else {  // literal without indexing (0x00) / never indexed (0x10)
        uint64_t idx;
        if (!read_int(p, n, &i, 4, &idx)) return false;
        std::string name, value;
        if (!read_name(p, n, &i, idx, &name)) return false;
        if (!read_str(p, n, &i, &value)) return false;
        out->emplace_back(std::move(name), std::move(value));
      }
    }
    return true;
  }

 private:
  std::deque<DynEntry> dyn_;
  size_t dyn_size_ = 0;

  static bool read_int(const uint8_t* p, size_t n, size_t* i, int prefix,
                       uint64_t* out) {
    if (*i >= n) return false;
    uint64_t max_pfx = (1u << prefix) - 1;
    uint64_t v = p[*i] & max_pfx;
    ++*i;
    if (v < max_pfx) {
      *out = v;
      return true;
    }
    int shift = 0;
    while (*i < n) {
      uint8_t b = p[*i];
      ++*i;
      v += (uint64_t)(b & 0x7f) << shift;
      if (v > (1ull << 32)) return false;
      if (!(b & 0x80)) {
        *out = v;
        return true;
      }
      shift += 7;
      if (shift > 28) return false;
    }
    return false;
  }

  static bool read_raw_str(const uint8_t* p, size_t n, size_t* i,
                           std::string* out) {
    if (*i >= n) return false;
    bool huff = (p[*i] & 0x80) != 0;
    uint64_t len;
    if (!read_int(p, n, i, 7, &len)) return false;
    if (*i + len > n || len > kMaxHeaderBlock) return false;
    if (huff) {
      if (!HuffmanDecode(p + *i, (size_t)len, out)) return false;
    } else {
      out->assign((const char*)p + *i, (size_t)len);
    }
    *i += (size_t)len;
    return true;
  }

  bool read_str(const uint8_t* p, size_t n, size_t* i, std::string* out) {
    return read_raw_str(p, n, i, out);
  }

  bool read_name(const uint8_t* p, size_t n, size_t* i, uint64_t idx,
                 std::string* name) {
    if (idx != 0) {
      std::string v_unused;
      return lookup(idx, name, &v_unused);
    }
    return read_raw_str(p, n, i, name);
  }

  bool lookup(uint64_t idx, std::string* name, std::string* value) {
    constexpr size_t kStatic = sizeof(kStaticTable) / sizeof(kStaticTable[0]);
    if (idx >= 1 && idx <= kStatic) {
      *name = kStaticTable[idx - 1].name;
      *value = kStaticTable[idx - 1].value;
      return true;
    }
    size_t d = (size_t)(idx - kStatic - 1);
    if (d >= dyn_.size()) return false;
    *name = dyn_[d].name;
    *value = dyn_[d].value;
    return true;
  }

  void add_entry(const std::string& name, const std::string& value) {
    DynEntry e{name, value};
    size_t sz = e.size();
    if (sz > max_size) {  // entry larger than table: table empties
      dyn_.clear();
      dyn_size_ = 0;
      return;
    }
    dyn_.push_front(std::move(e));
    dyn_size_ += sz;
    evict();
  }

  void evict() {
    while (dyn_size_ > max_size && !dyn_.empty()) {
      dyn_size_ -= dyn_.back().size();
      dyn_.pop_back();
    }
  }
};

// --- per-connection state --------------------------------------------------

struct StreamState {
  std::string header_block;   // accumulating until END_HEADERS
  bool headers_done = false;
  bool end_stream = false;
  bool responded = false;
  // progressive response in flight (H2RespondStart): FlushPending must
  // NOT end the stream when pending drains — more DATA is coming; only
  // H2StreamClose ends it
  bool progressive = false;
  H2Request req;
  int64_t send_window = kDefaultWindow;
  // bytes waiting for window (flushed on WINDOW_UPDATE), then trailers
  std::string pending;
  std::string pending_trailers;  // encoded HEADERS payload, sent after data
};

}  // namespace

class H2Conn {
 public:
  std::atomic<int> refs{1};  // registry's reference
  // lint:allow-blocking-bounded (frame-state mutation only; writes
  // leave the lock before Socket::Write; contention-profiled)
  ProfiledMutex mu;  // hot: every frame; contention-profiled
  Hpack hpack;
  std::unordered_map<uint32_t, StreamState> streams;
  uint32_t continuation_stream = 0;  // nonzero: expecting CONTINUATION
  uint32_t max_seen_sid = 0;  // highest client sid that sent HEADERS
  int64_t conn_send_window = kDefaultWindow;
  int64_t peer_initial_window = kDefaultWindow;
  bool goaway = false;
  // response path: concurrent usercode handlers submit wait-free; one
  // consumer fiber encodes frames in order (≙ the reference writing h2
  // through bthread ExecutionQueue instead of contending the conn lock)
  SocketId sock_id = INVALID_SOCKET_ID;
  ExecutionQueue resp_q;
  // bumped whenever send windows can have grown (WINDOW_UPDATE, SETTINGS
  // initial-window) or a stream died (RST, teardown): progressive
  // writers parked in H2StreamData re-check on every bump
  Butex* window_butex = nullptr;
  ~H2Conn() {
    if (window_butex != nullptr) {
      butex_destroy(window_butex);
    }
  }
};

namespace {

// lint:allow-blocking-bounded (O(1) registry map lookup/insert per
// connection event, no parks under it)
std::mutex g_conns_mu;
std::unordered_map<SocketId, H2Conn*> g_conns;

void put_frame_header(std::string* s, uint32_t len, uint8_t type,
                      uint8_t flags, uint32_t stream) {
  s->push_back((char)((len >> 16) & 0xff));
  s->push_back((char)((len >> 8) & 0xff));
  s->push_back((char)(len & 0xff));
  s->push_back((char)type);
  s->push_back((char)flags);
  s->push_back((char)((stream >> 24) & 0x7f));
  s->push_back((char)((stream >> 16) & 0xff));
  s->push_back((char)((stream >> 8) & 0xff));
  s->push_back((char)(stream & 0xff));
}

void write_frames(Socket* s, const std::string& frames) {
  IOBuf b;
  b.append(frames.data(), frames.size());
  s->Write(std::move(b));
}

void put_rst_stream(std::string* s, uint32_t sid, uint32_t err) {
  put_frame_header(s, 4, F_RST, 0, sid);
  s->push_back((char)((err >> 24) & 0xff));
  s->push_back((char)((err >> 16) & 0xff));
  s->push_back((char)((err >> 8) & 0xff));
  s->push_back((char)(err & 0xff));
}

// HPACK encode: literal without indexing, new name, no huffman.
void hpack_literal(std::string* out, const std::string& name,
                   const std::string& value) {
  auto put_len = [out](size_t len) {
    if (len < 127) {
      out->push_back((char)len);
    } else {
      out->push_back((char)127);
      size_t v = len - 127;
      while (v >= 128) {
        out->push_back((char)(0x80 | (v & 0x7f)));
        v >>= 7;
      }
      out->push_back((char)v);
    }
  };
  out->push_back((char)0x00);
  put_len(name.size());
  out->append(name);
  put_len(value.size());
  out->append(value);
}

// "Key: Value\r\n" lines → hpack literals with lower-cased keys.
void encode_blob(std::string* out, const char* blob) {
  if (blob == nullptr) return;
  const char* p = blob;
  while (*p) {
    const char* eol = strstr(p, "\r\n");
    size_t linelen = eol ? (size_t)(eol - p) : strlen(p);
    const char* colon = (const char*)memchr(p, ':', linelen);
    if (colon != nullptr && colon != p) {
      std::string name(p, colon - p);
      for (char& c : name) {
        if (c >= 'A' && c <= 'Z') c += 'a' - 'A';
      }
      const char* v = colon + 1;
      const char* vend = p + linelen;
      while (v < vend && *v == ' ') ++v;
      hpack_literal(out, name, std::string(v, vend - v));
    }
    if (!eol) break;
    p = eol + 2;
  }
}

int FatalGoaway(Socket* s, uint32_t last_stream, uint32_t err) {
  std::string f;
  std::string payload;
  payload.push_back((char)((last_stream >> 24) & 0x7f));
  payload.push_back((char)((last_stream >> 16) & 0xff));
  payload.push_back((char)((last_stream >> 8) & 0xff));
  payload.push_back((char)(last_stream & 0xff));
  payload.push_back((char)((err >> 24) & 0xff));
  payload.push_back((char)((err >> 16) & 0xff));
  payload.push_back((char)((err >> 8) & 0xff));
  payload.push_back((char)(err & 0xff));
  put_frame_header(&f, (uint32_t)payload.size(), F_GOAWAY, 0, 0);
  f += payload;
  write_frames(s, f);
  return -1;
}

// Process a fully-decoded header list into a request.
bool FillRequest(StreamState* st,
                 std::vector<std::pair<std::string, std::string>>& hdrs) {
  for (auto& kv : hdrs) {
    const std::string& k = kv.first;
    // RFC 9113 §8.2.1: field names/values containing CR, LF or NUL are
    // malformed — reject rather than let a value inject fake header
    // lines into the "k: v\n" blob handed to the service layer.
    static const std::string kBad("\r\n\0", 3);
    if (k.find_first_of(kBad) != std::string::npos ||
        k.find(':', 1) != std::string::npos ||
        kv.second.find_first_of(kBad) != std::string::npos) {
      return false;
    }
    if (k == ":method") {
      st->req.method = kv.second;
    } else if (k == ":path") {
      size_t q = kv.second.find('?');
      if (q == std::string::npos) {
        st->req.path = kv.second;
      } else {
        st->req.path = kv.second.substr(0, q);
        st->req.query = kv.second.substr(q + 1);
      }
    } else if (k == ":authority") {
      st->req.headers += "host: " + kv.second + "\n";
    } else if (!k.empty() && k[0] == ':') {
      // :scheme etc — drop
    } else {
      st->req.headers += k + ": " + kv.second + "\n";
    }
  }
  return !st->req.method.empty() && !st->req.path.empty();
}

}  // namespace

bool LooksLikeH2(const IOBuf& buf) {
  char head[kPrefaceLen];
  size_t n = std::min(buf.size(), kPrefaceLen);
  buf.copy_to(head, n);
  return memcmp(head, kPreface, n) == 0;
}

namespace {

struct H2RespondTask {
  H2Conn* c = nullptr;  // the task's own reference
  uint32_t stream_id = 0;
  int status = 200;
  std::string headers;
  std::string body;
  std::string trailers;
  bool has_trailers = false;
};

void RunRespondTask(void*, void* targ) {
  H2RespondTask* t = (H2RespondTask*)targ;
  Socket* s = Socket::Address(t->c->sock_id);
  if (s != nullptr) {
    H2Respond(t->c, s, t->stream_id, t->status, t->headers.c_str(),
              (const uint8_t*)t->body.data(), t->body.size(),
              t->has_trailers ? t->trailers.c_str() : nullptr);
    s->Dereference();
  }
  H2ConnRelease(t->c);
  delete t;
}

// the drain loop itself must outlive any task that drops the last
// object ref: the queue pins one ref per consumer run via these hooks
void RespQStart(void* qarg) {
  ((H2Conn*)qarg)->refs.fetch_add(1, std::memory_order_acq_rel);
}
void RespQExit(void* qarg) { H2ConnRelease((H2Conn*)qarg); }

}  // namespace

void H2RespondAsync(H2Conn* c, uint32_t stream_id, int status,
                    const char* headers_blob, const uint8_t* body,
                    size_t body_len, const char* trailers_blob) {
  H2RespondTask* t = new H2RespondTask();
  c->refs.fetch_add(1, std::memory_order_acq_rel);
  t->c = c;
  t->stream_id = stream_id;
  t->status = status;
  if (headers_blob != nullptr) {
    t->headers = headers_blob;
  }
  if (body != nullptr && body_len > 0) {
    t->body.assign((const char*)body, body_len);
  }
  if (trailers_blob != nullptr) {
    t->trailers = trailers_blob;
    t->has_trailers = true;
  }
  c->resp_q.Submit(t);
}

namespace {
// Wake progressive writers parked on the connection's window butex.
void bump_window_butex(H2Conn* c) {
  if (c->window_butex != nullptr) {
    butex_value(c->window_butex).fetch_add(1, std::memory_order_release);
    butex_wake_all(c->window_butex);
  }
}
}  // namespace

H2Conn* H2ConnCreate(Socket* s) {
  native_metrics().h2_connections.fetch_add(1, std::memory_order_relaxed);
  H2Conn* c = new H2Conn();
  c->refs.store(2, std::memory_order_relaxed);  // registry + caller
  c->window_butex = butex_create();
  c->sock_id = s->id();
  c->resp_q.Init(RunRespondTask, c, RespQStart, RespQExit);
  s->is_h2.store(true, std::memory_order_release);
  {
    std::lock_guard lk(g_conns_mu);
    g_conns[s->id()] = c;
  }
  // server preface: SETTINGS with our max frame size
  std::string f;
  std::string payload;
  auto put_setting = [&payload](uint16_t id, uint32_t v) {
    payload.push_back((char)(id >> 8));
    payload.push_back((char)(id & 0xff));
    payload.push_back((char)((v >> 24) & 0xff));
    payload.push_back((char)((v >> 16) & 0xff));
    payload.push_back((char)((v >> 8) & 0xff));
    payload.push_back((char)(v & 0xff));
  };
  put_setting(0x5, kOurMaxFrameSize);    // MAX_FRAME_SIZE
  put_setting(0x3, 1024);                // MAX_CONCURRENT_STREAMS
  put_frame_header(&f, (uint32_t)payload.size(), F_SETTINGS, 0, 0);
  f += payload;
  // generous connection-level recv window so clients can push big bodies
  put_frame_header(&f, 4, F_WINDOW_UPDATE, 0, 0);
  uint32_t inc = (1u << 24);
  f.push_back((char)((inc >> 24) & 0x7f));
  f.push_back((char)((inc >> 16) & 0xff));
  f.push_back((char)((inc >> 8) & 0xff));
  f.push_back((char)(inc & 0xff));
  write_frames(s, f);
  return c;
}

H2Conn* H2ConnFind(SocketId id) {
  std::lock_guard lk(g_conns_mu);
  auto it = g_conns.find(id);
  if (it == g_conns.end()) {
    return nullptr;
  }
  it->second->refs.fetch_add(1, std::memory_order_acq_rel);
  return it->second;
}

void H2ConnRelease(H2Conn* c) {
  if (c != nullptr &&
      c->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    delete c;
  }
}

void H2ConnDestroy(SocketId id) {
  H2Conn* c = nullptr;
  {
    std::lock_guard lk(g_conns_mu);
    auto it = g_conns.find(id);
    if (it != g_conns.end()) {
      c = it->second;
      g_conns.erase(it);
      native_metrics().h2_connections.fetch_sub(
          1, std::memory_order_relaxed);
    }
  }
  if (c != nullptr) {
    bump_window_butex(c);  // parked progressive writers re-check and fail
  }
  H2ConnRelease(c);  // drop the registry's reference
}

namespace {

// Try to flush a stream's pending bytes within current windows.
void FlushPending(H2Conn* c, Socket* s, uint32_t sid, StreamState* st,
                  std::string* frames) {
  while (!st->pending.empty() && c->conn_send_window > 0 &&
         st->send_window > 0) {
    size_t chunk = std::min({st->pending.size(),
                             (size_t)c->conn_send_window,
                             (size_t)st->send_window,
                             (size_t)kOurMaxFrameSize});
    bool last = chunk == st->pending.size();
    bool end_stream = last && st->pending_trailers.empty() &&
                      !st->progressive;
    put_frame_header(frames, (uint32_t)chunk, F_DATA,
                     end_stream ? FLAG_END_STREAM : 0, sid);
    frames->append(st->pending.data(), chunk);
    st->pending.erase(0, chunk);
    c->conn_send_window -= (int32_t)chunk;
    st->send_window -= (int32_t)chunk;
  }
  if (st->pending.empty() && !st->pending_trailers.empty()) {
    put_frame_header(frames, (uint32_t)st->pending_trailers.size(),
                     F_HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM, sid);
    frames->append(st->pending_trailers);
    st->pending_trailers.clear();
  }
  if (st->pending.empty() && st->pending_trailers.empty() &&
      st->responded && !st->progressive) {
    if (!st->end_stream) {
      // response finished first (END_STREAM already framed above):
      // RST_STREAM(NO_ERROR) tells the peer to abandon the rest of its
      // upload, RFC 9113 §8.1
      put_rst_stream(frames, sid, 0);
    }
    c->streams.erase(sid);
  }
}

}  // namespace

int H2ConnConsume(H2Conn* c, Socket* s, std::vector<H2Request>* out) {
  std::lock_guard lk(c->mu);
  std::string reply;  // protocol frames to write back
  while (true) {
    if (s->read_buf.size() < 9) {
      break;
    }
    uint8_t hdr[9];
    s->read_buf.copy_to(hdr, 9);
    uint32_t len = ((uint32_t)hdr[0] << 16) | ((uint32_t)hdr[1] << 8) |
                   hdr[2];
    uint8_t type = hdr[3];
    uint8_t flags = hdr[4];
    uint32_t sid = (((uint32_t)hdr[5] & 0x7f) << 24) |
                   ((uint32_t)hdr[6] << 16) | ((uint32_t)hdr[7] << 8) |
                   hdr[8];
    if (len > kMaxFrameAccept) {
      if (!reply.empty()) write_frames(s, reply);
      return FatalGoaway(s, 0, 6 /*FRAME_SIZE_ERROR*/);
    }
    if (s->read_buf.size() < 9 + (size_t)len) {
      break;
    }
    s->read_buf.pop_front(9);
    std::string payload;
    payload.resize(len);
    if (len > 0) {
      s->read_buf.copy_to(&payload[0], len);
      s->read_buf.pop_front(len);
    }
    const uint8_t* p = (const uint8_t*)payload.data();
    size_t n = payload.size();

    if (c->continuation_stream != 0 &&
        (type != F_CONTINUATION || sid != c->continuation_stream)) {
      if (!reply.empty()) write_frames(s, reply);
      return FatalGoaway(s, 0, 1 /*PROTOCOL_ERROR*/);
    }

    switch (type) {
      case F_SETTINGS: {
        if (flags & FLAG_ACK) break;
        for (size_t i = 0; i + 6 <= n; i += 6) {
          uint16_t id = ((uint16_t)p[i] << 8) | p[i + 1];
          uint32_t v = ((uint32_t)p[i + 2] << 24) |
                       ((uint32_t)p[i + 3] << 16) |
                       ((uint32_t)p[i + 4] << 8) | p[i + 5];
          if (id == 0x4) {  // INITIAL_WINDOW_SIZE: adjust live streams
            if (v > 0x7fffffffu) {  // RFC 7540 §6.5.2
              if (!reply.empty()) write_frames(s, reply);
              return FatalGoaway(s, 0, 3 /*FLOW_CONTROL_ERROR*/);
            }
            int64_t delta = (int64_t)v - c->peer_initial_window;
            c->peer_initial_window = (int64_t)v;
            for (auto& kv : c->streams) {
              kv.second.send_window += delta;
            }
            if (delta > 0) {
              bump_window_butex(c);
            }
          }
          // id 0x1 (HEADER_TABLE_SIZE) declares the PEER's decoder table;
          // our encoder never indexes, so nothing to adjust — and our
          // decoder's limit only changes via in-band size updates
        }
        put_frame_header(&reply, 0, F_SETTINGS, FLAG_ACK, 0);
        break;
      }
      case F_PING: {
        if (!(flags & FLAG_ACK) && n == 8) {
          put_frame_header(&reply, 8, F_PING, FLAG_ACK, 0);
          reply.append(payload);
        }
        break;
      }
      case F_WINDOW_UPDATE: {
        if (n != 4) break;
        uint32_t inc = (((uint32_t)p[0] & 0x7f) << 24) |
                       ((uint32_t)p[1] << 16) | ((uint32_t)p[2] << 8) |
                       p[3];
        if (sid == 0) {
          c->conn_send_window += (int64_t)inc;
          if (c->conn_send_window > 0x7fffffffLL) {  // RFC 7540 §6.9.1
            if (!reply.empty()) write_frames(s, reply);
            return FatalGoaway(s, 0, 3 /*FLOW_CONTROL_ERROR*/);
          }
        } else {
          auto it = c->streams.find(sid);
          if (it != c->streams.end()) {
            it->second.send_window += (int64_t)inc;
            if (it->second.send_window > 0x7fffffffLL) {
              if (!reply.empty()) write_frames(s, reply);
              return FatalGoaway(s, sid, 3);
            }
          }
        }
        // windows reopened: flush anything queued, then wake parked
        // progressive writers (their budget may have cleared)
        std::vector<uint32_t> sids;
        for (auto& kv : c->streams) sids.push_back(kv.first);
        for (uint32_t fsid : sids) {
          auto it = c->streams.find(fsid);
          if (it != c->streams.end()) {
            FlushPending(c, s, fsid, &it->second, &reply);
          }
        }
        bump_window_butex(c);
        break;
      }
      case F_HEADERS: {
        if (sid == 0) {
          if (!reply.empty()) write_frames(s, reply);
          return FatalGoaway(s, 0, 1);
        }
        size_t off = 0;
        if (flags & FLAG_PADDED) {
          if (n < 1) return FatalGoaway(s, 0, 1);
          uint8_t pad = p[0];
          off = 1;
          if (pad + off > n) return FatalGoaway(s, 0, 1);
          n -= pad;
        }
        if (flags & FLAG_PRIORITY) {
          if (off + 5 > n) return FatalGoaway(s, 0, 1);
          off += 5;
        }
        if (sid > c->max_seen_sid) {
          c->max_seen_sid = sid;
        }
        bool fresh = c->streams.find(sid) == c->streams.end();
        StreamState& st = c->streams[sid];
        if (fresh) {
          st.send_window = c->peer_initial_window;
        }
        st.req.stream_id = sid;
        st.header_block.append((const char*)p + off, n - off);
        if (st.header_block.size() > kMaxHeaderBlock) {
          if (!reply.empty()) write_frames(s, reply);
          return FatalGoaway(s, sid, 11 /*ENHANCE_YOUR_CALM*/);
        }
        if (flags & FLAG_END_STREAM) {
          st.end_stream = true;
        }
        if (flags & FLAG_END_HEADERS) {
          std::vector<std::pair<std::string, std::string>> hdrs;
          if (!c->hpack.decode_block(
                  (const uint8_t*)st.header_block.data(),
                  st.header_block.size(), &hdrs)) {
            if (!reply.empty()) write_frames(s, reply);
            return FatalGoaway(s, sid, 9 /*COMPRESSION_ERROR*/);
          }
          st.header_block.clear();
          st.headers_done = true;
          if (!FillRequest(&st, hdrs)) {
            if (!reply.empty()) write_frames(s, reply);
            return FatalGoaway(s, sid, 1);
          }
          if (st.end_stream) {
            if (c->goaway) {
              c->streams.erase(sid);  // client said goaway: refuse new work
            } else {
              out->push_back(std::move(st.req));
            }
          }
        } else {
          c->continuation_stream = sid;
        }
        break;
      }
      case F_CONTINUATION: {
        auto it = c->streams.find(sid);
        if (it == c->streams.end()) {
          if (!reply.empty()) write_frames(s, reply);
          return FatalGoaway(s, 0, 1);
        }
        StreamState& st = it->second;
        st.header_block.append((const char*)p, n);
        if (st.header_block.size() > kMaxHeaderBlock) {
          if (!reply.empty()) write_frames(s, reply);
          return FatalGoaway(s, sid, 11);
        }
        if (flags & FLAG_END_HEADERS) {
          c->continuation_stream = 0;
          std::vector<std::pair<std::string, std::string>> hdrs;
          if (!c->hpack.decode_block(
                  (const uint8_t*)st.header_block.data(),
                  st.header_block.size(), &hdrs)) {
            if (!reply.empty()) write_frames(s, reply);
            return FatalGoaway(s, sid, 9);
          }
          st.header_block.clear();
          st.headers_done = true;
          if (!FillRequest(&st, hdrs)) {
            if (!reply.empty()) write_frames(s, reply);
            return FatalGoaway(s, sid, 1);
          }
          if (st.end_stream) {
            if (c->goaway) {
              c->streams.erase(sid);
            } else {
              out->push_back(std::move(st.req));
            }
          }
        }
        break;
      }
      case F_DATA: {
        auto it = c->streams.find(sid);
        if (it == c->streams.end()) {
          if (sid == 0 || sid > c->max_seen_sid || (sid & 1) == 0) {
            // DATA on a stream that never opened: connection error
            // (RFC 9113 §6.1); only PAST streams we responded-and-erased
            // are tolerated below
            if (!reply.empty()) write_frames(s, reply);
            return FatalGoaway(s, 0, 1);
          }
          // we responded and erased our half; the client's remaining
          // upload is legal (RFC 9113 §5.1 half-closed(local)) — drop
          // the bytes but keep the connection window fed
          if (len > 0) {
            put_frame_header(&reply, 4, F_WINDOW_UPDATE, 0, 0);
            reply.push_back((char)((len >> 24) & 0x7f));
            reply.push_back((char)((len >> 16) & 0xff));
            reply.push_back((char)((len >> 8) & 0xff));
            reply.push_back((char)(len & 0xff));
          }
          break;
        }
        if (!it->second.headers_done) {
          if (!reply.empty()) write_frames(s, reply);
          return FatalGoaway(s, 0, 1);
        }
        StreamState& st = it->second;
        size_t off = 0;
        if (flags & FLAG_PADDED) {
          if (n < 1) return FatalGoaway(s, 0, 1);
          uint8_t pad = p[0];
          off = 1;
          if (pad + off > n) return FatalGoaway(s, 0, 1);
          n -= pad;
        }
        st.req.body.append((const char*)p + off, n - off);
        if (st.req.body.size() > max_body_bytes()) {
          // over the body cap: a complete 413 response before the
          // request ends, then RST_STREAM(NO_ERROR) per RFC 9113 §8.1
          // so the client stops uploading instead of stalling once its
          // stream window drains (we stop crediting an erased stream).
          // Strictly per-stream: other streams on the connection and
          // the connection window stay live.
          std::string block;
          block.push_back((char)0x08);  // literal, name = :status
          block.push_back((char)3);
          block += "413";
          put_frame_header(&reply, (uint32_t)block.size(), F_HEADERS,
                           FLAG_END_HEADERS | FLAG_END_STREAM, sid);
          reply += block;
          put_rst_stream(&reply, sid, 0 /*NO_ERROR*/);
          c->streams.erase(sid);
          // credit the CONNECTION window for this frame (the stream is
          // gone, but its bytes came out of the shared window — without
          // this, every 413 permanently shrinks it; later frames on the
          // erased stream are credited by the not-found branch above)
          if (len > 0) {
            put_frame_header(&reply, 4, F_WINDOW_UPDATE, 0, 0);
            reply.push_back((char)((len >> 24) & 0x7f));
            reply.push_back((char)((len >> 16) & 0xff));
            reply.push_back((char)((len >> 8) & 0xff));
            reply.push_back((char)(len & 0xff));
          }
          break;
        }
        // replenish recv windows (conn + stream) by what we consumed
        if (len > 0) {
          for (uint32_t wsid : {0u, sid}) {
            put_frame_header(&reply, 4, F_WINDOW_UPDATE, 0, wsid);
            reply.push_back((char)((len >> 24) & 0x7f));
            reply.push_back((char)((len >> 16) & 0xff));
            reply.push_back((char)((len >> 8) & 0xff));
            reply.push_back((char)(len & 0xff));
          }
        }
        if (flags & FLAG_END_STREAM) {
          st.end_stream = true;
          if (c->goaway) {
            c->streams.erase(sid);
          } else {
            out->push_back(std::move(st.req));
          }
        }
        break;
      }
      case F_RST: {
        c->streams.erase(sid);
        // a progressive writer may be parked on this stream's window:
        // wake it so it observes the stream is gone
        bump_window_butex(c);
        break;
      }
      case F_GOAWAY: {
        c->goaway = true;
        break;
      }
      case F_PRIORITY:
      case F_PUSH:
      default:
        break;  // ignore
    }
  }
  if (!reply.empty()) {
    write_frames(s, reply);
  }
  return 0;
}

namespace {
// :status pseudo-header (static table where possible) + header blob.
void encode_status_headers(std::string* block, int status,
                           const char* headers_blob) {
  switch (status) {  // RFC 7541 static entries 8..14
    case 200: block->push_back((char)0x88); break;
    case 204: block->push_back((char)0x89); break;
    case 206: block->push_back((char)0x8a); break;
    case 304: block->push_back((char)0x8b); break;
    case 400: block->push_back((char)0x8c); break;
    case 404: block->push_back((char)0x8d); break;
    case 500: block->push_back((char)0x8e); break;
    default: {
      // literal w/o indexing, name = static index 8 (:status)
      block->push_back((char)0x08);
      std::string v = std::to_string(status);
      block->push_back((char)v.size());
      *block += v;
    }
  }
  encode_blob(block, headers_blob);
}
}  // namespace

int H2Respond(H2Conn* c, Socket* s, uint32_t stream_id, int status,
              const char* headers_blob, const uint8_t* body,
              size_t body_len, const char* trailers_blob) {
  std::lock_guard lk(c->mu);
  auto it = c->streams.find(stream_id);
  if (it == c->streams.end()) {
    return -1;  // client reset the stream
  }
  StreamState& st = it->second;
  if (st.progressive || st.responded) {
    return -1;  // already owned by a progressive response
  }
  std::string frames;
  // response HEADERS
  std::string block;
  encode_status_headers(&block, status, headers_blob);
  bool no_body = body_len == 0 && trailers_blob == nullptr;
  put_frame_header(&frames, (uint32_t)block.size(), F_HEADERS,
                   FLAG_END_HEADERS | (no_body ? FLAG_END_STREAM : 0),
                   stream_id);
  frames += block;
  st.responded = true;
  if (no_body) {
    if (!st.end_stream) {
      // complete response before the request body ended: RFC 9113 §8.1
      // says RST_STREAM(NO_ERROR) so the peer abandons the upload (we
      // stop crediting the erased stream's window and a conformant
      // sender would otherwise stall on it)
      put_rst_stream(&frames, stream_id, 0);
    }
    c->streams.erase(stream_id);
    write_frames(s, frames);
    return 0;
  }
  st.pending.assign((const char*)body, body_len);
  if (trailers_blob != nullptr) {
    std::string tblock;
    encode_blob(&tblock, trailers_blob);
    st.pending_trailers = std::move(tblock);
  }
  FlushPending(c, s, stream_id, &st, &frames);
  write_frames(s, frames);
  return 0;
}

// --- progressive server responses (h2.h) -----------------------------------

namespace {
// Window-blocked bytes a progressive stream may buffer before its
// writer parks: deep enough to ride out one client credit round-trip,
// shallow enough that client flow control actually paces the handler.
constexpr size_t kProgressiveHighWater = 256 * 1024;
}  // namespace

int H2RespondStart(H2Conn* c, Socket* s, uint32_t stream_id, int status,
                   const char* headers_blob) {
  std::lock_guard lk(c->mu);
  auto it = c->streams.find(stream_id);
  if (it == c->streams.end()) {
    return -EPIPE;  // client reset the stream
  }
  StreamState& st = it->second;
  if (st.responded || st.progressive) {
    return -EINVAL;
  }
  st.progressive = true;
  std::string frames;
  std::string block;
  encode_status_headers(&block, status, headers_blob);
  put_frame_header(&frames, (uint32_t)block.size(), F_HEADERS,
                   FLAG_END_HEADERS, stream_id);  // stream stays open
  frames += block;
  write_frames(s, frames);
  return 0;
}

int H2StreamData(H2Conn* c, uint32_t stream_id, const uint8_t* data,
                 size_t len, int64_t timeout_us) {
  if (len == 0) {
    return 0;
  }
  int64_t deadline = monotonic_us() + timeout_us;
  size_t off = 0;
  while (off < len) {
    Socket* s = Socket::Address(c->sock_id);
    if (s == nullptr) {
      return -EPIPE;  // connection gone
    }
    int32_t seq;
    {
      std::lock_guard lk(c->mu);
      auto it = c->streams.find(stream_id);
      if (it == c->streams.end() || !it->second.progressive) {
        s->Dereference();
        return -EPIPE;  // stream RST / already closed
      }
      StreamState& st = it->second;
      if (st.pending.size() < kProgressiveHighWater) {
        // append AT MOST up to the high-water mark: one oversized
        // write must not balloon st.pending past the bound — the
        // remainder waits for the peer to drain what's queued, so per-
        // stream memory stays capped at high-water + one frame
        size_t room = kProgressiveHighWater - st.pending.size();
        size_t take = len - off < room ? len - off : room;
        st.pending.append((const char*)data + off, take);
        off += take;
        std::string frames;
        FlushPending(c, s, stream_id, &st, &frames);
        if (!frames.empty()) {
          write_frames(s, frames);
        }
        s->Dereference();
        continue;  // more to queue? re-check budget (flush may have
                   // drained pending within the windows)
      }
      // over high water: park until the peer credits a window (or the
      // stream/connection dies) — this is where client flow control
      // reaches back and paces the producing handler
      seq = butex_value(c->window_butex).load(std::memory_order_acquire);
    }
    s->Dereference();
    int64_t left = deadline - monotonic_us();
    if (left <= 0) {
      return -ETIMEDOUT;
    }
    butex_wait(c->window_butex, seq,
               left < 200 * 1000 ? left : 200 * 1000);
  }
  return 0;
}

int H2StreamClose(H2Conn* c, uint32_t stream_id,
                  const char* trailers_blob) {
  Socket* s = Socket::Address(c->sock_id);
  if (s == nullptr) {
    return -EPIPE;
  }
  std::lock_guard lk(c->mu);
  auto it = c->streams.find(stream_id);
  if (it == c->streams.end()) {
    s->Dereference();
    return 0;  // client reset first: nothing left to finish
  }
  StreamState& st = it->second;
  st.progressive = false;
  st.responded = true;
  std::string frames;
  if (trailers_blob != nullptr && trailers_blob[0] != '\0') {
    std::string tblock;
    encode_blob(&tblock, trailers_blob);
    st.pending_trailers = std::move(tblock);
  }
  if (st.pending.empty() && st.pending_trailers.empty()) {
    // nothing buffered and no trailers: a bare END_STREAM DATA frame
    put_frame_header(&frames, 0, F_DATA, FLAG_END_STREAM, stream_id);
    if (!st.end_stream) {
      put_rst_stream(&frames, stream_id, 0);  // RFC 9113 §8.1
    }
    c->streams.erase(stream_id);
  } else {
    // FlushPending ends the stream (trailers or final DATA) and RSTs
    // the unfinished request side when it drains — possibly on a later
    // WINDOW_UPDATE if the peer's windows are currently exhausted
    FlushPending(c, s, stream_id, &st, &frames);
  }
  if (!frames.empty()) {
    write_frames(s, frames);
  }
  s->Dereference();
  return 0;
}

// ---------------------------------------------------------------------------
// HTTP/2 client (see h2.h).  Same frame machinery as the server half,
// mirrored: odd stream ids originate here, HEADERS carry the request
// pseudo-headers, and responses flow back through the edge_fn below.

namespace {

constexpr int64_t kClientConnWindow = 1 << 30;  // opened wide at create
// per-stream receive budget: bounds how far a server can run ahead of a
// slow reader (streaming calls replenish from read(), so this is also
// the max bytes buffered per stream); unary streams replenish on arrival
constexpr int64_t kClientStreamWindow = 4 << 20;

struct H2ClientStream {
  Butex* done = nullptr;  // 0 -> 1 when the stream completes/fails
  int error = 0;          // -TRPC_* when failed
  bool headers_done = false;
  H2ClientResult result;
  // CONTINUATION accumulation for this stream's current header block
  std::string hdr_block;
  bool hdr_end_stream = false;
  // streaming mode (h2_client_stream_*): response DATA is delivered
  // incrementally through `chunks` + a bump-counter wake instead of
  // accumulating into result.body
  bool streaming = false;
  std::deque<std::string> chunks;
  Butex* data_butex = nullptr;  // bumped on every chunk/completion
  // receive-window bytes consumed but not yet credited back: unary
  // credits on arrival (the body is consumed immediately); streaming
  // credits from read() so a slow reader throttles the server
  uint64_t stream_unacked = 0;
};

struct H2ClientConn {
  SocketId sock = INVALID_SOCKET_ID;
  // lint:allow-blocking-bounded (stream-table mutation only; the
  // HEADERS write ordering uses header_mu so this is never held
  // across Socket::Write; contention-profiled)
  ProfiledMutex mu;  // hot: every frame/call; contention-profiled
  // serializes stream-id allocation with the HEADERS write (RFC 9113
  // §5.1.1 increasing-id order) WITHOUT holding mu across Socket::Write:
  // a failed inline write runs H2ClientOnFailed, which takes mu.
  // Ordering: header_mu may wrap mu, never the reverse.
  std::mutex header_mu;
  Hpack hpack_rx;  // decodes response header blocks
  uint32_t next_stream = 1;
  std::unordered_map<uint32_t, H2ClientStream*> streams;
  // send flow control (peer's receive budget)
  int64_t conn_send_window = 65535;
  int64_t peer_initial_window = 65535;
  std::unordered_map<uint32_t, int64_t> stream_send_window;
  uint32_t peer_max_frame = 16384;
  Butex* window_butex = nullptr;  // bumped whenever windows grow
  // receive replenishment
  int64_t consumed_since_update = 0;
  uint32_t continuation_stream = 0;
  // header block of a stream that no longer exists (timed out): HPACK
  // state is connection-wide, so the block must still reach the decoder
  std::string orphan_block;
  bool tls = false;
  std::atomic<bool> failed{false};
};

void H2ClientCompleteLocked(H2ClientConn* c, uint32_t sid,
                            H2ClientStream* st, int error) {
  st->error = error;
  c->streams.erase(sid);
  c->stream_send_window.erase(sid);
  butex_value(st->done).store(1, std::memory_order_release);
  butex_wake_all(st->done);
  if (st->data_butex != nullptr) {
    butex_value(st->data_butex).fetch_add(1, std::memory_order_release);
    butex_wake_all(st->data_butex);
  }
  // a sender parked on flow control must notice the completion (e.g.
  // the peer finished the response before the request body was done)
  butex_value(c->window_butex).fetch_add(1, std::memory_order_release);
  butex_wake_all(c->window_butex);
}

void H2ClientFailAllLocked(H2ClientConn* c, int error) {
  if (c->continuation_stream != 0) {
    // a header block is mid-flight: keep its accumulated prefix so the
    // remaining CONTINUATION frames still decode as one full block
    auto it = c->streams.find(c->continuation_stream);
    if (it != c->streams.end()) {
      c->orphan_block = std::move(it->second->hdr_block);
    }
  }
  for (auto& kv : c->streams) {
    H2ClientStream* st = kv.second;
    st->error = error;
    butex_value(st->done).store(1, std::memory_order_release);
    butex_wake_all(st->done);
    if (st->data_butex != nullptr) {
      butex_value(st->data_butex).fetch_add(1, std::memory_order_release);
      butex_wake_all(st->data_butex);
    }
  }
  c->streams.clear();
  c->stream_send_window.clear();
  butex_value(c->window_butex).fetch_add(1, std::memory_order_release);
  butex_wake_all(c->window_butex);
}

void H2ClientOnFailed(Socket* s) {
  H2ClientConn* c = (H2ClientConn*)s->user;
  if (c == nullptr) {
    return;
  }
  c->failed.store(true, std::memory_order_release);
  std::lock_guard lk(c->mu);
  H2ClientFailAllLocked(c, -TRPC_EFAILEDSOCKET);  // also wakes senders
}

// Decode one complete header block into st->result (headers, then
// trailers on the second block).  Returns false on HPACK corruption.
bool H2ClientHeaderBlock(H2ClientConn* c, H2ClientStream* st,
                         const std::string& block) {
  std::vector<std::pair<std::string, std::string>> hs;
  if (!c->hpack_rx.decode_block((const uint8_t*)block.data(), block.size(),
                                &hs)) {
    return false;
  }
  std::string* sink =
      st->headers_done ? &st->result.trailers : &st->result.headers;
  for (auto& kv : hs) {
    if (kv.first == ":status") {
      st->result.status = atoi(kv.second.c_str());
    } else if (!kv.first.empty() && kv.first[0] != ':') {
      *sink += kv.first;
      *sink += ": ";
      *sink += kv.second;
      *sink += "\n";
    }
  }
  st->headers_done = true;
  return true;
}

void H2ClientOnMessages(Socket* s) {
  H2ClientConn* c = (H2ClientConn*)s->user;
  bool eof = false;
  ssize_t r = s->ReadToBuf(&eof);
  bool dead = eof || (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                      errno != EINTR);
  std::unique_lock lk(c->mu);
  std::string reply;
  bool window_grew = false;
  while (true) {
    if (s->read_buf.size() < 9) {
      break;
    }
    uint8_t hdr[9];
    s->read_buf.copy_to(hdr, 9);
    uint32_t len = ((uint32_t)hdr[0] << 16) | ((uint32_t)hdr[1] << 8) |
                   hdr[2];
    uint8_t type = hdr[3];
    uint8_t flags = hdr[4];
    uint32_t sid = (((uint32_t)hdr[5] & 0x7f) << 24) |
                   ((uint32_t)hdr[6] << 16) | ((uint32_t)hdr[7] << 8) |
                   hdr[8];
    if (len > kMaxFrameAccept) {
      lk.unlock();
      s->SetFailed(EPROTO);
      return;
    }
    if (s->read_buf.size() < 9 + (size_t)len) {
      break;
    }
    s->read_buf.pop_front(9);
    std::string payload;
    payload.resize(len);
    if (len > 0) {
      s->read_buf.copy_to(&payload[0], len);
      s->read_buf.pop_front(len);
    }
    const uint8_t* p = (const uint8_t*)payload.data();
    size_t n = payload.size();

    if (c->continuation_stream != 0 &&
        (type != F_CONTINUATION || sid != c->continuation_stream)) {
      lk.unlock();
      s->SetFailed(EPROTO);
      return;
    }

    switch (type) {
      case F_SETTINGS: {
        if (flags & FLAG_ACK) break;
        for (size_t i = 0; i + 6 <= n; i += 6) {
          uint16_t id = ((uint16_t)p[i] << 8) | p[i + 1];
          uint32_t v = ((uint32_t)p[i + 2] << 24) |
                       ((uint32_t)p[i + 3] << 16) |
                       ((uint32_t)p[i + 4] << 8) | p[i + 5];
          if (id == 0x4) {
            int64_t delta = (int64_t)v - c->peer_initial_window;
            c->peer_initial_window = (int64_t)v;
            for (auto& kv : c->stream_send_window) {
              kv.second += delta;
            }
            window_grew = window_grew || delta > 0;
          } else if (id == 0x5 && v >= 16384 && v <= (1u << 24)) {
            c->peer_max_frame = v;
          }
        }
        put_frame_header(&reply, 0, F_SETTINGS, FLAG_ACK, 0);
        break;
      }
      case F_PING: {
        if (!(flags & FLAG_ACK) && n == 8) {
          put_frame_header(&reply, 8, F_PING, FLAG_ACK, 0);
          reply.append(payload);
        }
        break;
      }
      case F_WINDOW_UPDATE: {
        if (n != 4) break;
        uint32_t inc = (((uint32_t)p[0] & 0x7f) << 24) |
                       ((uint32_t)p[1] << 16) | ((uint32_t)p[2] << 8) |
                       p[3];
        if (sid == 0) {
          c->conn_send_window += (int64_t)inc;
        } else {
          auto it = c->stream_send_window.find(sid);
          if (it != c->stream_send_window.end()) {
            it->second += (int64_t)inc;
          }
        }
        window_grew = true;
        break;
      }
      case F_HEADERS:
      case F_CONTINUATION: {
        auto it = c->streams.find(sid);
        // even when the stream is gone (timed out and erased) the block
        // MUST still run through the connection-wide HPACK decoder, or
        // its dynamic-table updates are lost and every later response
        // decodes corrupt — accumulate orphans and decode-then-discard
        H2ClientStream* st = it == c->streams.end() ? nullptr : it->second;
        std::string* blk = st != nullptr ? &st->hdr_block : &c->orphan_block;
        size_t off = 0;
        if (type == F_HEADERS) {
          size_t pad = 0;
          if (flags & FLAG_PADDED) {
            if (n < 1) break;
            pad = p[0];
            off += 1;
          }
          if (flags & FLAG_PRIORITY) {
            off += 5;
          }
          if (off + pad > n) {  // malformed padding/priority lengths
            lk.unlock();
            s->SetFailed(EPROTO);
            return;
          }
          blk->assign((const char*)p + off, n - off - pad);
          if (st != nullptr) {
            st->hdr_end_stream = (flags & FLAG_END_STREAM) != 0;
          }
        } else {
          blk->append((const char*)p, n);
        }
        if (flags & FLAG_END_HEADERS) {
          c->continuation_stream = 0;
          bool ok;
          if (st != nullptr) {
            ok = H2ClientHeaderBlock(c, st, st->hdr_block);
            st->hdr_block.clear();
          } else {
            std::vector<std::pair<std::string, std::string>> discard;
            ok = c->hpack_rx.decode_block(
                (const uint8_t*)c->orphan_block.data(),
                c->orphan_block.size(), &discard);
            c->orphan_block.clear();
          }
          if (!ok) {
            lk.unlock();
            s->SetFailed(EPROTO);
            return;
          }
          if (st != nullptr && st->hdr_end_stream) {
            H2ClientCompleteLocked(c, sid, st, 0);
          }
        } else {
          c->continuation_stream = sid;
        }
        break;
      }
      case F_DATA: {
        size_t off = 0;
        size_t dlen = n;
        if (flags & FLAG_PADDED) {
          if (n < 1 || (size_t)p[0] + 1 > n) {  // pad exceeds payload
            lk.unlock();
            s->SetFailed(EPROTO);
            return;
          }
          off = 1;
          dlen = n - 1 - p[0];
        }
        c->consumed_since_update += (int64_t)n;
        auto it = c->streams.find(sid);
        if (it != c->streams.end()) {
          H2ClientStream* st = it->second;
          if (st->streaming) {
            if (dlen > 0) {
              st->chunks.emplace_back((const char*)p + off, dlen);
              butex_value(st->data_butex)
                  .fetch_add(1, std::memory_order_release);
              butex_wake_all(st->data_butex);
            }
            // stream-window credit for DATA comes from
            // h2_client_stream_read: a slow reader deliberately
            // throttles the server.  Padding overhead (the pad-length
            // byte + pad bytes, n - dlen) never reaches the reader, so
            // it is credited AT ARRIVAL — a padding-heavy server would
            // otherwise permanently shrink the 4MB stream window (every
            // padded frame consumes n of it but only dlen ever gets
            // credited back).  stream_unacked holds only bytes the
            // reader consumed plus this overhead, so flushing it here
            // cannot open the window for unread data.
            st->stream_unacked += (uint64_t)(n - dlen);
            if (!(flags & FLAG_END_STREAM) &&
                st->stream_unacked >= (uint64_t)kClientStreamWindow / 2) {
              put_frame_header(&reply, 4, F_WINDOW_UPDATE, 0, sid);
              uint32_t inc = (uint32_t)st->stream_unacked;
              reply.push_back((char)((inc >> 24) & 0x7f));
              reply.push_back((char)(inc >> 16));
              reply.push_back((char)(inc >> 8));
              reply.push_back((char)inc);
              st->stream_unacked = 0;
            }
          } else {
            st->result.body.append((const char*)p + off, dlen);
            // unary consumes on arrival: credit the stream window so
            // responses larger than the initial window keep flowing
            st->stream_unacked += n;
            if (!(flags & FLAG_END_STREAM) &&
                st->stream_unacked >= (uint64_t)kClientStreamWindow / 2) {
              put_frame_header(&reply, 4, F_WINDOW_UPDATE, 0, sid);
              uint32_t inc = (uint32_t)st->stream_unacked;
              reply.push_back((char)((inc >> 24) & 0x7f));
              reply.push_back((char)(inc >> 16));
              reply.push_back((char)(inc >> 8));
              reply.push_back((char)inc);
              st->stream_unacked = 0;
            }
          }
          if (flags & FLAG_END_STREAM) {
            H2ClientCompleteLocked(c, sid, st, 0);
          }
        }
        // replenish the CONNECTION window in 1MB slabs.  (The conn
        // window was opened to 1GB via WINDOW_UPDATE at create; each
        // STREAM got a 4MB initial window via SETTINGS
        // INITIAL_WINDOW_SIZE = kClientStreamWindow and is credited
        // separately: unary streams on arrival, streaming reads from
        // h2_client_stream_read — a slow reader throttles the server —
        // and padding overhead at arrival, all in half-window slabs.)
        if (c->consumed_since_update >= (1 << 20)) {
          put_frame_header(&reply, 4, F_WINDOW_UPDATE, 0, 0);
          uint32_t inc = (uint32_t)c->consumed_since_update;
          reply.push_back((char)((inc >> 24) & 0x7f));
          reply.push_back((char)(inc >> 16));
          reply.push_back((char)(inc >> 8));
          reply.push_back((char)inc);
          c->consumed_since_update = 0;
        }
        break;
      }
      case F_RST: {
        auto it = c->streams.find(sid);
        if (it != c->streams.end()) {
          H2ClientCompleteLocked(c, sid, it->second, -TRPC_EINTERNAL);
        }
        break;
      }
      case F_GOAWAY: {
        H2ClientFailAllLocked(c, -TRPC_ESTOP);
        break;
      }
      default:
        break;  // PRIORITY, PUSH (we never enable push): ignore
    }
  }
  if (window_grew) {
    butex_value(c->window_butex).fetch_add(1, std::memory_order_release);
    butex_wake_all(c->window_butex);
  }
  lk.unlock();
  if (!reply.empty()) {
    write_frames(s, reply);
  }
  if (dead) {
    s->SetFailed(errno != 0 ? errno : ECONNRESET);
  }
}

}  // namespace

void* h2_client_create(const char* ip, int port, int64_t connect_timeout_us,
                       int* rc_out) {
  return h2_client_create_tls(ip, port, connect_timeout_us, nullptr,
                              rc_out);
}

void* h2_client_create_tls(const char* ip, int port,
                           int64_t connect_timeout_us, void* tls_ctx,
                           int* rc_out) {
  fiber_runtime_init(0);
  int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (fd < 0) {
    *rc_out = -errno;
    return nullptr;
  }
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) {
    // hostnames resolve on the Python side; a bad literal must not turn
    // into a connect to 255.255.255.255
    *rc_out = -EINVAL;
    ::close(fd);
    return nullptr;
  }
  // bounded blocking connect
  timeval tv;
  tv.tv_sec = connect_timeout_us / 1000000;
  tv.tv_usec = connect_timeout_us % 1000000;
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    *rc_out = -errno;
    ::close(fd);
    return nullptr;
  }
  fd_set_nodelay(fd);
  // epoll-driven reads drain to EAGAIN: the fd MUST be non-blocking or
  // the dispatcher blocks inside read(2) once the data runs out
  fd_set_nonblock(fd);

  // TLS: handshake synchronously on the fresh fd (same pattern as
  // DialConn); once socket->tls is set, Write/ReadToBuf encrypt and
  // decrypt transparently — the h2 framing layer never notices
  TlsState* tls_st = nullptr;
  if (tls_ctx != nullptr) {
    tls_st = tls_state_create(tls_ctx, 1);
    if (tls_st == nullptr ||
        tls_client_handshake_fd(tls_st, fd,
                                monotonic_us() + connect_timeout_us) != 0) {
      tls_state_free(tls_st);
      ::close(fd);
      *rc_out = -EPROTO;
      return nullptr;
    }
  }

  H2ClientConn* c = new H2ClientConn();
  c->tls = tls_ctx != nullptr;  // drives ':scheme' on every request
  c->window_butex = butex_create();
  SocketOptions opts;
  opts.fd = fd;
  opts.edge_fn = H2ClientOnMessages;
  opts.user = c;
  opts.on_failed = H2ClientOnFailed;
  if (Socket::Create(opts, &c->sock) != 0) {
    ::close(fd);
    butex_destroy(c->window_butex);
    delete c;
    *rc_out = -ENOMEM;
    return nullptr;
  }
  // preface + SETTINGS (huge initial stream window) + a wide connection
  // window, all in one write
  std::string hello = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
  std::string settings;
  settings.push_back(0x00);
  settings.push_back(0x04);  // INITIAL_WINDOW_SIZE (per stream)
  settings.push_back((char)((kClientStreamWindow >> 24) & 0xff));
  settings.push_back((char)((kClientStreamWindow >> 16) & 0xff));
  settings.push_back((char)((kClientStreamWindow >> 8) & 0xff));
  settings.push_back((char)(kClientStreamWindow & 0xff));
  put_frame_header(&hello, (uint32_t)settings.size(), F_SETTINGS, 0, 0);
  hello += settings;
  uint32_t winc = (uint32_t)(kClientConnWindow - 65535);
  put_frame_header(&hello, 4, F_WINDOW_UPDATE, 0, 0);
  hello.push_back((char)((winc >> 24) & 0x7f));
  hello.push_back((char)(winc >> 16));
  hello.push_back((char)(winc >> 8));
  hello.push_back((char)winc);
  Socket* s = Socket::Address(c->sock);
  if (s != nullptr) {
    s->tls = tls_st;
    s->tls_checked = true;
    write_frames(s, hello);
    EventDispatcher::Instance().AddConsumer(c->sock, fd, s->shard);
    s->Dereference();
  } else if (tls_st != nullptr) {
    tls_state_free(tls_st);
  }
  *rc_out = 0;
  return c;
}

namespace {

// Allocate a stream id, register `st`, and put the request HEADERS on
// the wire — sid allocation and the write share the header_mu critical
// section so ids reach the wire in increasing order (RFC 9113 §5.1.1).
uint32_t H2ClientSendHeaders(H2ClientConn* c, Socket* s, H2ClientStream* st,
                             const char* method, const char* path,
                             const char* headers_blob, bool end_stream) {
  // pseudo-headers first, then the caller's blob (built before the
  // lock — nothing in it depends on the stream id)
  std::string block;
  hpack_literal(&block, ":method", method);
  hpack_literal(&block, ":scheme", c->tls ? "https" : "http");
  hpack_literal(&block, ":path", path);
  hpack_literal(&block, ":authority", "localhost");
  encode_blob(&block, headers_blob);
  uint32_t sid;
  std::lock_guard order_lk(c->header_mu);
  size_t maxf;
  {
    std::lock_guard lk(c->mu);
    sid = c->next_stream;
    c->next_stream += 2;
    c->streams[sid] = st;
    c->stream_send_window[sid] = c->peer_initial_window;
    maxf = c->peer_max_frame;
  }
  // split the header block across CONTINUATION frames when it exceeds
  // the peer's max frame size (the server enforces it with a GOAWAY)
  std::string frames;
  size_t off = 0;
  bool first = true;
  do {
    size_t chunk = block.size() - off;
    if (chunk > maxf) chunk = maxf;
    bool last = off + chunk == block.size();
    uint8_t type = first ? F_HEADERS : F_CONTINUATION;
    uint8_t flags = (last ? FLAG_END_HEADERS : 0) |
                    (first && end_stream ? FLAG_END_STREAM : 0);
    put_frame_header(&frames, (uint32_t)chunk, type, flags, sid);
    frames.append(block, off, chunk);
    off += chunk;
    first = false;
  } while (off < block.size());
  write_frames(s, frames);
  return sid;
}

// Flow-controlled DATA send (whole buffer; optionally END_STREAM on the
// last frame).  Returns 0, 1 when the peer completed the response early
// (upload abandoned with RST NO_ERROR — take the response), or -TRPC_*.
int H2ClientSendData(H2ClientConn* c, Socket* s, uint32_t sid,
                     H2ClientStream* st, const uint8_t* body,
                     size_t body_len, bool end_stream, int64_t deadline) {
  if (body_len == 0 && end_stream) {
    {
      std::lock_guard lk(c->mu);
      if (c->stream_send_window.find(sid) == c->stream_send_window.end()) {
        return 1;  // already completed/failed: nothing left to close
      }
    }
    std::string df;
    put_frame_header(&df, 0, F_DATA, FLAG_END_STREAM, sid);
    write_frames(s, df);  // empty close frame needs no window
    return 0;
  }
  size_t sent = 0;
  while (sent < body_len) {
    size_t want = body_len - sent;
    std::unique_lock lk(c->mu);
    int64_t avail = c->conn_send_window;
    auto it = c->stream_send_window.find(sid);
    if (it == c->stream_send_window.end()) {
      if (st->error == 0 &&
          butex_value(st->done).load(std::memory_order_acquire) != 0) {
        // the peer finished the response before we finished the request
        // (legal per RFC 9113 §8.1, common for early 404/413): stop
        // uploading, tell the server via RST NO_ERROR, take the response
        lk.unlock();
        std::string rst;
        put_frame_header(&rst, 4, F_RST, 0, sid);
        rst.append("\x00\x00\x00\x00", 4);  // NO_ERROR
        write_frames(s, rst);
        return 1;
      }
      return st->error != 0 ? st->error : -TRPC_EINTERNAL;
    }
    avail = avail < it->second ? avail : it->second;
    if (avail <= 0) {
      int32_t seq =
          butex_value(c->window_butex).load(std::memory_order_acquire);
      lk.unlock();
      int64_t left = deadline - monotonic_us();
      if (left <= 0 || butex_wait(c->window_butex, seq, left) != 0) {
        if (errno == ETIMEDOUT || left <= 0) {
          return -TRPC_ERPCTIMEDOUT;
        }
      }
      if (c->failed.load(std::memory_order_acquire)) {
        return -TRPC_EFAILEDSOCKET;
      }
      continue;
    }
    size_t chunk = want;
    if ((int64_t)chunk > avail) chunk = (size_t)avail;
    if (chunk > c->peer_max_frame) chunk = c->peer_max_frame;
    c->conn_send_window -= (int64_t)chunk;
    it->second -= (int64_t)chunk;
    bool last = sent + chunk == body_len;
    lk.unlock();
    std::string df;
    put_frame_header(&df, (uint32_t)chunk, F_DATA,
                     last && end_stream ? FLAG_END_STREAM : 0, sid);
    df.append((const char*)body + sent, chunk);
    write_frames(s, df);
    sent += chunk;
  }
  return 0;
}

}  // namespace

int h2_client_call(void* conn, const char* method, const char* path,
                   const char* headers_blob, const uint8_t* body,
                   size_t body_len, int64_t timeout_us,
                   H2ClientResult* out) {
  H2ClientConn* c = (H2ClientConn*)conn;
  if (c->failed.load(std::memory_order_acquire)) {
    return -TRPC_EFAILEDSOCKET;
  }
  int64_t deadline = monotonic_us() + timeout_us;
  H2ClientStream st;
  st.done = butex_create();
  butex_value(st.done).store(0, std::memory_order_relaxed);

  Socket* s = Socket::Address(c->sock);
  if (s == nullptr) {
    butex_destroy(st.done);
    return -TRPC_EFAILEDSOCKET;
  }

  uint32_t sid = H2ClientSendHeaders(c, s, &st, method, path, headers_blob,
                                     body_len == 0);
  int rc = 0;
  if (body_len > 0) {
    rc = H2ClientSendData(c, s, sid, &st, body, body_len, true, deadline);
    if (rc > 0) {
      rc = 0;  // early response: fall through and take it
    }
  }

  // await completion
  if (rc == 0) {
    while (butex_value(st.done).load(std::memory_order_acquire) == 0) {
      int64_t left = deadline - monotonic_us();
      if (left <= 0) {
        rc = -TRPC_ERPCTIMEDOUT;
        break;
      }
      butex_wait(st.done, 0, left);
    }
  }
  if (rc == 0) {
    rc = st.error;
  }

  bool still_registered;
  {
    std::lock_guard lk(c->mu);
    still_registered = c->streams.erase(sid) > 0;
    c->stream_send_window.erase(sid);
    if (still_registered && c->continuation_stream == sid) {
      // erased mid-header-block: the rest of the block arrives as
      // CONTINUATION for a gone stream — hand the accumulated prefix to
      // the orphan buffer so the HPACK decoder still sees a full block
      c->orphan_block = std::move(st.hdr_block);
    }
  }
  if (still_registered) {
    // timed out / failed before the peer finished: reset the stream so
    // late frames can't touch our stack-allocated state
    std::string rst;
    put_frame_header(&rst, 4, F_RST, 0, sid);
    rst.append("\x00\x00\x00\x08", 4);  // CANCEL
    write_frames(s, rst);
  }
  s->Dereference();
  if (rc == 0 && out != nullptr) {
    *out = std::move(st.result);
  }
  butex_destroy(st.done);
  return rc;
}


// --- streaming client calls (≙ the reference h2 client expressing what
// stream.cc speaks natively: request-body streaming + response streaming
// to a reader, progressive_reader.h:36-shaped) ------------------------------

struct H2ClientStreamHandle {
  H2ClientConn* c = nullptr;
  uint32_t sid = 0;
  H2ClientStream* st = nullptr;  // heap; owned by the handle
};

void* h2_client_stream_open(void* conn, const char* method, const char* path,
                            const char* headers_blob, int* rc_out) {
  H2ClientConn* c = (H2ClientConn*)conn;
  if (c->failed.load(std::memory_order_acquire)) {
    *rc_out = -TRPC_EFAILEDSOCKET;
    return nullptr;
  }
  Socket* s = Socket::Address(c->sock);
  if (s == nullptr) {
    *rc_out = -TRPC_EFAILEDSOCKET;
    return nullptr;
  }
  H2ClientStream* st = new H2ClientStream();
  st->done = butex_create();
  butex_value(st->done).store(0, std::memory_order_relaxed);
  st->streaming = true;
  st->data_butex = butex_create();
  butex_value(st->data_butex).store(0, std::memory_order_relaxed);
  H2ClientStreamHandle* h = new H2ClientStreamHandle();
  h->c = c;
  h->st = st;
  h->sid = H2ClientSendHeaders(c, s, st, method, path, headers_blob, false);
  s->Dereference();
  *rc_out = 0;
  return h;
}

int h2_client_stream_write(void* stream, const uint8_t* data, size_t len,
                           int64_t timeout_us) {
  H2ClientStreamHandle* h = (H2ClientStreamHandle*)stream;
  Socket* s = Socket::Address(h->c->sock);
  if (s == nullptr) {
    return -TRPC_EFAILEDSOCKET;
  }
  int rc = H2ClientSendData(h->c, s, h->sid, h->st, data, len, false,
                            monotonic_us() + timeout_us);
  s->Dereference();
  // rc==1: the peer already completed the response — callers switch to
  // reading; surface as EPIPE-shaped "stop sending"
  return rc == 1 ? -TRPC_ESTOP : rc;
}

int h2_client_stream_close_send(void* stream) {
  H2ClientStreamHandle* h = (H2ClientStreamHandle*)stream;
  Socket* s = Socket::Address(h->c->sock);
  if (s == nullptr) {
    return -TRPC_EFAILEDSOCKET;
  }
  int rc = H2ClientSendData(h->c, s, h->sid, h->st, nullptr, 0, true,
                            monotonic_us());
  s->Dereference();
  return rc == 1 ? 0 : rc;
}

// Next response-body chunk: >0 = length (malloc'd into *out, caller
// frees with h2_client_stream_chunk_free), 0 = EOF (status/headers/
// trailers now final), -TRPC_ERPCTIMEDOUT, or the stream error.
int64_t h2_client_stream_read(void* stream, int64_t timeout_us,
                              uint8_t** out) {
  H2ClientStreamHandle* h = (H2ClientStreamHandle*)stream;
  H2ClientStream* st = h->st;
  *out = nullptr;
  int64_t deadline = monotonic_us() + timeout_us;
  while (true) {
    int32_t seq;
    bool have_chunk = false;
    std::string chunk;
    bool credit = false;
    uint32_t inc = 0;
    {
      std::lock_guard lk(h->c->mu);
      if (!st->chunks.empty()) {
        chunk = std::move(st->chunks.front());
        st->chunks.pop_front();
        have_chunk = true;
        // reader-driven flow control: credit what we just consumed so
        // the server can send more — but only as fast as we read
        st->stream_unacked += chunk.size();
        credit =
            st->stream_unacked >= (uint64_t)kClientStreamWindow / 2 &&
            butex_value(st->done).load(std::memory_order_acquire) == 0;
        if (credit) {
          inc = (uint32_t)st->stream_unacked;
          st->stream_unacked = 0;
        }
      }
      if (!have_chunk &&
          butex_value(st->done).load(std::memory_order_acquire) != 0) {
        return st->error != 0 ? st->error : 0;  // EOF (or the failure)
      }
      seq = butex_value(st->data_butex).load(std::memory_order_acquire);
    }
    if (have_chunk) {
      if (credit) {
        // outside c->mu: an inline write failure runs H2ClientOnFailed,
        // which takes c->mu (the round-5 self-deadlock lesson)
        Socket* sock = Socket::Address(h->c->sock);
        if (sock != nullptr) {
          std::string wu;
          put_frame_header(&wu, 4, F_WINDOW_UPDATE, 0, h->sid);
          wu.push_back((char)((inc >> 24) & 0x7f));
          wu.push_back((char)(inc >> 16));
          wu.push_back((char)(inc >> 8));
          wu.push_back((char)inc);
          write_frames(sock, wu);
          sock->Dereference();
        }
      }
      uint8_t* mem = (uint8_t*)malloc(chunk.size() > 0 ? chunk.size() : 1);
      memcpy(mem, chunk.data(), chunk.size());
      *out = mem;
      return (int64_t)chunk.size();
    }
    int64_t left = deadline - monotonic_us();
    if (left <= 0) {
      return -TRPC_ERPCTIMEDOUT;
    }
    butex_wait(st->data_butex, seq, left);
  }
}

void h2_client_stream_chunk_free(uint8_t* p) { free(p); }

int h2_client_stream_status(void* stream) {
  H2ClientStreamHandle* h = (H2ClientStreamHandle*)stream;
  std::lock_guard lk(h->c->mu);
  return h->st->result.status;
}

size_t h2_client_stream_headers(void* stream, const uint8_t** p) {
  H2ClientStreamHandle* h = (H2ClientStreamHandle*)stream;
  std::lock_guard lk(h->c->mu);
  *p = (const uint8_t*)h->st->result.headers.data();
  return h->st->result.headers.size();
}

size_t h2_client_stream_trailers(void* stream, const uint8_t** p) {
  H2ClientStreamHandle* h = (H2ClientStreamHandle*)stream;
  std::lock_guard lk(h->c->mu);
  *p = (const uint8_t*)h->st->result.trailers.data();
  return h->st->result.trailers.size();
}

void h2_client_stream_destroy(void* stream) {
  H2ClientStreamHandle* h = (H2ClientStreamHandle*)stream;
  H2ClientConn* c = h->c;
  bool still_registered;
  {
    std::lock_guard lk(c->mu);
    still_registered = c->streams.erase(h->sid) > 0;
    c->stream_send_window.erase(h->sid);
    if (still_registered && c->continuation_stream == h->sid) {
      c->orphan_block = std::move(h->st->hdr_block);
    }
  }
  if (still_registered) {
    // abandoned before the peer finished: reset so late frames can't
    // touch the freed state
    Socket* s = Socket::Address(c->sock);
    if (s != nullptr) {
      std::string rst;
      put_frame_header(&rst, 4, F_RST, 0, h->sid);
      rst.append("\x00\x00\x00\x08", 4);  // CANCEL
      write_frames(s, rst);
      s->Dereference();
    }
  }
  // no frame-loop thread can touch st once it is out of c->streams (the
  // erase above and every st access share c->mu)
  butex_destroy(h->st->done);
  butex_destroy(h->st->data_butex);
  delete h->st;
  delete h;
}

void h2_client_destroy(void* conn) {
  H2ClientConn* c = (H2ClientConn*)conn;
  Socket* s = Socket::Address(c->sock);
  if (s != nullptr) {
    s->SetFailed(TRPC_ESTOP);
    s->Dereference();
  }
  // after recycle no edge_fn / on_failed can be running against c
  Socket::WaitRecycled(c->sock);
  butex_destroy(c->window_butex);
  delete c;
}

}  // namespace trpc
