#include "iobuf.h"

#include <errno.h>
#include <stdlib.h>
#include <unistd.h>

#include "heap_profiler.h"

namespace trpc {

// ---------------------------------------------------------------------------
// IOBlock

IOBlock* IOBlock::New(uint32_t payload) {
  char* mem = (char*)malloc(sizeof(IOBlock) + payload);
  IOBlock* b = new (mem) IOBlock();
  b->cap = payload;
  b->data = mem + sizeof(IOBlock);
  // block memory dominates an RPC process's heap: the sampled heap
  // profiler attributes it here (no-op unless /pprof/heap enabled it)
  if (heap_profiler_enabled()) {
    heap_record_alloc(mem, sizeof(IOBlock) + payload);
  }
  return b;
}

IOBlock* IOBlock::NewUser(void* data, uint32_t len, UserBlockDeleter d,
                          void* meta) {
  IOBlock* b = (IOBlock*)malloc(sizeof(IOBlock));
  new (b) IOBlock();
  b->cap = len;
  b->size = len;
  b->data = (char*)data;
  b->deleter = d;
  b->meta = meta;
  return b;
}

void IOBlock::Unref() {
  if (nshared.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    if (deleter != nullptr) {
      deleter(data, meta);
    }
    if (heap_profiler_enabled()) {
      heap_record_free(this);
    }
    this->~IOBlock();
    free(this);
  }
}

// Per-thread active tail block, dropped at thread exit (short-lived
// threads must not strand their tail block).
struct TlsBlockHolder {
  IOBlock* b = nullptr;
  ~TlsBlockHolder() {
    if (b != nullptr) {
      b->Unref();
      b = nullptr;
    }
  }
};
static thread_local TlsBlockHolder g_tls_block;

IOBlock* tls_acquire_block() {
  IOBlock* b = g_tls_block.b;
  if (b == nullptr || b->spare() == 0) {
    if (b != nullptr) {
      b->Unref();
    }
    b = IOBlock::New();
    g_tls_block.b = b;
  }
  return b;
}

void tls_release_block() {
  if (g_tls_block.b != nullptr) {
    g_tls_block.b->Unref();
    g_tls_block.b = nullptr;
  }
}

// ---------------------------------------------------------------------------
// IOBuf

void IOBuf::clear() {
  for (auto& r : refs_) {
    r.block->Unref();
  }
  refs_.clear();
  length_ = 0;
}

void IOBuf::push_ref(const BlockRef& r) {
  if (!refs_.empty()) {
    BlockRef& last = refs_.back();
    if (last.block == r.block && last.offset + last.length == r.offset) {
      last.length += r.length;  // merge contiguous refs of the same block
      length_ += r.length;
      r.block->Unref();  // merged: drop the extra reference
      return;
    }
  }
  refs_.push_back(r);
  length_ += r.length;
}

size_t IOBuf::shrink(size_t compact_max) {
  if (length_ == 0) {
    size_t freed = refs_.capacity() * sizeof(BlockRef);
    if (freed == 0) {
      return 0;
    }
    std::vector<BlockRef>().swap(refs_);
    return freed;
  }
  if (length_ > compact_max) {
    return 0;  // a real payload is parked here; leave it alone
  }
  size_t pinned = 0;
  for (const auto& r : refs_) {
    pinned += r.block->cap;
  }
  // only compact when the remainder pins meaningfully more capacity than
  // it uses — re-homing 100 banked bytes out of an 8KB pooled block is
  // the win; copying a block that is already right-sized is churn
  if (pinned < length_ + sizeof(IOBlock) + 64) {
    return 0;
  }
  IOBlock* b = IOBlock::New((uint32_t)length_);
  copy_to(b->data, length_);
  b->size = (uint32_t)length_;
  size_t len = length_;
  clear();  // unrefs the pinning blocks, zeroes length_
  std::vector<BlockRef>().swap(refs_);  // release banked ref capacity too
  BlockRef r{b, 0, (uint32_t)len};
  push_ref(r);  // b's initial ref transfers to this buf
  return pinned - len;
}

void IOBuf::append(const void* data, size_t n) {
  const char* p = (const char*)data;
  // Large appends get one dedicated right-sized block instead of a chain
  // of 8KB pooled blocks: downstream device DMA (tpu_h2d_from_iobuf) and
  // writev both want contiguity, and a >=16KB payload was never going to
  // amortize the pooled block anyway.
  if (n >= kBigBlockThreshold) {
    IOBlock* big = IOBlock::New((uint32_t)n);
    memcpy(big->data, p, n);
    big->size = (uint32_t)n;
    BlockRef r{big, 0, (uint32_t)n};
    push_ref(r);  // big's initial ref transfers to this buf
    return;
  }
  while (n > 0) {
    IOBlock* b = tls_acquire_block();
    uint32_t copy = b->spare() < n ? b->spare() : (uint32_t)n;
    memcpy(b->data + b->size, p, copy);
    BlockRef r{b, b->size, copy};
    b->Ref();
    b->size += copy;
    push_ref(r);
    p += copy;
    n -= copy;
  }
}

// Read up to `want` bytes into a single dedicated block (continuing the
// current tail block when it has spare room), so a large frame's body
// lands contiguously instead of as ~want/8KB chained pooled blocks.
// Returns bytes read this call, 0 on EAGAIN-with-nothing, -1 on error.
ssize_t IOBuf::append_from_fd_big(int fd, size_t want, bool* eof) {
  if (eof != nullptr) {
    *eof = false;
  }
  size_t total = 0;
  while (want > 0) {
    IOBlock* blk = nullptr;
    bool fresh = false;
    if (!refs_.empty()) {
      BlockRef& last = refs_.back();
      IOBlock* lb = last.block;
      // continue filling the tail block iff this buf owns its end AND it
      // is itself a dedicated big block (continuing a pooled 8KB tail
      // would break the alignment the caller set up)
      if (lb->spare() > 0 && lb->deleter == nullptr &&
          lb->cap > IOBlock::kDefaultPayload &&
          last.offset + last.length == lb->size) {
        blk = lb;
      }
    }
    if (blk == nullptr) {
      blk = IOBlock::New((uint32_t)want);
      fresh = true;
    }
    size_t room = blk->spare() < want ? blk->spare() : want;
    ssize_t n = ::read(fd, blk->data + blk->size, room);
    if (n < 0) {
      if (fresh) {
        blk->Unref();
      }
      if (errno == EINTR) {
        continue;
      }
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        return (ssize_t)total;
      }
      return total > 0 ? (ssize_t)total : -1;
    }
    if (n == 0) {
      if (fresh) {
        blk->Unref();
      }
      if (eof != nullptr) {
        *eof = true;
      }
      return (ssize_t)total;
    }
    BlockRef r{blk, blk->size, (uint32_t)n};
    if (!fresh) {
      blk->Ref();
    }
    blk->size += (uint32_t)n;
    push_ref(r);
    total += (size_t)n;
    want -= (size_t)n;
  }
  return (ssize_t)total;
}

void IOBuf::append(const IOBuf& other) {
  for (const auto& r : other.refs_) {
    r.block->Ref();
    push_ref(r);
  }
}

void IOBuf::append(IOBuf&& other) {
  if (refs_.empty()) {
    refs_ = std::move(other.refs_);
    length_ = other.length_;
  } else {
    for (const auto& r : other.refs_) {
      refs_.push_back(r);  // transfer refs without re-counting
    }
    length_ += other.length_;
  }
  other.refs_.clear();
  other.length_ = 0;
}

void IOBuf::append_user_data(void* data, size_t n, UserBlockDeleter d,
                             void* meta) {
  IOBlock* b = IOBlock::NewUser(data, (uint32_t)n, d, meta);
  BlockRef r{b, 0, (uint32_t)n};
  push_ref(r);  // b starts with refcount 1 owned by this buf
}

void IOBuf::realign_tail(size_t off, size_t block_cap) {
  if (off >= length_) {
    return;
  }
  size_t tail_len = length_ - off;
  if (block_cap < tail_len) {
    block_cap = tail_len;
  }
  IOBlock* big = IOBlock::New((uint32_t)block_cap);
  copy_to(big->data, tail_len, off);
  big->size = (uint32_t)tail_len;
  // drop the refs covering [off, size)
  size_t seen = 0;
  size_t i = 0;
  for (; i < refs_.size(); ++i) {
    if (seen + refs_[i].length > off) {
      break;
    }
    seen += refs_[i].length;
  }
  size_t first_drop = i;
  if (i < refs_.size() && off > seen) {
    refs_[i].length = (uint32_t)(off - seen);  // keep the head of this ref
    first_drop = i + 1;
  }
  for (size_t j = first_drop; j < refs_.size(); ++j) {
    refs_[j].block->Unref();
  }
  refs_.resize(first_drop);
  length_ = off;
  BlockRef r{big, 0, (uint32_t)tail_len};
  push_ref(r);  // big's initial ref transfers to this buf
}

size_t IOBuf::cutn(IOBuf* out, size_t n) {
  size_t cut = 0;
  size_t i = 0;
  while (i < refs_.size() && cut < n) {
    BlockRef& r = refs_[i];
    if (r.length <= n - cut) {
      out->push_ref(r);  // transfer whole ref (ownership moves)
      cut += r.length;
      ++i;
    } else {
      uint32_t take = (uint32_t)(n - cut);
      BlockRef part{r.block, r.offset, take};
      r.block->Ref();
      out->push_ref(part);
      r.offset += take;
      r.length -= take;
      cut += take;
      break;
    }
  }
  refs_.erase(refs_.begin(), refs_.begin() + i);
  length_ -= cut;
  return cut;
}

size_t IOBuf::pop_front(size_t n) {
  size_t popped = 0;
  size_t i = 0;
  while (i < refs_.size() && popped < n) {
    BlockRef& r = refs_[i];
    if (r.length <= n - popped) {
      popped += r.length;
      r.block->Unref();
      ++i;
    } else {
      uint32_t take = (uint32_t)(n - popped);
      r.offset += take;
      r.length -= take;
      popped += take;
      break;
    }
  }
  refs_.erase(refs_.begin(), refs_.begin() + i);
  length_ -= popped;
  return popped;
}

size_t IOBuf::copy_to(void* dst, size_t n, size_t from) const {
  char* out = (char*)dst;
  size_t copied = 0;
  size_t pos = 0;
  for (const auto& r : refs_) {
    if (copied >= n) {
      break;
    }
    if (pos + r.length <= from) {
      pos += r.length;
      continue;
    }
    uint32_t off = (uint32_t)(from > pos ? from - pos : 0);
    uint32_t avail = r.length - off;
    uint32_t copy = (uint32_t)(n - copied < avail ? n - copied : avail);
    memcpy(out + copied, r.block->data + r.offset + off, copy);
    copied += copy;
    pos += r.length;
  }
  return copied;
}

std::string IOBuf::to_string() const {
  std::string s;
  s.resize(length_);
  copy_to(&s[0], length_);
  return s;
}

// Unused fresh block kept per thread so append_from_fd does not pay a
// malloc/free round-trip per short read; released at thread exit like
// the tail block above.
static thread_local TlsBlockHolder g_tls_spare_holder;
#define g_tls_spare g_tls_spare_holder.b

ssize_t IOBuf::append_from_fd(int fd, size_t max, bool* eof) {
  if (eof != nullptr) {
    *eof = false;
  }
  size_t total = 0;
  while (total < max) {
    IOBlock* tail = tls_acquire_block();
    iovec vec[2];
    size_t budget = max - total;
    vec[0].iov_base = tail->data + tail->size;
    vec[0].iov_len = tail->spare() < budget ? tail->spare() : budget;
    budget -= vec[0].iov_len;
    // a second fresh block so big bursts need fewer syscalls
    IOBlock* extra = g_tls_spare != nullptr ? g_tls_spare : IOBlock::New();
    g_tls_spare = nullptr;
    vec[1].iov_base = extra->data;
    vec[1].iov_len = extra->cap < budget ? extra->cap : budget;
    ssize_t n = readv(fd, vec, 2);
    if (n < 0) {
      g_tls_spare = extra;
      if (errno == EINTR) {
        continue;
      }
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        return (ssize_t)total;
      }
      return total > 0 ? (ssize_t)total : -1;
    }
    if (n == 0) {
      g_tls_spare = extra;
      if (eof != nullptr) {
        *eof = true;
      }
      return (ssize_t)total;
    }
    size_t left = (size_t)n;
    uint32_t into_tail =
        left < vec[0].iov_len ? (uint32_t)left : (uint32_t)vec[0].iov_len;
    if (into_tail > 0) {
      BlockRef r{tail, tail->size, into_tail};
      tail->Ref();
      tail->size += into_tail;
      push_ref(r);
      left -= into_tail;
    }
    if (left > 0) {
      extra->size = (uint32_t)left;
      BlockRef r{extra, 0, (uint32_t)left};
      push_ref(r);  // extra's initial ref transfers to this buf
    } else {
      g_tls_spare = extra;
    }
    total += (size_t)n;
    if ((size_t)n < vec[0].iov_len + vec[1].iov_len) {
      return (ssize_t)total;  // short read: kernel buffer drained
    }
  }
  return (ssize_t)total;
}

ssize_t IOBuf::cut_into_fd(int fd, size_t max) {
  if (refs_.empty()) {
    return 0;
  }
  iovec vec[64];
  int nvec = 0;
  size_t queued = 0;
  for (const auto& r : refs_) {
    if (nvec == 64 || queued >= max) {
      break;
    }
    size_t len = r.length;
    if (queued + len > max) {
      len = max - queued;
    }
    vec[nvec].iov_base = r.block->data + r.offset;
    vec[nvec].iov_len = len;
    queued += len;
    ++nvec;
  }
  ssize_t n = writev(fd, vec, nvec);
  if (n < 0) {
    return -1;
  }
  pop_front((size_t)n);
  return n;
}

}  // namespace trpc
