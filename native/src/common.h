// common.h — shared basics for the brpc_tpu native core.
// The native core is the TPU-host equivalent of the reference's
// butil+bthread+brpc hot paths (SURVEY.md §2.1/§2.3/§2.4), written fresh
// for this framework: C++17, Linux/x86_64, no external deps.
#pragma once

#include <time.h>

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#define TRPC_LIKELY(x) __builtin_expect(!!(x), 1)
#define TRPC_UNLIKELY(x) __builtin_expect(!!(x), 0)

#define TRPC_DISALLOW_COPY(T) \
  T(const T&) = delete;       \
  T& operator=(const T&) = delete

namespace trpc {

inline int64_t monotonic_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

inline int64_t monotonic_us() { return monotonic_ns() / 1000; }

// Error codes shared with the Python layer (see brpc_tpu/rpc/errors.py).
enum ErrorCode {
  TRPC_OK = 0,
  TRPC_ERPCTIMEDOUT = 1008,   // RPC deadline exceeded (≙ brpc ERPCTIMEDOUT)
  TRPC_EFAILEDSOCKET = 1009,  // the connection was broken
  TRPC_EBACKUPREQUEST = 1010, // backup-request timer fired (internal)
  TRPC_EREQUEST = 1011,       // bad request bytes
  TRPC_ERESPONSE = 1013,      // bad response bytes (client-side decode)
  TRPC_ENOSERVICE = 1001,     // no such service
  TRPC_ENOMETHOD = 1002,      // no such method
  TRPC_ESTOP = 1012,          // server is stopping
  TRPC_EINTERNAL = 2001,      // server-side user exception
  TRPC_EOVERCROWDED = 2004,   // too many buffered writes (≙ brpc EOVERCROWDED)
  TRPC_ELIMIT = 2005,         // concurrency limiter rejected (≙ brpc ELIMIT)
  TRPC_ESTREAMUNACCEPTED = 2006,  // handshake RPC ok but no StreamAccept
  TRPC_ECANCELED = 2007,      // caller canceled the call (≙ brpc ECANCELED)
  TRPC_EAUTH = 2008,          // credential verify failed (≙ brpc ERPCAUTH)
  TRPC_EDEADLINE = 2009,      // propagated deadline budget already spent
                              // before dispatch (ISSUE 19)
};

// xorshift per-thread fast random (≙ butil fast_rand).
inline uint64_t fast_rand() {
  static thread_local uint64_t s = 0x9e3779b97f4a7c15ULL ^
      (uint64_t)(uintptr_t)&s ^ (uint64_t)monotonic_ns();
  s ^= s << 13;
  s ^= s >> 7;
  s ^= s << 17;
  return s;
}

}  // namespace trpc
