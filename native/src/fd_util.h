// fd_util.h — the fd option helpers the reference keeps in butil/fd_utility
// (≙ butil/fd_utility.h: make_non_blocking / make_close_on_exec /
// make_no_delay), consolidated from the former inline call sites so
// every transport configures sockets through one seam.
#pragma once

#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>

namespace trpc {

inline int fd_set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  return fl < 0 ? fl : fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

inline int fd_set_cloexec(int fd) {
  int fl = fcntl(fd, F_GETFD, 0);
  return fl < 0 ? fl : fcntl(fd, F_SETFD, fl | FD_CLOEXEC);
}

inline int fd_set_nodelay(int fd) {
  int one = 1;
  return setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

inline int fd_set_reuseaddr(int fd) {
  int one = 1;
  return setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
}

}  // namespace trpc
