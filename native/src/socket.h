// socket.h — the connection object + event dispatcher (capability of the
// reference brpc/socket.h:269 and event_dispatcher_epoll.cpp):
//   * 64-bit SocketId with versioned refcount for ABA-safe addressing
//     (≙ _versioned_ref, socket.h:808: Address/SetFailed/Dereference —
//     "like shared_ptr/weak_ptr with forced-zero", docs/en/io.md:39)
//   * wait-free write: producers exchange onto an atomic stack; the first
//     writer writes inline once and hands the rest to a KeepWrite fiber
//     (≙ Socket::Write socket.cpp:1850, StartWrite :1924, KeepWrite :2066)
//   * edge-triggered epoll dispatcher; EPOLLIN spawns a processing fiber
//     with an atomic event-count dedup (≙ StartInputEvent socket.cpp:2553)
#pragma once

#include <atomic>
#include <functional>

#include "fiber.h"
#include "iobuf.h"

namespace trpc {

class Socket;
struct TimerTask;  // timer_thread.h — pending re-kick/idle timer handle

// (version << 32) | pool slot
typedef uint64_t SocketId;
constexpr SocketId INVALID_SOCKET_ID = (uint64_t)-1;

// Edge-trigger callback: consume readiness (read+parse, or accept loop).
typedef void (*EdgeFn)(Socket*);

struct WriteRequest {
  IOBuf data;
  // atomic: producers publish the stack linkage concurrently with the
  // KeepWrite fiber spinning on it in GrabNewer
  std::atomic<WriteRequest*> next{nullptr};
  // notify_butex: optional completion hook (streaming flow control)
  Butex* notify = nullptr;
};

struct SocketOptions {
  int fd = -1;
  EdgeFn edge_fn = nullptr;
  void* user = nullptr;       // owner: Server* / Channel* / Acceptor ctx
  void (*on_failed)(Socket*) = nullptr;  // called once from SetFailed
  // Invoked by ReadToBuf between bounded drain chunks so the protocol
  // layer can arm frame_bytes_hint/frame_attach_hint for a large frame
  // IN PROGRESS — without this, a frame that is already fully buffered
  // in the kernel would drain into pooled 8KB blocks in one gulp and a
  // big attachment would lose its single-block (zero-copy DMA) landing.
  void (*frame_hint_fn)(Socket*) = nullptr;
  // corked: Write() never writes inline — it enqueues and lets the flush
  // fiber (scheduled after the currently-ready fibers) drain the queue in
  // one writev.  Concurrent producers coalesce into one syscall; costs
  // one fiber hop of latency.  Used by client channels where many caller
  // fibers share a connection.
  bool corked = false;
  // Owning shard (shard.h): -1 = assign from context (the creating
  // worker's shard, round-robin off-worker).  The socket's epoll/ring
  // registration and processing fibers all stay on this shard.
  int shard = -1;
  // Enable the idle-kick heartbeat (TRPC_IDLE_KICK_MS): a periodic
  // timer-plane beat, armed from the socket's own processing fiber, that
  // shrinks banked per-connection memory when no ingress arrived during
  // the interval.  Servers set this on accepted connections; listeners
  // keep kick_timer for accept backoff/pacing instead.
  bool idle_kick = false;
};

class Socket {
 public:
  int fd = -1;
  uint32_t slot = 0;
  // owning shard (shard.h): set once at Create, read-only after — the
  // whole parse→dispatch→respond lifecycle runs on this shard's reactor
  int shard = 0;
  std::atomic<uint64_t> versioned_ref{0};  // [version:32][nref:32]
  std::atomic<WriteRequest*> write_head{nullptr};
  std::atomic<uint32_t> nevent{0};
  std::atomic<bool> failed{false};
  int error_code = 0;
  IOBuf read_buf;
  EdgeFn edge_fn = nullptr;
  void* user = nullptr;
  void (*on_failed)(Socket*) = nullptr;
  void (*frame_hint_fn)(Socket*) = nullptr;  // see SocketOptions
  // Lazily materialized by the FIRST writer that hits EAGAIN (per-
  // connection memory diet, ISSUE 16): an idle or read-only connection
  // never allocates it.  Wakers (HandleEpollOut/SetFailed) that load
  // nullptr have nobody to wake — a waiter publishes the butex before
  // registering for EPOLLOUT, and the waits carry timeouts that re-check
  // `failed`, so the publish/wake race degrades to one bounded timeout,
  // never a hang.  Freed (and re-nulled) at TryRecycle.
  std::atomic<Butex*> epollout_butex{nullptr};
  // running statistics
  std::atomic<uint64_t> bytes_in{0};
  std::atomic<uint64_t> bytes_out{0};
  // server auth state: set once the first request's credential verifies
  // (≙ brpc verifying auth on a connection's first message); stream frames
  // are only honored on authed connections
  std::atomic<bool> authed{false};
  // set at h2 preface: gates the (mutexed) H2Conn registry lookup so
  // TRPC/HTTP/redis connections never touch the global map on reads
  std::atomic<bool> is_h2{false};
  // peer asked for the device plane (meta tag 14): every response on this
  // connection advertises the server's plane caps back
  std::atomic<bool> advertise_device_caps{false};
  // peer's tpu_plane_uid from the tag-15 handshake (0 = unknown/none);
  // == our own tpu_plane_uid() means both ends share one PJRT client,
  // enabling handle-passing device frames on streams over this socket
  std::atomic<uint64_t> peer_plane_uid{0};
  // a SEND_ZC notification on THIS connection reported the kernel
  // copied anyway (loopback / non-SG route): the egress rail falls back
  // to writev for this socket only — whether zerocopy works is a
  // property of the route, not the process
  std::atomic<bool> sendzc_copied{false};
  // opaque per-connection parser/pipelining state owned by the protocol
  // io_uring staging (uring.h RingFeed): when non-null, ReadToBuf drains
  // it instead of calling recv(2); freed at recycle time
  void* ring_feed = nullptr;
  // layer (rpc.cc: ConnState); freed via parse_state_free at recycle time
  // (after the last Address ref is gone — respond paths may touch it)
  void* parse_state = nullptr;
  void (*parse_state_free)(void*) = nullptr;
  // Pending timer-plane kick (accept backoff/pacing re-kick on listeners,
  // idle-kick heartbeat on connections).  Whoever exchange()s the pointer
  // out owns the single timer_cancel_and_free: the processing fiber
  // consumes it at the top of its drain, SetFailed sweeps it at teardown.
  std::atomic<TimerTask*> kick_timer{nullptr};
  // Accept-plane pending-handshake charge (rpc.cc listener cap): points
  // at the accepting listener until the first ingress bytes (or
  // teardown) release it — whoever exchange()s the pointer out does the
  // one decrement (mirrors the kick_timer ownership discipline).
  std::atomic<void*> handshake_charge{nullptr};
  // Idle-kick heartbeat state (SocketOptions.idle_kick).  idle_check is
  // set by the fired timer callback (tick thread) and consumed by the
  // processing fiber; the rest is touched ONLY by the processing fiber
  // (the nevent protocol guarantees a single one per socket).
  std::atomic<bool> idle_check{false};
  bool idle_kick_enabled = false;
  bool idle_armed = false;
  uint64_t idle_seen_bytes_in = 0;
  bool corked = false;  // see SocketOptions.corked
  // Parse-batch response corking (≙ the reference batching all responses
  // of one InputMessenger cut into a single Socket::Write): while
  // cork_depth > 0 the first writer to take head ownership parks the
  // queue instead of writing (doorbell held) — Uncork() flushes the
  // accumulated chain as ONE writev/SEND_ZC batch.  cork_anchor is the
  // parked owner request, published via the cork_held release-store and
  // claimed by exactly one actor (Uncork, or a producer that observes
  // the cork lifted before Uncork saw its hold).
  std::atomic<int32_t> cork_depth{0};
  std::atomic<bool> cork_held{false};
  WriteRequest* cork_anchor = nullptr;
  // TLS engine (tls.h TlsState*), set by the server sniff (first record
  // byte 0x16) or the client dial.  When set, ReadToBuf decrypts into
  // read_buf and Write encrypts before the wait-free queue — every
  // protocol on the shared port transparently speaks TLS.  Owned; freed
  // at recycle.  tls_checked: the sniff ran (plaintext conn stays plain).
  void* tls = nullptr;
  bool tls_checked = false;
  // Protocol-layer hints for the partially-read frame at the head of
  // read_buf (large frames only).  frame_bytes_hint = the frame's total
  // wire size; frame_attach_hint = offset where its attachment begins.
  // ReadToBuf reads bytes before the attachment into pooled blocks, then
  // the attachment into ONE dedicated block starting exactly at its
  // offset — so the cut attachment is a single BlockRef, a zero-copy
  // device-DMA source.  Only touched by the socket's processing fiber.
  size_t frame_bytes_hint = 0;
  size_t frame_attach_hint = 0;
  // Deadline-budget ingress anchor (ISSUE 19, rpc.cc tag-18 plane): the
  // coarse drain stamp when read_buf last went empty→non-empty.  Frames
  // parsed in a LATER drain have waited (drain_ns - read_arm_ns) on this
  // host; the parse fiber sheds the ones whose propagated budget that
  // wait already spent.  0 = buffer empty.  Only touched by the socket's
  // processing fiber (the nevent protocol guarantees a single one).
  int64_t read_arm_ns = 0;

  static int Create(const SocketOptions& opts, SocketId* id_out);
  // +1 ref; nullptr if the id is stale.
  static Socket* Address(SocketId id);
  void Dereference();
  SocketId id() const;
  uint32_t version() const {
    return (uint32_t)(versioned_ref.load(std::memory_order_acquire) >> 32);
  }

  // Mark broken: wakes writers, runs on_failed once, drops the owner ref.
  void SetFailed(int err);

  // True once the id's generation has fully recycled: every ref is gone,
  // the fd is closed and parse_state freed.  Safe on stale ids (slot
  // memory is stable in the ResourcePool slab).
  static bool IsRecycled(SocketId id);
  // Event-driven wait for IsRecycled (≙ the reference joining a socket's
  // refs out during teardown) — no fixed-interval sleep loop; wakes on a
  // global recycle-generation butex bumped by every TryRecycle.
  static void WaitRecycled(SocketId id);

  // Wait-free write; takes ownership of data.  Returns 0 or -errno.
  // With TLS active, data is encrypted first (WriteRaw skips that — the
  // TLS pump uses it to emit already-encrypted handshake bytes).
  int Write(IOBuf&& data, Butex* notify = nullptr);
  int WriteRaw(IOBuf&& data, Butex* notify = nullptr);

  // Hold/release the response doorbell around one parse drain.  Writes
  // issued while corked accumulate on the wait-free queue; the matching
  // Uncork flushes them in one batch.  Cork/Uncork pairs nest; every
  // exit path of a drain must Uncork (use a scope guard).
  void Cork();
  void Uncork();

  // Called by the dispatcher on EPOLLIN/EPOLLOUT.
  static void StartInputEvent(SocketId id);
  static void HandleEpollOut(SocketId id);

  // Read until EAGAIN into read_buf.  Returns bytes read; sets *eof.
  ssize_t ReadToBuf(bool* eof);
  // re-queue an input event for THIS socket: used when a hard read error
  // was swallowed behind banked bytes (the ET edge that announced it is
  // consumed), so the next pass observes the sticky error and fails fast
  void RearmInputEvent();

 private:
  friend struct KeepWriteArg;
  // CAS-install the lazy epollout butex (EAGAIN writers only).
  Butex* EnsureEpolloutButex();
  // Arm/re-arm the idle-kick heartbeat; processing fiber only, so the
  // wheel arm is always shard-confined (zero foreign-wheel routing).
  void ArmIdleKick();
  // Consume a fired idle beat: shrink banked memory if no ingress
  // arrived since the last beat, then re-arm.  Processing fiber only.
  void MaybeIdleShrink();
  static void ProcessEventFiber(void* arg);
  static void KeepWriteFiber(void* arg);
  void RunKeepWrite(WriteRequest* req);  // drain loop (fiber or inline)
  WriteRequest* GrabNewer(WriteRequest* anchor);  // see .cc
  int OwnerFlush(WriteRequest* req);  // write-as-owner tail of WriteRaw
  void TryRecycle(uint32_t odd_ver);
};

// Global epoll dispatcher threads (flag: event_dispatcher_num): N epoll
// instances, one thread each; sockets map to an instance by fd so all ops
// for one fd hit the same epoll (≙ event_dispatcher_epoll.cpp's
// event_dispatcher_num).
extern std::atomic<int> g_event_dispatcher_num;

class EventDispatcher {
 public:
  static constexpr int kMaxEpollThreads = 16;

  static EventDispatcher& Instance();
  void Start(int nthreads);
  // `shard` >= 0 pins the fd to that shard's epoll instance when the
  // runtime is sharded (shard.h); -1 (and shards=1) keeps the original
  // fd-hash mapping.  Add/Remove/Register must pass the same shard.
  int AddConsumer(SocketId id, int fd, int shard = -1);
  int RemoveConsumer(int fd, int shard = -1);
  // `ring_fed` = the socket's receives are fed by io_uring (it never went
  // through AddConsumer): Register ADDs an EPOLLOUT-only watch and
  // Unregister DELs it, instead of MODing a registration that isn't there.
  int RegisterEpollOut(SocketId id, int fd, int shard = -1,
                       bool ring_fed = false);
  int UnregisterEpollOut(SocketId id, int fd, int shard = -1,
                         bool ring_fed = false);

 private:
  EventDispatcher() = default;
  void Loop(int epfd);
  int EpfdFor(int fd, int shard) const;
  int epfds_[kMaxEpollThreads] = {};
  int nepfd_ = 0;
  bool sharded_ = false;  // shard-pinned mapping active (shards > 1)
  std::atomic<bool> started_{false};
  std::atomic<bool> ready_{false};  // epfds_/nepfd_ published
};

// Diagnostic text dump of every live socket in the process (clients +
// servers; ≙ builtin sockets_service.cpp).  Returns bytes written.
size_t socket_dump_all(char* buf, size_t cap);

// Timer-plane trampoline: StartInputEvent on the SocketId packed into
// `arg`.  Safe on stale ids (Address catches the recycled generation).
void socket_timer_kick(void* arg);

// Idle-kick heartbeat interval in ms (TRPC_IDLE_KICK_MS, 0 = off,
// flag-cached; reloadable through trpc_set_idle_kick_ms).
int idle_kick_ms();
void set_idle_kick_ms(int ms);

}  // namespace trpc
