// dump.cc — native flight recorder (≙ the reference rpc_dump.cpp:68-150:
// RpcDumpContext sampling throttled by bvar::Collector, serialized into
// rotated recordio segments).  This is the fast-path half our PR-3 inline
// dispatch made necessary: echo / HbmEcho / redis-cache / stream frames
// never reach Python, so brpc_tpu/rpc/dump.py cannot see them.  Capture
// runs on the parse fiber through the PR-9 span-ring discipline
// (metrics.cc rpcz_capture): per-shard seqlock'd rings, claim-before-
// write, counted drops.  The rings differ from SpanRing in ONE way: a
// DumpRecord holds IOBuf chains, which are not memcpy-safe under the
// plain read-retry seqlock — so the DRAIN side also claims slots
// (even -> odd CAS) before touching a record, and releases them back to
// even.  Writers and the drain therefore never co-touch a record; a
// failed claim on either side is a counted drop (writer) or a skip
// (drain), never a torn IOBuf.
#include "dump.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <mutex>

#include "common.h"
#include "metrics.h"
#include "shard.h"

namespace trpc {

namespace {

constexpr int kDumpRingSlots = 64;  // per shard; drained at read time

// One captured wire-form frame.  Payload/attachment are IOBuf block-ref
// shares of the inbound bytes — capture copies pointers, never bytes.
struct DumpRecord {
  char method[64] = {};
  uint32_t method_len = 0;
  uint64_t trace_id = 0;
  uint64_t span_id = 0;
  uint64_t correlation_id = 0;
  uint64_t stream_id = 0;
  int64_t wall_us = 0;  // CLOCK_REALTIME at capture (Python-schema time)
  uint8_t compress_type = 0;
  uint8_t payload_codec = 0;
  uint8_t attach_codec = 0;
  uint8_t stream_frame_type = 0;
  // 1 = holds an unconsumed capture.  A capture whose claim FAILED still
  // advanced head, so the drain visits that index and finds whatever the
  // slot last held — without this flag it would re-emit an
  // already-consumed record (stale meta, empty payload).
  uint8_t live = 0;
  int32_t shard = 0;
  // ring index that produced this record (written under the slot
  // claim): the capture-side strand check must only self-reclaim its
  // OWN record, never one a lapping capture put in the slot since
  uint64_t owner_idx = 0;
  IOBuf payload;
  IOBuf attachment;
};

struct DumpSlot {
  // seqlock: odd = a writer OR the drain is inside (both sides claim)
  std::atomic<uint32_t> seq{0};
  DumpRecord rec;
};

struct DumpRing {
  std::atomic<uint64_t> head{0};  // next slot index to claim (mod slots)
  // Consumed watermark.  Advanced only under drain_mu, but READ by
  // dump_capture's post-publish strand check: a capture that allocated
  // its index and then lost the race to claim its slot before a drain
  // walked that index would otherwise publish a record no future walk
  // revisits (counted captured, never drained nor dropped).  The drain
  // publishes tail=i+1 BEFORE each claim attempt so a capture seeing
  // tail > idx after publishing knows to self-reclaim.
  std::atomic<uint64_t> tail{0};
  std::mutex drain_mu;
  DumpSlot slots[kDumpRingSlots];
};

DumpRing g_dump_rings[kMaxShards];

// -1 = resolve TRPC_DUMP on first use (flag-cached; the Python rpc_dump
// flag validator overrides through trpc_set_dump)
std::atomic<int> g_dump{-1};
// -1 = resolve TRPC_DUMP_BUDGET on first use (flag-cached; the Python
// rpc_dump_max_samples_per_second validator overrides)
std::atomic<int64_t> g_dump_budget{-1};
// token bucket refilled per ~second (monotonic_ns >> 30 ≈ 1.07s epochs;
// the same collector-style pacing as rpcz_try_sample — ≙ the ONE
// bvar::Collector throttling both rpcz spans and rpc_dump samples)
std::atomic<int64_t> g_dump_epoch{-1};
std::atomic<int64_t> g_dump_left{0};

int dump_resolve() {
  // flag-cached: the ONE env read; the resolved value lives in g_dump
  const char* e = getenv("TRPC_DUMP");
  int on = (e != nullptr && e[0] != '\0' && e[0] != '0') ? 1 : 0;
  int expected = -1;
  g_dump.compare_exchange_strong(expected, on, std::memory_order_acq_rel);
  return g_dump.load(std::memory_order_acquire);
}

int64_t dump_budget_resolve() {
  // flag-cached: the ONE env read; the resolved value lives in
  // g_dump_budget (default matches rpc_dump_max_samples_per_second)
  const char* e = getenv("TRPC_DUMP_BUDGET");
  int64_t per_second = 1024;
  if (e != nullptr && e[0] != '\0') {
    long v = strtol(e, nullptr, 10);
    per_second = v > 0 ? (int64_t)v : 0;
  }
  int64_t expected = -1;
  g_dump_budget.compare_exchange_strong(expected, per_second,
                                        std::memory_order_acq_rel);
  return g_dump_budget.load(std::memory_order_acquire);
}

inline int dump_clamp_shard(int shard) {
  return shard >= 0 && shard < kMaxShards ? shard : 0;
}

inline int64_t wall_us_now() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (int64_t)ts.tv_sec * 1000000 + ts.tv_nsec / 1000;
}

}  // namespace

void dump_set_enabled(int on) {
  g_dump.store(on != 0 ? 1 : 0, std::memory_order_release);
}

bool dump_native_enabled() {
  int v = g_dump.load(std::memory_order_acquire);
  if (TRPC_UNLIKELY(v < 0)) {
    v = dump_resolve();
  }
  return v != 0;
}

void dump_set_budget(int64_t per_second) {
  g_dump_budget.store(per_second > 0 ? per_second : 0,
                      std::memory_order_release);
}

bool dump_try_sample() {
  if (!dump_native_enabled()) {
    return false;
  }
  int64_t budget = g_dump_budget.load(std::memory_order_acquire);
  if (TRPC_UNLIKELY(budget < 0)) {
    budget = dump_budget_resolve();
  }
  int64_t epoch = monotonic_ns() >> 30;
  int64_t seen = g_dump_epoch.load(std::memory_order_acquire);
  if (seen != epoch &&
      g_dump_epoch.compare_exchange_strong(seen, epoch,
                                           std::memory_order_acq_rel)) {
    // refill winner: losers draw from whatever remains of the old epoch
    // for one race window — collector semantics, not an exact meter
    g_dump_left.store(budget, std::memory_order_release);
  }
  return g_dump_left.fetch_sub(1, std::memory_order_acq_rel) > 0;
}

void dump_capture(const DumpMeta& m, const IOBuf& payload,
                  const IOBuf& attachment) {
  int shard = dump_clamp_shard(m.shard);
  DumpRing& ring = g_dump_rings[shard];
  NativeMetrics& nm = native_metrics();
  uint64_t idx = ring.head.fetch_add(1, std::memory_order_acq_rel);
  DumpSlot& slot = ring.slots[idx % kDumpRingSlots];
  // CLAIM the slot (even -> odd CAS) before writing: captures come from
  // arbitrary parse fibers, the drain claims slots too, and the ring can
  // lap a stalled tenant.  A failed claim means someone is inside the
  // slot: this sample is DROPPED (counted), never co-written — an IOBuf
  // co-write would corrupt block refcounts, not just tear bytes.
  uint32_t seq = slot.seq.load(std::memory_order_acquire);
  if ((seq & 1u) != 0 ||
      !slot.seq.compare_exchange_strong(seq, seq + 1,
                                        std::memory_order_acq_rel)) {
    nm.dump_dropped.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  DumpRecord& r = slot.rec;
  r.method_len = m.method_len < sizeof(r.method) ? (uint32_t)m.method_len
                                                 : (uint32_t)sizeof(r.method);
  for (uint32_t i = 0; i < r.method_len; ++i) {
    // sanitized at capture so the drain can embed it in a JSON head
    // without escaping: quotes/backslashes/control chars -> '_'
    char c = m.method[i];
    r.method[i] = (c == '"' || c == '\\' || (unsigned char)c < 0x20)
                      ? '_'
                      : c;
  }
  r.trace_id = m.trace_id;
  r.span_id = m.span_id;
  r.correlation_id = m.correlation_id;
  r.stream_id = m.stream_id;
  r.wall_us = wall_us_now();
  r.compress_type = m.compress_type;
  r.payload_codec = m.payload_codec;
  r.attach_codec = m.attach_codec;
  r.stream_frame_type = m.stream_frame_type;
  r.live = 1;
  r.shard = shard;
  r.owner_idx = idx;
  // block-ref shares: the wire bytes are never copied or flattened here
  r.payload = payload;
  r.attachment = attachment;
  slot.seq.fetch_add(1, std::memory_order_release);  // even: stable
  nm.dump_captured.fetch_add(1, std::memory_order_relaxed);
  if (TRPC_UNLIKELY(ring.tail.load(std::memory_order_acquire) > idx)) {
    // A drain walked index idx between our head allocation and the
    // claim above (the claim's acquire orders this load after it, and
    // the drain stores tail=i+1 before every claim attempt, so the
    // strand is always observed): no future walk revisits idx, the
    // record would sit live-in-slot with the books short by one.
    // Reclaim our own slot and count the sample dropped.  Racing
    // lappers/drains can inflate dropped by one here — safe, the
    // reconciliation contract is one-sided (captured <= drained +
    // dropped).
    uint32_t s2 = slot.seq.load(std::memory_order_acquire);
    if ((s2 & 1u) == 0 &&
        slot.seq.compare_exchange_strong(s2, s2 + 1,
                                         std::memory_order_acq_rel)) {
      if (r.live && r.owner_idx == idx) {
        r.payload.clear();
        r.attachment.clear();
        r.live = 0;
      }
      slot.seq.fetch_add(1, std::memory_order_release);
    }
    nm.dump_dropped.fetch_add(1, std::memory_order_relaxed);
  }
}

size_t dump_drain(char* buf, size_t cap) {
  size_t off = 0;
  NativeMetrics& nm = native_metrics();
  for (int k = 0; k < kMaxShards; ++k) {
    DumpRing& ring = g_dump_rings[k];
    std::lock_guard<std::mutex> lk(ring.drain_mu);
    uint64_t head = ring.head.load(std::memory_order_acquire);
    uint64_t from = ring.tail.load(std::memory_order_relaxed);
    if (head - from > (uint64_t)kDumpRingSlots) {
      // ring lapped the drain: the overwritten records are gone (their
      // IOBuf refs were released by the overwriting capture's assign)
      uint64_t lost = head - from - kDumpRingSlots;
      nm.dump_dropped.fetch_add(lost, std::memory_order_relaxed);
      from = head - kDumpRingSlots;
    }
    for (uint64_t i = from; i < head; ++i) {
      DumpSlot& slot = ring.slots[i % kDumpRingSlots];
      // tail advances BEFORE the claim attempt: a capture that claims
      // this slot after we pass it must observe tail > idx when it
      // publishes, so it self-reclaims instead of stranding the record
      // (see DumpRing::tail).
      ring.tail.store(i + 1, std::memory_order_release);
      // CLAIM before reading — a DumpRecord holds IOBufs, so the
      // read-retry trick SpanRing's drain uses would race refcounts.
      uint32_t s0 = slot.seq.load(std::memory_order_acquire);
      if ((s0 & 1u) != 0 ||
          !slot.seq.compare_exchange_strong(s0, s0 + 1,
                                            std::memory_order_acq_rel)) {
        // a writer is mid-slot (the ring lapped us during the walk):
        // skip it — counted as dropped, never emitted half-written
        nm.dump_dropped.fetch_add(1, std::memory_order_relaxed);
        continue;
      }
      DumpRecord& r = slot.rec;
      if (r.live == 0) {
        // this index's capture lost its claim (already counted dropped);
        // the slot holds a consumed record — nothing to emit
        slot.seq.fetch_add(1, std::memory_order_release);
        continue;
      }
      // v2 sample head, shared schema with brpc_tpu/rpc/dump.py —
      // method was sanitized at capture, every other field is numeric,
      // so plain snprintf emits valid JSON
      char head_buf[512];
      int head_len = snprintf(
          head_buf, sizeof(head_buf),
          "{\"method\": \"%.*s\", \"compress_type\": %u, "
          "\"timestamp\": %lld.%06lld, \"payload_len\": %zu, "
          "\"attachment_len\": %zu, \"trace_id\": %llu, "
          "\"span_id\": %llu, \"payload_codec\": %u, "
          "\"attach_codec\": %u, \"stream_id\": %llu, "
          "\"stream_frame_type\": %u}",
          (int)r.method_len, r.method, (unsigned)r.compress_type,
          (long long)(r.wall_us / 1000000),
          (long long)(r.wall_us % 1000000), r.payload.size(),
          r.attachment.size(), (unsigned long long)r.trace_id,
          (unsigned long long)r.span_id, (unsigned)r.payload_codec,
          (unsigned)r.attach_codec, (unsigned long long)r.stream_id,
          (unsigned)r.stream_frame_type);
      char pfx_buf[16];
      int pfx_len = snprintf(pfx_buf, sizeof(pfx_buf), "%d\n", head_len);
      size_t blob_len = 1 + (size_t)pfx_len + (size_t)head_len +
                        r.payload.size() + r.attachment.size();
      size_t total = 4 + blob_len;
      if (off + total > cap) {
        if (off == 0 && total > cap) {
          // one record larger than the whole drain buffer can never be
          // emitted: drop it so the drain does not stall forever
          r.payload.clear();
          r.attachment.clear();
          r.live = 0;
          nm.dump_dropped.fetch_add(1, std::memory_order_relaxed);
          slot.seq.fetch_add(1, std::memory_order_release);
          continue;
        }
        // out of buffer: release the claim with the record INTACT
        // (seq advances to even, content untouched) so it surfaces on
        // the next drain — rewind tail so the next walk revisits it (a
        // capture that glimpsed tail=i+1 in the window self-drops its
        // own record: rare, counted, collector semantics)
        slot.seq.fetch_add(1, std::memory_order_release);
        ring.tail.store(i, std::memory_order_release);
        return off;
      }
      // u32 LE length prefix, then the v2 blob
      buf[off] = (char)(blob_len & 0xff);
      buf[off + 1] = (char)((blob_len >> 8) & 0xff);
      buf[off + 2] = (char)((blob_len >> 16) & 0xff);
      buf[off + 3] = (char)((blob_len >> 24) & 0xff);
      off += 4;
      buf[off++] = (char)0x02;  // schema-version byte
      memcpy(buf + off, pfx_buf, (size_t)pfx_len);
      off += (size_t)pfx_len;
      memcpy(buf + off, head_buf, (size_t)head_len);
      off += (size_t)head_len;
      off += r.payload.copy_to(buf + off, r.payload.size());
      off += r.attachment.copy_to(buf + off, r.attachment.size());
      // consume: drop the block refs before releasing the slot
      r.payload.clear();
      r.attachment.clear();
      r.live = 0;
      slot.seq.fetch_add(1, std::memory_order_release);
      nm.dump_drained.fetch_add(1, std::memory_order_relaxed);
    }
    ring.tail.store(head, std::memory_order_release);
  }
  return off;
}

uint64_t dump_captured_total() {
  return native_metrics().dump_captured.load(std::memory_order_relaxed);
}

uint64_t dump_dropped_total() {
  return native_metrics().dump_dropped.load(std::memory_order_relaxed);
}

uint64_t dump_drained_total() {
  return native_metrics().dump_drained.load(std::memory_order_relaxed);
}

}  // namespace trpc
