"""Operator tools (≙ the reference tools/ suite, SURVEY.md §2.7):

    python -m brpc_tpu.tools.rpc_press     — load generator (≙ rpc_press)
    python -m brpc_tpu.tools.rpc_replay    — replay rpc_dump samples
    python -m brpc_tpu.tools.rpc_view      — proxy a remote builtin portal
    python -m brpc_tpu.tools.parallel_http — mass concurrent HTTP fetch

Each module also exposes a callable API used by the tests.
"""
