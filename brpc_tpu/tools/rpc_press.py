"""rpc_press — load generator (≙ reference tools/rpc_press: target QPS,
concurrency, latency bvars printed by an info thread,
rpc_press_impl.{h,cpp}).

    python -m brpc_tpu.tools.rpc_press -s 127.0.0.1:8000 -m Echo.echo \
        -d 'hello' -q 10000 -c 8 -t 10

Overload-control cannon (ISSUE 11): ``--ramp lo:hi:steps`` sweeps the
offered concurrency across ``steps`` levels and reports, per step,
admitted-vs-shed counts and ADMITTED-ONLY latency percentiles in the
``--json`` line — the acceptance harness for the native overload plane
(shed = server answered TRPC_ELIMIT without executing).  The ramp is
open-loop per level: every caller thread keeps its next request queued
regardless of how the server answered the last one, so offered load does
not back off when the server sheds.

Token cannon (ISSUE 14): ``--stream`` opens ``-c`` open-loop concurrent
streams against a serving method (one message = one token) and reports
per-stream TTFT / inter-token-gap p50/p99/p999 — admitted-only, with
ELIMIT handshakes counted as shed and mid-stream RSTs (eviction/
preemption) as resets.  The LLM serving bench's client side.

Connection cannon (ISSUE 16): ``--connections N --hot M`` holds N idle
connections against the server while M hot callers keep echoing, through
three legs — ramp (open the N), churn (steady close/reopen), reconnect
storm (drop and re-dial every idle connection at once).  The ``--json``
line reports hot-subset p50/p99/p999 PER LEG beside the open/failed/
shed/reconnect counts: the acceptance harness for the million-connection
ingress work (per-shard timer wheel + memory diet + accept pacing) —
idle-connection bookkeeping must not bend the hot path's tail.
"""

from __future__ import annotations

import argparse
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class PressResult:
    calls: int = 0
    errors: int = 0
    shed: int = 0   # server-side ELIMIT rejects (never executed)
    wall_s: float = 0.0
    qps: float = 0.0
    # admitted-only latencies: a shed answer is the overload plane
    # working, not a serving latency — mixing them in would let a fast
    # reject path mask a collapsing admitted path
    latencies_us: List[int] = field(default_factory=list)

    @property
    def admitted(self) -> int:
        return self.calls - self.errors - self.shed

    def percentile(self, p: float) -> float:
        if not self.latencies_us:
            return 0.0
        s = sorted(self.latencies_us)
        return s[min(len(s) - 1, int(p * len(s)))]

    def summary(self) -> str:
        return (f"calls={self.calls} admitted={self.admitted} "
                f"shed={self.shed} errors={self.errors} "
                f"qps={self.qps:.0f} "
                f"p50={self.percentile(.5):.0f}us "
                f"p90={self.percentile(.9):.0f}us "
                f"p99={self.percentile(.99):.0f}us "
                f"p999={self.percentile(.999):.0f}us")

    def step_dict(self, concurrency: int = 0) -> dict:
        """One ramp step's machine-readable block (admitted-only
        percentiles beside the admitted/shed split)."""
        d = {
            "calls": self.calls,
            "admitted": self.admitted,
            "shed": self.shed,
            "errors": self.errors,
            "wall_s": round(self.wall_s, 3),
            "qps": round(self.qps, 1),
            "p50_us": self.percentile(.5),
            "p90_us": self.percentile(.9),
            "p99_us": self.percentile(.99),
            "p999_us": self.percentile(.999),
        }
        if concurrency:
            d["concurrency"] = concurrency
        return d

    def to_json_line(self) -> str:
        """One machine-readable JSON line (the overload-control harness
        of ROADMAP item 2 diff-checks these across pressure levels)."""
        import json
        return json.dumps({"metric": "rpc_press", **self.step_dict()})


@dataclass
class StreamPressResult:
    """--stream token-cannon tallies.  TTFT / inter-token-gap
    percentiles are ADMITTED-ONLY (streams that produced >= 1 token):
    a shed handshake is the overload plane working, not a serving
    latency."""
    streams: int = 0      # create_stream attempts
    completed: int = 0    # streams that reached clean EOF
    shed: int = 0         # ELIMIT handshakes (never admitted)
    resets: int = 0       # mid-stream RST (eviction/preemption surface)
    errors: int = 0
    tokens: int = 0
    wall_s: float = 0.0
    ttft_us: List[int] = field(default_factory=list)
    gap_us: List[int] = field(default_factory=list)

    @property
    def tokens_per_s(self) -> float:
        return self.tokens / self.wall_s if self.wall_s > 0 else 0.0

    @staticmethod
    def _pct(xs: List[int], p: float) -> float:
        if not xs:
            return 0.0
        s = sorted(xs)
        return s[min(len(s) - 1, int(p * len(s)))]

    def summary(self) -> str:
        return (f"streams={self.streams} completed={self.completed} "
                f"shed={self.shed} resets={self.resets} "
                f"errors={self.errors} tokens={self.tokens} "
                f"tok/s={self.tokens_per_s:.0f} "
                f"ttft_p50={self._pct(self.ttft_us, .5):.0f}us "
                f"ttft_p99={self._pct(self.ttft_us, .99):.0f}us "
                f"gap_p50={self._pct(self.gap_us, .5):.0f}us "
                f"gap_p99={self._pct(self.gap_us, .99):.0f}us "
                f"gap_p999={self._pct(self.gap_us, .999):.0f}us")

    def to_json_line(self) -> str:
        import json
        return json.dumps({
            "metric": "rpc_press_stream",
            "streams": self.streams,
            "completed": self.completed,
            "shed": self.shed,
            "resets": self.resets,
            "errors": self.errors,
            "tokens": self.tokens,
            "wall_s": round(self.wall_s, 3),
            "tokens_per_s": round(self.tokens_per_s, 1),
            "ttft_p50_us": self._pct(self.ttft_us, .5),
            "ttft_p99_us": self._pct(self.ttft_us, .99),
            "ttft_p999_us": self._pct(self.ttft_us, .999),
            "gap_p50_us": self._pct(self.gap_us, .5),
            "gap_p99_us": self._pct(self.gap_us, .99),
            "gap_p999_us": self._pct(self.gap_us, .999),
        })


def press_stream(server: str, method: str, payload: bytes,
                 concurrency: int = 4, duration_s: float = 5.0,
                 timeout_ms: float = 30000.0,
                 read_timeout_s: float = 60.0) -> StreamPressResult:
    """The serving bench's client side: `concurrency` open-loop workers
    each repeatedly open a stream on `method` and drain tokens to EOF,
    recording per-stream TTFT (handshake issue -> first token) and
    inter-token gaps.  ELIMIT handshakes count as shed and the worker
    immediately re-offers — offered load does not back off when the
    server sheds (same open-loop posture as press())."""
    from brpc_tpu.rpc import errors
    from brpc_tpu.rpc.channel import Channel, ChannelOptions
    from brpc_tpu.rpc.stream import StreamReset, StreamTimeout

    res = StreamPressResult()
    lock = threading.Lock()
    stop = threading.Event()

    def worker():
        ch = Channel(server, ChannelOptions(timeout_ms=timeout_ms,
                                            max_retry=0))
        ttft, gaps = [], []
        streams = completed = shed = resets = errs = tokens = 0
        while not stop.is_set():
            streams += 1
            t0 = time.monotonic_ns()
            try:
                _, st = ch.create_stream(method, payload)
            except errors.RpcError as e:
                if e.code == errors.ELIMIT:
                    shed += 1
                else:
                    errs += 1
                continue
            n, last = 0, 0
            try:
                while True:
                    msg = st.read(timeout_s=read_timeout_s)
                    if msg is None:
                        completed += 1
                        break
                    now = time.monotonic_ns()
                    if n == 0:
                        ttft.append((now - t0) // 1000)
                    else:
                        gaps.append((now - last) // 1000)
                    n, last = n + 1, now
                    tokens += 1
            except StreamReset:
                resets += 1   # evicted/preempted mid-stream: shed surface
            except StreamTimeout:
                errs += 1
            except Exception:
                errs += 1
            st.destroy()
        ch.close()
        with lock:
            res.streams += streams
            res.completed += completed
            res.shed += shed
            res.resets += resets
            res.errors += errs
            res.tokens += tokens
            res.ttft_us.extend(ttft)
            res.gap_us.extend(gaps)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=read_timeout_s + timeout_ms / 1000 + 1)
    res.wall_s = time.monotonic() - t0
    return res


@dataclass
class ConnCannonResult:
    """--connections tallies.  Hot-subset latencies are kept PER LEG so
    the acceptance check can diff the storm leg's p99 against the ramp
    leg's — a flat tail across reconnect storms is the point."""
    connections: int = 0
    hot: int = 0
    opened: int = 0      # successful idle dials (initial + re-dials)
    failed: int = 0      # dials refused / timed out
    sheds: int = 0       # idle conns found dead at sweep time (server
    #                      closed them unused: accept-shed / eviction)
    reconnects: int = 0  # churn + storm re-dials
    storms: int = 0
    ramp_s: float = 0.0  # wall time to open the first N
    wall_s: float = 0.0
    calls: int = 0
    errors: int = 0
    leg_lat_us: dict = field(default_factory=dict)  # leg -> [us]

    @staticmethod
    def _pct(xs: List[int], p: float) -> float:
        if not xs:
            return 0.0
        s = sorted(xs)
        return s[min(len(s) - 1, int(p * len(s)))]

    def leg_dicts(self) -> List[dict]:
        out = []
        for leg in ("ramp", "churn", "storm"):
            xs = self.leg_lat_us.get(leg, [])
            out.append({"leg": leg, "calls": len(xs),
                        "p50_us": self._pct(xs, .5),
                        "p99_us": self._pct(xs, .99),
                        "p999_us": self._pct(xs, .999)})
        return out

    def summary(self) -> str:
        lines = [f"connections={self.connections} hot={self.hot} "
                 f"opened={self.opened} failed={self.failed} "
                 f"sheds={self.sheds} reconnects={self.reconnects} "
                 f"storms={self.storms} ramp_s={self.ramp_s:.2f} "
                 f"calls={self.calls} errors={self.errors}"]
        for d in self.leg_dicts():
            lines.append(f"  {d['leg']}: calls={d['calls']} "
                         f"p50={d['p50_us']:.0f}us "
                         f"p99={d['p99_us']:.0f}us "
                         f"p999={d['p999_us']:.0f}us")
        return "\n".join(lines)

    def to_json_line(self) -> str:
        import json
        return json.dumps({
            "metric": "rpc_press_connections",
            "connections": self.connections, "hot": self.hot,
            "opened": self.opened, "failed": self.failed,
            "sheds": self.sheds, "reconnects": self.reconnects,
            "storms": self.storms, "ramp_s": round(self.ramp_s, 3),
            "wall_s": round(self.wall_s, 3),
            "calls": self.calls, "errors": self.errors,
            "legs": self.leg_dicts(),
        })


def press_connections(server: str, method: str, payload: bytes,
                      connections: int = 1000, hot: int = 4,
                      duration_s: float = 5.0, churn_per_s: float = 50.0,
                      storms: int = 1,
                      timeout_ms: float = 5000.0) -> ConnCannonResult:
    """The million-connection ingress harness: `connections` idle raw
    sockets dialed and HELD (they never speak — first-byte-lazy parse
    state, idle-kick diet, and the timer wheel all get exercised server
    side), while `hot` caller threads echo continuously.  Legs:

    - ramp:  dial the N idle connections as fast as the server admits,
             then dwell `duration_s` under steady hot traffic.
    - churn: close+re-dial `churn_per_s` random idle connections per
             second for `duration_s`.
    - storm: `storms` rounds of dropping EVERY idle connection at once
             and re-dialing the full set (the accept-storm leg).

    Hot-subset latencies are recorded under the leg active at call time.
    A dial the server refuses (or a held connection found dead at sweep
    time — the overload plane closing unused fds) counts toward
    failed/sheds; the cannon re-dials and keeps going."""
    import errno as _errno
    import random
    import socket as _socket

    from brpc_tpu.rpc.channel import Channel, ChannelOptions

    res = ConnCannonResult(connections=connections, hot=hot,
                           storms=storms)
    for leg in ("ramp", "churn", "storm"):
        res.leg_lat_us[leg] = []
    lock = threading.Lock()
    stop = threading.Event()
    leg_now = ["ramp"]  # single writer (main thread), racy read is fine

    def hot_worker():
        ch = Channel(server, ChannelOptions(timeout_ms=timeout_ms,
                                            max_retry=0))
        local: dict = {"ramp": [], "churn": [], "storm": []}
        calls = errs = 0
        while not stop.is_set():
            leg = leg_now[0]
            t0 = time.monotonic_ns()
            try:
                ch.call(method, payload)
                local[leg].append((time.monotonic_ns() - t0) // 1000)
            except Exception:
                errs += 1
            calls += 1
        ch.close()
        with lock:
            res.calls += calls
            res.errors += errs
            for leg, xs in local.items():
                res.leg_lat_us[leg].extend(xs)

    host, _, port_s = server.rpartition(":")
    addr = (host, int(port_s))

    def dial() -> Optional[_socket.socket]:
        try:
            c = _socket.create_connection(addr, timeout=timeout_ms / 1000)
            c.setblocking(False)
            res.opened += 1
            return c
        except OSError:
            res.failed += 1
            return None

    def sweep_dead(conns: List[_socket.socket]) -> List[_socket.socket]:
        """Drop held connections the server has closed under us (accept
        shed / idle eviction read as EOF or reset on a silent socket)."""
        live = []
        for c in conns:
            try:
                if c.recv(1) == b"":
                    res.sheds += 1
                    c.close()
                    continue
            except OSError as e:
                if e.errno in (_errno.EAGAIN, _errno.EWOULDBLOCK):
                    live.append(c)
                    continue
                res.sheds += 1
                c.close()
                continue
            live.append(c)  # server spoke first (unexpected): keep it
        return live

    threads = [threading.Thread(target=hot_worker, daemon=True)
               for _ in range(hot)]
    t_start = time.monotonic()
    for t in threads:
        t.start()

    # --- ramp ---
    idle: List[_socket.socket] = []
    t0 = time.monotonic()
    for _ in range(connections):
        c = dial()
        if c is not None:
            idle.append(c)
    res.ramp_s = time.monotonic() - t0
    time.sleep(duration_s)

    # --- churn ---
    leg_now[0] = "churn"
    interval = 1.0 / churn_per_s if churn_per_s > 0 else duration_s
    t_end = time.monotonic() + duration_s
    while time.monotonic() < t_end:
        if idle:
            k = random.randrange(len(idle))
            idle[k].close()
            c = dial()
            if c is not None:
                idle[k] = c
                res.reconnects += 1
            else:
                idle.pop(k)
        time.sleep(interval)

    # --- reconnect storms ---
    leg_now[0] = "storm"
    for _ in range(storms):
        idle = sweep_dead(idle)
        want = len(idle)
        for c in idle:
            c.close()
        idle = []
        for _ in range(want):
            c = dial()
            if c is not None:
                idle.append(c)
                res.reconnects += 1
        time.sleep(max(duration_s / max(storms, 1), 0.2))

    stop.set()
    for t in threads:
        t.join(timeout=timeout_ms / 1000 + 1)
    idle = sweep_dead(idle)
    for c in idle:
        c.close()
    res.wall_s = time.monotonic() - t_start
    return res


def press(server: str, method: str, payload: bytes, qps: float = 0.0,
          concurrency: int = 4, duration_s: float = 5.0,
          attachment: bytes = b"",
          timeout_ms: float = 1000.0, protocol: str = "trpc") -> PressResult:
    """Drive `method` at `qps` (0 = as fast as possible) with `concurrency`
    caller threads for `duration_s`.

    protocol: "trpc" (default), "h2" (method = "VERB /path" over the
    native HTTP/2 client) or "grpc" (method = "Service/Method", payload =
    serialized request).  For HTTP/1.1, a method starting with "GET " /
    "POST " etc. is an HTTP target ("GET /health") driven through the
    framework's own client (≙ rpc_press's multi-protocol support)."""
    from brpc_tpu.rpc import errors
    from brpc_tpu.rpc.channel import Channel, ChannelOptions
    from brpc_tpu.rpc.http_client import HttpChannel

    http_verb = None
    http_target = "/"
    if method.startswith(("GET ", "POST ", "PUT ", "DELETE ", "HEAD ")):
        http_verb, _, http_target = method.partition(" ")

    res = PressResult()
    lock = threading.Lock()
    stop = threading.Event()
    # per-thread QPS share via interval pacing (≙ rpc_press -qps)
    interval = concurrency / qps if qps > 0 else 0.0

    def worker():
        if protocol == "h2":
            from brpc_tpu.rpc.h2_client import H2Channel
            h2 = H2Channel(server)
            verb, _, target = method.partition(" ")
            if not target:
                verb, target = "GET", method

            def call_once():
                r = h2.request(verb, target, body=payload,
                               timeout_ms=timeout_ms)
                if r.status >= 400:
                    raise RuntimeError(f"h2 {r.status}")

            closer = h2.close
        elif protocol == "grpc":
            from brpc_tpu.rpc.h2_client import GrpcChannel
            g = GrpcChannel(server)
            service, _, meth = method.rpartition("/")

            def call_once():
                g.call(service, meth, payload, timeout_ms=timeout_ms)

            closer = g.close
        elif http_verb is not None:
            hch = HttpChannel(server)

            def call_once():
                r = hch.request(http_verb, http_target, body=payload,
                                timeout_ms=timeout_ms)
                if r.status >= 400:
                    raise RuntimeError(f"http {r.status}")

            closer = hch.close
        else:
            ch = Channel(server, ChannelOptions(timeout_ms=timeout_ms,
                                                max_retry=0))

            def call_once():
                ch.call(method, payload, attachment)

            closer = ch.close
        local_lat, local_calls, local_errs, local_shed = [], 0, 0, 0
        next_at = time.monotonic()
        while not stop.is_set():
            if interval > 0:
                now = time.monotonic()
                if now < next_at:
                    time.sleep(min(next_at - now, 0.05))
                    continue
                next_at += interval
            t0 = time.monotonic_ns()
            try:
                call_once()
                local_lat.append((time.monotonic_ns() - t0) // 1000)
            except errors.RpcError as e:
                if e.code == errors.ELIMIT:
                    local_shed += 1  # shed, never executed — not an error
                else:
                    local_errs += 1
            except Exception:
                local_errs += 1
            local_calls += 1
        closer()
        with lock:
            res.calls += local_calls
            res.errors += local_errs
            res.shed += local_shed
            res.latencies_us.extend(local_lat)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=timeout_ms / 1000 + 1)
    res.wall_s = time.monotonic() - t0
    res.qps = res.calls / res.wall_s if res.wall_s > 0 else 0.0
    return res


def parse_ramp(spec: str) -> List[int]:
    """'lo:hi:steps' -> the concurrency level per step (inclusive,
    linearly spaced, deduplicated ascending)."""
    parts = spec.split(":")
    if len(parts) != 3:
        raise ValueError(f"--ramp wants lo:hi:steps, got {spec!r}")
    lo, hi, steps = int(parts[0]), int(parts[1]), int(parts[2])
    if lo < 1 or hi < lo or steps < 1:
        raise ValueError(f"--ramp wants 1 <= lo <= hi, steps >= 1 "
                         f"(got {spec!r})")
    if steps == 1:
        return [hi]
    levels = []
    for i in range(steps):
        c = lo + round(i * (hi - lo) / (steps - 1))
        if not levels or c > levels[-1]:
            levels.append(c)
    return levels


def ramp(server: str, method: str, payload: bytes, spec: str,
         step_s: float, qps: float = 0.0, attachment: bytes = b"",
         timeout_ms: float = 1000.0, protocol: str = "trpc") -> List[dict]:
    """The overload cannon: one open-loop press() per concurrency level,
    each step_s long, reporting admitted/shed + admitted-only
    percentiles per step."""
    out = []
    for level in parse_ramp(spec):
        r = press(server, method, payload, qps=qps, concurrency=level,
                  duration_s=step_s, attachment=attachment,
                  timeout_ms=timeout_ms, protocol=protocol)
        out.append(r.step_dict(concurrency=level))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="rpc_press load generator")
    ap.add_argument("-s", "--server", required=True, help="ip:port")
    ap.add_argument("-m", "--method", default="Echo.echo")
    ap.add_argument("-d", "--data", default="", help="request payload")
    ap.add_argument("-f", "--file", help="read payload from file")
    ap.add_argument("-q", "--qps", type=float, default=0.0,
                    help="target qps (0 = unlimited)")
    ap.add_argument("-c", "--concurrency", type=int, default=4)
    ap.add_argument("-p", "--protocol", default="trpc",
                    choices=["trpc", "h2", "grpc"],
                    help="wire protocol (HTTP/1.1 via 'GET /path' methods)")
    ap.add_argument("-t", "--time", type=float, default=5.0,
                    help="duration seconds (per step with --ramp)")
    ap.add_argument("--stream", action="store_true",
                    help="token-cannon mode: -c open-loop concurrent "
                         "streams on -m, draining tokens to EOF; "
                         "reports per-stream TTFT and inter-token-gap "
                         "p50/p99/p999 (admitted-only) plus tokens/s")
    ap.add_argument("--read-timeout", type=float, default=60.0,
                    help="--stream per-read budget seconds")
    ap.add_argument("--connections", type=int, default=0,
                    help="connection-cannon mode: hold N idle "
                         "connections through ramp/churn/reconnect-storm "
                         "legs while --hot callers keep echoing; "
                         "reports hot-subset p50/p99/p999 per leg")
    ap.add_argument("--hot", type=int, default=4,
                    help="--connections hot-subset caller threads")
    ap.add_argument("--churn", type=float, default=50.0,
                    help="--connections churn leg: idle close+re-dials "
                         "per second")
    ap.add_argument("--storms", type=int, default=1,
                    help="--connections reconnect-storm rounds")
    ap.add_argument("--ramp", metavar="lo:hi:steps",
                    help="open-loop concurrency ramp: one -t second "
                         "step per level; reports admitted-vs-shed and "
                         "admitted-only p50/p99/p999 per step (the "
                         "overload-control cannon)")
    ap.add_argument("--json", action="store_true",
                    help="print ONE JSON summary line (qps + "
                         "admitted/shed + p50/p90/p99/p999; with "
                         "--ramp, a per-step array) instead of text")
    args = ap.parse_args(argv)
    payload = (open(args.file, "rb").read() if args.file
               else args.data.encode())
    if args.connections > 0:
        res = press_connections(args.server, args.method, payload,
                                connections=args.connections,
                                hot=args.hot, duration_s=args.time,
                                churn_per_s=args.churn,
                                storms=args.storms)
        print(res.to_json_line() if args.json else res.summary())
        return 1 if res.errors and not res.calls - res.errors else 0
    if args.stream:
        res = press_stream(args.server, args.method, payload,
                           concurrency=args.concurrency,
                           duration_s=args.time,
                           read_timeout_s=args.read_timeout)
        print(res.to_json_line() if args.json else res.summary())
        return 1 if res.errors and not res.tokens else 0
    if args.ramp:
        import json
        steps = ramp(args.server, args.method, payload, args.ramp,
                     args.time, qps=args.qps, protocol=args.protocol)
        if args.json:
            print(json.dumps({"metric": "rpc_press_ramp",
                              "method": args.method, "steps": steps}))
        else:
            for st in steps:
                print(f"c={st['concurrency']} qps={st['qps']:.0f} "
                      f"admitted={st['admitted']} shed={st['shed']} "
                      f"errors={st['errors']} p50={st['p50_us']:.0f}us "
                      f"p99={st['p99_us']:.0f}us "
                      f"p999={st['p999_us']:.0f}us")
        return 0
    res = press(args.server, args.method, payload, args.qps,
                args.concurrency, args.time, protocol=args.protocol)
    print(res.to_json_line() if args.json else res.summary())
    return 1 if res.errors and not res.calls - res.errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
