"""rpc_press — load generator (≙ reference tools/rpc_press: target QPS,
concurrency, latency bvars printed by an info thread,
rpc_press_impl.{h,cpp}).

    python -m brpc_tpu.tools.rpc_press -s 127.0.0.1:8000 -m Echo.echo \
        -d 'hello' -q 10000 -c 8 -t 10
"""

from __future__ import annotations

import argparse
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class PressResult:
    calls: int = 0
    errors: int = 0
    wall_s: float = 0.0
    qps: float = 0.0
    latencies_us: List[int] = field(default_factory=list)

    def percentile(self, p: float) -> float:
        if not self.latencies_us:
            return 0.0
        s = sorted(self.latencies_us)
        return s[min(len(s) - 1, int(p * len(s)))]

    def summary(self) -> str:
        return (f"calls={self.calls} errors={self.errors} "
                f"qps={self.qps:.0f} "
                f"p50={self.percentile(.5):.0f}us "
                f"p90={self.percentile(.9):.0f}us "
                f"p99={self.percentile(.99):.0f}us "
                f"p999={self.percentile(.999):.0f}us")

    def to_json_line(self) -> str:
        """One machine-readable JSON line (the overload-control harness
        of ROADMAP item 4 diff-checks these across pressure levels)."""
        import json
        return json.dumps({
            "metric": "rpc_press",
            "calls": self.calls,
            "errors": self.errors,
            "wall_s": round(self.wall_s, 3),
            "qps": round(self.qps, 1),
            "p50_us": self.percentile(.5),
            "p90_us": self.percentile(.9),
            "p99_us": self.percentile(.99),
            "p999_us": self.percentile(.999),
        })


def press(server: str, method: str, payload: bytes, qps: float = 0.0,
          concurrency: int = 4, duration_s: float = 5.0,
          attachment: bytes = b"",
          timeout_ms: float = 1000.0, protocol: str = "trpc") -> PressResult:
    """Drive `method` at `qps` (0 = as fast as possible) with `concurrency`
    caller threads for `duration_s`.

    protocol: "trpc" (default), "h2" (method = "VERB /path" over the
    native HTTP/2 client) or "grpc" (method = "Service/Method", payload =
    serialized request).  For HTTP/1.1, a method starting with "GET " /
    "POST " etc. is an HTTP target ("GET /health") driven through the
    framework's own client (≙ rpc_press's multi-protocol support)."""
    from brpc_tpu.rpc.channel import Channel, ChannelOptions
    from brpc_tpu.rpc.http_client import HttpChannel

    http_verb = None
    http_target = "/"
    if method.startswith(("GET ", "POST ", "PUT ", "DELETE ", "HEAD ")):
        http_verb, _, http_target = method.partition(" ")

    res = PressResult()
    lock = threading.Lock()
    stop = threading.Event()
    # per-thread QPS share via interval pacing (≙ rpc_press -qps)
    interval = concurrency / qps if qps > 0 else 0.0

    def worker():
        if protocol == "h2":
            from brpc_tpu.rpc.h2_client import H2Channel
            h2 = H2Channel(server)
            verb, _, target = method.partition(" ")
            if not target:
                verb, target = "GET", method

            def call_once():
                r = h2.request(verb, target, body=payload,
                               timeout_ms=timeout_ms)
                if r.status >= 400:
                    raise RuntimeError(f"h2 {r.status}")

            closer = h2.close
        elif protocol == "grpc":
            from brpc_tpu.rpc.h2_client import GrpcChannel
            g = GrpcChannel(server)
            service, _, meth = method.rpartition("/")

            def call_once():
                g.call(service, meth, payload, timeout_ms=timeout_ms)

            closer = g.close
        elif http_verb is not None:
            hch = HttpChannel(server)

            def call_once():
                r = hch.request(http_verb, http_target, body=payload,
                                timeout_ms=timeout_ms)
                if r.status >= 400:
                    raise RuntimeError(f"http {r.status}")

            closer = hch.close
        else:
            ch = Channel(server, ChannelOptions(timeout_ms=timeout_ms,
                                                max_retry=0))

            def call_once():
                ch.call(method, payload, attachment)

            closer = ch.close
        local_lat, local_calls, local_errs = [], 0, 0
        next_at = time.monotonic()
        while not stop.is_set():
            if interval > 0:
                now = time.monotonic()
                if now < next_at:
                    time.sleep(min(next_at - now, 0.05))
                    continue
                next_at += interval
            t0 = time.monotonic_ns()
            try:
                call_once()
                local_lat.append((time.monotonic_ns() - t0) // 1000)
            except Exception:
                local_errs += 1
            local_calls += 1
        closer()
        with lock:
            res.calls += local_calls
            res.errors += local_errs
            res.latencies_us.extend(local_lat)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=timeout_ms / 1000 + 1)
    res.wall_s = time.monotonic() - t0
    res.qps = res.calls / res.wall_s if res.wall_s > 0 else 0.0
    return res


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="rpc_press load generator")
    ap.add_argument("-s", "--server", required=True, help="ip:port")
    ap.add_argument("-m", "--method", default="Echo.echo")
    ap.add_argument("-d", "--data", default="", help="request payload")
    ap.add_argument("-f", "--file", help="read payload from file")
    ap.add_argument("-q", "--qps", type=float, default=0.0,
                    help="target qps (0 = unlimited)")
    ap.add_argument("-c", "--concurrency", type=int, default=4)
    ap.add_argument("-p", "--protocol", default="trpc",
                    choices=["trpc", "h2", "grpc"],
                    help="wire protocol (HTTP/1.1 via 'GET /path' methods)")
    ap.add_argument("-t", "--time", type=float, default=5.0,
                    help="duration seconds")
    ap.add_argument("--json", action="store_true",
                    help="print ONE JSON summary line (qps + "
                         "p50/p90/p99/p999) instead of the text summary")
    args = ap.parse_args(argv)
    payload = (open(args.file, "rb").read() if args.file
               else args.data.encode())
    res = press(args.server, args.method, payload, args.qps,
                args.concurrency, args.time, protocol=args.protocol)
    print(res.to_json_line() if args.json else res.summary())
    return 1 if res.errors and not res.calls - res.errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
