"""parallel_http — mass concurrent HTTP fetcher
(≙ reference tools/parallel_http: fetch many URLs with bounded
concurrency and report per-URL outcomes).  Drives the FRAMEWORK'S OWN
HTTP client (rpc/http_client.py — native data path, pooled per host),
not urllib.

    python -m brpc_tpu.tools.parallel_http --url-file urls.txt -c 32
"""

from __future__ import annotations

import argparse
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlsplit

from brpc_tpu.rpc.http_client import HttpChannel


@dataclass
class FetchResult:
    url: str
    status: int          # HTTP status, or -1 on transport error
    bytes: int
    latency_ms: float
    error: str = ""


class _ChannelCache:
    """One HttpChannel per (scheme, host, port) — fetches to one host
    share its connection pool."""

    def __init__(self, tls_verify: bool = True):
        self._lock = threading.Lock()
        self._chans: Dict[Tuple[str, str, int], HttpChannel] = {}
        self._tls_verify = tls_verify

    def get(self, scheme: str, host: str, port: int) -> HttpChannel:
        key = (scheme, host, port)
        with self._lock:
            ch = self._chans.get(key)
            if ch is None:
                ch = HttpChannel(f"{host}:{port}", host=host,
                                 tls=(scheme == "https"),
                                 tls_verify=self._tls_verify)
                self._chans[key] = ch
            return ch

    def close(self):
        with self._lock:
            for ch in self._chans.values():
                ch.close()
            self._chans.clear()


def fetch_all(urls: List[str], concurrency: int = 16,
              timeout_s: float = 10.0,
              tls_verify: bool = True) -> List[FetchResult]:
    cache = _ChannelCache(tls_verify=tls_verify)

    def one(url: str) -> FetchResult:
        t0 = time.monotonic()
        try:
            u = urlsplit(url if "//" in url else "http://" + url)
            port = u.port or (443 if u.scheme == "https" else 80)
            ch = cache.get(u.scheme or "http", u.hostname or "127.0.0.1",
                           port)
            target = (u.path or "/") + (f"?{u.query}" if u.query else "")
            r = ch.get(target, timeout_ms=timeout_s * 1000)
            return FetchResult(url, r.status, len(r.body),
                               (time.monotonic() - t0) * 1000)
        except Exception as e:
            return FetchResult(url, -1, 0,
                               (time.monotonic() - t0) * 1000, str(e))

    try:
        with ThreadPoolExecutor(max_workers=concurrency) as pool:
            return list(pool.map(one, urls))
    finally:
        cache.close()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="mass HTTP fetch")
    ap.add_argument("urls", nargs="*", help="URLs to fetch")
    ap.add_argument("--url-file", help="file with one URL per line")
    ap.add_argument("-c", "--concurrency", type=int, default=16)
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument("--insecure", action="store_true",
                    help="skip TLS certificate verification")
    args = ap.parse_args(argv)
    urls = list(args.urls)
    if args.url_file:
        with open(args.url_file) as f:
            urls += [ln.strip() for ln in f if ln.strip()]
    if not urls:
        ap.error("no URLs given")
    results = fetch_all(urls, args.concurrency, args.timeout,
                        tls_verify=not args.insecure)
    ok = 0
    for r in results:
        mark = "OK " if 200 <= r.status < 300 else "ERR"
        ok += mark == "OK "
        print(f"{mark} {r.status:4d} {r.bytes:8d}B {r.latency_ms:7.1f}ms "
              f"{r.url} {r.error}", file=sys.stdout)
    print(f"{ok}/{len(results)} succeeded")
    return 0 if ok == len(results) else 1


if __name__ == "__main__":
    raise SystemExit(main())
