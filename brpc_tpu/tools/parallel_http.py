"""parallel_http — mass concurrent HTTP fetcher
(≙ reference tools/parallel_http: fetch many URLs with bounded
concurrency and report per-URL outcomes).

    python -m brpc_tpu.tools.parallel_http --url-file urls.txt -c 32
"""

from __future__ import annotations

import argparse
import sys
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import List, Optional, Tuple


@dataclass
class FetchResult:
    url: str
    status: int          # HTTP status, or -1 on transport error
    bytes: int
    latency_ms: float
    error: str = ""


def fetch_all(urls: List[str], concurrency: int = 16,
              timeout_s: float = 10.0) -> List[FetchResult]:
    def one(url: str) -> FetchResult:
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as r:
                body = r.read()
                return FetchResult(url, r.status, len(body),
                                   (time.monotonic() - t0) * 1000)
        except urllib.error.HTTPError as e:
            return FetchResult(url, e.code, 0,
                               (time.monotonic() - t0) * 1000)
        except Exception as e:
            return FetchResult(url, -1, 0,
                               (time.monotonic() - t0) * 1000, str(e))

    with ThreadPoolExecutor(max_workers=concurrency) as pool:
        return list(pool.map(one, urls))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="mass HTTP fetch")
    ap.add_argument("urls", nargs="*", help="URLs to fetch")
    ap.add_argument("--url-file", help="file with one URL per line")
    ap.add_argument("-c", "--concurrency", type=int, default=16)
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    urls = list(args.urls)
    if args.url_file:
        with open(args.url_file) as f:
            urls += [ln.strip() for ln in f if ln.strip()]
    if not urls:
        ap.error("no URLs given")
    results = fetch_all(urls, args.concurrency, args.timeout)
    ok = 0
    for r in results:
        mark = "OK " if 200 <= r.status < 300 else "ERR"
        ok += mark == "OK "
        print(f"{mark} {r.status:4d} {r.bytes:8d}B {r.latency_ms:7.1f}ms "
              f"{r.url} {r.error}", file=sys.stdout)
    print(f"{ok}/{len(results)} succeeded")
    return 0 if ok == len(results) else 1


if __name__ == "__main__":
    raise SystemExit(main())
