"""rpc_replay — replay rpc_dump sample files against a live server at a
chosen QPS (≙ reference tools/rpc_replay over SampleIterator,
rpc_dump.h:81).

    python -m brpc_tpu.tools.rpc_replay -s 127.0.0.1:8000 \
        --dir ./rpc_dump -q 1000 --loop 3
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class ReplayResult:
    sent: int = 0
    errors: int = 0
    wall_s: float = 0.0

    def summary(self) -> str:
        qps = self.sent / self.wall_s if self.wall_s > 0 else 0.0
        return f"replayed={self.sent} errors={self.errors} qps={qps:.0f}"


def replay(server: str, dump_dir: str, qps: float = 0.0, loops: int = 1,
           timeout_ms: float = 1000.0) -> ReplayResult:
    from brpc_tpu.rpc.channel import Channel, ChannelOptions
    from brpc_tpu.rpc.dump import SampleIterator

    ch = Channel(server, ChannelOptions(timeout_ms=timeout_ms, max_retry=0))
    res = ReplayResult()
    interval = 1.0 / qps if qps > 0 else 0.0
    t0 = time.monotonic()
    next_at = t0
    try:
        for _ in range(loops):
            for sample in SampleIterator(dump_dir):
                if interval > 0:
                    now = time.monotonic()
                    if now < next_at:
                        time.sleep(next_at - now)
                    next_at += interval
                try:
                    if ch._sub is not None:
                        # raw wire-form replay: the payload is re-sent
                        # exactly as captured (still compressed if it was),
                        # the sample's compress tag riding along untouched
                        code, _, _, _ = ch._sub.call_once(
                            sample.method.encode(), sample.payload,
                            sample.attachment, int(timeout_ms * 1000),
                            compress=sample.compress_type)
                        if code != 0:
                            res.errors += 1
                    else:
                        ch.call(sample.method, sample.payload,
                                sample.attachment)
                except Exception:
                    res.errors += 1
                res.sent += 1
    finally:
        ch.close()
    res.wall_s = time.monotonic() - t0
    return res


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="replay rpc_dump samples")
    ap.add_argument("-s", "--server", required=True, help="ip:port")
    ap.add_argument("--dir", default="./rpc_dump", help="dump directory")
    ap.add_argument("-q", "--qps", type=float, default=0.0)
    ap.add_argument("--loop", type=int, default=1,
                    help="times to replay the whole set")
    args = ap.parse_args(argv)
    res = replay(args.server, args.dir, args.qps, args.loop)
    print(res.summary())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
