"""rpc_replay — the flight-recorder replay cannon: drive captured
rpc_dump segments against a live server, byte-for-byte, at a chosen
speed (≙ reference tools/rpc_replay over SampleIterator, rpc_dump.h:81).

    python -m brpc_tpu.tools.rpc_replay -s 127.0.0.1:8000 \
        --dir ./rpc_dump --speed 10 -c 8 --json

Replay posture (the rpc_press discipline):

- Byte-for-byte: each sample's payload/attachment are re-sent in their
  captured WIRE form — still codec-encoded (meta tags 16/17) and/or
  compressed (tag 6) — through ``Channel.call_raw``, which skips the
  client-side encode and stamps the captured tags verbatim.
- ``--speed N`` replays the capture at N× its original rate: inter-
  request gaps come from the captured timestamps, divided by N
  (``--qps`` overrides with a fixed rate; neither = as fast as possible).
- Open-loop: workers never back off when the server sheds — a replayed
  incident must offer the load the incident offered.  ELIMIT answers
  count as shed, and latency percentiles are ADMITTED-ONLY.
- ``--stream`` replays captured token-stream sessions (stream-open
  samples) end-to-end: each session re-opens its stream and drains
  tokens to EOF, reporting TTFT / inter-token-gap percentiles.
- ``--sched-seed S`` arms the PR-6 schedule-replay seed first: a
  captured segment + seed is a deterministic incident reproduction.
"""

from __future__ import annotations

import argparse
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class ReplayResult:
    """Replay tallies — shed/admitted split and admitted-only
    percentiles, exactly the rpc_press accounting (a shed answer is the
    overload plane working, not a serving latency)."""
    samples: int = 0     # replayable unary samples in the set
    skipped: int = 0     # non-unary records (stream frames, REDIS, ...)
    calls: int = 0
    errors: int = 0
    shed: int = 0        # server-side ELIMIT rejects (never executed)
    behind: int = 0      # sends issued past their due time (cannon lag)
    wall_s: float = 0.0
    speed: float = 1.0
    sched_seed: Optional[int] = None
    latencies_us: List[int] = field(default_factory=list)

    @property
    def admitted(self) -> int:
        return self.calls - self.errors - self.shed

    @property
    def qps(self) -> float:
        return self.calls / self.wall_s if self.wall_s > 0 else 0.0

    def percentile(self, p: float) -> float:
        if not self.latencies_us:
            return 0.0
        s = sorted(self.latencies_us)
        return s[min(len(s) - 1, int(p * len(s)))]

    def summary(self) -> str:
        return (f"samples={self.samples} skipped={self.skipped} "
                f"calls={self.calls} admitted={self.admitted} "
                f"shed={self.shed} errors={self.errors} "
                f"qps={self.qps:.0f} "
                f"p50={self.percentile(.5):.0f}us "
                f"p99={self.percentile(.99):.0f}us "
                f"p999={self.percentile(.999):.0f}us")

    def to_json_line(self) -> str:
        import json
        d = {
            "metric": "rpc_replay",
            "samples": self.samples,
            "skipped": self.skipped,
            "calls": self.calls,
            "admitted": self.admitted,
            "shed": self.shed,
            "errors": self.errors,
            "behind": self.behind,
            "wall_s": round(self.wall_s, 3),
            "qps": round(self.qps, 1),
            "speed": self.speed,
            "p50_us": self.percentile(.5),
            "p99_us": self.percentile(.99),
            "p999_us": self.percentile(.999),
        }
        if self.sched_seed is not None:
            d["sched_seed"] = self.sched_seed
        return json.dumps(d)


@dataclass
class StreamReplayResult:
    """--stream tallies: captured token sessions replayed to EOF
    (TTFT/gap percentiles admitted-only, the rpc_press --stream shape)."""
    sessions: int = 0
    completed: int = 0
    shed: int = 0
    resets: int = 0
    errors: int = 0
    tokens: int = 0
    wall_s: float = 0.0
    sched_seed: Optional[int] = None
    ttft_us: List[int] = field(default_factory=list)
    gap_us: List[int] = field(default_factory=list)

    @staticmethod
    def _pct(xs: List[int], p: float) -> float:
        if not xs:
            return 0.0
        s = sorted(xs)
        return s[min(len(s) - 1, int(p * len(s)))]

    def summary(self) -> str:
        return (f"sessions={self.sessions} completed={self.completed} "
                f"shed={self.shed} resets={self.resets} "
                f"errors={self.errors} tokens={self.tokens} "
                f"ttft_p50={self._pct(self.ttft_us, .5):.0f}us "
                f"gap_p50={self._pct(self.gap_us, .5):.0f}us "
                f"gap_p99={self._pct(self.gap_us, .99):.0f}us")

    def to_json_line(self) -> str:
        import json
        d = {
            "metric": "rpc_replay_stream",
            "sessions": self.sessions,
            "completed": self.completed,
            "shed": self.shed,
            "resets": self.resets,
            "errors": self.errors,
            "tokens": self.tokens,
            "wall_s": round(self.wall_s, 3),
            "ttft_p50_us": self._pct(self.ttft_us, .5),
            "ttft_p99_us": self._pct(self.ttft_us, .99),
            "gap_p50_us": self._pct(self.gap_us, .5),
            "gap_p99_us": self._pct(self.gap_us, .99),
            "gap_p999_us": self._pct(self.gap_us, .999),
        }
        if self.sched_seed is not None:
            d["sched_seed"] = self.sched_seed
        return json.dumps(d)


def _arm_sched_seed(seed: Optional[int]) -> None:
    """PR-6 pairing: push the schedule-perturbation seed before traffic
    so the replayed segment runs under the captured interleaving draw."""
    if seed is None:
        return
    from brpc_tpu.utils import flags
    flags.set_flag("sched_seed", int(seed))


def _load_unary(dump_dir: str):
    """Split a capture set into replayable unary samples (timestamp-
    ordered) and a skipped count.  Stream-internal frames replay through
    --stream; REDIS records are RESP blobs a TRPC channel can't carry."""
    from brpc_tpu.rpc.dump import SampleIterator
    unary, skipped = [], 0
    for s in SampleIterator(dump_dir):
        if s.stream_frame_type != 0 or s.stream_id != 0 \
                or s.method == "REDIS" or not s.method:
            skipped += 1
            continue
        unary.append(s)
    unary.sort(key=lambda s: s.timestamp)
    return unary, skipped


def replay(server: str, dump_dir: str, speed: float = 1.0,
           qps: float = 0.0, loops: int = 1, concurrency: int = 4,
           timeout_ms: float = 1000.0,
           sched_seed: Optional[int] = None) -> ReplayResult:
    """Replay every captured unary sample `loops` times.  Pacing: the
    captured inter-request gaps divided by `speed` (the incident's own
    shape, sped up), or a fixed `qps`, or flat-out when neither is set.
    Open-loop across `concurrency` workers: a worker whose sample is due
    sends it regardless of how the server answered the last one."""
    from brpc_tpu.rpc import errors
    from brpc_tpu.rpc.channel import Channel, ChannelOptions

    _arm_sched_seed(sched_seed)
    samples, skipped = _load_unary(dump_dir)
    res = ReplayResult(samples=len(samples), skipped=skipped,
                       speed=speed, sched_seed=sched_seed)
    if not samples:
        return res

    # due[i]: seconds after replay start at which shot i fires.  The
    # captured timestamps carry the incident's burst structure; --speed
    # compresses it.  A zero-gap capture (or --qps) degrades to uniform
    # pacing; speed/qps both unset = every shot due immediately.
    n_total = len(samples) * max(loops, 1)
    t_base = samples[0].timestamp
    due = [0.0] * n_total
    span = (samples[-1].timestamp - t_base) if len(samples) > 1 else 0.0
    for k in range(n_total):
        i = k % len(samples)
        lap = k // len(samples)
        if qps > 0:
            due[k] = k / qps
        elif speed > 0:
            off = samples[i].timestamp - t_base
            due[k] = (off + lap * span) / speed
        else:
            due[k] = 0.0

    lock = threading.Lock()
    next_idx = [0]
    t0 = time.monotonic()

    def worker():
        ch = Channel(server, ChannelOptions(timeout_ms=timeout_ms,
                                            max_retry=0))
        lat, calls, errs, shed, behind = [], 0, 0, 0, 0
        while True:
            with lock:
                k = next_idx[0]
                if k >= n_total:
                    break
                next_idx[0] += 1
            s = samples[k % len(samples)]
            at = t0 + due[k]
            now = time.monotonic()
            if now < at:
                time.sleep(at - now)
            elif due[k] > 0:
                behind += 1  # lagging the capture's shape: still send
            t1 = time.monotonic_ns()
            try:
                ch.call_raw(s.method, s.payload, s.attachment,
                            timeout_ms=timeout_ms,
                            compress_type=s.compress_type,
                            payload_codec=s.payload_codec,
                            attach_codec=s.attach_codec)
                lat.append((time.monotonic_ns() - t1) // 1000)
            except errors.RpcError as e:
                if e.code == errors.ELIMIT:
                    shed += 1  # the overload plane working, not an error
                else:
                    errs += 1
            except Exception:
                errs += 1
            calls += 1
        ch.close()
        with lock:
            res.calls += calls
            res.errors += errs
            res.shed += shed
            res.behind += behind
            res.latencies_us.extend(lat)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(concurrency, 1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    res.wall_s = time.monotonic() - t0
    return res


def replay_stream(server: str, dump_dir: str, loops: int = 1,
                  concurrency: int = 2, timeout_ms: float = 30000.0,
                  read_timeout_s: float = 60.0,
                  sched_seed: Optional[int] = None) -> StreamReplayResult:
    """Replay captured token-stream sessions end-to-end: every captured
    stream-OPEN sample (a request frame carrying a stream id) re-issues
    its handshake and drains tokens to EOF — the serving-workload half
    of the cannon (data/close frames ride the re-opened stream; the
    captured ones are session-internal and are not re-sent)."""
    from brpc_tpu.rpc import errors
    from brpc_tpu.rpc.channel import Channel, ChannelOptions
    from brpc_tpu.rpc.dump import SampleIterator
    from brpc_tpu.rpc.stream import StreamReset, StreamTimeout

    _arm_sched_seed(sched_seed)
    opens = [s for s in SampleIterator(dump_dir)
             if s.stream_id != 0 and s.stream_frame_type == 0
             and s.method and s.method != "REDIS"]
    opens.sort(key=lambda s: s.timestamp)
    res = StreamReplayResult(sched_seed=sched_seed)
    if not opens:
        return res

    sessions = [opens[k % len(opens)]
                for k in range(len(opens) * max(loops, 1))]
    lock = threading.Lock()
    next_idx = [0]
    t_start = time.monotonic()

    def worker():
        ch = Channel(server, ChannelOptions(timeout_ms=timeout_ms,
                                            max_retry=0))
        ttft, gaps = [], []
        ses = completed = shed = resets = errs = tokens = 0
        while True:
            with lock:
                k = next_idx[0]
                if k >= len(sessions):
                    break
                next_idx[0] += 1
            s = sessions[k]
            ses += 1
            t0 = time.monotonic_ns()
            try:
                _, st = ch.create_stream(s.method, s.payload, s.attachment)
            except errors.RpcError as e:
                if e.code == errors.ELIMIT:
                    shed += 1
                else:
                    errs += 1
                continue
            n, last = 0, 0
            try:
                while True:
                    msg = st.read(timeout_s=read_timeout_s)
                    if msg is None:
                        completed += 1
                        break
                    now = time.monotonic_ns()
                    if n == 0:
                        ttft.append((now - t0) // 1000)
                    else:
                        gaps.append((now - last) // 1000)
                    n, last = n + 1, now
                    tokens += 1
            except StreamReset:
                resets += 1
            except StreamTimeout:
                errs += 1
            except Exception:
                errs += 1
            st.destroy()
        ch.close()
        with lock:
            res.sessions += ses
            res.completed += completed
            res.shed += shed
            res.resets += resets
            res.errors += errs
            res.tokens += tokens
            res.ttft_us.extend(ttft)
            res.gap_us.extend(gaps)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(concurrency, 1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    res.wall_s = time.monotonic() - t_start
    return res


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="replay captured rpc_dump segments byte-for-byte")
    ap.add_argument("-s", "--server", required=True, help="ip:port")
    ap.add_argument("--dir", default="./rpc_dump", help="dump directory")
    ap.add_argument("--speed", type=float, default=1.0,
                    help="replay at N x the captured rate (gaps from the "
                         "captured timestamps, divided by N; 0 = flat out)")
    ap.add_argument("-q", "--qps", type=float, default=0.0,
                    help="fixed-rate override (ignores captured gaps)")
    ap.add_argument("--loop", type=int, default=1,
                    help="times to replay the whole set")
    ap.add_argument("-c", "--concurrency", type=int, default=4,
                    help="open-loop worker threads")
    ap.add_argument("--timeout-ms", type=float, default=1000.0)
    ap.add_argument("--stream", action="store_true",
                    help="replay captured token-stream sessions to EOF "
                         "(TTFT / inter-token-gap percentiles)")
    ap.add_argument("--read-timeout", type=float, default=60.0,
                    help="--stream per-read budget seconds")
    ap.add_argument("--sched-seed", type=int, default=None,
                    help="arm TRPC_SCHED_SEED schedule replay before "
                         "traffic (deterministic incident reproduction)")
    ap.add_argument("--json", action="store_true",
                    help="print ONE machine-readable JSON line")
    args = ap.parse_args(argv)
    if args.stream:
        sres = replay_stream(args.server, args.dir, loops=args.loop,
                             concurrency=args.concurrency,
                             read_timeout_s=args.read_timeout,
                             sched_seed=args.sched_seed)
        print(sres.to_json_line() if args.json else sres.summary())
        return 1 if sres.errors and not sres.tokens else 0
    res = replay(args.server, args.dir, speed=args.speed, qps=args.qps,
                 loops=args.loop, concurrency=args.concurrency,
                 timeout_ms=args.timeout_ms, sched_seed=args.sched_seed)
    print(res.to_json_line() if args.json else res.summary())
    return 1 if res.errors and not res.admitted else 0


if __name__ == "__main__":
    raise SystemExit(main())
