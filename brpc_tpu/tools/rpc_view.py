"""rpc_view — proxy a remote server's builtin portal through a local port
(≙ reference tools/rpc_view: view builtin pages of a server that is only
reachable from this host).

    python -m brpc_tpu.tools.rpc_view --target 10.0.0.7:8000 --port 8888
    # then browse http://localhost:8888/status etc.
"""

from __future__ import annotations

import argparse
import urllib.error
import urllib.request
from typing import List, Optional

from brpc_tpu.rpc.http import HttpRequest, HttpResponse
from brpc_tpu.rpc.server import Server, ServerOptions


def make_proxy(target: str) -> Server:
    """A Server whose every HTTP path forwards to `target`'s portal."""
    srv = Server(ServerOptions(enable_builtin_services=False))

    def forward(req: HttpRequest) -> HttpResponse:
        url = f"http://{target}{req.path}"
        if req.query:
            url += "?" + req.query
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                return HttpResponse(
                    r.status,
                    {"Content-Type": r.headers.get(
                        "Content-Type", "text/plain")},
                    r.read())
        except urllib.error.HTTPError as e:
            return HttpResponse(e.code, {}, e.read())
        except OSError as e:
            return HttpResponse.text(f"cannot reach {target}: {e}\n", 502)

    srv.register_http("/", forward, prefix=True)
    return srv


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="portal proxy")
    ap.add_argument("--target", required=True, help="remote ip:port")
    ap.add_argument("--port", type=int, default=8888)
    args = ap.parse_args(argv)
    srv = make_proxy(args.target)
    srv.start(f"0.0.0.0:{args.port}")
    print(f"viewing {args.target} on http://localhost:{srv.port}/")
    try:
        import time
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.destroy()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
