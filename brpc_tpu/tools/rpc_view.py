"""rpc_view — proxy a remote server's builtin portal through a local port
(≙ reference tools/rpc_view: view builtin pages of a server that is only
reachable from this host).

    python -m brpc_tpu.tools.rpc_view --target 10.0.0.7:8000 --port 8888
    # then browse http://localhost:8888/status etc.

``--dump DIR`` instead renders a flight-recorder capture set (rpc_dump /
native dump segments) human-readably — one line per sample: timestamp,
method, payload/attachment sizes, codec/compress tags, trace id, stream
frame kind — the quick "what is in this capture?" look before replaying.
"""

from __future__ import annotations

import argparse
import urllib.error
import urllib.request
from typing import List, Optional


def make_proxy(target: str):
    """A Server whose every HTTP path forwards to `target`'s portal."""
    from brpc_tpu.rpc.http import HttpRequest, HttpResponse
    from brpc_tpu.rpc.server import Server, ServerOptions
    srv = Server(ServerOptions(enable_builtin_services=False))

    def forward(req: HttpRequest) -> HttpResponse:
        url = f"http://{target}{req.path}"
        if req.query:
            url += "?" + req.query
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                return HttpResponse(
                    r.status,
                    {"Content-Type": r.headers.get(
                        "Content-Type", "text/plain")},
                    r.read())
        except urllib.error.HTTPError as e:
            return HttpResponse(e.code, {}, e.read())
        except OSError as e:
            return HttpResponse.text(f"cannot reach {target}: {e}\n", 502)

    srv.register_http("/", forward, prefix=True)
    return srv


_FRAME_KINDS = {0: "unary", 1: "data", 2: "close", 3: "feedback"}


def format_sample(s) -> str:
    """One human line per captured sample (method, sizes, codec/compress
    tags, trace id, timestamp, stream frame kind)."""
    import datetime
    ts = datetime.datetime.fromtimestamp(s.timestamp).strftime(
        "%Y-%m-%d %H:%M:%S.%f") if s.timestamp else "-"
    kind = _FRAME_KINDS.get(s.stream_frame_type,
                            str(s.stream_frame_type))
    if s.stream_id and s.stream_frame_type == 0:
        kind = "stream-open"
    parts = [ts, f"{s.method or '-':<24}", kind,
             f"payload={len(s.payload)}B"]
    if s.attachment:
        parts.append(f"attach={len(s.attachment)}B")
    if s.compress_type:
        parts.append(f"compress={s.compress_type}")
    if s.payload_codec or s.attach_codec:
        parts.append(f"codec={s.payload_codec}/{s.attach_codec}")
    if s.trace_id:
        parts.append(f"trace={s.trace_id:016x}")
    if s.stream_id:
        parts.append(f"stream={s.stream_id}")
    return "  ".join(parts)


def view_dump(dump_dir: str) -> int:
    """Render every sample in a capture set, one line each, plus a
    trailing per-method tally.  Returns the sample count."""
    from collections import Counter

    from brpc_tpu.rpc.dump import SampleIterator
    n = 0
    by_method: Counter = Counter()
    for s in SampleIterator(dump_dir):
        print(format_sample(s))
        by_method[s.method or "-"] += 1
        n += 1
    if n:
        tally = ", ".join(f"{m}={c}" for m, c in by_method.most_common())
        print(f"-- {n} samples: {tally}")
    else:
        print(f"-- no samples under {dump_dir}")
    return n


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="portal proxy / dump viewer")
    ap.add_argument("--target", help="remote ip:port to proxy")
    ap.add_argument("--port", type=int, default=8888)
    ap.add_argument("--dump", metavar="DIR",
                    help="render a flight-recorder capture set instead "
                         "of proxying (one line per sample)")
    args = ap.parse_args(argv)
    if args.dump:
        view_dump(args.dump)
        return 0
    if not args.target:
        ap.error("--target is required unless --dump is given")
    srv = make_proxy(args.target)
    srv.start(f"0.0.0.0:{args.port}")
    print(f"viewing {args.target} on http://localhost:{srv.port}/")
    try:
        import time
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.destroy()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
