"""Device data plane — the PJRT-backed transport under ``tpu://`` endpoints.

Python face of ``native/src/tpu.{h,cc}`` (≙ the reference's RDMA transport,
``rdma/rdma_endpoint.h`` + ``rdma/block_pool.cpp``, re-designed for TPU):

* ``init()`` dlopens a PJRT C API plugin (``libtpu.so`` on TPU VMs; the
  plugin path can be forced with ``$TRPC_PJRT_PLUGIN``) and creates a
  client.  No JAX involvement — the native core talks PJRT directly.
* ``h2d()/d2h()`` move bytes host↔HBM through single DMA transfers whose
  completion events store 1 into a butex and wake waiting fibers
  (the butex↔device-event seam the north star names: a fiber awaiting a
  device transfer costs no thread).
* RPC attachments ride this plane zero-copy: a large attachment lands in
  ONE IOBuf block (Socket::frame_bytes_hint) and that block's memory is
  the DMA source — ``stats()["zero_copy_sends"]`` counts the pointer-
  identity transfers, ``gather_copies`` the multi-block sends that needed
  one gather (never silent).
* Channels to ``tpu://`` endpoints run an explicit handshake on the
  connection's first call (meta tag 14) and settle into ``device`` or
  ``fallback_tcp`` — visible via ``Channel.transport_state``, never a
  silent downgrade (≙ rdma_endpoint.h:95 FALLBACK_TCP).
"""

from __future__ import annotations

import ctypes
import errno
from typing import Dict, Optional

from brpc_tpu._native import lib

TRANSPORT_STATES = {0: "tcp", 1: "handshaking", 2: "device",
                    3: "fallback_tcp"}


def init(plugin_path: Optional[str] = None) -> bool:
    """Bring up the device plane; returns availability.  Idempotent.
    On failure the reason is in :func:`error` and callers fall back to
    TCP explicitly."""
    L = lib()
    L.trpc_tpu_plane_init(plugin_path.encode() if plugin_path else None)
    return bool(L.trpc_tpu_plane_available())


def available() -> bool:
    return bool(lib().trpc_tpu_plane_available())


def error() -> str:
    return (lib().trpc_tpu_plane_error() or b"").decode()


def platform() -> str:
    return (lib().trpc_tpu_plane_platform() or b"").decode()


def device_count() -> int:
    return lib().trpc_tpu_device_count()


class DeviceBuffer:
    """A byte buffer resident in HBM.  Handle semantics are versioned
    (ABA-safe) like SocketIds; ``free()`` is idempotent.

    Source lifetime is handled natively: the ctypes boundary copies the
    bytes once and the DMA's release hook frees the copy when the
    transfer is done — Python object lifetimes never gate the DMA."""

    __slots__ = ("_id", "_len")

    def __init__(self, buf_id: int, length: int):
        self._id = buf_id
        self._len = length

    def __len__(self) -> int:
        return self._len

    @property
    def handle(self) -> int:
        return self._id

    def wait(self, timeout_s: float = 30.0) -> None:
        """Block (fiber-friendly) until the buffer is resident in HBM."""
        rc = lib().trpc_tpu_buf_wait(self._id, int(timeout_s * 1e6))
        if rc == 0:
            return
        if rc == -errno.ETIMEDOUT:
            raise TimeoutError(f"device transfer not ready: rc={rc}")
        raise IOError(f"device transfer failed: rc={rc} ({error()})")

    def to_host(self) -> bytes:
        """DMA the buffer back to host memory."""
        L = lib()
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = L.trpc_tpu_d2h(self._id, ctypes.byref(out))
        if n < 0:
            raise IOError(f"d2h failed: rc={n} ({error()})")
        try:
            return ctypes.string_at(out, n)
        finally:
            L.trpc_tpu_buf_release(out)

    def free(self) -> None:
        lib().trpc_tpu_buf_free(self._id)

    def __enter__(self) -> "DeviceBuffer":
        return self

    def __exit__(self, *exc) -> None:
        self.free()


def h2d(data: bytes, device: int = 0) -> DeviceBuffer:
    """DMA ``data`` into HBM; returns immediately (transfer is async —
    ``wait()`` parks on the completion butex)."""
    if not available():
        raise RuntimeError(f"device plane unavailable: {error()}")
    buf_id = lib().trpc_tpu_h2d(data, len(data), device)
    if buf_id == 0:
        raise IOError(f"h2d failed: {error()}")
    return DeviceBuffer(buf_id, len(data))


def stats() -> Dict[str, int]:
    """Plane counters (feeds /vars via the native metrics seam)."""
    out = (ctypes.c_uint64 * 11)()
    lib().trpc_tpu_plane_stats(out)
    keys = ("h2d_transfers", "d2h_transfers", "h2d_bytes", "d2h_bytes",
            "events_fired", "gather_copies", "zero_copy_sends",
            "live_buffers", "errors", "d2d_transfers", "d2d_bytes")
    return dict(zip(keys, out))


def plane_uid() -> int:
    """Nonzero token identifying THIS process's PJRT client; exchanged in
    the tpu:// handshake so connections learn whether both ends share one
    client (enabling device-to-device stream frames)."""
    return lib().trpc_tpu_plane_uid()


def d2d(buf: DeviceBuffer, device: int) -> DeviceBuffer:
    """Copy a device buffer to another device of THIS client over the
    device fabric (PJRT CopyToDevice — no host landing zone).  Returns a
    new buffer; the source stays valid and still needs its own free()."""
    nb = lib().trpc_tpu_d2d(buf.handle, device)
    if nb == 0:
        raise IOError(f"d2d failed: {error()}")
    return DeviceBuffer(nb, len(buf))
