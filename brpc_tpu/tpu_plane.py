"""Device data plane — the PJRT-backed transport under ``tpu://`` endpoints.

Python face of ``native/src/tpu.{h,cc}`` (≙ the reference's RDMA transport,
``rdma/rdma_endpoint.h`` + ``rdma/block_pool.cpp``, re-designed for TPU):

* ``init()`` dlopens a PJRT C API plugin (``libtpu.so`` on TPU VMs; the
  plugin path can be forced with ``$TRPC_PJRT_PLUGIN``) and creates a
  client.  No JAX involvement — the native core talks PJRT directly.
* ``h2d()/d2h()`` move bytes host↔HBM through single DMA transfers whose
  completion events store 1 into a butex and wake waiting fibers
  (the butex↔device-event seam the north star names: a fiber awaiting a
  device transfer costs no thread).
* RPC attachments ride this plane zero-copy: a large attachment lands in
  ONE IOBuf block (Socket::frame_bytes_hint) and that block's memory is
  the DMA source — ``stats()["zero_copy_sends"]`` counts the pointer-
  identity transfers, ``gather_copies`` the multi-block sends that needed
  one gather (never silent).
* Channels to ``tpu://`` endpoints run an explicit handshake on the
  connection's first call (meta tag 14) and settle into ``device`` or
  ``fallback_tcp`` — visible via ``Channel.transport_state``, never a
  silent downgrade (≙ rdma_endpoint.h:95 FALLBACK_TCP).
"""

from __future__ import annotations

import ctypes
import errno
import threading
from typing import Dict, Optional

from brpc_tpu._native import lib

TRANSPORT_STATES = {0: "tcp", 1: "handshaking", 2: "device",
                    3: "fallback_tcp"}


def init(plugin_path: Optional[str] = None) -> bool:
    """Bring up the device plane; returns availability.  Idempotent.
    On failure the reason is in :func:`error` and callers fall back to
    TCP explicitly."""
    L = lib()
    L.trpc_tpu_plane_init(plugin_path.encode() if plugin_path else None)
    return bool(L.trpc_tpu_plane_available())


def available() -> bool:
    return bool(lib().trpc_tpu_plane_available())


def error() -> str:
    return (lib().trpc_tpu_plane_error() or b"").decode()


def platform() -> str:
    return (lib().trpc_tpu_plane_platform() or b"").decode()


def device_count() -> int:
    return lib().trpc_tpu_device_count()


class DeviceBuffer:
    """A byte buffer resident in HBM.  Handle semantics are versioned
    (ABA-safe) like SocketIds; ``free()`` is idempotent.

    Source lifetime is handled natively: the ctypes boundary copies the
    bytes once and the DMA's release hook frees the copy when the
    transfer is done — Python object lifetimes never gate the DMA."""

    __slots__ = ("_id", "_len")

    def __init__(self, buf_id: int, length: int):
        self._id = buf_id
        self._len = length

    def __len__(self) -> int:
        return self._len

    @property
    def handle(self) -> int:
        return self._id

    def wait(self, timeout_s: float = 30.0) -> None:
        """Block (fiber-friendly) until the buffer is resident in HBM."""
        rc = lib().trpc_tpu_buf_wait(self._id, int(timeout_s * 1e6))
        if rc == 0:
            return
        if rc == -errno.ETIMEDOUT:
            raise TimeoutError(f"device transfer not ready: rc={rc}")
        raise IOError(f"device transfer failed: rc={rc} ({error()})")

    def to_host(self) -> bytes:
        """DMA the buffer back to host memory."""
        L = lib()
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = L.trpc_tpu_d2h(self._id, ctypes.byref(out))
        if n < 0:
            raise IOError(f"d2h failed: rc={n} ({error()})")
        try:
            return ctypes.string_at(out, n)
        finally:
            L.trpc_tpu_buf_release(out)

    def free(self) -> None:
        lib().trpc_tpu_buf_free(self._id)

    def __enter__(self) -> "DeviceBuffer":
        return self

    def __exit__(self, *exc) -> None:
        self.free()


def h2d(data: bytes, device: int = 0) -> DeviceBuffer:
    """DMA ``data`` into HBM; returns immediately (transfer is async —
    ``wait()`` parks on the completion butex)."""
    if not available():
        raise RuntimeError(f"device plane unavailable: {error()}")
    buf_id = lib().trpc_tpu_h2d(data, len(data), device)
    if buf_id == 0:
        raise IOError(f"h2d failed: {error()}")
    return DeviceBuffer(buf_id, len(data))


def stats() -> Dict[str, int]:
    """Plane counters (feeds /vars via the native metrics seam)."""
    out = (ctypes.c_uint64 * 11)()
    lib().trpc_tpu_plane_stats(out)
    keys = ("h2d_transfers", "d2h_transfers", "h2d_bytes", "d2h_bytes",
            "events_fired", "gather_copies", "zero_copy_sends",
            "live_buffers", "errors", "d2d_transfers", "d2d_bytes")
    return dict(zip(keys, out))


def plane_uid() -> int:
    """Nonzero token identifying THIS process's PJRT client; exchanged in
    the tpu:// handshake so connections learn whether both ends share one
    client (enabling device-to-device stream frames)."""
    return lib().trpc_tpu_plane_uid()


def d2d(buf: DeviceBuffer, device: int) -> DeviceBuffer:
    """Copy a device buffer to another device of THIS client over the
    device fabric (PJRT CopyToDevice — no host landing zone).  Returns a
    new buffer; the source stays valid and still needs its own free()."""
    nb = lib().trpc_tpu_d2d(buf.handle, device)
    if nb == 0:
        raise IOError(f"d2d failed: {error()}")
    return DeviceBuffer(nb, len(buf))


class PoolExhausted(Exception):
    """alloc() against a pool whose block budget is spent — the caller
    sheds or preempts; the pool NEVER queues (admission control happens
    above the device plane, before any DMA is issued)."""


class DeviceBufPool:
    """Budgeted fixed-size-block allocator over the plane's DeviceBuffers
    (≙ the reference's rdma/block_pool.cpp: a hard block budget with
    every allocation charged against it, re-designed: blocks are HBM
    DeviceBuffers and migration is a PJRT d2d hop instead of an ibverbs
    MR hand-off).

    Hard accounting: every `alloc()` charges one block until `free()`;
    `migrate()` moves a block between devices without changing the
    charge (the source is freed as soon as the copy is enqueued).
    `assert_balanced()` proves nothing leaked — the serving plane calls
    it after every drain and the suite calls it after every cancel leg.

    Thread-safe: the ledger mutates under one lock; DMA waits happen
    outside it."""

    def __init__(self, block_bytes: int, max_blocks: int):
        if block_bytes <= 0 or max_blocks <= 0:
            raise ValueError("block_bytes and max_blocks must be positive")
        self.block_bytes = block_bytes
        self.max_blocks = max_blocks
        self._lock = threading.Lock()
        self._live: Dict[int, DeviceBuffer] = {}   # handle -> buffer
        self._allocs = 0
        self._frees = 0
        self._migrations = 0
        self._exhausted = 0

    # -- ledger -------------------------------------------------------------

    @property
    def used_blocks(self) -> int:
        with self._lock:
            return len(self._live)

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return self.max_blocks - len(self._live)

    def pool_stats(self) -> Dict[str, int]:
        with self._lock:
            return {"block_bytes": self.block_bytes,
                    "max_blocks": self.max_blocks,
                    "used_blocks": len(self._live),
                    "allocs": self._allocs, "frees": self._frees,
                    "migrations": self._migrations,
                    "exhausted": self._exhausted}

    def assert_balanced(self) -> None:
        """Every charged block was freed; raises with the leak count
        otherwise (the serving accounting proof rides on this)."""
        with self._lock:
            leaked = len(self._live)
        if leaked:
            raise AssertionError(
                f"DeviceBufPool leaked {leaked} block(s): "
                f"allocs={self._allocs} frees={self._frees}")

    # -- data path ----------------------------------------------------------

    def alloc(self, data: bytes, device: int = 0) -> DeviceBuffer:
        """Charge one block and DMA `data` (at most block_bytes, padded
        to the block size so every block is pool-shaped) onto `device`.
        Raises PoolExhausted when the budget is spent — callers shed
        BEFORE this ever queues."""
        if len(data) > self.block_bytes:
            raise ValueError(
                f"block payload {len(data)} > block_bytes "
                f"{self.block_bytes}")
        with self._lock:
            if len(self._live) >= self.max_blocks:
                self._exhausted += 1
                raise PoolExhausted(
                    f"block budget spent ({self.max_blocks} blocks)")
            self._allocs += 1
        pad = self.block_bytes - len(data)
        try:
            buf = h2d(data + b"\x00" * pad, device)
        except Exception:
            with self._lock:
                self._allocs -= 1
            raise
        with self._lock:
            self._live[buf.handle] = buf
        return buf

    def migrate(self, buf: DeviceBuffer, device: int) -> DeviceBuffer:
        """Move a charged block to `device` over the d2d fabric; the
        charge transfers to the new buffer and the source is freed.  On
        d2d failure the source stays charged and valid."""
        with self._lock:
            if buf.handle not in self._live:
                raise KeyError("migrate() of a buffer not in this pool")
        nb = d2d(buf, device)
        with self._lock:
            del self._live[buf.handle]
            self._live[nb.handle] = nb
            self._migrations += 1
        buf.free()
        return nb

    def adopt(self, buf: DeviceBuffer) -> DeviceBuffer:
        """Charge an externally-created DeviceBuffer (e.g. a host-rail
        re-upload) against this pool's budget.  Raises PoolExhausted
        rather than over-committing; the buffer is NOT freed on refusal
        (it was never ours)."""
        with self._lock:
            if len(self._live) >= self.max_blocks:
                self._exhausted += 1
                raise PoolExhausted(
                    f"block budget spent ({self.max_blocks} blocks)")
            self._allocs += 1
            self._live[buf.handle] = buf
        return buf

    def release(self, buf: DeviceBuffer) -> None:
        """Un-charge a block WITHOUT freeing the underlying buffer —
        ownership leaves the pool (e.g. handed to stream.write_device,
        which consumes the buffer on success)."""
        with self._lock:
            if self._live.pop(buf.handle, None) is not None:
                self._frees += 1

    def free(self, buf: DeviceBuffer) -> None:
        """Return a block: idempotent, like DeviceBuffer.free itself."""
        with self._lock:
            if self._live.pop(buf.handle, None) is None:
                return
            self._frees += 1
        buf.free()

    def free_all(self) -> None:
        """Drop every outstanding block (teardown path)."""
        with self._lock:
            live = list(self._live.values())
            self._live.clear()
            self._frees += len(live)
        for b in live:
            b.free()
