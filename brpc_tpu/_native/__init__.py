"""ctypes loader for the native core (libbrpc_tpu_core.so).

Builds the library on first import if it is missing (cmake+ninja via
native/build.sh).  The native core provides the hot paths: IOBuf, the M:N
fiber scheduler with butex (≙ reference src/bthread), and — as later layers
land — sockets, the TRPC wire protocol, and the in-process bench loops.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_SO = os.path.join(_HERE, "libbrpc_tpu_core.so")

_lib = None
_lib_lock = threading.Lock()

FIBER_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)

# HTTP request dispatcher: (token, verb, path, query, headers, headers_len,
# body, body_len, user) — headers is "lower-key: value\n" lines
HTTP_FN = ctypes.CFUNCTYPE(
    None, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t, ctypes.c_void_p)

# Progressive body chunk callback of the HTTP client (user, data, len)
HTTP_CHUNK_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t)


def _build() -> None:
    script = os.path.join(_REPO, "native", "build.sh")
    subprocess.run(["bash", script], check=True, capture_output=True)


def lib() -> ctypes.CDLL:
    """The loaded native library (building it if needed)."""
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO):
            _build()
        L = ctypes.CDLL(_SO)
        _declare(L)
        _lib = L
        return L


def _declare(L: ctypes.CDLL) -> None:
    c = ctypes
    L.trpc_init.argtypes = [c.c_int]
    L.trpc_init.restype = c.c_int
    L.trpc_workers.restype = c.c_int
    L.trpc_runtime_stats.argtypes = [c.POINTER(c.c_uint64)]
    L.trpc_runtime_stats.restype = None

    L.trpc_fiber_start.argtypes = [c.POINTER(c.c_uint64), FIBER_FN, c.c_void_p]
    L.trpc_fiber_start.restype = c.c_int
    L.trpc_fiber_join.argtypes = [c.c_uint64]
    L.trpc_fiber_join.restype = c.c_int
    L.trpc_fiber_start_bound.argtypes = [c.c_int, c.POINTER(c.c_uint64),
                                         FIBER_FN, c.c_void_p]
    L.trpc_fiber_start_bound.restype = c.c_int
    L.trpc_fiber_jump_group.argtypes = [c.c_int]
    L.trpc_fiber_jump_group.restype = c.c_int
    L.trpc_fiber_worker_index.argtypes = []
    L.trpc_fiber_worker_index.restype = c.c_int
    L.trpc_fiber_register_worker_hook.argtypes = [c.c_void_p, c.c_void_p]
    L.trpc_fiber_register_worker_hook.restype = c.c_int
    L.trpc_fiber_key_create.argtypes = [c.POINTER(c.c_uint64), c.c_void_p]
    L.trpc_fiber_key_create.restype = c.c_int
    L.trpc_fiber_key_delete.argtypes = [c.c_uint64]
    L.trpc_fiber_key_delete.restype = c.c_int
    L.trpc_fiber_setspecific.argtypes = [c.c_uint64, c.c_void_p]
    L.trpc_fiber_setspecific.restype = c.c_int
    L.trpc_fiber_getspecific.argtypes = [c.c_uint64]
    L.trpc_fiber_getspecific.restype = c.c_void_p
    L.trpc_fiber_yield.restype = None
    L.trpc_fiber_usleep.argtypes = [c.c_int64]
    L.trpc_fiber_usleep.restype = None
    L.trpc_in_fiber.restype = c.c_int

    L.trpc_butex_create.restype = c.c_void_p
    L.trpc_butex_destroy.argtypes = [c.c_void_p]
    L.trpc_butex_destroy.restype = None
    L.trpc_butex_load.argtypes = [c.c_void_p]
    L.trpc_butex_load.restype = c.c_int32
    L.trpc_butex_store.argtypes = [c.c_void_p, c.c_int32]
    L.trpc_butex_store.restype = None
    L.trpc_butex_add.argtypes = [c.c_void_p, c.c_int32]
    L.trpc_butex_add.restype = c.c_int32
    L.trpc_butex_wait.argtypes = [c.c_void_p, c.c_int32, c.c_int64]
    L.trpc_butex_wait.restype = c.c_int
    L.trpc_butex_wake.argtypes = [c.c_void_p]
    L.trpc_butex_wake.restype = c.c_int
    L.trpc_butex_wake_all.argtypes = [c.c_void_p]
    L.trpc_butex_wake_all.restype = c.c_int

    # server
    L.trpc_server_create.restype = c.c_void_p
    L.trpc_server_add_echo.argtypes = [c.c_void_p]
    L.trpc_server_add_echo.restype = c.c_int
    L.trpc_server_add_service.argtypes = [c.c_void_p, c.c_char_p,
                                          c.c_void_p, c.c_void_p]
    L.trpc_server_add_service.restype = c.c_int
    L.trpc_server_start.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
    L.trpc_server_start.restype = c.c_int
    L.trpc_server_port.argtypes = [c.c_void_p]
    L.trpc_server_port.restype = c.c_int
    L.trpc_server_stop.argtypes = [c.c_void_p]
    L.trpc_server_stop.restype = c.c_int
    L.trpc_server_destroy.argtypes = [c.c_void_p]
    L.trpc_server_destroy.restype = None
    L.trpc_server_requests.argtypes = [c.c_void_p]
    L.trpc_server_requests.restype = c.c_uint64
    L.trpc_respond.argtypes = [c.c_uint64, c.c_int32, c.c_char_p,
                               c.c_char_p, c.c_size_t, c.c_char_p,
                               c.c_size_t]
    L.trpc_respond.restype = c.c_int
    L.trpc_respond_compressed.argtypes = [c.c_uint64, c.c_int32, c.c_char_p,
                                          c.c_char_p, c.c_size_t, c.c_char_p,
                                          c.c_size_t, c.c_int]
    L.trpc_respond_compressed.restype = c.c_int
    L.trpc_token_compress.argtypes = [c.c_uint64]
    L.trpc_token_compress.restype = c.c_int
    # pluggable-Authenticator surface (rpc/auth.py)
    L.trpc_token_auth.argtypes = [c.c_uint64, c.c_char_p, c.c_size_t]
    L.trpc_token_auth.restype = c.c_size_t
    L.trpc_token_peer.argtypes = [c.c_uint64, c.c_char_p, c.c_size_t]
    L.trpc_token_peer.restype = c.c_size_t

    # HTTP on the shared port
    L.trpc_server_set_http_handler.argtypes = [c.c_void_p, c.c_void_p,
                                               c.c_void_p]
    L.trpc_server_set_http_handler.restype = None
    L.trpc_http_respond.argtypes = [c.c_uint64, c.c_int, c.c_char_p,
                                    c.c_char_p, c.c_size_t]
    L.trpc_http_respond.restype = c.c_int
    L.trpc_http_respond_trailers.argtypes = [c.c_uint64, c.c_int,
                                             c.c_char_p, c.c_char_p,
                                             c.c_size_t, c.c_char_p]
    L.trpc_http_respond_trailers.restype = c.c_int

    # redis on the shared port
    L.trpc_server_set_redis_handler.argtypes = [c.c_void_p, c.c_void_p,
                                                c.c_void_p]
    L.trpc_server_set_redis_handler.restype = None
    L.trpc_redis_respond.argtypes = [c.c_uint64, c.c_char_p, c.c_size_t]
    L.trpc_redis_respond.restype = c.c_int

    # framed thrift on the shared port
    L.trpc_server_set_thrift_handler.argtypes = [c.c_void_p, c.c_void_p,
                                                 c.c_void_p]
    L.trpc_server_set_thrift_handler.restype = None
    L.trpc_thrift_respond.argtypes = [c.c_uint64, c.c_char_p, c.c_size_t]
    L.trpc_thrift_respond.restype = c.c_int

    # user-registered protocols on the shared port
    L.trpc_server_register_protocol.argtypes = [
        c.c_void_p, c.c_char_p, c.c_char_p, c.c_size_t, c.c_void_p,
        c.c_void_p, c.c_void_p]
    L.trpc_server_register_protocol.restype = c.c_int
    L.trpc_proto_respond.argtypes = [c.c_uint64, c.c_char_p, c.c_size_t]
    L.trpc_proto_respond.restype = c.c_int

    # HTTP/2 client
    L.trpc_h2_client_create.argtypes = [c.c_char_p, c.c_int, c.c_int64,
                                        c.POINTER(c.c_int)]
    L.trpc_h2_client_create.restype = c.c_void_p
    L.trpc_h2_client_create_tls.argtypes = [c.c_char_p, c.c_int, c.c_int64,
                                            c.c_int, c.c_char_p,
                                            c.POINTER(c.c_int)]
    L.trpc_h2_client_create_tls.restype = c.c_void_p
    L.trpc_h2_client_call.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p,
                                      c.c_char_p, c.c_char_p, c.c_size_t,
                                      c.c_int64, c.POINTER(c.c_void_p)]
    L.trpc_h2_client_call.restype = c.c_int
    L.trpc_h2_result_status.argtypes = [c.c_void_p]
    L.trpc_h2_result_status.restype = c.c_int
    for f in ("headers", "body", "trailers"):
        fn = getattr(L, f"trpc_h2_result_{f}")
        fn.argtypes = [c.c_void_p, c.POINTER(c.POINTER(c.c_uint8))]
        fn.restype = c.c_size_t
    L.trpc_h2_result_destroy.argtypes = [c.c_void_p]
    L.trpc_h2_result_destroy.restype = None
    L.trpc_h2_client_destroy.argtypes = [c.c_void_p]
    L.trpc_h2_client_destroy.restype = None

    # progressive (chunked) HTTP responses
    L.trpc_http_respond_progressive.argtypes = [c.c_uint64, c.c_int,
                                                c.c_char_p]
    L.trpc_http_respond_progressive.restype = c.c_uint64
    L.trpc_pa_write.argtypes = [c.c_uint64, c.c_char_p, c.c_size_t]
    L.trpc_pa_write.restype = c.c_int
    L.trpc_pa_close.argtypes = [c.c_uint64]
    L.trpc_pa_close.restype = c.c_int
    L.trpc_pa_close_trailers.argtypes = [c.c_uint64, c.c_char_p]
    L.trpc_pa_close_trailers.restype = c.c_int

    # auth
    L.trpc_server_set_auth.argtypes = [c.c_void_p, c.c_char_p, c.c_size_t]
    L.trpc_server_set_auth.restype = None
    L.trpc_channel_set_auth.argtypes = [c.c_void_p, c.c_char_p, c.c_size_t]
    L.trpc_channel_set_auth.restype = None
    L.trpc_channel_set_connection_type.argtypes = [c.c_void_p, c.c_int]
    L.trpc_channel_set_connection_type.restype = None

    # introspection
    L.trpc_server_conn_stats.argtypes = [c.c_void_p, c.c_char_p, c.c_size_t]
    L.trpc_server_conn_stats.restype = c.c_size_t
    L.trpc_socket_dump.argtypes = [c.c_char_p, c.c_size_t]
    L.trpc_socket_dump.restype = c.c_size_t
    L.trpc_ids_dump.argtypes = [c.c_char_p, c.c_size_t]
    L.trpc_ids_dump.restype = c.c_size_t

    # io_uring transport
    L.trpc_set_io_uring.argtypes = [c.c_int]
    L.trpc_set_io_uring.restype = None
    L.trpc_io_uring_available.argtypes = []
    L.trpc_io_uring_available.restype = c.c_int
    L.trpc_set_sendzc.argtypes = [c.c_int]
    L.trpc_set_sendzc.restype = None
    L.trpc_set_sendzc_threshold.argtypes = [c.c_uint64]
    L.trpc_set_sendzc_threshold.restype = None
    L.trpc_sendzc_available.argtypes = []
    L.trpc_sendzc_available.restype = c.c_int
    L.trpc_sendzc_active.argtypes = []
    L.trpc_sendzc_active.restype = c.c_int

    # crc32c
    L.trpc_crc32c_extend.argtypes = [c.c_uint32, c.c_char_p, c.c_size_t]
    L.trpc_crc32c_extend.restype = c.c_uint32
    L.trpc_crc32c_hardware.argtypes = []
    L.trpc_crc32c_hardware.restype = c.c_int

    # snappy codec
    L.trpc_snappy_max_compressed_length.argtypes = [c.c_size_t]
    L.trpc_snappy_max_compressed_length.restype = c.c_size_t
    L.trpc_snappy_compress.argtypes = [c.c_char_p, c.c_size_t, c.c_char_p]
    L.trpc_snappy_compress.restype = c.c_size_t
    L.trpc_snappy_uncompressed_length.argtypes = [c.c_char_p, c.c_size_t]
    L.trpc_snappy_uncompressed_length.restype = c.c_size_t
    L.trpc_snappy_decompress.argtypes = [c.c_char_p, c.c_size_t, c.c_char_p,
                                         c.c_size_t]
    L.trpc_snappy_decompress.restype = c.c_size_t

    # payload-codec rail (native/src/codec.h)
    L.trpc_set_payload_codec.argtypes = [c.c_int]
    L.trpc_set_payload_codec.restype = None
    L.trpc_payload_codec.restype = c.c_int
    L.trpc_set_codec_min_bytes.argtypes = [c.c_int64]
    L.trpc_set_codec_min_bytes.restype = None
    L.trpc_codec_id.argtypes = [c.c_char_p]
    L.trpc_codec_id.restype = c.c_int
    L.trpc_codec_name.argtypes = [c.c_int]
    L.trpc_codec_name.restype = c.c_char_p
    L.trpc_codec_encode.argtypes = [c.c_int, c.c_char_p, c.c_size_t,
                                    c.POINTER(c.POINTER(c.c_uint8)),
                                    c.POINTER(c.c_int)]
    L.trpc_codec_encode.restype = c.c_int64
    L.trpc_codec_decode.argtypes = [c.c_int, c.c_char_p, c.c_size_t,
                                    c.POINTER(c.POINTER(c.c_uint8))]
    L.trpc_codec_decode.restype = c.c_int64
    L.trpc_codec_buf_free.argtypes = [c.POINTER(c.c_uint8)]
    L.trpc_codec_buf_free.restype = None
    L.trpc_codec_roundtrip_chained.argtypes = [c.c_int, c.c_char_p,
                                               c.c_size_t, c.c_size_t,
                                               c.POINTER(c.c_double)]
    L.trpc_codec_roundtrip_chained.restype = c.c_int

    L.trpc_set_usercode_workers.argtypes = [c.c_int]
    L.trpc_set_usercode_workers.restype = None
    L.trpc_set_event_dispatcher_num.argtypes = [c.c_int]
    L.trpc_set_event_dispatcher_num.restype = None

    # runtime sharding (native/src/shard.h): boot-frozen shard count +
    # SO_REUSEPORT listener gate + cross-shard hop counter
    L.trpc_set_shards.argtypes = [c.c_int]
    L.trpc_set_shards.restype = c.c_int
    L.trpc_shard_count.restype = c.c_int
    L.trpc_set_reuseport.argtypes = [c.c_int]
    L.trpc_set_reuseport.restype = c.c_int
    L.trpc_reuseport_enabled.restype = c.c_int
    L.trpc_current_shard.restype = c.c_int
    L.trpc_cross_shard_hops.restype = c.c_uint64

    # channel
    L.trpc_channel_create.argtypes = [c.c_char_p, c.c_int]
    L.trpc_channel_create.restype = c.c_void_p
    L.trpc_channel_destroy.argtypes = [c.c_void_p]
    L.trpc_channel_destroy.restype = None
    L.trpc_channel_set_connect_timeout.argtypes = [c.c_void_p, c.c_int64]
    L.trpc_channel_set_connect_timeout.restype = None
    L.trpc_channel_call.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p,
                                    c.c_size_t, c.c_char_p, c.c_size_t,
                                    c.c_int64, c.POINTER(c.c_void_p)]
    L.trpc_channel_call.restype = c.c_int
    L.trpc_channel_call_compressed.argtypes = [
        c.c_void_p, c.c_char_p, c.c_char_p, c.c_size_t, c.c_char_p,
        c.c_size_t, c.c_int64, c.c_int, c.POINTER(c.c_void_p)]
    L.trpc_channel_call_compressed.restype = c.c_int
    # replay rail (native/src/dump.h): wire-form bytes from a captured
    # sample, codec tags 16/17 stamped verbatim, encode skipped
    L.trpc_channel_call_raw.argtypes = [
        c.c_void_p, c.c_char_p, c.c_char_p, c.c_size_t, c.c_char_p,
        c.c_size_t, c.c_int64, c.c_int, c.c_int, c.c_int,
        c.POINTER(c.c_void_p)]
    L.trpc_channel_call_raw.restype = c.c_int
    L.trpc_result_compress.argtypes = [c.c_void_p]
    L.trpc_result_compress.restype = c.c_int
    L.trpc_result_error_code.argtypes = [c.c_void_p]
    L.trpc_result_error_code.restype = c.c_int32
    L.trpc_result_error_text.argtypes = [c.c_void_p]
    L.trpc_result_error_text.restype = c.c_char_p
    L.trpc_result_data.argtypes = [c.c_void_p,
                                   c.POINTER(c.POINTER(c.c_uint8))]
    L.trpc_result_data.restype = c.c_size_t
    L.trpc_result_attachment.argtypes = [c.c_void_p,
                                         c.POINTER(c.POINTER(c.c_uint8))]
    L.trpc_result_attachment.restype = c.c_size_t
    L.trpc_result_destroy.argtypes = [c.c_void_p]
    L.trpc_result_destroy.restype = None

    # streaming RPC
    L.trpc_channel_call_stream.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p,
                                           c.c_size_t, c.c_char_p, c.c_size_t,
                                           c.c_int64, c.c_uint64,
                                           c.POINTER(c.c_void_p)]
    L.trpc_channel_call_stream.restype = c.c_int
    L.trpc_stream_create.argtypes = [c.c_uint64]
    L.trpc_stream_create.restype = c.c_uint64
    L.trpc_token_stream_id.argtypes = [c.c_uint64]
    L.trpc_token_stream_id.restype = c.c_uint64
    L.trpc_stream_accept.argtypes = [c.c_uint64, c.c_uint64]
    L.trpc_stream_accept.restype = c.c_uint64
    L.trpc_stream_write.argtypes = [c.c_uint64, c.c_char_p, c.c_size_t,
                                    c.c_int64]
    L.trpc_stream_write.restype = c.c_int
    L.trpc_stream_read.argtypes = [c.c_uint64, c.c_int64,
                                   c.POINTER(c.POINTER(c.c_uint8))]
    L.trpc_stream_read.restype = c.c_int64
    L.trpc_stream_buf_free.argtypes = [c.POINTER(c.c_uint8)]
    L.trpc_stream_buf_free.restype = None
    L.trpc_stream_close.argtypes = [c.c_uint64]
    L.trpc_stream_close.restype = c.c_int
    L.trpc_stream_rst.argtypes = [c.c_uint64, c.c_int32]
    L.trpc_stream_rst.restype = c.c_int
    L.trpc_stream_rst_code.argtypes = [c.c_uint64]
    L.trpc_stream_rst_code.restype = c.c_int32
    L.trpc_stream_destroy.argtypes = [c.c_uint64]
    L.trpc_stream_destroy.restype = None
    L.trpc_stream_remote_closed.argtypes = [c.c_uint64]
    L.trpc_stream_remote_closed.restype = c.c_int
    L.trpc_stream_failed.argtypes = [c.c_uint64]
    L.trpc_stream_failed.restype = c.c_int
    L.trpc_stream_pending_bytes.argtypes = [c.c_uint64]
    L.trpc_stream_pending_bytes.restype = c.c_int64

    L.trpc_set_usercode_max_inflight.argtypes = [c.c_int64]
    L.trpc_set_usercode_max_inflight.restype = None

    # overload-control plane (native/src/overload.h): reloadable master
    # switch + gradient clamps, folded per-family reads for /status, the
    # per-method max_concurrency table, and the deterministic test hooks
    L.trpc_set_overload.argtypes = [c.c_int]
    L.trpc_set_overload.restype = None
    L.trpc_overload_active.argtypes = []
    L.trpc_overload_active.restype = c.c_int
    L.trpc_set_overload_min_concurrency.argtypes = [c.c_int]
    L.trpc_set_overload_min_concurrency.restype = None
    L.trpc_set_overload_max_concurrency.argtypes = [c.c_int]
    L.trpc_set_overload_max_concurrency.restype = None
    L.trpc_set_overload_window_ms.argtypes = [c.c_int]
    L.trpc_set_overload_window_ms.restype = None
    L.trpc_overload_limit.argtypes = [c.c_int]
    L.trpc_overload_limit.restype = c.c_int64
    L.trpc_overload_inflight.argtypes = [c.c_int]
    L.trpc_overload_inflight.restype = c.c_int64
    L.trpc_overload_rejects.argtypes = [c.c_int]
    L.trpc_overload_rejects.restype = c.c_uint64
    L.trpc_overload_admits.argtypes = [c.c_int]
    L.trpc_overload_admits.restype = c.c_uint64
    L.trpc_server_set_method_max_concurrency.argtypes = [
        c.c_void_p, c.c_char_p, c.c_int64]
    L.trpc_server_set_method_max_concurrency.restype = c.c_int
    L.trpc_overload_test_feed.argtypes = [c.c_int, c.c_int, c.c_int64,
                                          c.c_int, c.c_int64]
    L.trpc_overload_test_feed.restype = None
    L.trpc_overload_test_reset.argtypes = [c.c_int, c.c_int]
    L.trpc_overload_test_reset.restype = None

    # client egress fast path: request corking + serialize-once fan-out
    L.trpc_set_client_cork.argtypes = [c.c_int]
    L.trpc_set_client_cork.restype = None
    L.trpc_client_cork_active.argtypes = []
    L.trpc_client_cork_active.restype = c.c_int
    L.trpc_fanout_call.argtypes = [
        c.POINTER(c.c_void_p), c.c_int, c.c_char_p, c.c_char_p, c.c_size_t,
        c.c_char_p, c.c_size_t, c.c_int64, c.POINTER(c.c_void_p)]
    L.trpc_fanout_call.restype = c.c_int

    # million-connection ingress: accept-storm pacing + memory diet
    L.trpc_set_accept_rate.argtypes = [c.c_int]
    L.trpc_set_accept_rate.restype = None
    L.trpc_set_accept_burst.argtypes = [c.c_int]
    L.trpc_set_accept_burst.restype = None
    L.trpc_set_accept_max_pending.argtypes = [c.c_int]
    L.trpc_set_accept_max_pending.restype = None
    L.trpc_set_idle_kick_ms.argtypes = [c.c_int]
    L.trpc_set_idle_kick_ms.restype = None

    # ingress fast path: run-to-completion dispatch + response corking
    L.trpc_set_inline_dispatch.argtypes = [c.c_int]
    L.trpc_set_inline_dispatch.restype = None
    L.trpc_inline_dispatch_active.argtypes = []
    L.trpc_inline_dispatch_active.restype = c.c_int
    L.trpc_set_inline_budget_requests.argtypes = [c.c_int]
    L.trpc_set_inline_budget_requests.restype = None
    L.trpc_set_inline_budget_us.argtypes = [c.c_int64]
    L.trpc_set_inline_budget_us.restype = None
    L.trpc_token_arm_ns.argtypes = [c.c_uint64]
    L.trpc_token_arm_ns.restype = c.c_int64

    # deadline-budget propagation (ISSUE 19)
    L.trpc_set_deadline_propagate.argtypes = [c.c_int]
    L.trpc_set_deadline_propagate.restype = None
    L.trpc_deadline_propagate_active.argtypes = []
    L.trpc_deadline_propagate_active.restype = c.c_int
    L.trpc_set_deadline_reserve_us.argtypes = [c.c_int64]
    L.trpc_set_deadline_reserve_us.restype = None
    L.trpc_deadline_reserve_us.argtypes = []
    L.trpc_deadline_reserve_us.restype = c.c_int64
    L.trpc_token_deadline_left_us.argtypes = [c.c_uint64,
                                              c.POINTER(c.c_int64)]
    L.trpc_token_deadline_left_us.restype = c.c_int
    L.trpc_server_enable_redis_cache.argtypes = [c.c_void_p]
    L.trpc_server_enable_redis_cache.restype = c.c_int
    L.trpc_server_http_cache_put.argtypes = [c.c_void_p, c.c_char_p,
                                             c.c_int, c.c_char_p,
                                             c.c_char_p, c.c_size_t]
    L.trpc_server_http_cache_put.restype = c.c_int

    # TLS (tls.h)
    L.trpc_tls_available.restype = c.c_int
    L.trpc_tls_error.restype = c.c_char_p
    L.trpc_server_set_tls.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p,
                                      c.c_char_p]
    L.trpc_server_set_tls.restype = c.c_int
    L.trpc_channel_set_tls.argtypes = [c.c_void_p, c.c_int, c.c_char_p,
                                       c.c_char_p, c.c_char_p]
    L.trpc_channel_set_tls.restype = c.c_int

    # fiber sync primitives (fiber_sync.h)
    L.trpc_mutex_create.restype = c.c_void_p
    L.trpc_mutex_destroy.argtypes = [c.c_void_p]
    L.trpc_mutex_destroy.restype = None
    L.trpc_mutex_lock.argtypes = [c.c_void_p]
    L.trpc_mutex_lock.restype = None
    L.trpc_mutex_trylock.argtypes = [c.c_void_p]
    L.trpc_mutex_trylock.restype = c.c_int
    L.trpc_mutex_unlock.argtypes = [c.c_void_p]
    L.trpc_mutex_unlock.restype = None
    L.trpc_cond_create.restype = c.c_void_p
    L.trpc_cond_destroy.argtypes = [c.c_void_p]
    L.trpc_cond_destroy.restype = None
    L.trpc_cond_wait.argtypes = [c.c_void_p, c.c_void_p, c.c_int64]
    L.trpc_cond_wait.restype = c.c_int
    L.trpc_cond_notify_one.argtypes = [c.c_void_p]
    L.trpc_cond_notify_one.restype = None
    L.trpc_cond_notify_all.argtypes = [c.c_void_p]
    L.trpc_cond_notify_all.restype = None
    L.trpc_countdown_create.argtypes = [c.c_int]
    L.trpc_countdown_create.restype = c.c_void_p
    L.trpc_countdown_destroy.argtypes = [c.c_void_p]
    L.trpc_countdown_destroy.restype = None
    L.trpc_countdown_signal.argtypes = [c.c_void_p, c.c_int]
    L.trpc_countdown_signal.restype = None
    L.trpc_countdown_add.argtypes = [c.c_void_p, c.c_int]
    L.trpc_countdown_add.restype = None
    L.trpc_countdown_wait.argtypes = [c.c_void_p, c.c_int64]
    L.trpc_countdown_wait.restype = c.c_int
    L.trpc_rwlock_create.restype = c.c_void_p
    L.trpc_rwlock_destroy.argtypes = [c.c_void_p]
    L.trpc_rwlock_destroy.restype = None
    L.trpc_rwlock_rdlock.argtypes = [c.c_void_p]
    L.trpc_rwlock_rdlock.restype = None
    L.trpc_rwlock_rdunlock.argtypes = [c.c_void_p]
    L.trpc_rwlock_rdunlock.restype = None
    L.trpc_rwlock_wrlock.argtypes = [c.c_void_p]
    L.trpc_rwlock_wrlock.restype = None
    L.trpc_rwlock_wrunlock.argtypes = [c.c_void_p]
    L.trpc_rwlock_wrunlock.restype = None

    # native metrics seam + profiler (metrics.h, profiler.h)
    L.trpc_native_metrics_dump.argtypes = [c.c_char_p, c.c_size_t]
    L.trpc_native_metrics_dump.restype = c.c_size_t
    # hot-path telemetry plane (metrics.h, ISSUE 9): per-shard latency
    # histograms, native rpcz span rings, cross-hop trace context
    L.trpc_set_telemetry.argtypes = [c.c_int]
    L.trpc_set_telemetry.restype = None
    L.trpc_telemetry_active.argtypes = []
    L.trpc_telemetry_active.restype = c.c_int
    L.trpc_telemetry_percentile_us.argtypes = [c.c_int, c.c_double]
    L.trpc_telemetry_percentile_us.restype = c.c_int64
    L.trpc_telemetry_count.argtypes = [c.c_int]
    L.trpc_telemetry_count.restype = c.c_uint64
    L.trpc_telemetry_inflight.argtypes = [c.c_int]
    L.trpc_telemetry_inflight.restype = c.c_int64
    L.trpc_telemetry_family_name.argtypes = [c.c_int]
    L.trpc_telemetry_family_name.restype = c.c_char_p
    L.trpc_telemetry_families.argtypes = []
    L.trpc_telemetry_families.restype = c.c_int
    L.trpc_telemetry_prom_dump.argtypes = [c.c_char_p, c.c_size_t]
    L.trpc_telemetry_prom_dump.restype = c.c_size_t
    L.trpc_set_rpcz.argtypes = [c.c_int]
    L.trpc_set_rpcz.restype = None
    L.trpc_rpcz_active.argtypes = []
    L.trpc_rpcz_active.restype = c.c_int
    L.trpc_set_rpcz_budget.argtypes = [c.c_int64]
    L.trpc_set_rpcz_budget.restype = None
    L.trpc_rpcz_drain.argtypes = [c.c_char_p, c.c_size_t]
    L.trpc_rpcz_drain.restype = c.c_size_t
    # native flight recorder (native/src/dump.h): wire-form traffic
    # capture on the fast paths + length-prefixed v2 sample drain
    L.trpc_set_dump.argtypes = [c.c_int]
    L.trpc_set_dump.restype = None
    L.trpc_dump_active.argtypes = []
    L.trpc_dump_active.restype = c.c_int
    L.trpc_set_dump_budget.argtypes = [c.c_int64]
    L.trpc_set_dump_budget.restype = None
    L.trpc_dump_drain.argtypes = [c.c_char_p, c.c_size_t]
    L.trpc_dump_drain.restype = c.c_size_t
    L.trpc_trace_set_current.argtypes = [c.c_uint64, c.c_uint64, c.c_int]
    L.trpc_trace_set_current.restype = None
    L.trpc_trace_current.argtypes = [c.POINTER(c.c_uint64),
                                     c.POINTER(c.c_uint64)]
    L.trpc_trace_current.restype = c.c_int
    L.trpc_trace_annotate.argtypes = [c.c_char_p]
    L.trpc_trace_annotate.restype = None
    L.trpc_token_trace.argtypes = [c.c_uint64, c.POINTER(c.c_uint64),
                                   c.POINTER(c.c_uint64)]
    L.trpc_token_trace.restype = c.c_int
    # schedule perturbation / replay (native/src/sched_perturb.h)
    L.trpc_sched_set_seed.argtypes = [c.c_uint64]
    L.trpc_sched_set_seed.restype = None
    L.trpc_sched_seed.argtypes = []
    L.trpc_sched_seed.restype = c.c_uint64
    L.trpc_sched_trace_hash.argtypes = []
    L.trpc_sched_trace_hash.restype = c.c_uint64
    L.trpc_sched_trace_dump.argtypes = [c.c_char_p, c.c_size_t]
    L.trpc_sched_trace_dump.restype = c.c_size_t
    L.trpc_profiler_start.argtypes = [c.c_int]
    L.trpc_profiler_start.restype = c.c_int
    # void* out-pointer (not c_char_p: ctypes would convert to bytes and
    # lose the pointer we must pass back to trpc_profiler_free)
    L.trpc_profiler_stop.argtypes = [c.POINTER(c.c_void_p)]
    L.trpc_profiler_stop.restype = c.c_size_t
    L.trpc_profiler_free.argtypes = [c.c_void_p]
    L.trpc_profiler_free.restype = None
    L.trpc_profiler_running.restype = c.c_int
    L.trpc_symbolize.argtypes = [c.c_void_p, c.c_char_p, c.c_size_t]
    L.trpc_symbolize.restype = c.c_size_t

    # device data plane (native/src/tpu.h: PJRT plugin dlopen'd at runtime)
    L.trpc_tpu_plane_init.argtypes = [c.c_char_p]
    L.trpc_tpu_plane_init.restype = c.c_int
    L.trpc_tpu_plane_available.restype = c.c_int
    L.trpc_tpu_plane_error.restype = c.c_char_p
    L.trpc_tpu_plane_platform.restype = c.c_char_p
    L.trpc_tpu_device_count.restype = c.c_int
    L.trpc_tpu_h2d.argtypes = [c.c_char_p, c.c_size_t, c.c_int]
    L.trpc_tpu_h2d.restype = c.c_uint64
    L.trpc_tpu_buf_wait.argtypes = [c.c_uint64, c.c_int64]
    L.trpc_tpu_buf_wait.restype = c.c_int
    L.trpc_tpu_buf_size.argtypes = [c.c_uint64]
    L.trpc_tpu_buf_size.restype = c.c_int64
    L.trpc_tpu_d2h.argtypes = [c.c_uint64,
                               c.POINTER(c.POINTER(c.c_uint8))]
    L.trpc_tpu_d2h.restype = c.c_int64
    L.trpc_tpu_buf_release.argtypes = [c.POINTER(c.c_uint8)]
    L.trpc_tpu_buf_release.restype = None
    L.trpc_tpu_buf_free.argtypes = [c.c_uint64]
    L.trpc_tpu_buf_free.restype = None
    L.trpc_tpu_plane_stats.argtypes = [c.POINTER(c.c_uint64)]
    L.trpc_tpu_plane_stats.restype = None
    L.trpc_tpu_d2d.argtypes = [c.c_uint64, c.c_int]
    L.trpc_tpu_d2d.restype = c.c_uint64
    L.trpc_tpu_plane_uid.restype = c.c_uint64
    L.trpc_stream_write_device.argtypes = [c.c_uint64, c.c_uint64,
                                           c.c_int64]
    L.trpc_stream_write_device.restype = c.c_int
    L.trpc_stream_read_device.argtypes = [
        c.c_uint64, c.c_int, c.c_int64, c.POINTER(c.c_uint64),
        c.POINTER(c.c_uint64)]
    L.trpc_stream_read_device.restype = c.c_int
    L.trpc_server_add_hbm_echo.argtypes = [c.c_void_p, c.c_char_p]
    L.trpc_server_add_hbm_echo.restype = c.c_int
    L.trpc_channel_request_device_plane.argtypes = [c.c_void_p, c.c_int]
    L.trpc_channel_request_device_plane.restype = None
    L.trpc_channel_transport_state.argtypes = [c.c_void_p]
    L.trpc_channel_transport_state.restype = c.c_int

    # heap + contention profiler (heap_profiler.h)
    L.trpc_heap_profiler_enable.argtypes = [c.c_int64]
    L.trpc_heap_profiler_enable.restype = None
    L.trpc_heap_profiler_enabled.restype = c.c_int
    L.trpc_heap_dump.argtypes = [c.c_int, c.POINTER(c.c_void_p)]
    L.trpc_heap_dump.restype = c.c_size_t
    L.trpc_contention_dump.argtypes = [c.POINTER(c.c_void_p)]
    L.trpc_contention_dump.restype = c.c_size_t
    L.trpc_contention_profiler_set.argtypes = [c.c_int]
    L.trpc_contention_profiler_set.restype = None

    L.trpc_server_add_tls_sni.argtypes = [c.c_void_p, c.c_char_p,
                                          c.c_char_p, c.c_char_p]
    L.trpc_server_add_tls_sni.restype = c.c_int

    # streaming h2/gRPC client
    L.trpc_h2_stream_open.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p,
                                      c.c_char_p, c.POINTER(c.c_int)]
    L.trpc_h2_stream_open.restype = c.c_void_p
    L.trpc_h2_stream_write.argtypes = [c.c_void_p, c.c_char_p, c.c_size_t,
                                       c.c_int64]
    L.trpc_h2_stream_write.restype = c.c_int
    L.trpc_h2_stream_close_send.argtypes = [c.c_void_p]
    L.trpc_h2_stream_close_send.restype = c.c_int
    L.trpc_h2_stream_read.argtypes = [c.c_void_p, c.c_int64,
                                      c.POINTER(c.POINTER(c.c_uint8))]
    L.trpc_h2_stream_read.restype = c.c_int64
    L.trpc_h2_stream_chunk_free.argtypes = [c.POINTER(c.c_uint8)]
    L.trpc_h2_stream_chunk_free.restype = None
    L.trpc_h2_stream_status.argtypes = [c.c_void_p]
    L.trpc_h2_stream_status.restype = c.c_int
    L.trpc_h2_stream_headers.argtypes = [c.c_void_p,
                                         c.POINTER(c.POINTER(c.c_uint8))]
    L.trpc_h2_stream_headers.restype = c.c_size_t
    L.trpc_h2_stream_trailers.argtypes = [c.c_void_p,
                                          c.POINTER(c.POINTER(c.c_uint8))]
    L.trpc_h2_stream_trailers.restype = c.c_size_t
    L.trpc_h2_stream_destroy.argtypes = [c.c_void_p]
    L.trpc_h2_stream_destroy.restype = None

    # RPC cancellation (≙ Controller::StartCancel / NotifyOnCancel)
    L.trpc_channel_call_cancelable.argtypes = [
        c.c_void_p, c.c_char_p, c.c_char_p, c.c_size_t, c.c_char_p,
        c.c_size_t, c.c_int64, c.c_uint64, c.c_int,
        c.POINTER(c.c_uint64), c.POINTER(c.c_void_p)]
    L.trpc_channel_call_cancelable.restype = c.c_int
    L.trpc_call_cancel.argtypes = [c.c_uint64]
    L.trpc_call_cancel.restype = c.c_int
    L.trpc_call_canceled.argtypes = [c.c_uint64]
    L.trpc_call_canceled.restype = c.c_int
    L.trpc_call_wait_canceled.argtypes = [c.c_uint64, c.c_int64]
    L.trpc_call_wait_canceled.restype = c.c_int

    # HTTP client (the framework's own; rpc/http_client.py)
    L.trpc_channel_set_http.argtypes = [c.c_void_p, c.c_char_p]
    L.trpc_channel_set_http.restype = None
    L.trpc_http_client_call.argtypes = [
        c.c_void_p, c.c_char_p, c.c_char_p, c.c_char_p, c.c_char_p,
        c.c_size_t, c.c_int64, HTTP_CHUNK_CB, c.c_void_p,
        c.POINTER(c.c_void_p)]
    L.trpc_http_client_call.restype = c.c_int
    L.trpc_http_result_status.argtypes = [c.c_void_p]
    L.trpc_http_result_status.restype = c.c_int
    L.trpc_http_result_error_text.argtypes = [c.c_void_p]
    L.trpc_http_result_error_text.restype = c.c_char_p
    L.trpc_http_result_headers.argtypes = [
        c.c_void_p, c.POINTER(c.POINTER(c.c_uint8))]
    L.trpc_http_result_headers.restype = c.c_size_t
    L.trpc_http_result_body.argtypes = [
        c.c_void_p, c.POINTER(c.POINTER(c.c_uint8))]
    L.trpc_http_result_body.restype = c.c_size_t
    L.trpc_http_result_destroy.argtypes = [c.c_void_p]
    L.trpc_http_result_destroy.restype = None

    # bench
    L.trpc_run_echo_bench.argtypes = [c.c_char_p, c.c_int, c.c_int, c.c_int,
                                      c.c_int, c.c_int, c.c_double,
                                      c.POINTER(c.c_double)]
    L.trpc_run_echo_bench.restype = c.c_int
