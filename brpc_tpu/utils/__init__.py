"""utils — base library (≙ reference src/butil, SURVEY.md §2.1)."""
