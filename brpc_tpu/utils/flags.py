"""Runtime-reloadable flags — capability of gflags + reloadable_flags.

The reference's config system is pure gflags: every tunable is a DEFINE_xxx next
to its code, with validated hot reload (reference reloadable_flags.h:32-60) and
live GET/SET through the builtin /flags HTTP service
(reference builtin/flags_service.cpp).  This module reproduces that model:

    FLAGS = define_int32("event_dispatcher_num", 1, "number of epoll threads")
    ...
    set_flag("event_dispatcher_num", 4)     # validated hot reload

Flags are also mirrored into the metrics registry on demand (the reference
mirrors gflags as bvars, bvar/gflag.cpp) — see metrics.bvar.GFlag.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterable, Optional


class FlagError(Exception):
    pass


class Flag:
    __slots__ = ("name", "default", "help", "type", "validator", "_value", "reloadable")

    def __init__(self, name: str, default: Any, help: str, type_: type,
                 validator: Optional[Callable[[Any], bool]] = None,
                 reloadable: bool = True):
        self.name = name
        self.default = default
        self.help = help
        self.type = type_
        self.validator = validator
        self.reloadable = reloadable
        self._value = default

    @property
    def value(self) -> Any:
        return self._value

    def set(self, value: Any) -> None:
        try:
            if self.type is bool and isinstance(value, str):
                value = value.lower() in ("1", "true", "yes", "on")
            else:
                value = self.type(value)
        except (TypeError, ValueError) as e:
            raise FlagError(f"flag {self.name}: cannot convert {value!r} to "
                            f"{self.type.__name__}") from e
        if not self.reloadable and _registry.frozen:
            raise FlagError(f"flag {self.name} is not reloadable")
        if self.validator is not None and not self.validator(value):
            raise FlagError(f"flag {self.name}: validator rejected {value!r}")
        self._value = value


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._flags: Dict[str, Flag] = {}
        self.frozen = False  # set once a Server starts; non-reloadable flags lock

    def define(self, name: str, default: Any, help: str, type_: type,
               validator=None, reloadable=True) -> Flag:
        with self._lock:
            if name in self._flags:
                raise FlagError(f"flag {name} already defined")
            f = Flag(name, default, help, type_, validator, reloadable)
            self._flags[name] = f
            return f

    def get(self, name: str) -> Flag:
        try:
            return self._flags[name]
        except KeyError:
            raise FlagError(f"no such flag: {name}") from None

    def set(self, name: str, value: Any) -> None:
        self.get(name).set(value)

    def all(self) -> Iterable[Flag]:
        return list(self._flags.values())


_registry = _Registry()


def define_int32(name, default, help="", validator=None, reloadable=True) -> Flag:
    return _registry.define(name, int(default), help, int, validator, reloadable)


define_int64 = define_int32


def define_bool(name, default, help="", validator=None, reloadable=True) -> Flag:
    return _registry.define(name, bool(default), help, bool, validator, reloadable)


def define_double(name, default, help="", validator=None, reloadable=True) -> Flag:
    return _registry.define(name, float(default), help, float, validator, reloadable)


def define_string(name, default, help="", validator=None, reloadable=True) -> Flag:
    return _registry.define(name, str(default), help, str, validator, reloadable)


def get_flag(name: str) -> Any:
    return _registry.get(name).value


def set_flag(name: str, value: Any) -> None:
    _registry.set(name, value)


def flag_exists(name: str) -> bool:
    try:
        _registry.get(name)
        return True
    except FlagError:
        return False


def all_flags():
    return _registry.all()


def freeze_nonreloadable():
    _registry.frozen = True
