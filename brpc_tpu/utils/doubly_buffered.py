"""DoublyBufferedData — read-mostly RCU-like container.

The reference (butil/containers/doubly_buffered_data.h) keeps two copies of the
data; readers grab the foreground copy through a thread-local reference with no
contended atomics, the writer modifies the background copy, atomically flips,
waits out readers of the old foreground, then applies the same modification to
the (now background) old copy.  It is the backbone of load-balancer server
lists (reference load_balancer.h:72) and the client SocketMap.

The Python flip keeps the same reader guarantee (a reader never observes a
torn copy, and never blocks the writer's first modification) using a
per-reader epoch ticket instead of thread-local mutexes; CPython reference
assignment is atomic, so readers take a snapshot of the foreground index
without locking.  The native C++ core has the faithful wait-free reader
(native/src/doubly_buffered.h) for hot paths.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, List, TypeVar

from brpc_tpu.utils import logging as _log

T = TypeVar("T")


class DoublyBufferedData(Generic[T]):
    def __init__(self, factory: Callable[[], T]):
        self._data: List[T] = [factory(), factory()]
        self._fg = 0  # index of foreground copy; assignment is atomic in CPython
        self._write_lock = threading.Lock()
        # per-copy reader counters guarded by a lock each; readers touch only
        # the counter of their snapshot copy (cheap, uncontended with writer
        # except during a flip)
        self._ref_locks = [threading.Lock(), threading.Lock()]
        self._refs = [0, 0]
        self._no_readers = [threading.Condition(self._ref_locks[0]),
                            threading.Condition(self._ref_locks[1])]

    class ScopedPtr(Generic[T]):
        """Reader handle (≙ DoublyBufferedData<T>::ScopedPtr)."""

        __slots__ = ("_dbd", "_idx", "data")

        def __init__(self, dbd: "DoublyBufferedData[T]"):
            self._dbd = dbd
            while True:
                idx = dbd._fg
                with dbd._ref_locks[idx]:
                    if idx == dbd._fg:  # not flipped between snapshot and lock
                        dbd._refs[idx] += 1
                        self._idx = idx
                        self.data = dbd._data[idx]
                        return

        def __enter__(self) -> T:
            return self.data

        def __exit__(self, *exc) -> None:
            self.release()

        def release(self) -> None:
            dbd, idx = self._dbd, self._idx
            with dbd._ref_locks[idx]:
                dbd._refs[idx] -= 1
                if dbd._refs[idx] == 0:
                    dbd._no_readers[idx].notify_all()

    def read(self) -> "DoublyBufferedData.ScopedPtr[T]":
        return DoublyBufferedData.ScopedPtr(self)

    def modify(self, fn: Callable[[T], bool]) -> bool:
        """Apply ``fn`` to both copies, flipping in between (≙ Modify()).

        ``fn`` must be deterministic given the copy's state: it is applied
        twice (once per copy).  If the second application fails the copies
        have diverged — that is a caller bug; it is logged CRITICAL and
        re-raised rather than silently ignored.
        """
        with self._write_lock:
            bg = 1 - self._fg
            if not fn(self._data[bg]):
                return False
            self._fg = bg  # flip: new readers go to the modified copy
            old = 1 - bg
            with self._ref_locks[old]:
                while self._refs[old] != 0:
                    self._no_readers[old].wait()
            try:
                ok = fn(self._data[old])
            except Exception:
                _log.LOG(_log.LOG_FATAL,
                         "DoublyBufferedData.modify: fn failed on the second "
                         "copy after the flip; copies have diverged")
                raise
            if not ok:
                _log.LOG(_log.LOG_ERROR,
                         "DoublyBufferedData.modify: fn returned False on the "
                         "second copy; copies have diverged")
            return True
