"""EndPoint — address value type, extended with tpu:// device endpoints.

The reference's EndPoint (butil/endpoint.h:87-147) is an ip:port value type with
parsing/resolving helpers and unix-socket support.  The TPU build extends the
grammar with device endpoints (BASELINE.json north star: a Channel can dial
``tpu://slice/chip``):

    "127.0.0.1:8000"          host TCP endpoint
    "unix:/tmp/s.sock"        unix domain socket
    "tpu://0/3"               slice 0, chip 3 (data plane rides ICI/PJRT;
                              control plane rides DCN/TCP — the RDMA split,
                              reference rdma/rdma_endpoint.h:95)
    "tpu://0/3@10.0.0.2:9000" device endpoint with explicit control address
"""

from __future__ import annotations

import re
import socket as _socket
from dataclasses import dataclass
from typing import Optional, Tuple


class EndPointError(ValueError):
    pass


@dataclass(frozen=True)
class EndPoint:
    """ip:port | unix path | tpu device coordinate (immutable value type)."""

    ip: str = ""
    port: int = 0
    # "tcp" | "unix" | "tpu"
    scheme: str = "tcp"
    # tpu:// coordinates (scheme == "tpu")
    slice_id: int = -1
    chip_id: int = -1

    def __str__(self) -> str:
        if self.scheme == "unix":
            return f"unix:{self.ip}"
        if self.scheme == "tpu":
            base = f"tpu://{self.slice_id}/{self.chip_id}"
            if self.ip:
                return f"{base}@{self.ip}:{self.port}"
            return base
        return f"{self.ip}:{self.port}"

    @property
    def is_device(self) -> bool:
        return self.scheme == "tpu"

    def control_address(self) -> Tuple[str, int]:
        """Host address carrying the control plane (handshake/meta)."""
        if self.scheme == "tpu" and not self.ip:
            raise EndPointError(f"{self} has no control address attached")
        return (self.ip, self.port)


_TPU_RE = re.compile(r"^tpu://(\d+)/(\d+)(?:@([^:]+):(\d+))?$")


def str2endpoint(s: str) -> EndPoint:
    """Parse any endpoint grammar (≙ butil::str2endpoint, endpoint.h:107)."""
    s = s.strip()
    if s.startswith("unix:"):
        path = s[len("unix:"):]
        if not path:
            raise EndPointError(f"empty unix path in {s!r}")
        return EndPoint(ip=path, port=0, scheme="unix")
    m = _TPU_RE.match(s)
    if m:
        slice_id, chip_id = int(m.group(1)), int(m.group(2))
        ip = m.group(3) or ""
        port = int(m.group(4)) if m.group(4) else 0
        return EndPoint(ip=ip, port=port, scheme="tpu",
                        slice_id=slice_id, chip_id=chip_id)
    if s.startswith("tpu://"):
        raise EndPointError(f"malformed tpu endpoint {s!r}")
    # ip:port  (allow [v6]:port)
    if s.startswith("["):
        host, _, rest = s[1:].partition("]")
        if not rest.startswith(":"):
            raise EndPointError(f"malformed endpoint {s!r}")
        return EndPoint(ip=host, port=_parse_port(rest[1:], s), scheme="tcp")
    host, sep, port = s.rpartition(":")
    if not sep:
        raise EndPointError(f"missing port in {s!r}")
    return EndPoint(ip=host, port=_parse_port(port, s), scheme="tcp")


def _parse_port(p: str, whole: str) -> int:
    try:
        v = int(p)
    except ValueError:
        raise EndPointError(f"bad port in {whole!r}") from None
    if not (0 <= v <= 65535):
        raise EndPointError(f"port out of range in {whole!r}")
    return v


def hostname2endpoint(host: str, port: Optional[int] = None) -> EndPoint:
    """Resolve host[:port] via DNS (≙ butil::hostname2endpoint, endpoint.h:117)."""
    if port is None:
        name, sep, p = host.rpartition(":")
        if not sep:
            raise EndPointError(f"missing port in {host!r}")
        port = _parse_port(p, host)
        host = name
    ip = _socket.gethostbyname(host)
    return EndPoint(ip=ip, port=port, scheme="tcp")
