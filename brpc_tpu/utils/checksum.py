"""CRC-32C (Castagnoli) through the native core (≙ butil/crc32c.h —
hardware SSE4.2 when available, sliced software fallback otherwise).
Matches the iSCSI/ext4/leveldb polynomial, so values interoperate with
other crc32c implementations."""

from __future__ import annotations

from brpc_tpu._native import lib


def crc32c(data: bytes, init: int = 0) -> int:
    """Checksum of `data`; pass a previous result as `init` to stream."""
    return int(lib().trpc_crc32c_extend(init & 0xFFFFFFFF, data, len(data)))


def crc32c_hardware() -> bool:
    """True when the SSE4.2 instruction path is in use."""
    return bool(lib().trpc_crc32c_hardware())
