"""Undo the axon sitecustomize's platform force-selection when the
caller explicitly wants CPU.

The sitecustomize (triggered by PALLAS_AXON_POOL_IPS) runs
``jax.config.update("jax_platforms", "axon,cpu")`` at interpreter start,
which overrides the JAX_PLATFORMS env var — and a dead device tunnel
then hangs every ``jax.devices()``.  Calling this when
``JAX_PLATFORMS=cpu`` restores the env var's intent.
"""

from __future__ import annotations

import os
import sys


def force_cpu_platform() -> None:
    """If JAX_PLATFORMS=cpu, make jax honor it despite sitecustomize."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        return
    try:
        import jax
        from jax.extend import backend as _jex_backend
    except ImportError:
        return  # no jax here: nothing to undo
    try:
        _jex_backend.clear_backends()
        jax.config.update("jax_platforms", "cpu")
    except Exception as e:  # jax API moved: the hang guard is GONE — say so
        print(f"warning: force_cpu_platform failed ({e!r}); "
              "jax may still select the tunneled platform",
              file=sys.stderr)


def shard_map_compat():
    """(shard_map, nocheck_kwargs) across jax generations.

    jax >= 0.6 exports ``jax.shard_map`` with the ``check_vma`` kwarg;
    0.4.x containers only have ``jax.experimental.shard_map`` with
    ``check_rep``.  Callers splat the returned kwargs to disable the
    replication/varying-mesh check in either generation — the parallel
    layer must stay importable on both (test containers rotate between
    jax builds; a bare ``from jax import shard_map`` kills collection
    of every test that touches the mesh layer on the older ones).
    """
    import jax
    if hasattr(jax, "shard_map"):
        return jax.shard_map, {"check_vma": False}
    from jax.experimental.shard_map import shard_map  # noqa: PLC0415
    return shard_map, {"check_rep": False}
