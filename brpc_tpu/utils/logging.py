"""Chromium-style logging facade (≙ butil/logging.h).

Capabilities kept from the reference: leveled LOG streams, CHECK macros,
VLOG with per-module runtime-adjustable verbosity (surfaced by the builtin
/vlog service, reference builtin/vlog_service.cpp), and a pluggable LogSink.
Implemented over the stdlib logging module so users can interpose handlers.
"""

from __future__ import annotations

import logging as _pylog
import sys
import threading
from typing import Dict, Optional

_logger = _pylog.getLogger("brpc_tpu")
if not _logger.handlers:
    _h = _pylog.StreamHandler(sys.stderr)
    _h.setFormatter(_pylog.Formatter(
        "%(levelname).1s%(asctime)s %(threadName)s %(filename)s:%(lineno)d] %(message)s",
        datefmt="%m%d %H:%M:%S"))
    _logger.addHandler(_h)
    _logger.setLevel(_pylog.INFO)
    _logger.propagate = False

LOG_INFO = _pylog.INFO
LOG_WARNING = _pylog.WARNING
LOG_ERROR = _pylog.ERROR
LOG_FATAL = _pylog.CRITICAL


class CheckError(AssertionError):
    pass


def LOG(level: int, msg: str, *args) -> None:
    _logger.log(level, msg, *args, stacklevel=2)


def LOG_IF(level: int, cond: bool, msg: str, *args) -> None:
    if cond:
        _logger.log(level, msg, *args, stacklevel=2)


def CHECK(cond, msg: str = "", *args):
    if not cond:
        text = ("CHECK failed: " + (msg % args if args else msg)) if msg \
            else "CHECK failed"
        _logger.critical(text, stacklevel=2)
        raise CheckError(text)
    return cond


def CHECK_EQ(a, b, msg: str = ""):
    if a != b:
        CHECK(False, f"{a!r} != {b!r} {msg}")


# --- VLOG with per-module runtime levels (≙ /vlog service) -------------------

_vlock = threading.Lock()
_vmodule: Dict[str, int] = {}
_global_v = 0


def set_vlog_level(level: int, module: Optional[str] = None) -> None:
    global _global_v
    with _vlock:
        if module is None:
            _global_v = level
        else:
            _vmodule[module] = level


def vlog_level(module: Optional[str] = None) -> int:
    with _vlock:
        if module is not None and module in _vmodule:
            return _vmodule[module]
        return _global_v


def vlog_modules() -> Dict[str, int]:
    with _vlock:
        return dict(_vmodule)


def VLOG(verbosity: int, msg: str, *args, module: Optional[str] = None) -> None:
    if verbosity <= vlog_level(module):
        if args:
            text = msg % args
        else:
            text = msg  # no args: treat literally (may contain raw '%')
        _logger.info("[v%d] %s", verbosity, text, stacklevel=2)


def set_log_level(level: int) -> None:
    _logger.setLevel(level)


def add_sink(handler: _pylog.Handler) -> None:
    """Pluggable LogSink (≙ logging::SetLogSink)."""
    _logger.addHandler(handler)
