"""Time helpers (≙ butil/time.h: cpuwide_time_us, gettimeofday_us, Timer)."""

from __future__ import annotations

import time


def monotonic_ns() -> int:
    return time.monotonic_ns()


def monotonic_us() -> int:
    return time.monotonic_ns() // 1000


def realtime_us() -> int:
    return time.time_ns() // 1000


class Timer:
    """Start/stop stopwatch (≙ butil::Timer, time.h)."""

    __slots__ = ("_start", "_stop")

    def __init__(self, start: bool = False):
        self._start = 0
        self._stop = 0
        if start:
            self.start()

    def start(self) -> None:
        self._start = time.monotonic_ns()
        self._stop = self._start

    def stop(self) -> None:
        self._stop = time.monotonic_ns()

    def n_elapsed(self) -> int:
        return self._stop - self._start

    def u_elapsed(self) -> int:
        return self.n_elapsed() // 1000

    def m_elapsed(self) -> int:
        return self.n_elapsed() // 1_000_000
