"""recordio — length-prefixed record files (capability of the reference
butil/recordio.{h,cpp}: the persistent format under rpc_dump sample files,
replayed by tools/rpc_replay).

Record framing (new design, not the reference's on-disk layout):
    magic "TREC" | u32 payload_len (LE) | u32 crc32(payload) | payload
A torn tail (partial record after a crash) is skipped by scanning for the
next magic, the same recovery property the reference format has.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, Optional

_MAGIC = b"TREC"
_HDR = struct.Struct("<4sII")


class RecordWriter:
    def __init__(self, path: str):
        self._f = open(path, "ab")

    def write(self, payload: bytes) -> None:
        self._f.write(_HDR.pack(_MAGIC, len(payload),
                                zlib.crc32(payload) & 0xFFFFFFFF))
        self._f.write(payload)

    def flush(self) -> None:
        self._f.flush()

    def tell(self) -> int:
        return self._f.tell()

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_records(path: str) -> Iterator[bytes]:
    """Yields payloads; corrupt/torn records are skipped by resyncing on
    the magic."""
    with open(path, "rb") as f:
        data = f.read()
    i = 0
    n = len(data)
    while i + _HDR.size <= n:
        magic, length, crc = _HDR.unpack_from(data, i)
        if magic != _MAGIC:
            j = data.find(_MAGIC, i + 1)
            if j < 0:
                return
            i = j
            continue
        start = i + _HDR.size
        end = start + length
        if end > n:
            return  # torn tail
        payload = data[start:end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) == crc:
            yield payload
            i = end
        else:
            j = data.find(_MAGIC, i + 1)
            if j < 0:
                return
            i = j
