"""KV-cache block plane — sequences' K/V as budgeted device-plane blocks
(≙ the reference's rdma/block_pool.cpp block budget, re-designed: blocks
are HBM DeviceBuffers, migration is a PJRT d2d hop, and fabric-lib's
point-to-point KV rail — PAPERS.md arXiv 2510.27656 — is the template
for keeping KV transfer distinct from the collective plane).

Lifecycle of one sequence:

    seq_alloc(id, kv_bytes)   prefill K/V chunked into blocks, DMA'd onto
                              the PREFILL device (h2d); charged against
                              the pool budget — PoolExhausted here means
                              the batcher must shed or preempt
    seq_migrate(id)           blocks hop to the DECODE device:
                                local rail — tpu_d2d per block, no host
                                  landing (both ends share one PJRT
                                  client; stats()["d2d_transfers"] is the
                                  proof counter)
                                host rail — d2h → optional bf16/int8
                                  codec on the landing bytes → h2d
                                  (non-shared-PJRT fallback per the
                                  PARITY ruling; the codec mirrors
                                  parallel/quantize.py wire formats)
    seq_grow(id)              one more block as decode crosses a block
                              boundary (the preemption trigger)
    seq_fetch(id)             the migrated bytes, host-side, for
                              models/decode.install()
    seq_free(id)              EVERY block back to the pool — finish,
                              eviction, and cancel all end here;
                              idempotent, and assert_balanced() proves
                              nothing leaked
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from brpc_tpu import tpu_plane
from brpc_tpu.utils import flags

flags.define_int32(
    "serving_block_bytes",
    int(os.environ.get("TRPC_SERVING_BLOCK_BYTES", "4096")),
    "KV-cache block size in bytes (serving/kv_cache.py)",
    reloadable=False)
flags.define_int32(
    "serving_kv_blocks",
    int(os.environ.get("TRPC_SERVING_KV_BLOCKS", "64")),
    "KV-cache pool budget in blocks; admission sheds beyond it",
    reloadable=False)
flags.define_string(
    "serving_kv_rail",
    os.environ.get("TRPC_SERVING_KV_RAIL", "auto"),
    "prefill->decode KV migration rail: auto|local|host "
    "(auto = local d2d when the plane is up, else host)",
    validator=lambda v: v in ("auto", "local", "host"))
flags.define_string(
    "serving_kv_codec",
    os.environ.get("TRPC_SERVING_KV_CODEC", "none"),
    "codec applied to host-rail KV migration bytes: none|bf16|int8 "
    "(the local d2d rail is device-native and rides uncoded)",
    validator=lambda v: v in ("none", "bf16", "int8"))


@dataclass
class _SeqBlocks:
    nbytes: int                                  # real payload bytes
    blocks: List[tpu_plane.DeviceBuffer] = field(default_factory=list)
    device: int = 0                              # where the blocks live
    migrated: bool = False


class KvBlockPlane:
    """Per-sequence block tables over one DeviceBufPool.  Thread-safe:
    the decode loop migrates/grows while handler threads cancel."""

    def __init__(self, block_bytes: Optional[int] = None,
                 n_blocks: Optional[int] = None,
                 prefill_device: int = 0,
                 decode_device: Optional[int] = None,
                 rail: Optional[str] = None,
                 codec: Optional[str] = None):
        self.block_bytes = block_bytes or flags.get_flag(
            "serving_block_bytes")
        self.n_blocks = n_blocks or flags.get_flag("serving_kv_blocks")
        self.prefill_device = prefill_device
        if decode_device is None:
            decode_device = 1 if (tpu_plane.available()
                                  and tpu_plane.device_count() > 1) else 0
        self.decode_device = decode_device
        self.rail = rail or flags.get_flag("serving_kv_rail")
        self.codec = codec or flags.get_flag("serving_kv_codec")
        self.pool = tpu_plane.DeviceBufPool(self.block_bytes, self.n_blocks)
        self._lock = threading.Lock()
        self._seqs: Dict[int, _SeqBlocks] = {}
        self._migrations_local = 0
        self._migrations_host = 0
        self._codec_bytes = 0
        self._grown = 0
        self._freed_seqs = 0

    # -- sizing -------------------------------------------------------------

    def blocks_needed(self, nbytes: int) -> int:
        return max(1, -(-nbytes // self.block_bytes))

    @property
    def free_blocks(self) -> int:
        return self.pool.free_blocks

    @property
    def used_blocks(self) -> int:
        return self.pool.used_blocks

    def live_seqs(self) -> int:
        with self._lock:
            return len(self._seqs)

    # -- sequence lifecycle -------------------------------------------------

    def seq_alloc(self, seq_id: int, kv_bytes: bytes) -> int:
        """Chunk a sequence's prefill K/V into blocks on the prefill
        device.  All-or-nothing: a mid-sequence PoolExhausted rolls back
        the blocks already charged before re-raising."""
        with self._lock:
            if seq_id in self._seqs:
                raise KeyError(f"seq {seq_id} already has a block table")
        table = _SeqBlocks(nbytes=len(kv_bytes),
                           device=self.prefill_device)
        try:
            for off in range(0, max(len(kv_bytes), 1), self.block_bytes):
                table.blocks.append(self.pool.alloc(
                    kv_bytes[off:off + self.block_bytes],
                    self.prefill_device))
        except tpu_plane.PoolExhausted:
            for b in table.blocks:
                self.pool.free(b)
            raise
        with self._lock:
            self._seqs[seq_id] = table
        return len(table.blocks)

    def seq_migrate(self, seq_id: int) -> str:
        """Move the sequence's blocks prefill→decode device; returns the
        rail taken ("local"/"host"/"none" when devices coincide)."""
        with self._lock:
            table = self._seqs[seq_id]
        if table.migrated or self.decode_device == table.device:
            table.migrated = True
            return "none"
        use_local = (self.rail == "local"
                     or (self.rail == "auto" and tpu_plane.available()))
        # in-place per-block replacement: a mid-migration failure leaves
        # every charged block reachable from the table, so seq_free still
        # returns all of them
        if use_local:
            for i, b in enumerate(table.blocks):
                table.blocks[i] = self.pool.migrate(b, self.decode_device)
            with self._lock:
                self._migrations_local += len(table.blocks)
        else:
            for i, b in enumerate(table.blocks):
                b.wait()
                data = self._transcode(b.to_host())
                # free-then-alloc so a full pool can still land the hop
                # (alloc-first would deadlock at the budget edge); on an
                # alloc failure the engine sheds the sequence and
                # seq_free skips the already-freed source (idempotent)
                self.pool.free(b)
                table.blocks[i] = self.pool.alloc(data, self.decode_device)
            with self._lock:
                self._migrations_host += len(table.blocks)
        table.device = self.decode_device
        table.migrated = True
        return "local" if use_local else "host"

    def seq_grow(self, seq_id: int, tail: bytes = b"") -> int:
        """Charge one more block (decode crossed a block boundary).
        PoolExhausted propagates — the batcher preempts on it."""
        blk = self.pool.alloc(tail[:self.block_bytes], self.decode_device)
        with self._lock:
            table = self._seqs[seq_id]
            table.blocks.append(blk)
            self._grown += 1
        return len(table.blocks)

    def seq_blocks(self, seq_id: int) -> int:
        """Blocks currently charged to the sequence (0 if unknown)."""
        with self._lock:
            table = self._seqs.get(seq_id)
            return len(table.blocks) if table else 0

    def seq_fetch(self, seq_id: int) -> bytes:
        """The migrated K/V bytes, host-side (feeds decode.install)."""
        with self._lock:
            table = self._seqs[seq_id]
        out = []
        for b in table.blocks:
            b.wait()
            out.append(b.to_host())
        return b"".join(out)[:table.nbytes]

    def seq_free(self, seq_id: int) -> int:
        """Return every block of the sequence; idempotent (finish,
        evict, and cancel can race — first caller wins)."""
        with self._lock:
            table = self._seqs.pop(seq_id, None)
            if table is None:
                return 0
            self._freed_seqs += 1
        for b in table.blocks:
            self.pool.free(b)
        return len(table.blocks)

    def free_all(self) -> None:
        with self._lock:
            ids = list(self._seqs)
        for sid in ids:
            self.seq_free(sid)

    # -- accounting ---------------------------------------------------------

    def assert_balanced(self) -> None:
        """No live sequences and no charged blocks — the accounting
        proof after a drain."""
        with self._lock:
            live = len(self._seqs)
        if live:
            raise AssertionError(f"{live} sequence table(s) still live")
        self.pool.assert_balanced()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            d = {"kv_live_seqs": len(self._seqs),
                 "kv_migrations_local": self._migrations_local,
                 "kv_migrations_host": self._migrations_host,
                 "kv_codec_bytes": self._codec_bytes,
                 "kv_grown_blocks": self._grown,
                 "kv_freed_seqs": self._freed_seqs}
        d.update({f"kv_pool_{k}": v
                  for k, v in self.pool.pool_stats().items()})
        return d

    # -- host-rail codec ----------------------------------------------------

    def _transcode(self, data: bytes) -> bytes:
        """bf16/int8 quantize→dequantize pass on host-rail landing bytes
        (same per-block formats as the wire codec; lossy but bounded —
        parallel/quantize.py)."""
        if self.codec in ("", "none"):
            return data
        import numpy as np
        from brpc_tpu.parallel import quantize
        n = len(data) // 4 * 4
        if n == 0:
            return data
        arr = np.frombuffer(data[:n], np.float32)
        out = np.asarray(quantize.fake_quant(arr, self.codec),
                         np.float32).tobytes()
        with self._lock:
            self._codec_bytes += n
        return out + data[n:]
