"""Serving front-end — stream-RPC ingress, one decode-loop thread, and
the eviction paths that keep the KV accounting exact.

Request wire format (method "LLM.Generate", payload JSON):

    {"prompt": [token ids...], "max_new_tokens": 8}
    {"prompt_len": 12, "max_new_tokens": 8}      # deterministic prompt

The handler accepts the call's stream, then submits to the scheduler —
which sheds with ELIMIT before any prefill compute or DMA (the PR-11
posture; the native per-method concurrency cap is the first gate in
front of this, see `method_cap`).  Each generated token rides the stream
as 4 little-endian bytes; a clean close is end-of-generation, an RST
carries the eviction/cancel code.

Every exit path — finish, preemption, slow-consumer timeout, stream RST,
RPC cancel, client socket death — funnels through `_end()`, which frees
the sequence's KV blocks exactly once; `assert_drained()` +
`tpu_plane.stats()["live_buffers"]` is the accounting proof the suite
pins.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from brpc_tpu.models import decode as D
from brpc_tpu.models.transformer import ModelConfig
from brpc_tpu.models import transformer
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.stream import StreamClosed, StreamReset, StreamTimeout
from brpc_tpu.serving import scheduler as S
from brpc_tpu.serving.kv_cache import KvBlockPlane
from brpc_tpu.utils import flags

flags.define_int32(
    "serving_slots",
    int(os.environ.get("TRPC_SERVING_SLOTS", "4")),
    "decode-batch slot count (engine.py); static jit shape",
    reloadable=False)
flags.define_int32(
    "serving_write_timeout_ms",
    int(os.environ.get("TRPC_SERVING_WRITE_TIMEOUT_MS", "2000")),
    "per-token stream write budget; a consumer slower than this is "
    "evicted (shed, not queued — engine.py)")

TOKEN_FMT = "<I"  # one generated token = 4 LE bytes on the stream


def tiny_config(**over) -> ModelConfig:
    """The serving acceptance model: small enough that an 8-device CPU
    mesh prefills + decodes in test time, big enough that K/V spans
    multiple pool blocks per sequence."""
    kw = dict(vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
              max_seq=64, n_experts=0, dtype=jnp.float32)
    kw.update(over)
    return ModelConfig(**kw)


class ServingEngine:
    """Continuous-batching LLM server core.  One instance per process;
    `register()` it on a Server, `start()` the decode loop."""

    def __init__(self, cfg: Optional[ModelConfig] = None,
                 params: Optional[Dict] = None, mesh=None,
                 n_slots: Optional[int] = None,
                 max_len: Optional[int] = None,
                 kv: Optional[KvBlockPlane] = None,
                 max_waiting: Optional[int] = None, seed: int = 0):
        self.cfg = cfg or tiny_config()
        self.mesh = mesh
        self.params = params if params is not None else transformer.init(
            jax.random.PRNGKey(seed), self.cfg)
        self.n_slots = n_slots or flags.get_flag("serving_slots")
        self.max_len = max_len or self.cfg.max_seq
        self.kv = kv or KvBlockPlane()
        self.sched = S.Scheduler(self.n_slots, self.kv,
                                 D.kv_bytes_per_token(self.cfg),
                                 max_waiting=max_waiting)
        self.cache = D.init_cache(self.cfg, self.n_slots, self.max_len,
                                  mesh)
        self._jstep = jax.jit(
            lambda p, c, t, a: D.decode_step(p, c, t, a, self.cfg,
                                             self.mesh))
        self._jprefill: Dict[int, object] = {}   # prompt len -> jitted fn
        self._seq_ids = iter(range(1, 1 << 62))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # counters
        self.tokens_out = 0
        self.prefills = 0
        self.steps = 0
        self.preemptions = 0
        self.rails = {"local": 0, "host": 0, "none": 0}

    # -- ingress (handler threads) ------------------------------------------

    @property
    def method_cap(self) -> int:
        """Recommended ServerOptions.method_max_concurrency for the
        Generate method: what the scheduler could even hold.  The native
        cap sheds the rest with ELIMIT on the parse fiber — before the
        request ever reaches Python (the PR-11 first gate)."""
        return self.n_slots + self.sched.max_waiting + 1

    def register(self, server, method: str = "LLM.Generate") -> None:
        server.add_service(method, self.handle)

    def handle(self, cntl, req: bytes):
        """The Generate handler: parse, accept the stream, submit."""
        try:
            body = json.loads(req.decode() or "{}")
            prompt = body.get("prompt")
            if prompt is None:
                plen = int(body.get("prompt_len", 8))
                prompt = [1 + (i % (self.cfg.vocab - 1))
                          for i in range(plen)]
            prompt = [int(t) % self.cfg.vocab for t in prompt]
            max_new = int(body.get("max_new_tokens", 8))
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            raise errors.RpcError(errors.EREQUEST, f"bad request: {e}")
        if not prompt or max_new < 1:
            raise errors.RpcError(errors.EREQUEST,
                                  "prompt and max_new_tokens required")
        if len(prompt) + max_new > self.max_len:
            raise errors.RpcError(
                errors.EREQUEST,
                f"prompt+max_new_tokens {len(prompt) + max_new} > "
                f"cache max_len {self.max_len}")
        st = cntl.accept_stream()
        if st is None:
            raise errors.RpcError(errors.EREQUEST,
                                  "Generate wants an attached stream")
        seq = S.Sequence(seq_id=next(self._seq_ids), prompt=prompt,
                         max_new_tokens=max_new, stream=st, cntl=cntl)
        try:
            self.sched.submit(seq)   # sheds ELIMIT before device work
        except errors.RpcError:
            st.rst(errors.ELIMIT)
            st.destroy()
            raise
        return json.dumps({"seq": seq.seq_id,
                           "prompt_len": len(prompt)}).encode()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="serving-decode", daemon=True)
        self._thread.start()

    def stop(self, drain_timeout_s: float = 10.0) -> None:
        """Stop the loop and drain: every live or queued sequence is
        evicted and its blocks freed."""
        self._stop.set()
        self.sched.work.set()
        if self._thread is not None:
            self._thread.join(drain_timeout_s)
        for seq in self.sched.drain_waiting():
            self._end(seq, S.EVICTED, "server stopping",
                      rst_code=errors.ESTOP)
        for seq in self.sched.running():
            self._end(seq, S.EVICTED, "server stopping",
                      rst_code=errors.ESTOP)
        self.kv.free_all()

    def assert_drained(self) -> None:
        self.kv.assert_balanced()

    def stats(self) -> Dict[str, int]:
        d = {
            "tokens_out": self.tokens_out,
            "prefills": self.prefills,
            "steps": self.steps,
            "preemptions": self.preemptions,
            "rail_local": self.rails["local"],
            "rail_host": self.rails["host"],
            "submitted": self.sched.submitted,
            "admitted": self.sched.admitted,
            "shed_queue": self.sched.shed_queue,
            "shed_budget": self.sched.shed_budget,
            "shed": self.sched.shed_queue + self.sched.shed_budget,
            "finished": self.sched.finished,
            "evicted": self.sched.evicted,
            "canceled": self.sched.canceled,
            "waiting": self.sched.waiting_depth(),
            "running": len(self.sched.running()),
        }
        d.update(self.kv.stats())
        return d

    # -- decode loop --------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            did = False
            # prefill/decode interleave: at most ONE admission per step,
            # so running streams keep their inter-token cadence while
            # the waiting room drains
            seq = self.sched.pop_admittable()
            if seq is not None:
                self._admit(seq)
                did = True
            batch = [s for s in self.sched.running()
                     if s.state == S.RUNNING]
            if batch:
                self._decode_batch(batch)
                did = True
            if not did:
                self.sched.work.wait(0.02)
                self.sched.work.clear()

    def _prefill_fn(self, plen: int):
        fn = self._jprefill.get(plen)
        if fn is None:
            fn = jax.jit(lambda p, t: D.prefill(p, t, self.cfg, self.mesh))
            self._jprefill[plen] = fn
        return fn

    def _admit(self, seq: S.Sequence) -> None:
        """Prefill one admitted sequence: compute K/V, charge blocks,
        migrate prefill→decode device, install, emit the first token."""
        if seq.cntl is not None and seq.cntl.is_canceled():
            self._end(seq, S.CANCELED, "canceled before prefill")
            return
        plen = seq.prompt_len
        toks = jnp.asarray([seq.prompt], jnp.int32)
        logits, k, v = self._prefill_fn(plen)(self.params, toks)
        kvb = D.kv_to_bytes(k[:, 0], v[:, 0])
        try:
            self.kv.seq_alloc(seq.seq_id, kvb)
            rail = self.kv.seq_migrate(seq.seq_id)
        except Exception as e:  # PoolExhausted or a plane fault
            self.kv.seq_free(seq.seq_id)
            self._end(seq, S.EVICTED, f"prefill shed: {e}",
                      rst_code=errors.ELIMIT)
            return
        self.rails[rail] = self.rails.get(rail, 0) + 1
        k2, v2 = D.kv_from_bytes(self.kv.seq_fetch(seq.seq_id),
                                 self.cfg, plen)
        self.cache = D.install(self.cache, seq.slot, k2, v2, plen)
        self.prefills += 1
        first = int(np.asarray(jnp.argmax(logits[0])))
        if self._emit(seq, first) and seq.generated >= seq.max_new_tokens:
            self._end(seq, S.FINISHED, "max_new_tokens")

    def _decode_batch(self, batch) -> None:
        tokens = np.zeros((self.n_slots,), np.int32)
        active = np.zeros((self.n_slots,), bool)
        for s in batch:
            tokens[s.slot] = s.last_token
            active[s.slot] = True
        logits, self.cache = self._jstep(self.params, self.cache,
                                         jnp.asarray(tokens),
                                         jnp.asarray(active))
        self.steps += 1
        nxt = np.asarray(jnp.argmax(logits, axis=-1))
        for s in batch:
            if s.state != S.RUNNING:
                continue  # evicted by a preemption earlier in this pass
            if s.cntl is not None and s.cntl.is_canceled():
                # the RPC cancel already RST the accepted stream
                # natively; our job is only the block accounting
                self._end(s, S.CANCELED, "rpc canceled", rst=False)
                continue
            if self._emit(s, int(nxt[s.slot])) and \
                    s.generated >= s.max_new_tokens:
                self._end(s, S.FINISHED, "max_new_tokens")

    def _emit(self, seq: S.Sequence, token: int) -> bool:
        """Send one token and keep the block charge covering the
        sequence's total length; False when the sequence ended here."""
        try:
            seq.stream.write(struct.pack(TOKEN_FMT, token),
                             timeout_s=flags.get_flag(
                                 "serving_write_timeout_ms") / 1e3)
        except StreamReset:
            self._end(seq, S.CANCELED, "stream reset by peer", rst=False)
            return False
        except StreamClosed:
            self._end(seq, S.CANCELED, "peer gone", rst=False)
            return False
        except StreamTimeout:
            self._end(seq, S.EVICTED, "slow consumer",
                      rst_code=errors.ELIMIT)
            return False
        except errors.RpcError:
            self._end(seq, S.CANCELED, "connection failed", rst=False)
            return False
        seq.generated += 1
        seq.last_token = token
        self.tokens_out += 1
        return self._grow(seq)

    def _grow(self, seq: S.Sequence) -> bool:
        """Charge blocks for the sequence's grown K/V; preempt-by-
        eviction (youngest first) when the pool runs dry."""
        needed = self.kv.blocks_needed(
            seq.total_len * self.sched.bytes_per_token)
        while self.kv.seq_blocks(seq.seq_id) < needed:
            try:
                self.kv.seq_grow(seq.seq_id)
            except Exception:  # PoolExhausted
                victim = self.sched.preempt_victim()
                if victim is None:
                    victim = seq
                self.preemptions += 1
                self._end(victim, S.EVICTED, "preempted: KV pool dry",
                          rst_code=errors.ELIMIT)
                if victim is seq:
                    return False
        return True

    def _end(self, seq: S.Sequence, state: str, reason: str,
             rst: bool = True, rst_code: int = errors.ECANCELED) -> None:
        """The single retirement path: slot back, blocks freed exactly
        once, stream closed (clean for FINISHED, RST otherwise)."""
        self.sched.release(seq, state, reason)
        if seq.slot >= 0:
            self.cache = D.reset_slot(self.cache, seq.slot)
        self.kv.seq_free(seq.seq_id)
        try:
            if state == S.FINISHED:
                seq.stream.close()
            elif rst:
                seq.stream.rst(rst_code)
        except Exception:
            pass  # the peer may already be gone; accounting is done
