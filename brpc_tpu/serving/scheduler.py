"""Continuous-batching scheduler — admit/evict per decode step against
the KV block budget (≙ the overload posture of ISSUE 11 applied to a
serving loop: shed with ELIMIT BEFORE any device work, never queue past
budget; the decode-slot churn itself is the vLLM-style continuous batch,
which the reference's §2.9 combo channels have no analogue for — see the
PARITY.md ruling).

Admission is optimistic about decode growth: a sequence is charged its
PROMPT blocks up front and grows block-by-block as it decodes, so the
pool can overcommit — exactly the pressure `preempt_victim()` resolves
by evicting the youngest running sequence when `seq_grow` hits
PoolExhausted.  The two shed reasons stay distinct in the counters:

    shed_queue   the waiting room is full (serving_max_waiting)
    shed_budget  prompt blocks + the waiting room's commitments exceed
                 the pool budget
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional

from brpc_tpu.rpc import errors
from brpc_tpu.serving.kv_cache import KvBlockPlane
from brpc_tpu.utils import flags

flags.define_int32(
    "serving_max_waiting",
    int(os.environ.get("TRPC_SERVING_MAX_WAITING", "4")),
    "continuous-batching waiting-room depth; admission sheds ELIMIT "
    "beyond it (scheduler.py)",
    reloadable=False)

# sequence states
WAITING = "waiting"
RUNNING = "running"
FINISHED = "finished"
EVICTED = "evicted"      # preempted or shed mid-decode (ELIMIT surface)
CANCELED = "canceled"    # client RST / RPC cancel / dead socket


@dataclass
class Sequence:
    """One generation request, from admission to drained blocks."""
    seq_id: int
    prompt: List[int]
    max_new_tokens: int
    stream: object = None          # rpc.stream.Stream (server half)
    cntl: object = None            # rpc.controller.Controller
    state: str = WAITING
    slot: int = -1
    generated: int = 0
    last_token: int = 0
    submit_ns: int = field(default_factory=time.monotonic_ns)
    admit_ns: int = 0
    end_reason: str = ""

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def total_len(self) -> int:
        return len(self.prompt) + self.generated


class Scheduler:
    """Slots + waiting room over one KvBlockPlane.  submit() runs on
    handler threads; everything else on the engine's decode loop."""

    def __init__(self, n_slots: int, kv: KvBlockPlane,
                 bytes_per_token: int,
                 max_waiting: Optional[int] = None):
        self.n_slots = n_slots
        self.kv = kv
        self.bytes_per_token = bytes_per_token
        self.max_waiting = (max_waiting if max_waiting is not None
                            else flags.get_flag("serving_max_waiting"))
        self._lock = threading.Lock()
        self._slots: List[Optional[Sequence]] = [None] * n_slots
        self._waiting: deque = deque()
        self.work = threading.Event()
        # counters (engine.stats() merges these)
        self.submitted = 0
        self.admitted = 0
        self.shed_queue = 0
        self.shed_budget = 0
        self.finished = 0
        self.evicted = 0
        self.canceled = 0

    # -- admission (handler threads) ----------------------------------------

    def prompt_blocks(self, seq: Sequence) -> int:
        return self.kv.blocks_needed(seq.prompt_len * self.bytes_per_token)

    def submit(self, seq: Sequence) -> None:
        """Admit into the waiting room or shed with ELIMIT — decided
        here, before any prefill compute or DMA happens."""
        with self._lock:
            self.submitted += 1
            if len(self._waiting) >= self.max_waiting:
                self.shed_queue += 1
                raise errors.RpcError(
                    errors.ELIMIT,
                    f"serving waiting room full "
                    f"({self.max_waiting} sequences)")
            need = self.prompt_blocks(seq)
            committed = sum(self.prompt_blocks(s) for s in self._waiting)
            if self.kv.used_blocks + committed + need > self.kv.n_blocks:
                self.shed_budget += 1
                raise errors.RpcError(
                    errors.ELIMIT,
                    f"KV block budget exhausted "
                    f"(need {need}, used {self.kv.used_blocks}, "
                    f"committed {committed} of {self.kv.n_blocks})")
            self._waiting.append(seq)
        self.work.set()

    # -- decode-loop side ---------------------------------------------------

    def pop_admittable(self) -> Optional[Sequence]:
        """Next waiting sequence IF a slot is free (the caller prefills
        it; the slot is reserved before the lock drops)."""
        with self._lock:
            if not self._waiting:
                return None
            try:
                slot = self._slots.index(None)
            except ValueError:
                return None
            seq = self._waiting.popleft()
            seq.slot = slot
            seq.state = RUNNING
            seq.admit_ns = time.monotonic_ns()
            self._slots[slot] = seq
            self.admitted += 1
            return seq

    def running(self) -> List[Sequence]:
        with self._lock:
            return [s for s in self._slots if s is not None]

    def waiting_depth(self) -> int:
        with self._lock:
            return len(self._waiting)

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._waiting) or \
                any(s is not None for s in self._slots)

    def preempt_victim(self) -> Optional[Sequence]:
        """Youngest running sequence — the one whose eviction wastes the
        least completed work (last admitted, LIFO preemption)."""
        with self._lock:
            live = [s for s in self._slots if s is not None]
            if not live:
                return None
            return max(live, key=lambda s: s.admit_ns)

    def release(self, seq: Sequence, state: str, reason: str = "") -> None:
        """Retire a sequence from its slot (finish/evict/cancel).  Block
        freeing is the engine's job (it owns the order vs stream close);
        this just flips the state machine and the counters."""
        with self._lock:
            if 0 <= seq.slot < self.n_slots and \
                    self._slots[seq.slot] is seq:
                self._slots[seq.slot] = None
            if seq.state in (FINISHED, EVICTED, CANCELED):
                return  # already retired (racing cancel vs finish)
            seq.state = state
            seq.end_reason = reason
            if state == FINISHED:
                self.finished += 1
            elif state == EVICTED:
                self.evicted += 1
            elif state == CANCELED:
                self.canceled += 1
        self.work.set()

    def drain_waiting(self) -> List[Sequence]:
        """Teardown: pull everything still queued."""
        with self._lock:
            out = list(self._waiting)
            self._waiting.clear()
            return out
