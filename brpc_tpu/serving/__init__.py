"""serving/ — the LLM-serving flagship workload (ISSUE 14).

The first workload that earns the runtime: continuous batching over a
pjit decode loop, with the KV cache living on the device plane as
budgeted blocks and every client a streaming RPC.

Three layers, composed bottom-up:

* :mod:`kv_cache` — the KV-cache block plane.  A sequence's prefill K/V
  is chunked into fixed-size ``tpu_plane.DeviceBufPool`` blocks on the
  prefill device and migrated to the decode device over the ``tpu_d2d``
  local rail (host landing-zone rail with optional bf16/int8 codec when
  the ends don't share a PJRT client — the PARITY ruling's fallback
  shape).  Hard accounting: blocks free on finish/evict/cancel and
  ``tpu_plane.stats()`` balances to zero after a drain.
* :mod:`scheduler` — continuous batching.  Admission sheds with ELIMIT
  against the block budget BEFORE any device work (the PR-11 overload
  posture: shed, never queue, beyond budget); running sequences
  admit/evict per decode step; preemption-by-eviction when the pool
  runs dry mid-decode.
* :mod:`engine` — the serving front-end.  A stream-RPC handler feeds
  the scheduler; one decode-loop thread drives
  ``models/decode.decode_step`` under pjit and fans one token per step
  to each live stream; stream RST / RPC cancel / slow-consumer timeout
  evict the sequence and free its blocks.

``examples/llm_server.py`` is the end-to-end proof;
``tools/rpc_press.py --stream`` is the load cannon.
"""

from brpc_tpu.serving.engine import ServingEngine  # noqa: F401
from brpc_tpu.serving.kv_cache import KvBlockPlane  # noqa: F401
from brpc_tpu.serving.scheduler import Scheduler, Sequence  # noqa: F401
