"""XLA collectives over a mesh axis — the data plane ParallelChannel and
PartitionChannel lower onto (SURVEY.md §2.9: "AllGather/AllReduce fan-out +
merge over ICI; merger = XLA reduction op").

Everything here is shard_map over a Mesh: callers hand in a host-side global
array (or an already-sharded jax.Array) and name the axis; XLA emits the
collective and it rides ICI.  These are the primitive verbs; the RPC-flavored
API (fail_limit, CallMapper/ResponseMerger) lives in parallel/channels.py.

`bus_bandwidth_gbps` is the driver's "ICI allreduce bus-bw" metric
(BASELINE.json): algbw * 2*(n-1)/n, the standard ring-allreduce bus formula.
"""

from __future__ import annotations

import time
from functools import lru_cache
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from brpc_tpu.utils.jaxenv import shard_map_compat

shard_map, _SHMAP_NOCHECK = shard_map_compat()


def _shmap(mesh: Mesh, axis: str, body: Callable, in_spec, out_spec):
    return shard_map(body, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
                     **_SHMAP_NOCHECK)


@lru_cache(maxsize=None)
def _jitted(kind: str, mesh: Mesh, axis: str, extra):
    """One compiled executable per (verb, mesh, axis, extra) — jit caches by
    function identity, so the closure must be built once, not per call."""
    if kind == "all_reduce":
        red = {"add": jax.lax.psum, "max": jax.lax.pmax,
               "min": jax.lax.pmin}[extra]

        def body(s):
            return red(s, axis)

        return jax.jit(_shmap(mesh, axis, body, P(axis), P(axis)))
    if kind == "all_gather":

        def body(s):
            return jax.lax.all_gather(s, axis, tiled=extra)

        return jax.jit(_shmap(mesh, axis, body, P(axis), P()))
    if kind == "reduce_scatter":

        def body(s):
            return jax.lax.psum_scatter(s, axis, scatter_dimension=0,
                                        tiled=True)

        return jax.jit(_shmap(mesh, axis, body, P(axis), P(axis)))
    if kind == "ring_permute":
        n = mesh.shape[axis]
        perm = [(i, (i + extra) % n) for i in range(n)]

        def body(s):
            return jax.lax.ppermute(s, axis, perm)

        return jax.jit(_shmap(mesh, axis, body, P(axis), P(axis)))
    if kind == "all_to_all":

        def body(s):
            return jax.lax.all_to_all(s, axis, split_axis=1, concat_axis=0,
                                      tiled=True)

        return jax.jit(_shmap(mesh, axis, body, P(axis), P(None, axis)))
    raise ValueError(kind)


def all_reduce(mesh: Mesh, axis: str, x, op: str = "add"):
    """psum/pmax/pmin over one mesh axis; x is sharded on `axis` along dim 0.

    ≙ ParallelChannel broadcast + ResponseMerger when the merger is a
    reduction (reference parallel_channel.h:127).
    """
    return _jitted("all_reduce", mesh, axis, op)(x)


def all_gather(mesh: Mesh, axis: str, x, *, tiled: bool = True):
    """Gather shards along dim 0 of one mesh axis onto every member."""
    return _jitted("all_gather", mesh, axis, tiled)(x)


def reduce_scatter(mesh: Mesh, axis: str, x):
    """psum_scatter: reduce over the axis, leave each member 1/n of dim 0."""
    return _jitted("reduce_scatter", mesh, axis, None)(x)


def ring_permute(mesh: Mesh, axis: str, x, shift: int = 1):
    """ppermute ring step — the building block of ring attention and
    pipeline-parallel stage handoff."""
    return _jitted("ring_permute", mesh, axis, shift)(x)


def all_to_all(mesh: Mesh, axis: str, x):
    """The Ulysses sequence-parallel verb: reshard a 2D+ array from
    dim0-sharded to dim1-sharded (gather sequence, scatter heads).  The
    global value is unchanged; only the layout moves."""
    return _jitted("all_to_all", mesh, axis, None)(x)


def bus_bandwidth_gbps(mesh: Mesh, axis: str,
                       mbytes_per_shard: float = 64.0,
                       iters: int = 10,
                       dtype=jnp.bfloat16) -> float:
    """Measure allreduce bus bandwidth over a mesh axis.

    busbw = algbw * 2*(n-1)/n  (ring allreduce moves 2*(n-1)/n bytes per
    byte reduced).  This is the driver's ICI allreduce metric.
    """
    n = mesh.shape[axis]
    elems = int(mbytes_per_shard * 1e6 / jnp.dtype(dtype).itemsize)
    sharding = NamedSharding(mesh, P(axis))
    x = jax.device_put(
        jnp.ones((n * elems,), dtype=dtype), sharding)

    def body(s):
        return jax.lax.psum(s, axis)

    fn = jax.jit(_shmap(mesh, axis, body, P(axis), P(axis)))
    fn(x).block_until_ready()  # compile + warm
    t0 = time.perf_counter()
    y = x
    for _ in range(iters):
        y = fn(y)
    jax.block_until_ready(y)
    dt = time.perf_counter() - t0
    algbw = mbytes_per_shard * 1e6 * iters / dt / 1e9
    return algbw * 2 * (n - 1) / n
