"""parallel/ — distribution layer.

Two halves, mirroring the reference's split between "combo channels" and the
transport underneath (SURVEY.md §2.9):

  mesh.py / collectives.py — the TPU-native lowering target: a
      jax.sharding.Mesh over ICI/DCN plus XLA collectives (psum, all_gather,
      reduce_scatter, ppermute).  This is the layer ParallelChannel and
      PartitionChannel lower onto when their member set is a mesh axis
      (reference parallel_channel.h:185, partition_channel.h:136).
  channels.py — the host-side combo channels themselves (CallMapper /
      ResponseMerger / fail_limit semantics) for heterogeneous member sets
      that are NOT a mesh axis (talking over TCP/DCN like the reference).
"""

from brpc_tpu.parallel.mesh import (  # noqa: F401
    auto_mesh,
    axis_size,
    make_mesh,
)
from brpc_tpu.parallel.collectives import (  # noqa: F401
    all_gather,
    all_reduce,
    all_to_all,
    bus_bandwidth_gbps,
    reduce_scatter,
    ring_permute,
)
from brpc_tpu.parallel.channels import (  # noqa: F401
    CallMapper,
    DynamicPartitionChannel,
    FirstResponseMerger,
    MeshParallelChannel,
    MeshPartitionChannel,
    ParallelChannel,
    PartitionChannel,
    PartitionParser,
    ResponseMerger,
    SelectiveChannel,
    SubCall,
)
