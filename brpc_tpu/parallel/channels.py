"""Combo channels — fan-out/merge, partitioned, and selective composition
(≙ reference ParallelChannel parallel_channel.h:94-216, PartitionChannel /
DynamicPartitionChannel partition_channel.h:46-136, SelectiveChannel
selective_channel.h:52-72 — re-designed: the host side keeps the
CallMapper/ResponseMerger/fail_limit vocabulary, and the same vocabulary
lowers to ONE XLA collective over a mesh axis when the member set is a TPU
mesh axis instead of N host RPCs, per SURVEY.md §2.9's lowering table).

Host-side classes (heterogeneous members over TCP/DCN):
    ParallelChannel   — scatter/broadcast to all sub-channels, merge
    PartitionChannel  — shard one logical request across "i/n"-tagged
                        partitions from a naming service
    DynamicPartitionChannel — several partitioning schemes live at once,
                        traffic weighted by scheme capacity
    SelectiveChannel  — LB across sub-channels, failover between them

Mesh lowering (member set == a mesh axis):
    MeshParallelChannel  — merge IS the collective: psum/pmax/concat ride
                           ICI (all_reduce / all_gather)
    MeshPartitionChannel — partitioned request = sharded array; gather or
                           reduce-scatter is the merge
"""

from __future__ import annotations

import random
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from brpc_tpu.cluster.naming import (ServerNode, Watcher,
                                     acquire_naming_watcher)
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller

# --- call mapping / response merging (≙ parallel_channel.h:94,127) ---------


@dataclass
class SubCall:
    """What one sub-channel should be asked (≙ reference SubCall: method +
    request + flags)."""
    method: str
    payload: bytes
    attachment: bytes = b""


SKIP = None  # a CallMapper may return SKIP to leave a sub-channel out


class CallMapper:
    """Maps the logical call onto each sub-channel
    (≙ CallMapper::Map(channel_index, method, request), return SKIP to
    skip).  Default: broadcast the same request to every member."""

    def map(self, channel_index: int, nchannels: int, method: str,
            payload: bytes, attachment: bytes) -> Optional[SubCall]:
        return SubCall(method, payload, attachment)


class ResponseMerger:
    """Merges sub-responses into the final response
    (≙ ResponseMerger::Merge).  `results` has one slot per sub-channel:
    bytes on success, None on failure or SKIP.  Default: in-order concat
    of successes."""

    def merge(self, results: List[Optional[bytes]]) -> bytes:
        return b"".join(r for r in results if r is not None)


class FirstResponseMerger(ResponseMerger):
    """First successful response wins (broadcast-race semantics)."""

    def merge(self, results: List[Optional[bytes]]) -> bytes:
        for r in results:
            if r is not None:
                return r
        return b""


# --- ParallelChannel -------------------------------------------------------


class ParallelChannel:
    """Fan a call out to every sub-channel concurrently and merge
    (≙ brpc::ParallelChannel, parallel_channel.h:185; fail_limit :168).

    fail_limit=None means every mapped sub-call must succeed (the
    reference's default); fail_limit=k tolerates up to k failures.
    """

    def __init__(self, response_merger: Optional[ResponseMerger] = None,
                 fail_limit: Optional[int] = None,
                 timeout_ms: float = 1000.0):
        self._subs: List[Tuple[object, CallMapper]] = []
        self._merger = response_merger or ResponseMerger()
        self.fail_limit = fail_limit
        self.timeout_ms = timeout_ms
        self._pool: Optional[ThreadPoolExecutor] = None
        self._lock = threading.Lock()

    def add_channel(self, channel, call_mapper: Optional[CallMapper] = None):
        """`channel` is anything with .call(method, payload, attachment=,
        cntl=) — an rpc.Channel, another combo channel, ... (the reference
        nests combo channels the same way)."""
        self._subs.append((channel, call_mapper or CallMapper()))

    def channel_count(self) -> int:
        return len(self._subs)

    def _submit_all(self, fn, arg_tuples):
        """Grow-and-submit atomically: submissions happen under the lock,
        so a pool replaced by a concurrent grower can be shut down right
        away — nobody can be submitting to it (shutdown(wait=False) lets
        already-submitted work finish on the old pool's threads)."""
        with self._lock:
            want = max(4, 2 * len(self._subs))
            if self._pool is None or self._pool._max_workers < want:
                old = self._pool
                self._pool = ThreadPoolExecutor(
                    max_workers=want,
                    thread_name_prefix="parallel_channel")
                if old is not None:
                    old.shutdown(wait=False)
            return [self._pool.submit(fn, *args) for args in arg_tuples]

    def close(self):
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def _native_fanout_attempt(self, pending, results, cntl,
                               fail_codes) -> bool:
        """Serialize-once fast path: when every mapped member is a plain
        rpc.Channel with a direct native sub-channel and the mapper
        broadcast identical SubCalls, issue the whole group through ONE
        native channel_fanout_call — the request body is serialized once
        and shared as refcounted IOBuf blocks across all N frames
        (native_fanout_shared_serializations counts exactly 1 per group),
        and sub-responses are completed by the arriving parse fibers
        instead of trampolining through a pool thread per sub-response.
        Returns False when the group is not eligible (heterogeneous
        members, per-member payloads, cluster/compressed channels) — the
        caller then takes the thread-pool path.  Failed members are left
        as None in `results` with their native error code recorded in
        `fail_codes[i]`, so the caller can apply each channel's OWN retry
        policy before re-issuing anything."""
        if not pending:
            return True
        from brpc_tpu.rpc.channel import Channel as _RpcChannel
        from brpc_tpu.rpc.channel import native_fanout
        first_sc = pending[0][1]
        subs = []
        for i, sc in pending:
            ch = self._subs[i][0]
            if (not isinstance(ch, _RpcChannel) or ch._cluster is not None
                    or ch._sub is None
                    or ch.options.request_compress_type
                    # backup-request hedging lives in Channel.call's
                    # _backup_race — a member that asked for it must not
                    # silently lose the hedge to the native wave
                    or ch.options.backup_request_ms is not None
                    or cntl.backup_request_ms is not None):
                return False
            if (sc.method != first_sc.method
                    or sc.payload != first_sc.payload
                    or sc.attachment != first_sc.attachment):
                return False  # not a broadcast: nothing to share
            # the fast path bypasses Channel.call, so time-boxed
            # credentials must rotate HERE too (no-op without an
            # authenticator) — a fanout-only workload otherwise starts
            # failing EAUTH at max_skew_s
            ch._maybe_refresh_credential()
            subs.append(ch._sub)
        timeout_ms = (cntl.timeout_ms if cntl.timeout_ms is not None
                      else self.timeout_ms)
        # observability parity with Channel.call: one rpcz span per
        # sub-call, the class-wide client LatencyRecorder, and the tpu://
        # transport-settled announcement — the fast path must not make
        # the metrics the parity docs key on silently vanish
        import time as _t
        from brpc_tpu.rpc import span as span_mod
        sps = [span_mod.start_span("client", first_sc.method)
               for _ in pending]
        t0 = _t.monotonic_ns()
        try:
            outs = native_fanout(subs, first_sc.method.encode(),
                                 first_sc.payload, first_sc.attachment,
                                 int(timeout_ms * 1000))
        except Exception:
            for sp in sps:
                if sp is not None:
                    span_mod.finish_span(sp, errors.EINTERNAL)
            return False  # e.g. a member closed mid-call: slow path
        lat_us = (_t.monotonic_ns() - t0) // 1000
        any_ok = False
        for k, ((i, _), (code, text, data, _att)) in enumerate(
                zip(pending, outs)):
            if sps[k] is not None:
                span_mod.finish_span(sps[k], code)
            if code == 0:
                results[i] = data
                any_ok = True
                self._subs[i][0]._check_transport_settled()
            else:
                fail_codes[i] = (code, text)
        # the native API times the GROUP, not each member: record that
        # wall-clock ONCE (recording it per member would weight every
        # sample at the slowest member's latency and inflate the
        # class-wide rpc_client percentiles N-fold)
        if any_ok and _RpcChannel._latency is not None:
            _RpcChannel._latency.record(lat_us)
        return True

    def call(self, method: str, payload: bytes = b"",
             attachment: bytes = b"",
             cntl: Optional[Controller] = None) -> bytes:
        cntl = cntl or Controller()
        n = len(self._subs)
        if n == 0:
            raise errors.RpcError(errors.ENOSERVICE, "no sub-channels")
        mapped: List[Optional[SubCall]] = [
            mapper.map(i, n, method, payload, attachment)
            for i, (_, mapper) in enumerate(self._subs)]
        results: List[Optional[bytes]] = [None] * n
        first_err: List[Optional[errors.RpcError]] = [None]

        import time as _t
        start = _t.monotonic()
        total_ms = (cntl.timeout_ms if cntl.timeout_ms is not None
                    else self.timeout_ms)

        def one(i: int, sub_call: SubCall, max_retry=None,
                timeout_ms=None):
            ch, _ = self._subs[i]
            sub_cntl = Controller()
            sub_cntl.timeout_ms = (total_ms if timeout_ms is None
                                   else timeout_ms)
            sub_cntl.max_retry = max_retry  # None = the channel's own
            try:
                results[i] = ch.call(sub_call.method, sub_call.payload,
                                     attachment=sub_call.attachment,
                                     cntl=sub_cntl)
            except errors.RpcError as e:
                if first_err[0] is None:
                    first_err[0] = e

        pending = [(i, sc) for i, sc in enumerate(mapped) if sc is not None]
        fail_codes: Dict[int, Tuple[int, str]] = {}
        if self._native_fanout_attempt(pending, results, cntl, fail_codes):
            # happy path done natively.  The unhappy tail re-runs through
            # the per-sub path ONLY where that channel's own retry policy
            # says the error is retriable — re-issuing a timed-out
            # non-idempotent call would execute it twice (the default
            # policy deliberately excludes ERPCTIMEDOUT, channel.py).
            from brpc_tpu.rpc.channel import _default_retry
            retriable = []
            for i, sc in pending:
                if results[i] is not None:
                    continue
                code, text = fail_codes.get(i, (errors.EINTERNAL, ""))
                ch = self._subs[i][0]
                policy = (getattr(ch, "options", None)
                          and ch.options.retry_policy) or _default_retry
                # the native wave spent attempt #1 AND part of the clock:
                # the fallback gets the REMAINING attempt budget and the
                # REMAINING deadline, so a max_retry=0 channel executes
                # exactly once and the group never exceeds its timeout
                budget = (cntl.max_retry if cntl.max_retry is not None
                          else ch.options.max_retry)
                left_ms = total_ms - (_t.monotonic() - start) * 1e3
                probe = Controller()
                probe.error_code, probe.error_text = code, text
                if budget > 0 and left_ms > 1.0 and policy.do_retry(probe):
                    retriable.append((i, sc, budget - 1, left_ms))
                elif first_err[0] is None:
                    first_err[0] = errors.RpcError(code, text)
            pending = retriable
        if pending:
            futures = self._submit_all(one, pending)
            for f in futures:
                f.result()
        mapped_n = sum(1 for sc in mapped if sc is not None)
        ok_n = sum(1 for i, sc in enumerate(mapped)
                   if sc is not None and results[i] is not None)
        failures = mapped_n - ok_n
        limit = self.fail_limit if self.fail_limit is not None else 0
        if failures > limit:
            err = first_err[0] or errors.RpcError(errors.EINTERNAL)
            cntl.set_failed(err.code, err.text)
            raise errors.RpcError(
                err.code, f"{failures}/{mapped_n} sub-calls failed "
                          f"(fail_limit={limit}): {err.text}")
        return self._merger.merge(results)


# --- PartitionChannel ------------------------------------------------------


class PartitionParser:
    """Parses a naming tag into (partition_index, partition_count), or None
    if the tag is not a partition of this channel (≙ reference
    PartitionParser::ParseFromTag, partition_channel.h:46).  Default tag
    grammar: "i/n" e.g. "0/4"."""

    def parse(self, tag: str) -> Optional[Tuple[int, int]]:
        try:
            i, n = tag.split("/", 1)
            i, n = int(i), int(n)
        except ValueError:
            return None
        if n <= 0 or not 0 <= i < n:
            return None
        return i, n


class PartitionChannel:
    """Shards one logical request across the partitions of a cluster
    (≙ brpc::PartitionChannel, partition_channel.h:75).  Members come from
    a naming service whose tags carry "i/n"; each partition index becomes
    one sub-cluster (its own LB over the replicas of that partition), and
    a call fans out to ALL n partitions through the CallMapper/Merger
    machinery.  `partition_count` pins n; nodes of other schemes are
    ignored (DynamicPartitionChannel handles mixed schemes)."""

    def __init__(self, naming_url: str, partition_count: int,
                 call_mapper: Optional[CallMapper] = None,
                 response_merger: Optional[ResponseMerger] = None,
                 fail_limit: Optional[int] = None,
                 load_balancer: str = "rr",
                 timeout_ms: float = 1000.0):
        from brpc_tpu.rpc.channel import Channel  # cycle: parallel ↔ rpc
        self._Channel = Channel
        self.partition_count = partition_count
        self._parser = PartitionParser()
        self._mapper = call_mapper or CallMapper()
        self._merger = response_merger or ResponseMerger()
        self.fail_limit = fail_limit
        self.load_balancer = load_balancer
        self.timeout_ms = timeout_ms
        self._lock = threading.Lock()
        self._members: Dict[int, List[ServerNode]] = {}
        self._parts: Dict[int, object] = {}  # index -> rpc.Channel
        self._pc: Optional[ParallelChannel] = None  # persistent fan-out
        self._watcher = _PartitionWatcher(self)
        self._ns = acquire_naming_watcher(naming_url, self._watcher)
        self._ns.wait_first_resolve()
        self._rebuild(self._ns.nodes())

    # membership → per-partition list:// channels ---------------------------

    def _rebuild(self, nodes: Sequence[ServerNode]) -> None:
        groups: Dict[int, List[ServerNode]] = {}
        for node in nodes:
            parsed = self._parser.parse(node.tag)
            if parsed is None or parsed[1] != self.partition_count:
                continue
            groups.setdefault(parsed[0], []).append(node)
        old_pc = None
        with self._lock:
            old = self._members
            self._members = groups
            stale = [i for i in self._parts
                     if groups.get(i) != old.get(i)]
            for i in stale:
                ch = self._parts.pop(i)
                ch.close()
            if stale or groups.keys() != old.keys():
                old_pc, self._pc = self._pc, None  # fan-out set changed
        if old_pc is not None:
            old_pc.close()

    def _get_pc(self) -> "ParallelChannel":
        """The persistent fan-out channel: one member per logical partition
        (a dead placeholder where the partition has no servers, so the
        merger always sees `partition_count` positional slots and a missing
        partition counts against the SAME fail_limit as a failed RPC)."""
        with self._lock:
            if self._pc is not None:
                return self._pc
            pc = ParallelChannel(self._merger, self.fail_limit,
                                 self.timeout_ms)
            n = self.partition_count
            for i in range(n):
                ch = self._parts.get(i)
                if ch is None:
                    members = self._members.get(i, [])
                    if members:
                        url = "list://" + ",".join(
                            str(m.endpoint) for m in members)
                        ch = self._parts[i] = self._Channel(
                            url, load_balancer=self.load_balancer,
                            timeout_ms=self.timeout_ms)
                pc.add_channel(ch if ch is not None else _DeadChannel(i),
                               _FixedIndexMapper(self._mapper, i, n))
            self._pc = pc
            return pc

    def partitions_ready(self) -> int:
        with self._lock:
            return sum(1 for i in range(self.partition_count)
                       if self._members.get(i))

    # call ------------------------------------------------------------------

    def call(self, method: str, payload: bytes = b"",
             attachment: bytes = b"",
             cntl: Optional[Controller] = None) -> bytes:
        return self._get_pc().call(method, payload, attachment, cntl)

    def close(self):
        self._ns.remove_watcher(self._watcher)
        with self._lock:
            parts, self._parts = self._parts, {}
            pc, self._pc = self._pc, None
        for ch in parts.values():
            ch.close()
        if pc is not None:
            pc.close()


class _DeadChannel:
    """Placeholder member for a partition with no resolved servers — every
    call fails with ENOSERVICE so the missing partition spends the shared
    fail_limit budget exactly like a failed RPC."""

    def __init__(self, index: int):
        self._index = index

    def call(self, method, payload=b"", attachment=b"", cntl=None):
        raise errors.RpcError(errors.ENOSERVICE,
                              f"partition {self._index} has no servers")


class _FixedIndexMapper(CallMapper):
    """Adapts the user's mapper so partition i keeps its logical index even
    though the ParallelChannel underneath renumbers its members."""

    def __init__(self, inner: CallMapper, index: int, count: int):
        self._inner = inner
        self._index = index
        self._count = count

    def map(self, channel_index, nchannels, method, payload, attachment):
        return self._inner.map(self._index, self._count, method, payload,
                               attachment)


class _PartitionWatcher(Watcher):
    def __init__(self, owner: PartitionChannel):
        self._owner = owner

    def on_servers(self, added, removed, all_nodes):
        self._owner._rebuild(all_nodes)


class DynamicPartitionChannel:
    """Several partitioning schemes coexist; traffic is weighted by each
    scheme's capacity so migrations (2-way → 4-way) drain the old scheme
    gradually (≙ brpc::DynamicPartitionChannel, partition_channel.h:136,
    docs: dynamic_partition_echo example).  Capacity of scheme n = the
    number of complete replica sets it can serve ≈ min over partitions of
    the replica count (0 while incomplete)."""

    def __init__(self, naming_url: str,
                 call_mapper: Optional[CallMapper] = None,
                 response_merger: Optional[ResponseMerger] = None,
                 fail_limit: Optional[int] = None,
                 load_balancer: str = "rr",
                 timeout_ms: float = 1000.0):
        self._naming_url = naming_url
        self._mapper = call_mapper
        self._merger = response_merger
        self._fail_limit = fail_limit
        self._lb = load_balancer
        self._timeout_ms = timeout_ms
        self._lock = threading.Lock()
        self._schemes: Dict[int, PartitionChannel] = {}
        self._watcher = _DynWatcher(self)
        self._ns = acquire_naming_watcher(naming_url, self._watcher)
        self._ns.wait_first_resolve()
        self._sync_schemes(self._ns.nodes())

    def _sync_schemes(self, nodes: Sequence[ServerNode]) -> None:
        parser = PartitionParser()
        counts = set()
        for node in nodes:
            parsed = parser.parse(node.tag)
            if parsed is not None:
                counts.add(parsed[1])
        with self._lock:
            for n in counts:
                if n not in self._schemes:
                    self._schemes[n] = PartitionChannel(
                        self._naming_url, n, self._mapper, self._merger,
                        self._fail_limit, self._lb, self._timeout_ms)
            for n in list(self._schemes):
                if n not in counts:
                    self._schemes.pop(n).close()

    def scheme_capacities(self) -> Dict[int, int]:
        """scheme → complete replica sets (min replicas across partitions)."""
        with self._lock:
            schemes = dict(self._schemes)
        caps = {}
        for n, pc in schemes.items():
            with pc._lock:
                replicas = [len(pc._members.get(i, []))
                            for i in range(n)]
            caps[n] = min(replicas) if replicas and all(replicas) else 0
        return caps

    def call(self, method: str, payload: bytes = b"",
             attachment: bytes = b"",
             cntl: Optional[Controller] = None) -> bytes:
        caps = self.scheme_capacities()
        total = sum(caps.values())
        if total == 0:
            raise errors.RpcError(errors.ENOSERVICE,
                                  "no complete partitioning scheme")
        # weighted pick by capacity (≙ dynpart LB weighting by scheme size)
        r = random.uniform(0, total)
        acc = 0.0
        chosen = None
        for n, cap in sorted(caps.items()):
            acc += cap
            if r <= acc and cap > 0:
                chosen = n
                break
        if chosen is None:
            chosen = max((cap, n) for n, cap in caps.items())[1]
        with self._lock:
            pc = self._schemes.get(chosen)
        if pc is None:
            # naming update removed the scheme between snapshot and lookup
            raise errors.RpcError(
                errors.ENOSERVICE,
                f"partitioning scheme {chosen} disappeared during call")
        return pc.call(method, payload, attachment, cntl)

    def close(self):
        self._ns.remove_watcher(self._watcher)
        with self._lock:
            schemes, self._schemes = self._schemes, {}
        for pc in schemes.values():
            pc.close()


class _DynWatcher(Watcher):
    def __init__(self, owner: DynamicPartitionChannel):
        self._owner = owner

    def on_servers(self, added, removed, all_nodes):
        self._owner._sync_schemes(all_nodes)


# --- SelectiveChannel ------------------------------------------------------


class SelectiveChannel:
    """Load-balances whole calls across heterogeneous sub-channels and
    fails over between them (≙ brpc::SelectiveChannel,
    selective_channel.h:52: each sub-channel is one LB unit; a failed
    attempt moves to another unit).  Sub-channels can themselves be combo
    channels — slice-level failover in the TPU mapping (SURVEY §2.9)."""

    def __init__(self, max_retry: int = 1, isolation_s: float = 5.0):
        self._subs: List[object] = []
        self._bad_until: List[float] = []
        self._rr = 0
        self._lock = threading.Lock()
        self.max_retry = max_retry
        self.isolation_s = isolation_s

    def add_channel(self, channel) -> int:
        with self._lock:
            self._subs.append(channel)
            self._bad_until.append(0.0)
            return len(self._subs) - 1

    def channel_count(self) -> int:
        return len(self._subs)

    def _pick(self, excluded: set) -> Optional[int]:
        import time as _t
        now = _t.monotonic()
        with self._lock:
            n = len(self._subs)
            for off in range(n):
                i = (self._rr + off) % n
                if i in excluded:
                    continue
                if self._bad_until[i] <= now:
                    self._rr = i + 1
                    return i
            # all isolated/excluded: least-recently-bad non-excluded
            candidates = [i for i in range(n) if i not in excluded]
            if not candidates:
                return None
            return min(candidates, key=lambda i: self._bad_until[i])

    def call(self, method: str, payload: bytes = b"",
             attachment: bytes = b"",
             cntl: Optional[Controller] = None) -> bytes:
        import time as _t
        cntl = cntl or Controller()
        if not self._subs:
            raise errors.RpcError(errors.ENOSERVICE, "no sub-channels")
        excluded: set = set()
        last: Optional[errors.RpcError] = None
        for _ in range(self.max_retry + 1):
            i = self._pick(excluded)
            if i is None:
                break
            try:
                out = self._subs[i].call(method, payload,
                                         attachment=attachment, cntl=cntl)
                with self._lock:
                    self._bad_until[i] = 0.0
                return out
            except errors.RpcError as e:
                last = e
                excluded.add(i)
                with self._lock:
                    self._bad_until[i] = _t.monotonic() + self.isolation_s
        raise last or errors.RpcError(errors.ENOSERVICE,
                                      "all sub-channels failed")


# --- mesh lowering (SURVEY §2.9: fan-out+merge = ONE XLA collective) -------


class MeshParallelChannel:
    """ParallelChannel whose member set IS a mesh axis: the request is the
    per-chip shard, the "RPC fan-out + ResponseMerger" pair is a single
    XLA collective riding ICI (reference lowering table, SURVEY §2.9:
    "AllGather/AllReduce fan-out+merge over ICI; merger = XLA reduction
    op").  merger: "add"/"max"/"min" → all_reduce; "concat" → all_gather.

    `codec` ("none"/"int8"/"bf16", parallel/quantize.py) runs the reduce
    leg lossy-but-bounded: each worker's shard is quantized with the
    native payload-codec formats (codec.h) and the merge DEQUANTIZES-
    THEN-REDUCES — the EQuARX-style quantized allreduce (arXiv
    2506.17615) on this rail.  int8's per-worker bound is
    max|block|/127; the n-way sum's bound is the per-worker bounds
    added (quantize.int8_error_bound).
    """

    def __init__(self, mesh, axis: str, merger: str = "add",
                 codec: str = "none"):
        from brpc_tpu.parallel import collectives, quantize
        self._c = collectives
        self._q = quantize
        self.mesh = mesh
        self.axis = axis
        if merger not in ("add", "max", "min", "concat"):
            raise ValueError(f"unknown merger {merger!r}")
        if codec not in ("none", "int8", "bf16"):
            raise ValueError(f"unknown codec {codec!r}")
        if codec != "none" and merger != "add":
            # the documented error bounds are ADDITIVE (per-worker
            # bounds summed); they say nothing about max/min/concat —
            # refuse rather than hand out an unbounded lossy merge
            raise ValueError(
                f"codec {codec!r} applies to merger='add' only (the "
                f"quantize.int8_error_bound contract is additive)")
        self.merger = merger
        self.codec = codec

    def channel_count(self) -> int:
        return self.mesh.shape[self.axis]

    def call_tensor(self, x):
        """The whole ParallelChannel.call, compiled: scatter is implicit in
        the sharding, merge is the collective (dequantize-then-reduce
        when a codec is set)."""
        if self.codec != "none":
            x = self._q.fake_quant(x, self.codec)
        if self.merger == "concat":
            return self._c.all_gather(self.mesh, self.axis, x)
        return self._c.all_reduce(self.mesh, self.axis, x, op=self.merger)


class MeshPartitionChannel:
    """PartitionChannel on a mesh axis: the logical request is an array
    sharded over the axis (partition i holds shard i); "merge" is either
    gathering every partition's answer (all_gather) or reducing partial
    answers while re-sharding (reduce_scatter) — the parameter-server
    allreduce of BASELINE.json's north star is call_reduce_scatter over
    the gradient."""

    def __init__(self, mesh, axis: str):
        from brpc_tpu.parallel import collectives
        self._c = collectives
        self.mesh = mesh
        self.axis = axis

    def partition_count(self) -> int:
        return self.mesh.shape[self.axis]

    def call_gather(self, x):
        return self._c.all_gather(self.mesh, self.axis, x)

    def call_reduce_scatter(self, x):
        return self._c.reduce_scatter(self.mesh, self.axis, x)

    def call_all_to_all(self, x):
        return self._c.all_to_all(self.mesh, self.axis, x)
