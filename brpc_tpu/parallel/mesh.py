"""Device mesh construction.

The reference reaches N peers by fanning RPCs over sub-channels
(parallel_channel.cpp:776); a TPU slice reaches N chips through a
jax.sharding.Mesh whose axes ride ICI.  This module owns the factoring of a
device list into named axes so every other layer (models, combo channels,
streaming) agrees on axis names:

  dp — data parallel (batch dim; gradient psum)
  sp — sequence/context parallel (long-context activations)
  tp — tensor parallel (heads / ffn-hidden; layer-internal collectives)
  ep — expert parallel (MoE experts)
  pp — pipeline parallel (layer stages; ppermute between stages)

Axis order is outer→inner = slowest→fastest-varying over the device list, so
`tp` (the most collective-chatty axis) lands on adjacent devices — the
layout that keeps its collectives on ICI neighbors (the analog of the
reference pinning hot sockets to one worker's io_uring, task_group.h:190).
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

AXIS_ORDER = ("pp", "dp", "ep", "sp", "tp")


def prune_spec(spec: PartitionSpec, mesh: Mesh) -> PartitionSpec:
    """Drop PartitionSpec axes the mesh doesn't define (-> replicated), so
    one spec tree serves every mesh layout (a (dp, sp) ring-attention mesh
    simply replicates tp/ep dimensions)."""
    def _ok(a):
        names = a if isinstance(a, tuple) else (a,)
        return all(n in mesh.shape for n in names)

    return PartitionSpec(
        *[a if (a is None or _ok(a)) else None for a in spec])


def make_mesh(axes: Dict[str, int],
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh from {axis_name: size}.

    Sizes must multiply to the device count; a single axis may be -1 to
    absorb the remainder (like a reshape).  The canonical parallelism axes
    (AXIS_ORDER) are laid out slowest-to-fastest in that order — tp stays
    innermost so its collectives (the chattiest) ride neighbor ICI links.
    Custom axes (e.g. a combo-channel fan-out group) go OUTERMOST, in
    insertion order, so they never break that adjacency; their names must
    be ≥3 chars (every canonical name is 2, so 2-char unknowns are almost
    certainly typos of a canonical axis).
    """
    devs = list(devices if devices is not None else jax.devices())
    custom = [a for a in axes if a not in AXIS_ORDER]
    bad = [a for a in custom if len(a) < 3]
    if bad:
        raise ValueError(f"unknown 2-char axes {bad} look like typos of "
                         f"the canonical axes {AXIS_ORDER}; custom axis "
                         f"names must be >=3 chars")
    names = custom + [a for a in AXIS_ORDER if a in axes]
    sizes = [axes[a] for a in names]
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    known = math.prod(s for s in sizes if s != -1)
    if -1 in sizes:
        if len(devs) % known:
            raise ValueError(
                f"{len(devs)} devices not divisible by {known}")
        sizes[sizes.index(-1)] = len(devs) // known
    if math.prod(sizes) != len(devs):
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} wants {math.prod(sizes)} "
            f"devices, have {len(devs)}")
    arr = np.asarray(devs, dtype=object).reshape(sizes)
    return Mesh(arr, tuple(names))


def auto_mesh(n_devices: Optional[int] = None,
              axis_names: Sequence[str] = ("dp", "sp", "tp")) -> Mesh:
    """Factor n devices into the given axes; tp gets a factor first, then
    dp, ep, sp, pp (see `priority` below — ep outranks sp so expert
    parallelism is never silently degenerate).

    8 devices over (dp, ep, sp, tp) → tp=2, dp=2, ep=2, sp=1;
    8 over (dp, sp, tp) → all 2; 4 over (dp, tp) → tp=2, dp=2; prime
    counts degrade gracefully (leftover axes get size 1).
    """
    devs = list(jax.devices())
    n = n_devices if n_devices is not None else len(devs)
    devs = devs[:n]
    # axes that should get device factors first: tp (chattiest, wants ICI
    # neighbors), then dp (the gradient-psum axis), then ep (the MoE
    # all-to-all must get a real factor before sp so expert parallelism is
    # never silently degenerate at 8 devices), then sp, pp
    priority = [a for a in ("tp", "dp", "ep", "sp", "pp") if a in axis_names]
    priority += [a for a in axis_names if a not in priority]
    sizes = dict.fromkeys(axis_names, 1)
    i = 0
    for p in sorted(_primes(n), reverse=True):
        sizes[priority[i % len(priority)]] *= p
        i += 1
    return make_mesh(sizes, devices=devs)


def _primes(n: int) -> list:
    out = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return out


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape.get(name, 1)
