"""Ring attention + Ulysses sequence parallelism — long-context attention
over a mesh axis.

Capability lineage: the reference has NO sequence parallelism (SURVEY.md
§5.7 — what it has is PartitionChannel sharding of one big payload plus
streaming with windowed flow control); ring attention is the TPU-native
capability those map onto: the "one big payload" is the sequence sharded
over the `sp` mesh axis, and the "streaming" is K/V blocks rotating around
the ICI ring (ppermute) while each chip folds them into an online-softmax
accumulator (blockwise/flash-style), so peak HBM stays O(S/n) per chip.

Two first-class schemes (pick per workload):
  ring_attention     — K/V circulate over the axis; n-1 ppermute hops of
                       [B, S/n, H, K] each; compute/comm overlap comes from
                       XLA pipelining the scan body's einsums with the
                       collective-permute.
  ulysses_attention  — one all-to-all swaps sequence sharding for head
                       sharding, attention runs locally over the FULL
                       sequence per head group, a second all-to-all swaps
                       back.  Cheaper at moderate S (2 all-to-alls vs n-1
                       permutes) but needs n | heads.

Both are reverse-mode differentiable (lax.scan carries the ring state) and
compose with dp/tp sharding: shard_map maps dp/tp as plain sharded dims and
only sp participates in the collectives.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from brpc_tpu.utils.jaxenv import shard_map_compat

# "masked" logit: finite so the online max stays NaN-free even for
# fully-masked blocks.  np.float32, NOT jnp: this module is imported
# lazily inside a jit trace (models/transformer.py attention body), and
# a module-level jnp constant materialized under tracing becomes a
# DynamicJaxprTracer that leaks past the trace (UnexpectedTracerError on
# the second jit).
_NEG = np.float32(-1e30)


def _ring_body(axis: str, n: int, idx, q, scale, causal, chunk, carry, step):
    """One ring step: fold the currently-held K/V block into the online
    softmax state, then pass it along the ring."""
    m, l, o, k, v = carry
    # whose K/V block do we hold after `step` hops? blocks travel +1 each
    # hop, so we now hold the block that started at (idx - step)
    src = (idx - step) % n

    def fold(args):
        m, l, o = args
        s = jnp.einsum("bchk,bdhk->bhcd", q, k).astype(jnp.float32) * scale
        if causal:
            qpos = idx * chunk + jnp.arange(chunk)
            kpos = src * chunk + jnp.arange(chunk)
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, _NEG)
        m_new = jnp.maximum(m, s.max(-1))                # [B,H,C]
        p = jnp.exp(s - m_new[..., None])                # [B,H,C,Cd]
        alpha = jnp.exp(m - m_new)                       # [B,H,C]
        l_new = l * alpha + p.sum(-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhcd,bdhk->bhck", p, v.astype(jnp.float32))
        return m_new, l_new, o_new

    if causal:
        # skip fully-future blocks (max qpos < min kpos): on a causal ring
        # roughly half of all steps hold nothing visible — eliding the fold
        # halves attention FLOPs at long context
        visible = (idx * chunk + chunk - 1) >= (src * chunk)
        m, l, o = jax.lax.cond(visible, fold, lambda args: args, (m, l, o))
    else:
        m, l, o = fold((m, l, o))
    perm = [(i, (i + 1) % n) for i in range(n)]
    k = jax.lax.ppermute(k, axis, perm)
    v = jax.lax.ppermute(v, axis, perm)
    return (m, l, o, k, v), None


def _ring_shard(q, k, v, *, axis: str, causal: bool, scale: float):
    """Per-shard ring attention; shapes [B, C, H, K] with C = S/n."""
    n = jax.lax.psum(1, axis)
    idx = jax.lax.axis_index(axis)
    B, C, H, K = q.shape
    m0 = jnp.full((B, H, C), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, C), jnp.float32)
    o0 = jnp.zeros((B, H, C, K), jnp.float32)
    body = partial(_ring_body, axis, n, idx, q, scale, causal, C)
    (m, l, o, k, v), _ = jax.lax.scan(
        lambda c, s: body(c, s), (m0, l0, o0, k, v), jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.einsum("bhck->bchk", out).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = True,
                   scale: Optional[float] = None):
    """Blockwise ring attention over mesh axis `axis`.

    q/k/v: [B, S, H, K] logically; sharded [B@dp, S@axis, H@tp, K].
    Returns [B, S, H, K] with the same sharding as q.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P("dp" if "dp" in mesh.axis_names else None, axis,
             "tp" if "tp" in mesh.axis_names else None, None)
    shmap, nocheck = shard_map_compat()
    fn = shmap(
        partial(_ring_shard, axis=axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **nocheck)
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) sequence parallelism


def _dense_local(q, k, v, causal, scale):
    s = jnp.einsum("bchk,bdhk->bhcd", q, k).astype(jnp.float32) * scale
    if causal:
        Sq = q.shape[1]
        mask = jnp.tril(jnp.ones((Sq, Sq), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhcd,bdhk->bchk", w,
                      v.astype(jnp.float32)).astype(q.dtype)


def _ulysses_shard(q, k, v, *, axis: str, causal: bool, scale: float):
    """[B, S/n, H, K] → all-to-all → [B, S, H/n, K] → attend → back."""
    a2a = partial(jax.lax.all_to_all, axis_name=axis, split_axis=2,
                  concat_axis=1, tiled=True)
    q, k, v = a2a(q), a2a(k), a2a(v)
    o = _dense_local(q, k, v, causal, scale)
    return jax.lax.all_to_all(o, axis_name=axis, split_axis=1,
                              concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                      causal: bool = True,
                      scale: Optional[float] = None):
    """All-to-all sequence parallelism (Ulysses style): requires
    axis_size | n_heads (heads are re-sharded during attention)."""
    n = mesh.shape[axis]
    tp = mesh.shape.get("tp", 1) if "tp" in mesh.axis_names else 1
    local_heads = q.shape[2] // tp
    if local_heads % n != 0:
        raise ValueError(
            f"ulysses needs axis size {n} to divide per-tp-shard heads "
            f"{local_heads} (n_heads {q.shape[2]} / tp {tp})")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P("dp" if "dp" in mesh.axis_names else None, axis,
             "tp" if "tp" in mesh.axis_names else None, None)
    shmap, nocheck = shard_map_compat()
    fn = shmap(
        partial(_ulysses_shard, axis=axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **nocheck)
    return fn(q, k, v)
