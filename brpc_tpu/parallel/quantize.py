"""Quantized collective payloads — the tensor-rail mirror of the native
payload-codec formats (native/src/codec.h; ISSUE 8 tentpole leg (b)).

EQuARX (arXiv 2506.17615) shows quantized allreduce as a first-class
XLA optimization; here the MeshParallelChannel reduce leg applies the
SAME per-block int8 / bf16 formats the RPC rail puts on the wire, as
pure-jnp fake-quantization: each worker's shard is quantized, the merge
DEQUANTIZES-THEN-REDUCES (sum of dequantized shards), so the collective
observes exactly what a wire hop through the codec would have delivered
— lossy but bounded.

Formats mirror codec.cc:
  int8: per-block (256 floats) scale = max|block| / 127, round-to-
        nearest, clamp to [-127, 127]; all-zero/denormal blocks emit
        scale 0 and decode to exact zeros.
        Per-element bound of one pass: |err| <= max|block| / 127.
  bf16: round-to-nearest-even truncation to bfloat16.
"""

from __future__ import annotations

BLOCK = 256  # floats per int8 scale block (== codec.h kInt8BlockFloats)


def fake_quant_int8(x, block: int = BLOCK):
    """dequantize(quantize(x)) along the LAST axis in `block`-float
    groups — the tensor a peer would reconstruct after an int8 wire hop.
    Shape/dtype preserved; elementwise+reshape only, so it composes with
    sharded arrays (the per-shard values quantize independently of the
    mesh layout, matching per-worker wire encoding)."""
    import jax.numpy as jnp

    orig_shape = x.shape
    n = orig_shape[-1]
    pad = (-n) % block
    flat = x.reshape(*orig_shape[:-1], n)
    if pad:
        flat = jnp.pad(flat, [(0, 0)] * (len(orig_shape) - 1) + [(0, pad)])
    blocks = flat.reshape(*orig_shape[:-1], (n + pad) // block, block)
    maxabs = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    scale = maxabs / 127.0
    # scale==0 (all-zero / fully-denormal block): decode exact zeros
    q = jnp.where(scale > 0.0,
                  jnp.clip(jnp.round(blocks / jnp.where(scale > 0.0,
                                                        scale, 1.0)),
                           -127, 127),
                  0.0)
    dq = q * scale
    out = dq.reshape(*orig_shape[:-1], n + pad)
    if pad:
        out = out[..., :n]
    return out.astype(x.dtype)


def fake_quant_bf16(x):
    """dequantize(quantize(x)) through bfloat16 (round-to-nearest-even),
    the tensor after a bf16 wire hop."""
    import jax.numpy as jnp

    return x.astype(jnp.bfloat16).astype(x.dtype)


def fake_quant(x, codec: str, block: int = BLOCK):
    """Apply the named codec's quantize→dequantize pass ("none" = x)."""
    if codec in ("", "none"):
        return x
    if codec == "int8":
        return fake_quant_int8(x, block)
    if codec == "bf16":
        return fake_quant_bf16(x)
    raise ValueError(f"unknown tensor codec {codec!r} "
                     f"(none/int8/bf16)")


def int8_error_bound(x, block: int = BLOCK) -> float:
    """Max per-element error of ONE int8 pass over x (max over blocks of
    max|block|/127), as a python float.  For an n-way dequantize-then-
    reduce SUM, per-worker bounds add."""
    import jax.numpy as jnp
    import numpy as np

    n = x.shape[-1]
    pad = (-n) % block
    flat = x
    if pad:
        flat = jnp.pad(x, [(0, 0)] * (len(x.shape) - 1) + [(0, pad)])
    blocks = flat.reshape(*x.shape[:-1], (n + pad) // block, block)
    return float(np.asarray(
        jnp.max(jnp.max(jnp.abs(blocks), axis=-1)) / 127.0)) + 1e-30
