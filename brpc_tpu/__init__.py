"""brpc_tpu — a TPU-native RPC framework with the capability surface of apache/brpc.

Layering mirrors the reference's strict 4-library stack (see SURVEY.md §1 and
reference CMakeLists.txt:428-433) re-imagined for TPU hosts:

  utils/    ≙ src/butil   — IOBuf, pools, EndPoint (incl. tpu://), flags, logging
  metrics/  ≙ src/bvar    — lock-minimal metrics: Adder/Window/LatencyRecorder/...
  fiber/    ≙ src/bthread — M:N fiber scheduler (native C++ core under native/)
  rpc/      ≙ src/brpc    — Server, Channel, Controller, protocols
  cluster/  ≙ src/brpc/policy — naming services, load balancers, circuit breaker,
              health checking, concurrency limiters
  parallel/ — combo channels (ParallelChannel/PartitionChannel/SelectiveChannel,
              reference parallel_channel.h:185) lowered to XLA collectives over a
              jax.sharding.Mesh when sub-channels form a mesh axis
  streaming/ — streaming RPC (reference stream.h:102) + tensor streams
  builtin/  ≙ src/brpc/builtin — HTTP debug portal (/status /vars /flags /health ...)
  models/, ops/ — flagship workloads (parameter-server ResNet-50) and pallas kernels

The hot data path is C++ (native/), reached via ctypes; the TPU data plane is
jax/XLA (typed array transfers + collectives), with the control plane on bytes —
the split the reference's RDMA endpoint already makes (rdma/rdma_endpoint.h:95).
"""

__version__ = "0.1.0"

from brpc_tpu.utils.endpoint import EndPoint  # noqa: F401
from brpc_tpu.utils import flags  # noqa: F401
from brpc_tpu.metrics import bvar  # noqa: F401
