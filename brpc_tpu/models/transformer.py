"""Decoder-only transformer LM, TPU-first.

Design notes (why this shape, per the scaling-book recipe):
  - bf16 compute / f32 params: matmuls hit the MXU at full rate, optimizer
    state stays accurate.
  - static shapes everywhere; layers are a Python loop over stacked params
    (n_layers is static), each block wrapped in jax.checkpoint so the
    backward pass re-computes activations instead of holding them in HBM.
  - parallelism is expressed ONLY as sharding constraints; XLA inserts the
    collectives (psum for tp matmul partials, all-gather of K/V over sp,
    all-to-all for expert dispatch).  Axis convention from parallel/mesh.py:
      dp: batch    sp: sequence    tp: heads & ffn-hidden    ep: experts
  - sequence parallelism = activations sharded [B@dp, S@sp, D]; attention
    Q stays sequence-sharded while K/V are constrained head-sharded, so the
    compiler emits the all-gather-KV form of context parallelism (ring
    attention is the pallas upgrade path, ops/).
  - MoE every second block (cfg.moe_every>0): dense top-k dispatch via
    one-hot einsum — no ragged gather/scatter, so XLA can tile it; experts
    sharded over ep.

Capability lineage: the reference has no model code (SURVEY.md §5.7 —
"no ML parallelism"); this model exists to drive the framework's collective
data plane the way example/rdma_performance drives its RDMA path
(reference example/rdma_performance/client.cpp).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class ModelConfig:
    vocab: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq: int = 1024
    n_experts: int = 0       # 0 = dense-only
    moe_every: int = 2       # every k-th block is MoE (when n_experts > 0)
    moe_topk: int = 1
    dtype: Any = jnp.bfloat16
    # context parallelism over the sp axis (parallel/ring_attention.py):
    #   "gather"  — K/V all-gathered over sp (XLA-inserted; fine at short S)
    #   "ring"    — blockwise ring attention, K/V rotate via ppermute; peak
    #               HBM O(S/n) per chip — the long-context path
    #   "ulysses" — all-to-all head<->sequence re-shard, local attention
    attn_impl: str = "gather"

    def __post_init__(self):
        if self.attn_impl not in ("gather", "ring", "ulysses"):
            raise ValueError(
                f"attn_impl must be gather|ring|ulysses, "
                f"got {self.attn_impl!r}")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def is_moe(self, layer: int) -> bool:
        return self.n_experts > 0 and self.moe_every > 0 and \
            (layer % self.moe_every == self.moe_every - 1)


# ---------------------------------------------------------------------------
# params


def init(rng, cfg: ModelConfig) -> Dict:
    """f32 param pytree; stacked per-layer leaves [L, ...]."""
    k = iter(jax.random.split(rng, 16 + 4 * cfg.n_layers))

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                / np.sqrt(fan_in))

    L, D, H, hd, F = (cfg.n_layers, cfg.d_model, cfg.n_heads,
                      cfg.head_dim, cfg.d_ff)
    p = {
        "embed": dense(next(k), (cfg.vocab, D), D),
        "pos": dense(next(k), (cfg.max_seq, D), D),
        "ln_f": {"g": jnp.ones((D,)), "b": jnp.zeros((D,))},
        "blocks": {
            "ln1_g": jnp.ones((L, D)), "ln1_b": jnp.zeros((L, D)),
            "ln2_g": jnp.ones((L, D)), "ln2_b": jnp.zeros((L, D)),
            "wq": dense(next(k), (L, D, H, hd), D),
            "wk": dense(next(k), (L, D, H, hd), D),
            "wv": dense(next(k), (L, D, H, hd), D),
            "wo": dense(next(k), (L, H, hd, D), D),
            "w1": dense(next(k), (L, D, F), D),
            "w2": dense(next(k), (L, F, D), F),
        },
    }
    if cfg.n_experts > 0:
        E = cfg.n_experts
        # expert leaves are stacked over MoE layers only (moe_slot maps
        # layer index -> slot), not all L layers — half the expert HBM
        n_moe = sum(1 for i in range(L) if cfg.is_moe(i))
        if n_moe:
            p["moe"] = {
                "router": dense(next(k), (n_moe, D, E), D),
                "we1": dense(next(k), (n_moe, E, D, F), D),
                "we2": dense(next(k), (n_moe, E, F, D), F),
            }
    return p


def moe_slot(cfg: ModelConfig, layer: int) -> int:
    """Index into the stacked MoE leaves for a MoE layer."""
    return sum(1 for j in range(layer) if cfg.is_moe(j))


def param_specs(cfg: ModelConfig) -> Dict:
    """PartitionSpec tree matching init() — the tp/ep layout.

    Megatron split: qkv column-parallel on heads (tp), out-proj
    row-parallel; ffn w1 column- / w2 row-parallel; embeddings replicated
    (vocab is small relative to ffn; gather stays local).
    """
    specs = {
        "embed": P(None, None),
        "pos": P(None, None),
        "ln_f": {"g": P(None), "b": P(None)},
        "blocks": {
            "ln1_g": P(None, None), "ln1_b": P(None, None),
            "ln2_g": P(None, None), "ln2_b": P(None, None),
            "wq": P(None, None, "tp", None),
            "wk": P(None, None, "tp", None),
            "wv": P(None, None, "tp", None),
            "wo": P(None, "tp", None, None),
            "w1": P(None, None, "tp"),
            "w2": P(None, "tp", None),
        },
    }
    if cfg.n_experts > 0:
        specs["moe"] = {
            "router": P(None, None, None),
            "we1": P(None, "ep", None, "tp"),
            "we2": P(None, "ep", "tp", None),
        }
    return specs


# ---------------------------------------------------------------------------
# forward


def _cs(x, mesh: Optional[Mesh], spec: P):
    """Sharding constraint; identity when no mesh (single chip).  Axes the
    mesh doesn't define drop to replicated (prune_spec), so the model runs
    unchanged on partial meshes (e.g. a (dp, sp) ring mesh without tp/ep)."""
    if mesh is None or mesh.empty:
        return x
    from brpc_tpu.parallel.mesh import prune_spec
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, prune_spec(spec, mesh)))


def _layernorm(x, g, b):
    x = x.astype(jnp.float32)
    m = jnp.mean(x, -1, keepdims=True)
    v = jnp.mean(jnp.square(x - m), -1, keepdims=True)
    return ((x - m) * jax.lax.rsqrt(v + 1e-5) * g + b)


def _attention(x, lp, i, cfg: ModelConfig, mesh):
    B, S, D = x.shape
    xc = x.astype(cfg.dtype)
    q = jnp.einsum("bsd,dhk->bshk", xc, lp["wq"][i].astype(cfg.dtype))
    kk = jnp.einsum("bsd,dhk->bshk", xc, lp["wk"][i].astype(cfg.dtype))
    v = jnp.einsum("bsd,dhk->bshk", xc, lp["wv"][i].astype(cfg.dtype))
    use_sp = (mesh is not None and not mesh.empty
              and "sp" in mesh.axis_names and mesh.shape["sp"] > 1)
    if cfg.attn_impl != "gather" and use_sp:
        # sequence-parallel attention: q/k/v all stay sequence-sharded;
        # the collective (ring ppermute / all-to-all) IS the data plane
        from brpc_tpu.parallel import ring_attention as ra
        q = _cs(q, mesh, P("dp", "sp", "tp", None))
        kk = _cs(kk, mesh, P("dp", "sp", "tp", None))
        v = _cs(v, mesh, P("dp", "sp", "tp", None))
        fn = (ra.ring_attention if cfg.attn_impl == "ring"
              else ra.ulysses_attention)
        o = fn(q, kk, v, mesh, axis="sp", causal=True)
    else:
        # q keeps the sequence shard; k/v go head-sharded → XLA all-gathers
        # their sequence over sp (all-gather context parallelism)
        q = _cs(q, mesh, P("dp", "sp", "tp", None))
        kk = _cs(kk, mesh, P("dp", None, "tp", None))
        v = _cs(v, mesh, P("dp", None, "tp", None))
        scores = jnp.einsum("bshk,bthk->bhst", q, kk) / np.sqrt(cfg.head_dim)
        mask = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(mask[None, None], scores.astype(jnp.float32),
                           -1e30)
        w = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("bhst,bthk->bshk", w, v)
    o = _cs(o, mesh, P("dp", "sp", "tp", None))
    out = jnp.einsum("bshk,hkd->bsd", o.astype(cfg.dtype),
                     lp["wo"][i].astype(cfg.dtype))
    return _cs(out, mesh, P("dp", "sp", None))


def _ffn(x, lp, i, cfg: ModelConfig, mesh):
    xc = x.astype(cfg.dtype)
    h = jnp.einsum("bsd,df->bsf", xc, lp["w1"][i].astype(cfg.dtype))
    h = _cs(jax.nn.gelu(h), mesh, P("dp", "sp", "tp"))
    out = jnp.einsum("bsf,fd->bsd", h, lp["w2"][i].astype(cfg.dtype))
    return _cs(out, mesh, P("dp", "sp", None))


def _moe_ffn(x, mp, i, cfg: ModelConfig, mesh):
    """Dense top-k switch layer: one-hot dispatch keeps shapes static."""
    B, S, D = x.shape
    xc = x.astype(cfg.dtype)
    logits = jnp.einsum("bsd,de->bse", xc,
                        mp["router"][i].astype(cfg.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, cfg.moe_topk)
    oh = jax.nn.one_hot(topi, cfg.n_experts, dtype=probs.dtype)
    gates = (oh * topv[..., None]).sum(-2)          # [B,S,E]
    gates = (gates / (gates.sum(-1, keepdims=True) + 1e-9)).astype(cfg.dtype)
    # dense dispatch: compute every expert on every token, weight by gate.
    # experts sharded over ep → XLA partitions the E dim; gate-weighting is
    # the combine.  Ragged/pallas dispatch is the optimization path.
    h = jnp.einsum("bsd,edf->besf", xc, mp["we1"][i].astype(cfg.dtype))
    h = _cs(jax.nn.gelu(h), mesh, P("dp", "ep", "sp", "tp"))
    y = jnp.einsum("besf,efd->besd", h, mp["we2"][i].astype(cfg.dtype))
    out = jnp.einsum("besd,bse->bsd", y, gates)
    return _cs(out, mesh, P("dp", "sp", None))


def apply(params: Dict, tokens, cfg: ModelConfig,
          mesh: Optional[Mesh] = None):
    """tokens [B, S] int32 → logits [B, S, vocab] f32."""
    B, S = tokens.shape
    x = params["embed"][tokens] + params["pos"][:S][None]
    x = _cs(x, mesh, P("dp", "sp", None))
    lp, mp = params["blocks"], params.get("moe")

    def block(x, i):
        h = _layernorm(x, lp["ln1_g"][i], lp["ln1_b"][i])
        x = x + _attention(h, lp, i, cfg, mesh)
        h = _layernorm(x, lp["ln2_g"][i], lp["ln2_b"][i])
        if cfg.is_moe(i):
            x = x + _moe_ffn(h, mp, moe_slot(cfg, i), cfg, mesh)
        else:
            x = x + _ffn(h, lp, i, cfg, mesh)
        return _cs(x, mesh, P("dp", "sp", None))

    for i in range(cfg.n_layers):
        x = jax.checkpoint(block, static_argnums=(1,))(x, i)

    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    logits = jnp.einsum("bsd,vd->bsv", x.astype(cfg.dtype),
                        params["embed"].astype(cfg.dtype))
    return logits.astype(jnp.float32)
