"""Incremental (KV-cached) decode over models/transformer.py parameters.

The serving plane's compute half: `prefill()` runs the full causal
forward over a prompt ONCE and hands back the per-layer K/V it produced;
`decode_step()` then extends N independent sequences by one token each
against a slot-based KV cache (static shapes: [L, slots, max_len, H, hd]
— a slot is a row the continuous batcher assigns/evicts per step, so the
jitted step never recompiles as sequences come and go).

Capability lineage: the reference has no model code (SURVEY.md §5.7);
this mirrors how vLLM-style engines split prefill from decode so the
batcher can interleave them — here the seam matters because prefill K/V
migrates prefill-device → decode-device through the tpu_plane block rail
(serving/kv_cache.py) before `install()` makes it visible to the step.

Dense-only (cfg.n_experts == 0): the serving path drives the dense
transformer; MoE decode is an optimization path, not a serving
requirement.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from brpc_tpu.models.transformer import ModelConfig, _cs, _layernorm

# K/V serializes host-side as f32 (numpy has no bfloat16); the cache
# itself stays in cfg.dtype on device.
KV_WIRE_DTYPE = np.float32


def _check_dense(cfg: ModelConfig) -> None:
    if cfg.n_experts > 0:
        raise ValueError("decode path is dense-only (cfg.n_experts == 0)")


def kv_bytes_per_token(cfg: ModelConfig) -> int:
    """Host-wire bytes one token position contributes to a sequence's
    K/V: 2 (k+v) x L x H x head_dim f32 values."""
    return 2 * cfg.n_layers * cfg.n_heads * cfg.head_dim * \
        KV_WIRE_DTYPE().itemsize


# ---------------------------------------------------------------------------
# cache


def init_cache(cfg: ModelConfig, slots: int, max_len: int,
               mesh: Optional[Mesh] = None) -> Dict:
    """Slot-based decode cache: k/v [L, slots, max_len, H, hd] in
    cfg.dtype plus per-slot valid length `pos` [slots] int32."""
    _check_dense(cfg)
    shape = (cfg.n_layers, slots, max_len, cfg.n_heads, cfg.head_dim)
    cache = {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "pos": jnp.zeros((slots,), jnp.int32),
    }
    cache["k"] = _cs(cache["k"], mesh, P(None, "dp", None, "tp", None))
    cache["v"] = _cs(cache["v"], mesh, P(None, "dp", None, "tp", None))
    return cache


def cache_max_len(cache: Dict) -> int:
    return cache["k"].shape[2]


def install(cache: Dict, slot: int, k, v, length: int) -> Dict:
    """Make a migrated sequence's prefill K/V visible to decode_step:
    write k/v [L, S, H, hd] into `slot` at positions [0:S] and set the
    slot's valid length.  Eager (runs once per admit, outside the jitted
    step)."""
    k = jnp.asarray(k, cache["k"].dtype)
    v = jnp.asarray(v, cache["v"].dtype)
    s = int(k.shape[1])
    out = dict(cache)
    out["k"] = cache["k"].at[:, slot, :s].set(k)
    out["v"] = cache["v"].at[:, slot, :s].set(v)
    out["pos"] = cache["pos"].at[slot].set(np.int32(length))
    return out


def reset_slot(cache: Dict, slot: int) -> Dict:
    """Retire a slot (finish/evict/cancel): its row stops advancing and
    the stale K/V is dead weight the next install overwrites."""
    out = dict(cache)
    out["pos"] = cache["pos"].at[slot].set(np.int32(0))
    return out


# ---------------------------------------------------------------------------
# prefill


def prefill(params: Dict, tokens, cfg: ModelConfig,
            mesh: Optional[Mesh] = None) -> Tuple:
    """tokens [B, S] int32 -> (last-position logits [B, vocab] f32,
    k [L, B, S, H, hd], v [L, B, S, H, hd]).

    Same math as transformer.apply()'s gather branch, but inference-mode
    (no checkpoint) and the per-layer K/V survives as the migration
    payload instead of dying with the activations."""
    _check_dense(cfg)
    B, S = tokens.shape
    x = params["embed"][tokens] + params["pos"][:S][None]
    x = _cs(x, mesh, P("dp", "sp", None))
    lp = params["blocks"]
    ks, vs = [], []

    for i in range(cfg.n_layers):
        h = _layernorm(x, lp["ln1_g"][i], lp["ln1_b"][i])
        hc = h.astype(cfg.dtype)
        q = jnp.einsum("bsd,dhk->bshk", hc, lp["wq"][i].astype(cfg.dtype))
        k = jnp.einsum("bsd,dhk->bshk", hc, lp["wk"][i].astype(cfg.dtype))
        v = jnp.einsum("bsd,dhk->bshk", hc, lp["wv"][i].astype(cfg.dtype))
        ks.append(k)
        vs.append(v)
        q = _cs(q, mesh, P("dp", "sp", "tp", None))
        k = _cs(k, mesh, P("dp", None, "tp", None))
        v = _cs(v, mesh, P("dp", None, "tp", None))
        scores = jnp.einsum("bshk,bthk->bhst", q, k) / np.sqrt(cfg.head_dim)
        mask = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(mask[None, None], scores.astype(jnp.float32),
                           -1e30)
        w = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("bhst,bthk->bshk", w, v)
        x = x + jnp.einsum("bshk,hkd->bsd", o.astype(cfg.dtype),
                           lp["wo"][i].astype(cfg.dtype))
        h = _layernorm(x, lp["ln2_g"][i], lp["ln2_b"][i])
        hf = jnp.einsum("bsd,df->bsf", h.astype(cfg.dtype),
                        lp["w1"][i].astype(cfg.dtype))
        hf = jax.nn.gelu(hf)
        x = x + jnp.einsum("bsf,fd->bsd", hf, lp["w2"][i].astype(cfg.dtype))
        x = _cs(x, mesh, P("dp", "sp", None))

    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    last = x[:, -1]
    logits = jnp.einsum("bd,vd->bv", last.astype(cfg.dtype),
                        params["embed"].astype(cfg.dtype))
    return (logits.astype(jnp.float32),
            jnp.stack(ks), jnp.stack(vs))


# ---------------------------------------------------------------------------
# decode


def decode_step(params: Dict, cache: Dict, tokens, active,
                cfg: ModelConfig, mesh: Optional[Mesh] = None) -> Tuple:
    """One token for every slot: tokens [N] int32 (last emitted token per
    slot), active [N] bool -> (logits [N, vocab] f32, new cache).

    Inactive slots still flow through the math (static shapes) but their
    `pos` does not advance and their scatter lands on a clamped index the
    next install overwrites — the batcher just ignores their logits."""
    _check_dense(cfg)
    k_cache, v_cache, pos = cache["k"], cache["v"], cache["pos"]
    N = tokens.shape[0]
    S = k_cache.shape[2]
    lp = params["blocks"]
    p = jnp.minimum(pos, S - 1)                       # write index per slot
    rows = jnp.arange(N)
    x = params["embed"][tokens] + params["pos"][p]    # [N, D]
    x = _cs(x, mesh, P("dp", None))
    valid = jnp.arange(S)[None, :] <= p[:, None]      # [N, S] causal window

    for i in range(cfg.n_layers):
        h = _layernorm(x, lp["ln1_g"][i], lp["ln1_b"][i])
        hc = h.astype(cfg.dtype)
        q = jnp.einsum("nd,dhk->nhk", hc, lp["wq"][i].astype(cfg.dtype))
        k_new = jnp.einsum("nd,dhk->nhk", hc, lp["wk"][i].astype(cfg.dtype))
        v_new = jnp.einsum("nd,dhk->nhk", hc, lp["wv"][i].astype(cfg.dtype))
        k_cache = k_cache.at[i, rows, p].set(k_new)
        v_cache = v_cache.at[i, rows, p].set(v_new)
        q = _cs(q, mesh, P("dp", "tp", None))
        scores = jnp.einsum("nhk,nshk->nhs", q,
                            k_cache[i]) / np.sqrt(cfg.head_dim)
        scores = jnp.where(valid[:, None, :], scores.astype(jnp.float32),
                           -1e30)
        w = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("nhs,nshk->nhk", w, v_cache[i])
        x = x + jnp.einsum("nhk,hkd->nd", o.astype(cfg.dtype),
                           lp["wo"][i].astype(cfg.dtype))
        h = _layernorm(x, lp["ln2_g"][i], lp["ln2_b"][i])
        hf = jnp.einsum("nd,df->nf", h.astype(cfg.dtype),
                        lp["w1"][i].astype(cfg.dtype))
        hf = jax.nn.gelu(hf)
        x = x + jnp.einsum("nf,fd->nd", hf, lp["w2"][i].astype(cfg.dtype))
        x = _cs(x, mesh, P("dp", None))

    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    logits = jnp.einsum("nd,vd->nv", x.astype(cfg.dtype),
                        params["embed"].astype(cfg.dtype))
    new_cache = {
        "k": k_cache,
        "v": v_cache,
        "pos": pos + active.astype(jnp.int32),
    }
    return logits.astype(jnp.float32), new_cache


# ---------------------------------------------------------------------------
# host-wire (de)serialization — the bytes the KV block plane migrates


def kv_to_bytes(k, v) -> bytes:
    """[L, S, H, hd] k/v pair -> contiguous f32 host bytes (k then v)."""
    ka = np.ascontiguousarray(np.asarray(k, KV_WIRE_DTYPE))
    va = np.ascontiguousarray(np.asarray(v, KV_WIRE_DTYPE))
    return ka.tobytes() + va.tobytes()


def kv_from_bytes(data: bytes, cfg: ModelConfig, length: int) -> Tuple:
    """Inverse of kv_to_bytes for a `length`-token sequence."""
    shape = (cfg.n_layers, length, cfg.n_heads, cfg.head_dim)
    n = int(np.prod(shape))
    flat = np.frombuffer(data, KV_WIRE_DTYPE, count=2 * n)
    return flat[:n].reshape(shape), flat[n:].reshape(shape)
