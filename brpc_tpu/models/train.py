"""Training step for the flagship LM.

The step is one jit: forward (bf16) → CE loss → grads → adamw update.
Under a mesh, params carry the tp/ep specs from transformer.param_specs;
tokens arrive batch-sharded (dp only — their S+1 length is not sp-divisible)
and the model's first constraint re-shards activations to (dp, sp).  XLA
then emits the gradient psum over dp — which is exactly the ParallelChannel
parameter-server allreduce config from BASELINE.json, lowered to ICI
instead of host fan-out.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from brpc_tpu.models.transformer import ModelConfig, apply, init, param_specs


@dataclass
class TrainState:
    params: Dict
    opt_state: Any
    step: Any


def loss_fn(params, tokens, cfg: ModelConfig, mesh: Optional[Mesh] = None):
    """Next-token CE; targets are tokens shifted left."""
    logits = apply(params, tokens[:, :-1], cfg, mesh)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def make_optimizer(lr: float = 1e-3):
    return optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.01)


def make_train_step(cfg: ModelConfig, mesh: Optional[Mesh] = None,
                    lr: float = 1e-3, donate: bool = True):
    """Returns (optimizer, step_fn): the optax transform (use tx.init(params)
    to build the opt_state) and the jitted step with mesh shardings."""
    tx = make_optimizer(lr)

    def step(state: TrainState, tokens) -> Tuple[TrainState, jax.Array]:
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, tokens, cfg, mesh)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    if mesh is None or mesh.empty:
        return tx, jax.jit(step)

    pspecs = param_specs(cfg)

    def shard_of(spec):
        from brpc_tpu.parallel.mesh import prune_spec
        return NamedSharding(mesh, prune_spec(spec, mesh))

    param_sh = jax.tree.map(shard_of, pspecs,
                            is_leaf=lambda x: isinstance(x, P))
    # tokens are batch-sharded only: their length is S+1 (the shift target),
    # which sp cannot divide when sp | S — and int32 tokens are tiny; the
    # model's first sharding constraint re-shards activations to (dp, sp)
    batch_sh = NamedSharding(mesh, P("dp", None))
    repl = NamedSharding(mesh, P())

    # opt_state shardings mirror the params by TREE POSITION: any subtree of
    # the optax state whose structure equals the params' structure (adamw's
    # mu/nu) reuses the params' sharding tree; remaining leaves (step
    # counts) are replicated.  Shape-based matching would mis-shard
    # same-shaped but differently-split params (e.g. w1/w2 when D == F).
    params_shape = jax.eval_shape(
        lambda k: init(k, cfg), jax.random.key(0))
    opt_shape = jax.eval_shape(tx.init, params_shape)
    param_treedef = jax.tree.structure(params_shape)

    def _params_like(sub):
        try:
            return jax.tree.structure(sub) == param_treedef
        except Exception:
            return False

    opt_sh = jax.tree.map(
        lambda sub: param_sh if _params_like(sub) else repl,
        opt_shape, is_leaf=_params_like)
    state_sh = TrainState(params=param_sh, opt_state=opt_sh, step=repl)

    jstep = jax.jit(
        step,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,) if donate else (),
    )
    return tx, jstep


jax.tree_util.register_dataclass(
    TrainState, data_fields=["params", "opt_state", "step"], meta_fields=[])
