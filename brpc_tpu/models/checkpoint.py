"""TrainState checkpoint/restore (NEW-design obligation per SURVEY §5.4:
the reference has no model state — its closest capability is rpc_dump's
recordio snapshots; a training framework needs real state save/load).

Format: one .npz per checkpoint.  Every leaf of the state pytree is
stored under its tree path ("params/blocks_0/attn/wq", "opt_state/0/mu/
..."), fully gathered to host.  Restore rebuilds the pytree against a
caller-provided template (same treedef) and device_puts each leaf back
onto the template leaf's sharding — so a checkpoint taken on one mesh
restores onto any other mesh layout with the same global shapes
(resharding happens in device_put).

Deliberately dependency-light (numpy .npz, not orbax): checkpoints are
portable bytes with no library version coupling, and the save path works
from any host thread.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np


def _flatten(state: Any):
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    out = {}
    for path, leaf in leaves:
        key = "/".join(_seg(p) for p in path)
        out[key] = np.asarray(jax.device_get(leaf))
    return out


def _seg(p) -> str:
    # GetAttrKey('params') / DictKey('wq') / SequenceKey(0)
    for attr in ("name", "key", "idx"):
        if hasattr(p, attr):
            return str(getattr(p, attr))
    return str(p)


def save(path: str, state: Any) -> int:
    """Write the full state to `path` (.npz).  Returns bytes written.
    Atomic: writes to a temp file then renames (a crash mid-save never
    corrupts the previous checkpoint — ≙ recordio rotation hygiene)."""
    arrays = _flatten(state)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())  # data durable before the rename
    os.replace(tmp, path)
    # rename durable too: fsync the containing directory
    dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                  os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    return os.path.getsize(path)


def restore(path: str, template: Any) -> Any:
    """Rebuild `template`'s pytree from `path`; every leaf lands with the
    sharding of the corresponding template leaf (resharded if the mesh
    changed since save)."""
    with np.load(path) as z:
        stored = {k: z[k] for k in z.files}
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(template)[0]
    treedef = jax.tree_util.tree_structure(template)
    new_leaves = []
    for path_elems, leaf in leaves_with_paths:
        key = "/".join(_seg(p) for p in path_elems)
        if key not in stored:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        arr = stored[key]
        if hasattr(leaf, "sharding"):
            new_leaves.append(jax.device_put(arr, leaf.sharding))
        else:
            new_leaves.append(jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)
