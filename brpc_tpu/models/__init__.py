"""models/ — flagship workloads for the framework's acceptance configs.

The reference ships 25 example apps (SURVEY.md §2.8) whose heaviest
distributed workload is a parameter-server style fan-out (BASELINE.json
stretch config: "ParallelChannel parameter-server allreduce of grads").
Our flagship is a decoder-only transformer LM whose training step exercises
every mesh axis the framework defines (dp/sp/tp/ep — parallel/mesh.py):
its gradient allreduce IS the ParallelChannel lowering, its sequence
sharding IS the long-context path.
"""

from brpc_tpu.models.transformer import (  # noqa: F401
    ModelConfig,
    apply,
    init,
    param_specs,
)
from brpc_tpu.models.decode import (  # noqa: F401
    decode_step,
    init_cache,
    prefill,
)
from brpc_tpu.models.train import (  # noqa: F401
    TrainState,
    loss_fn,
    make_train_step,
)
