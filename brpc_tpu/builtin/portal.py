"""Builtin HTTP debug services (≙ the reference's builtin/ portal —
25+ services auto-registered at Server::Start, server.cpp:468-537:
index, status, vars, flags, connections, rpcz, prometheus metrics, health,
version, threads/bthreads introspection).

They ride the server's main port: the native transport sniffs HTTP beside
TRPC (native/src/http.cc), so `curl host:port/status` works against any
running server — same operator experience as the reference portal.
"""

from __future__ import annotations

import ctypes
import json
import os
import sys
import threading
import time
import traceback

from brpc_tpu._native import lib
from brpc_tpu.metrics import bvar
from brpc_tpu.rpc.http import HttpDispatcher, HttpRequest, HttpResponse
from brpc_tpu.utils import flags

VERSION = "brpc-tpu/0.1"

_START_TIME = time.time()

_SERVICES = [
    ("/", "index: this page"),
    ("/health", "liveness probe"),
    ("/version", "framework version"),
    ("/status", "per-method qps / latency / errors"),
    ("/vars", "all exposed bvars (?filter=substring)"),
    ("/flags", "gflags: list, /flags/<name>, ?setvalue= to reload"),
    ("/connections", "live server connections"),
    ("/metrics", "Prometheus text exposition"),
    ("/fibers", "fiber runtime counters (≙ /bthreads)"),
    ("/rpcz", "sampled RPC spans incl. native fast-path captures "
              "(?trace_id=, ?max_scan=, ?time= reads persisted spans "
              "back from disk, ?view=tree renders the trace tree)"),
    ("/hotspots", "collapsed-stack CPU samples (?seconds=, ?view=flame)"),
    ("/pprof/profile", "native SIGPROF profile (?seconds=, ?hz=)"),
    ("/pprof/heap", "sampled live heap (?interval=; first hit enables; "
                    "?view=flame)"),
    ("/pprof/growth", "cumulative allocation profile"),
    ("/pprof/contention", "sampled lock-wait stacks (always on)"),
    ("/sockets", "every live socket in the process"),
    ("/ids", "in-flight client correlation ids"),
    ("/threads", "python stacks + OS thread census"),
    ("/vlog", "VLOG verbosity: ?v=N[&module=] to set"),
    ("/protobufs", "registered pb message types"),
    ("/dir", "working-dir browser (needs builtin_writable)"),
]


def _index(req: HttpRequest) -> HttpResponse:
    rows = "".join(
        f'<tr><td><a href="{p}">{p}</a></td><td>{d}</td></tr>'
        for p, d in _SERVICES)
    return HttpResponse.html(
        "<html><head><title>brpc-tpu</title></head><body>"
        f"<h2>{VERSION} builtin services</h2>"
        f"<table border=1 cellpadding=4>{rows}</table></body></html>")


def _health(req: HttpRequest) -> str:
    return "OK\n"


def _version(req: HttpRequest) -> str:
    return VERSION + "\n"


def _vars(req: HttpRequest) -> HttpResponse:
    params = req.query_params()
    series = params.get("series")
    if series:
        # trend data for one windowed variable (≙ the flot plots behind
        # the reference's /vars): [[ts, per-second value], ...]
        data = bvar.series_of(series)
        if data is None:
            return HttpResponse.text(
                f"no sample history for {series!r}\n", 404)
        # samples carry process-monotonic stamps; emit epoch seconds so
        # external graphers get a real time axis
        offset = time.time() - time.monotonic()
        return HttpResponse.json(
            [[round(t + offset, 3), v] for t, v in data])
    needle = params.get("filter", "")
    lines = []
    for name, val in bvar.dump_exposed(
            (lambda n: needle in n) if needle else None):
        lines.append(f"{name} : {val}")
    return HttpResponse.text("\n".join(lines) + "\n")


def _metrics(req: HttpRequest) -> HttpResponse:
    # bvar gauges + the native histogram exposition (real cumulative
    # _bucket{le=...} series per method family — metrics.h telemetry)
    from brpc_tpu.metrics.native import native_prometheus_text
    text = bvar.dump_prometheus() + native_prometheus_text()
    return HttpResponse(200, {"Content-Type": "text/plain; version=0.0.4"},
                        text.encode())


def _fibers(req: HttpRequest) -> HttpResponse:
    out = (ctypes.c_uint64 * 5)()
    lib().trpc_runtime_stats(out)
    return HttpResponse.json({
        "fibers_created": out[0],
        "context_switches": out[1],
        "steals": out[2],
        "parks": out[3],
        "workers": out[4],
        "uptime_s": round(time.time() - _START_TIME, 1),
    })


def _flags_service(req: HttpRequest, writable: bool = False,
                   require_admin: bool = False) -> HttpResponse:
    """GET /flags — list; GET /flags/<name> — one; ?setvalue=v — hot reload
    (≙ builtin/flags_service.cpp: live GET/SET of gflags; only reloadable
    flags accept a set, reloadable_flags.h).  Writes require
    ServerOptions.builtin_writable — and, on a server with a pluggable
    Authenticator, a verified AuthContext carrying the "admin" role
    (rpc/auth.py): remote flag mutation is an identified action."""
    name = req.path[len("/flags"):].lstrip("/")
    params = req.query_params()
    if name and "setvalue" in params:
        if not writable:
            return HttpResponse.text(
                "flag writes disabled (ServerOptions.builtin_writable)\n",
                403)
        if require_admin:
            ctx = req.auth_context
            if ctx is None or not getattr(ctx, "has_role",
                                          lambda _r: False)("admin"):
                return HttpResponse.text(
                    "flag writes require an authenticated admin "
                    "credential (Authorization header verified by the "
                    "server's Authenticator with role 'admin')\n", 403)
        try:
            flags.set_flag(name, params["setvalue"])
        except Exception as e:
            return HttpResponse.text(f"set {name} failed: {e}\n", 400)
        return HttpResponse.text(f"{name} set to {flags.get_flag(name)}\n")
    if name:
        if not flags.flag_exists(name):
            return HttpResponse.text(f"no such flag {name}\n", 404)
        f = next(fl for fl in flags.all_flags() if fl.name == name)
        return HttpResponse.text(
            f"{name}={f.value} (default {f.default})"
            f"{' [reloadable]' if f.reloadable else ''}  {f.help}\n")
    lines = []
    for f in sorted(flags.all_flags(), key=lambda fl: fl.name):
        mark = " [R]" if f.reloadable else ""
        lines.append(f"{f.name}={f.value}{mark}  # {f.help}")
    return HttpResponse.text("\n".join(lines) + "\n")


_hotspots_gate = threading.Semaphore(1)


def _hotspots(req: HttpRequest) -> HttpResponse:
    """Sampling CPU profiler: collapsed stacks over ?seconds= (default 1) —
    the capability of /hotspots/cpu (builtin/hotspots_service.cpp drives
    pprof sampling); TPU build renders flamegraph-ready collapsed lines
    instead of embedding pprof perl.  Single profile at a time, capped at
    10s: the handler occupies one shared usercode-pool thread while it
    samples (≙ the reference rejecting concurrent profiling sessions)."""
    if not _hotspots_gate.acquire(blocking=False):
        return HttpResponse.text("another profile is running\n", 429)
    try:
        return _hotspots_locked(req)
    finally:
        _hotspots_gate.release()


def _hotspots_locked(req: HttpRequest) -> HttpResponse:
    seconds = min(float(req.query_params().get("seconds", "1")), 10.0)
    interval = 0.005
    counts: dict = {}
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            stack = []
            f = frame
            while f is not None and len(stack) < 64:
                code = f.f_code
                stack.append(f"{code.co_name} ({os.path.basename(code.co_filename)}:{f.f_lineno})")
                f = f.f_back
            key = ";".join(reversed(stack))
            counts[key] = counts.get(key, 0) + 1
        time.sleep(interval)
    lines = [f"{k} {v}" for k, v in
             sorted(counts.items(), key=lambda kv: -kv[1])]
    folded = "\n".join(lines) + "\n"
    if req.query_params().get("view") == "flame":
        # self-contained SVG straight from the folded text — no external
        # viz.js, tooltips are SVG-native <title> elements
        from brpc_tpu.builtin import flame
        svg = flame.folded_to_svg(
            folded, title=f"/hotspots ({seconds:g}s of Python stacks)")
        return HttpResponse(200, {"Content-Type": "image/svg+xml"},
                            svg.encode())
    return HttpResponse.text(folded)


def _pprof_profile(req: HttpRequest) -> HttpResponse:
    """Native CPU profile (≙ /pprof/profile, builtin/pprof_service.cpp:572
    — re-designed: SIGPROF sampling over ALL threads including the C++
    core's workers/dispatchers, folded flamegraph text out).  Query:
    seconds (default 1, max 30), hz (default 99)."""
    if not _hotspots_gate.acquire(blocking=False):
        return HttpResponse.text("another profile is running\n", 429)
    try:
        from brpc_tpu._native import lib as _lib
        L = _lib()
        try:
            seconds = float(req.query_params().get("seconds", "1"))
        except ValueError:
            return HttpResponse.text("bad seconds\n", 400)
        if not (seconds == seconds):  # NaN
            return HttpResponse.text("bad seconds\n", 400)
        seconds = min(max(seconds, 0.1), 30.0)
        hz = int(req.query_params().get("hz", "99"))
        rc = L.trpc_profiler_start(hz)
        if rc != 0:
            return HttpResponse.text(f"profiler_start failed rc={rc}\n", 500)
        try:
            time.sleep(seconds)
        finally:
            # the profiler must never outlive the request (a stuck
            # ITIMER_PROF samples the process forever)
            out = ctypes.c_void_p()
            n = L.trpc_profiler_stop(ctypes.byref(out))
        try:
            text = ctypes.string_at(out, n).decode(
                "utf-8", "replace") if n else ""
        finally:
            if out:
                L.trpc_profiler_free(out)
        return HttpResponse.text(text or "no samples\n")
    finally:
        _hotspots_gate.release()


# Disclosed on every /pprof/heap + /pprof/growth response (VERDICT round 5
# Weak #5): the sampler instruments the framework's own allocation seams,
# not the global allocator — operators must not read a clean-looking dump
# as "this process is lean".  Emitted as a '#' comment on line 2 so the
# "heap profile:" first line pprof parsers key on stays first.
_SEAM_SCOPE_NOTE = (
    "# scope: framework allocation seams only (IOBuf blocks, pool slabs, "
    "DMA landing zones); std::string, Python and other global-allocator "
    "memory is INVISIBLE here — a clean dump does not prove the process "
    "is lean")


def _with_seam_scope_note(text: str) -> str:
    head, sep, rest = text.partition("\n")
    if not sep:
        return text + "\n" + _SEAM_SCOPE_NOTE + "\n"
    return head + "\n" + _SEAM_SCOPE_NOTE + "\n" + rest


def _heap_profile(req: HttpRequest, growth: bool) -> HttpResponse:
    """≙ /pprof/heap + /pprof/growth (builtin/pprof_service.h:38,
    hotspots_service.cpp:1240 — re-designed: the framework samples its
    own allocation seams — IOBuf blocks, pool slabs, DMA landing zones —
    instead of interposing the global allocator).  First hit enables
    sampling (?interval= bytes/sample, default 512KB); later hits dump
    live (heap) or cumulative (growth) bytes with symbolized stacks."""
    from brpc_tpu._native import lib as _lib
    L = _lib()
    try:
        interval = int(req.query_params().get("interval", str(512 * 1024)))
    except ValueError:
        return HttpResponse.text("bad interval\n", 400)
    if req.query_params().get("disable"):
        L.trpc_heap_profiler_enable(0)
        return HttpResponse.text("heap profiler disabled\n"
                                 + _SEAM_SCOPE_NOTE + "\n")
    if not L.trpc_heap_profiler_enabled():
        L.trpc_heap_profiler_enable(max(interval, 4096))
        return HttpResponse.text(
            "heap profiler enabled (interval=%d); run load, then GET "
            "again for the dump\n" % max(interval, 4096)
            + _SEAM_SCOPE_NOTE + "\n")
    out = ctypes.c_void_p()
    n = L.trpc_heap_dump(1 if growth else 0, ctypes.byref(out))
    try:
        text = ctypes.string_at(out, n).decode("utf-8", "replace") \
            if n else "no samples\n"
    finally:
        if out:
            L.trpc_profiler_free(out)
    if req.query_params().get("view") == "flame":
        # the dump's "# symbolized" tail is already folded (leaf-first)
        from brpc_tpu.builtin import flame
        which = "growth" if growth else "heap"
        svg = flame.folded_to_svg(
            flame.heap_symbolized_tail(text),
            title=f"/pprof/{which} (bytes by allocation stack; "
                  "framework seams only)",
            leaf_first=True, unit="bytes")
        return HttpResponse(200, {"Content-Type": "image/svg+xml"},
                            svg.encode())
    return HttpResponse.text(_with_seam_scope_note(text))


def _pprof_contention(req: HttpRequest) -> HttpResponse:
    """≙ the bthread contention profiler's pprof dump (mutex.cpp:62-150):
    sampled lock-wait stacks from the core's hot mutexes, always on
    (rate-limited), dumped in '--- contention ---' format with a
    symbolized tail — /hotspots?view=contention shows the same data."""
    from brpc_tpu._native import lib as _lib
    L = _lib()
    out = ctypes.c_void_p()
    n = L.trpc_contention_dump(ctypes.byref(out))
    try:
        text = ctypes.string_at(out, n).decode("utf-8", "replace") \
            if n else "no contention sampled\n"
    finally:
        if out:
            L.trpc_profiler_free(out)
    return HttpResponse.text(text)


def _pprof_symbol(req: HttpRequest) -> HttpResponse:
    """≙ /pprof/symbol: resolve hex code addresses to symbol names.
    GET returns a capability marker (num_symbols); POST body is
    '0xADDR+0xADDR...' and the response maps each to a name."""
    from brpc_tpu._native import lib as _lib
    L = _lib()
    body = (req.body or b"").decode("ascii", "replace").strip()
    if not body:
        return HttpResponse.text("num_symbols: 1\n")
    out_lines = []
    buf = ctypes.create_string_buffer(512)
    for tok in body.replace("+", " ").split():
        try:
            addr = int(tok, 16)
        except ValueError:
            continue
        n = L.trpc_symbolize(ctypes.c_void_p(addr), buf, len(buf))
        out_lines.append(f"{tok}\t{buf.raw[:n].decode()}")
    return HttpResponse.text("\n".join(out_lines) + "\n")


def install_builtin_services(server, dispatcher: HttpDispatcher) -> None:
    """Register the portal routes on a server's dispatcher
    (≙ Server::AddBuiltinServices, server.cpp:468-537)."""
    d = dispatcher
    d.register("/", _index)
    d.register("/index", _index)
    d.register("/health", _health)
    d.register("/version", _version)
    # static builtins ride the native cached-response fast path: their
    # GET responses are pre-rendered at start() and answered inline on
    # the parse fiber (rpc.cc TryServeCachedHttp)
    server.cache_http_response("/health")
    server.cache_http_response("/version")
    d.register("/vars", _vars)
    d.register("/metrics", _metrics)
    d.register("/fibers", _fibers)
    writable = bool(getattr(server.options, "builtin_writable", False))
    # a pluggable Authenticator upgrades /flags mutation to an
    # identified action (verified AuthContext with role "admin")
    need_admin = getattr(server.options, "authenticator", None) is not None
    d.register("/flags", lambda r: _flags_service(r, writable, need_admin))
    d.register("/flags/", lambda r: _flags_service(r, writable, need_admin),
               prefix=True)
    d.register("/hotspots", _hotspots)
    d.register("/pprof/profile", _pprof_profile)
    d.register("/pprof/symbol", _pprof_symbol)
    d.register("/pprof/heap", lambda r: _heap_profile(r, growth=False))
    d.register("/pprof/growth", lambda r: _heap_profile(r, growth=True))
    d.register("/pprof/contention", _pprof_contention)

    def _status(req: HttpRequest) -> HttpResponse:
        # `methods` = Python-dispatched handlers (LatencyRecorder);
        # `native_methods` = the families that never leave the native
        # core (inline echo, redis cache, client unary, ...) read from
        # the per-shard histograms — the fast path's latency story
        # `overload` = the admission plane's per-family limit/inflight/
        # reject block (overload.h) — enabled:false means the plane is
        # inert and the numbers are the configured defaults
        from brpc_tpu.metrics.native import (native_family_stats,
                                             native_overload_stats)
        return HttpResponse.json({
            "version": VERSION,
            "uptime_s": round(time.time() - _START_TIME, 1),
            "requests": server.request_count(),
            "methods": server.method_stats(),
            "native_methods": native_family_stats(),
            "overload": native_overload_stats(),
        })

    def _connections(req: HttpRequest) -> HttpResponse:
        buf = ctypes.create_string_buffer(1 << 20)
        n = lib().trpc_server_conn_stats(server._handle, buf, len(buf))
        header = "sockid fd peer bytes_in bytes_out\n"
        return HttpResponse.text(header + buf.raw[:n].decode())

    def _sockets(req: HttpRequest) -> HttpResponse:
        """Every live socket in the process — servers AND clients (≙
        builtin/sockets_service.cpp over the whole SocketId space)."""
        buf = ctypes.create_string_buffer(1 << 20)
        n = lib().trpc_socket_dump(buf, len(buf))
        return HttpResponse.text(buf.raw[:n].decode())

    def _ids(req: HttpRequest) -> HttpResponse:
        """In-flight client correlation ids (≙ builtin/ids_service.cpp
        dumping live bthread_ids)."""
        buf = ctypes.create_string_buffer(1 << 20)
        n = lib().trpc_ids_dump(buf, len(buf))
        return HttpResponse.text(buf.raw[:n].decode())

    def _protobufs(req: HttpRequest) -> HttpResponse:
        """Registered pb message types (≙ builtin/protobufs_service.cpp
        listing descriptor pool messages): the request/response classes
        of every pb-typed method, with their field layout."""
        specs = getattr(server, "_pb_specs", {})
        out = {}
        for method, (req_cls, resp_cls) in sorted(specs.items()):
            out[method] = {
                "request": req_cls.DESCRIPTOR.full_name,
                "request_fields": [f.name for f in
                                   req_cls.DESCRIPTOR.fields],
                "response": resp_cls.DESCRIPTOR.full_name,
                "response_fields": [f.name for f in
                                    resp_cls.DESCRIPTOR.fields],
            }
        return HttpResponse.json(out)

    def _dir(req: HttpRequest) -> HttpResponse:
        """Working-directory browser (≙ builtin/dir_service.cpp), gated
        behind builtin_writable: an unauthenticated filesystem listing is
        too sharp to expose by default."""
        if not writable:
            return HttpResponse.text(
                "/dir requires ServerOptions(builtin_writable=True)\n",
                403)
        rel = req.query_params().get("path", ".")
        base = os.path.realpath(os.getcwd())
        target = os.path.realpath(os.path.join(base, rel))
        # bare startswith would admit /root/repo-secrets beside /root/repo
        if target != base and not target.startswith(base + os.sep):
            return HttpResponse.text("path escapes the working dir\n", 403)
        if not os.path.isdir(target):
            return HttpResponse.text(f"not a directory: {rel}\n", 404)
        rows = []
        for name in sorted(os.listdir(target)):
            full = os.path.join(target, name)
            try:
                st = os.lstat(full)
                import stat as _stat
                if _stat.S_ISLNK(st.st_mode):
                    kind, size = "link", 0
                elif _stat.S_ISDIR(st.st_mode):
                    kind, size = "dir", 0
                else:
                    kind, size = "file", st.st_size
            except OSError:
                # deleted between listdir and stat: one broken entry
                # must not 500 the whole listing
                kind, size = "unknown", 0
            rows.append({"name": name, "type": kind, "size": size})
        return HttpResponse.json({"path": os.path.relpath(target, base),
                                  "entries": rows})

    def _vlog(req: HttpRequest) -> HttpResponse:
        """Runtime VLOG verbosity (≙ builtin/vlog_service.cpp): GET shows
        levels; ?v=N (optionally &module=name) sets — writes gated like
        /flags."""
        from brpc_tpu.utils import logging as _log
        params = req.query_params()
        if "v" in params:
            if not writable:
                return HttpResponse.text(
                    "vlog writes disabled "
                    "(ServerOptions.builtin_writable)\n", 403)
            try:
                level = int(params["v"])
            except ValueError:
                return HttpResponse.text("bad v\n", 400)
            _log.set_vlog_level(level, params.get("module"))
        return HttpResponse.json({"global_v": _log.vlog_level(),
                                  "modules": _log.vlog_modules()})

    def _threads(req: HttpRequest) -> HttpResponse:
        """One stack per Python thread plus the native thread census from
        /proc/self/task (≙ builtin/threads_service.cpp attaching pstack;
        native frames come from /pprof or /hotspots?native=1)."""
        py_frames = sys._current_frames()
        by_ident = {t.ident: t for t in threading.enumerate()}
        out = []
        for tid, frame in py_frames.items():
            t = by_ident.get(tid)
            name = t.name if t else "?"
            daemon = " daemon" if t is not None and t.daemon else ""
            out.append(f"--- thread {tid} [{name}]{daemon}")
            for entry in traceback.format_stack(frame):
                out.extend(f"  {ln}" for ln in entry.rstrip().split("\n"))
        native = []
        try:
            for task in sorted(os.listdir("/proc/self/task"), key=int):
                try:
                    with open(f"/proc/self/task/{task}/comm") as f:
                        native.append(f"{task} {f.read().strip()}")
                except OSError:
                    continue
        except OSError:
            pass
        out.append(f"--- {len(native)} OS threads (tid name)")
        out.extend(f"  {ln}" for ln in native)
        return HttpResponse.text("\n".join(out) + "\n")

    def _rpcz(req: HttpRequest) -> HttpResponse:
        from brpc_tpu.rpc import span as _span
        params = req.query_params()
        trace_id = params.get("trace_id")
        try:
            # ids are printed as bare hex by Span.describe — parse them back
            # the same way
            tid = int(trace_id, 16) if trace_id else None
        except ValueError:
            return HttpResponse.text(f"bad trace_id {trace_id!r}\n", 400)
        max_scan = int(params.get("max_scan", "100"))
        at = params.get("time")
        if at is not None:
            # time-keyed DISK read-back (≙ browsing persisted spans,
            # span.cpp:672): spans at/before <epoch seconds>, straight
            # from the rotated recordio segments — they survive restarts
            try:
                at_ts = float(at)
            except ValueError:
                return HttpResponse.text(f"bad time {at!r}\n", 400)
            if not _span.persisting():
                return HttpResponse.text(
                    "span persistence is off (set the rpcz_persist_dir "
                    "flag)\n", 400)
            _span.drain_native()  # fast-path spans spill before the read
            spans = _span.read_persisted(at_ts, max_scan)
            if tid is not None:
                spans = [s for s in spans if s.trace_id == tid]
            if params.get("view") == "tree":
                return _rpcz_tree_html(spans)
            return HttpResponse.json([s.describe() for s in spans])
        spans = _span.recent_spans(max_scan, tid)
        if params.get("view") == "tree":
            return _rpcz_tree_html(spans)
        return HttpResponse.json([s.describe() for s in spans])

    def _rpcz_tree_html(spans) -> HttpResponse:
        """Trace tree: children indented under their parent_span_id
        (≙ rpcz_service.cpp's per-trace drill-down view)."""
        import html as _html
        by_parent = {}
        ids = {s.span_id for s in spans}
        for s in sorted(spans, key=lambda s: s.start_ts):
            # roots: no parent, or the parent's span lives in another
            # process (the cross-hop case — its subtree still renders)
            key = s.parent_span_id if s.parent_span_id in ids else 0
            by_parent.setdefault(key, []).append(s)
        lines = []

        def walk(parent_id: int, depth: int) -> None:
            for s in by_parent.get(parent_id, []):
                d = s.describe()
                annot = "; ".join(d["annotations"])
                lines.append(
                    "&nbsp;" * (4 * depth) +
                    _html.escape(
                        f"[{d['kind']}] {d['method']} span={d['span_id']} "
                        f"parent={d['parent_span_id']} "
                        f"{d['latency_us']}us err={d['error_code']}"
                        + (f"  // {annot}" if annot else "")))
                if s.span_id != parent_id:  # guard a self-parented span
                    walk(s.span_id, depth + 1)

        walk(0, 0)
        body = ("<html><head><title>rpcz trace tree</title></head><body>"
                "<tt>" + "<br>".join(lines or ["(no spans)"]) +
                "</tt></body></html>")
        return HttpResponse.html(body)

    d.register("/status", _status)
    d.register("/connections", _connections)
    d.register("/sockets", _sockets)
    d.register("/ids", _ids)
    d.register("/threads", _threads)
    d.register("/vlog", _vlog)
    d.register("/protobufs", _protobufs)
    d.register("/dir", _dir)
    d.register("/rpcz", _rpcz)
