"""Self-contained flamegraph SVG from folded-stack text (≙ the reference
rendering /hotspots flamegraphs — but where brpc embeds flamegraph.pl's
output via an external viz pipeline, this emits a plain SVG directly:
no JavaScript, no external tools, every <rect> carries an SVG-native
<title> tooltip, so the one response body is the whole artifact).

Input format: one stack per line, frames joined by ';', whitespace, then
an integer value — the exact output of /hotspots, /pprof/profile and the
"# symbolized" tail of /pprof/heap//pprof/growth:

    main (x.py:1);work (x.py:9);hot (y.py:3) 42
"""

from __future__ import annotations

import html
from typing import Dict, List, Optional, Tuple


class _Node:
    __slots__ = ("name", "value", "children")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self.children: Dict[str, "_Node"] = {}


def parse_folded(text: str, leaf_first: bool = False) -> _Node:
    """Folded lines -> merged call tree.  `leaf_first` reverses each
    stack (the heap profiler folds leaf-to-root; flame layout wants the
    root at the bottom)."""
    root = _Node("all")
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        stack_part, _, value_part = line.rpartition(" ")
        if not stack_part:
            continue
        try:
            value = int(float(value_part))
        except ValueError:
            continue
        if value <= 0:
            continue
        frames = [f for f in stack_part.split(";") if f]
        if not frames:
            continue
        if leaf_first:
            frames.reverse()
        root.value += value
        node = root
        for frame in frames[:96]:  # bound pathological depth
            child = node.children.get(frame)
            if child is None:
                child = node.children[frame] = _Node(frame)
            child.value += value
            node = child
    return root


def _color(name: str) -> str:
    """Deterministic warm fill per frame name (classic flame palette)."""
    h = 2166136261
    for ch in name:
        h = ((h ^ ord(ch)) * 16777619) & 0xFFFFFFFF
    r = 205 + (h & 0x3F) % 50
    g = 70 + ((h >> 8) & 0xFF) % 120
    b = ((h >> 20) & 0x3F) % 60
    return f"rgb({r},{g},{b})"


def folded_to_svg(text: str, title: str = "flame graph",
                  width: int = 1200, leaf_first: bool = False,
                  unit: str = "samples") -> str:
    """Render folded-stack text as one self-contained SVG document."""
    root = parse_folded(text, leaf_first=leaf_first)
    row_h = 17
    font = 11
    # depth of the merged tree bounds the canvas height
    def depth_of(n: _Node) -> int:
        return 1 + max((depth_of(c) for c in n.children.values()),
                       default=0)
    depth = depth_of(root)
    height = (depth + 2) * row_h + 26
    rects: List[str] = []

    def emit(n: _Node, x: float, w: float, level: int) -> None:
        y = height - (level + 1) * row_h - 4
        label = html.escape(n.name, quote=True)
        tip = f"{label} ({n.value} {unit})"
        # clip the RAW name first, escape after: clipping escaped text
        # could cut an entity (&lt; -> &l..) and break the whole XML
        clipped = html.escape(_clip(n.name, w, font), quote=True)
        rects.append(
            f'<g><title>{tip}</title>'
            f'<rect x="{x:.2f}" y="{y}" width="{max(w, 0.3):.2f}" '
            f'height="{row_h - 1}" fill="{_color(n.name)}" rx="1"/>'
            + (f'<text x="{x + 2:.2f}" y="{y + row_h - 5}" '
               f'font-size="{font}" font-family="monospace" '
               f'fill="#000">{clipped}</text>'
               if w >= 35 else "")
            + "</g>")
        if not n.children or n.value <= 0:
            return
        cx = x
        for name in sorted(n.children):
            c = n.children[name]
            cw = w * (c.value / n.value)
            emit(c, cx, cw, level + 1)
            cx += cw

    if root.value > 0:
        emit(root, 8.0, width - 16.0, 0)
    body = "\n".join(rects) if rects else (
        '<text x="10" y="40" font-size="13" font-family="monospace">'
        "no samples</text>")
    esc_title = html.escape(title, quote=True)
    return (
        f'<?xml version="1.0" standalone="no"?>\n'
        f'<svg xmlns="http://www.w3.org/2000/svg" version="1.1" '
        f'width="{width}" height="{height}" '
        f'viewBox="0 0 {width} {height}">\n'
        f'<rect x="0" y="0" width="{width}" height="{height}" '
        f'fill="#fdf6e3"/>\n'
        f'<text x="{width / 2:.0f}" y="17" text-anchor="middle" '
        f'font-size="14" font-family="monospace">{esc_title}</text>\n'
        f"{body}\n</svg>\n")


def _clip(label: str, w: float, font: int) -> str:
    """Trim a label to what fits inside its rect (≈0.62em per mono char)."""
    fit = max(int(w / (font * 0.62)) - 1, 0)
    if len(label) <= fit:
        return label
    if fit <= 2:
        return ""
    return label[: fit - 2] + ".."


def heap_symbolized_tail(dump_text: str) -> str:
    """The folded '# symbolized' section of a /pprof/heap or
    /pprof/growth dump (leaf-first lines; empty if absent)."""
    marker = "# symbolized"
    idx = dump_text.find(marker)
    if idx < 0:
        return ""
    nl = dump_text.find("\n", idx)
    return dump_text[nl + 1:] if nl >= 0 else ""
