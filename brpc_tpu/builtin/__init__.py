from brpc_tpu.builtin.portal import install_builtin_services  # noqa: F401
