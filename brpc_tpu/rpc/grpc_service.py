"""gRPC service adaptor (capability of the reference gRPC support,
grpc.{h,cpp}:208 + policy/http2_rpc_protocol.cpp: gRPC semantics layered on
HTTP/2 — the native core speaks h2 on the shared port, this module speaks
the gRPC wire format on top: 5-byte message framing, content-type
application/grpc, grpc-status/grpc-message trailers, grpc-encoding gzip,
and grpc-timeout parsing).

Real gRPC clients (e.g. grpcio with bytes serializers, or generated stubs
whose messages the handler decodes itself) interoperate directly:

    server.add_grpc_service("pkg.Echo", {"Echo": lambda cntl, b: b})
    # grpcio: channel.unary_unary("/pkg.Echo/Echo", ...)(payload)
"""

from __future__ import annotations

import gzip
import re
import time
from typing import Callable, Dict

from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.http import HttpRequest, HttpResponse

# grpc-status codes (subset we map onto)
GRPC_OK = 0
GRPC_DEADLINE_EXCEEDED = 4
GRPC_NOT_FOUND = 5
GRPC_RESOURCE_EXHAUSTED = 8
GRPC_UNIMPLEMENTED = 12
GRPC_INTERNAL = 13
GRPC_UNAVAILABLE = 14
GRPC_UNAUTHENTICATED = 16
GRPC_UNKNOWN = 2

_CODE_MAP = {
    errors.ENOSERVICE: GRPC_UNIMPLEMENTED,
    errors.ENOMETHOD: GRPC_UNIMPLEMENTED,
    errors.ERPCTIMEDOUT: GRPC_DEADLINE_EXCEEDED,
    errors.ELIMIT: GRPC_RESOURCE_EXHAUSTED,
    errors.EAUTH: GRPC_UNAUTHENTICATED,
    errors.ESTOP: GRPC_UNAVAILABLE,
    errors.EINTERNAL: GRPC_INTERNAL,
}

_TIMEOUT_UNITS = {"H": 3600e3, "M": 60e3, "S": 1e3, "m": 1.0,
                  "u": 1e-3, "n": 1e-6}


def parse_grpc_timeout(value: str) -> float:
    """grpc-timeout header → milliseconds (≙ grpc.cpp timeout parsing)."""
    m = re.fullmatch(r"(\d{1,8})([HMSmun])", value)
    if not m:
        raise ValueError(f"bad grpc-timeout {value!r}")
    return int(m.group(1)) * _TIMEOUT_UNITS[m.group(2)]


def _encode_grpc_message(message: str) -> str:
    """Percent-encode per the gRPC spec: grpc-message allows only printable
    ASCII minus '%'; anything else (incl. CR/LF, which would otherwise
    inject extra trailer lines) is %XX-escaped."""
    out = []
    for b in message.encode("utf-8", "replace"):
        if 0x20 <= b <= 0x7E and b != 0x25:
            out.append(chr(b))
        else:
            out.append(f"%{b:02X}")
    return "".join(out)


def _grpc_error(status: int, message: str) -> HttpResponse:
    # error responses are headers + trailers, no message body
    return HttpResponse(
        200, {"content-type": "application/grpc"}, b"",
        trailers={"grpc-status": str(status),
                  "grpc-message": _encode_grpc_message(message)})


class ServerStreaming:
    """Marks a handler fn(cntl, request_bytes) -> iterable[bytes] as
    server-streaming: each yielded message becomes one length-prefixed
    frame of the response (≙ gRPC server streaming; the h2 layer flushes
    the frames as the response body with grpc-status trailers)."""

    def __init__(self, fn):
        self.fn = fn


class ClientStreaming:
    """fn(cntl, [request_bytes, ...]) -> response_bytes: the client
    sends any number of frames before half-closing."""

    def __init__(self, fn):
        self.fn = fn


class BidiStreaming:
    """fn(cntl, [request_bytes, ...]) -> iterable[bytes]."""

    def __init__(self, fn):
        self.fn = fn


def _split_frames(body: bytes):
    """All length-prefixed messages in a gRPC body (raises on junk)."""
    msgs = []
    i = 0
    while i < len(body):
        if len(body) - i < 5:
            raise ValueError("truncated grpc frame")
        compressed = body[i]
        mlen = int.from_bytes(body[i + 1:i + 5], "big")
        msg = body[i + 5:i + 5 + mlen]
        if len(msg) != mlen:
            raise ValueError("truncated grpc message")
        msgs.append((compressed, msg))
        i += 5 + mlen
    return msgs


def _wrap(method_full: str, handler) -> Callable:
    streaming_in = isinstance(handler, (ClientStreaming, BidiStreaming))
    streaming_out = isinstance(handler, (ServerStreaming, BidiStreaming))
    fn = handler.fn if isinstance(
        handler, (ServerStreaming, ClientStreaming, BidiStreaming)) \
        else handler

    def serve(req: HttpRequest) -> HttpResponse:
        t0 = time.monotonic()
        ct = req.headers.get("content-type", "")
        if not ct.startswith("application/grpc"):
            return HttpResponse.text("expected application/grpc\n", 415)
        try:
            frames = _split_frames(req.body)
        except ValueError as e:
            return _grpc_error(GRPC_INTERNAL, str(e))
        if not frames and not streaming_in:
            return _grpc_error(GRPC_INTERNAL, "truncated grpc frame")
        if not streaming_in and len(frames) != 1:
            # more than one length-prefixed frame = client streaming,
            # which unary handlers must not silently truncate
            return _grpc_error(GRPC_UNIMPLEMENTED,
                               "client streaming not supported")
        msgs = []
        for compressed, msg in frames:
            if compressed:
                if req.headers.get("grpc-encoding") != "gzip":
                    return _grpc_error(GRPC_UNIMPLEMENTED,
                                       "unsupported grpc-encoding")
                try:
                    msg = gzip.decompress(msg)
                except Exception:  # zlib.error / EOFError / OSError
                    return _grpc_error(GRPC_INTERNAL, "bad gzip message")
            msgs.append(msg)
        cntl = Controller()
        cntl.method = method_full
        deadline = None
        if "grpc-timeout" in req.headers:
            try:
                cntl.timeout_ms = parse_grpc_timeout(
                    req.headers["grpc-timeout"])
                deadline = t0 + cntl.timeout_ms / 1000.0
            except ValueError:
                pass
        if deadline is not None and time.monotonic() >= deadline:
            return _grpc_error(GRPC_DEADLINE_EXCEEDED,
                               "deadline expired before dispatch")
        try:
            out = fn(cntl, msgs if streaming_in else msgs[0])
        except errors.RpcError as e:
            return _grpc_error(_CODE_MAP.get(e.code, GRPC_UNKNOWN), e.text)
        except Exception as e:  # noqa: BLE001 — handler bug → INTERNAL
            return _grpc_error(GRPC_INTERNAL, str(e))
        if streaming_out:
            # progressive: each yielded message flushes as DATA frames
            # the moment the handler produces it; grpc-status rides the
            # trailers at generator exhaustion.  Long-lived streams emit
            # incrementally, and a slow client's flow control reaches
            # back through the blocked write and paces the handler.
            return _pump_streaming(cntl, iter(out or ()), deadline)
        if cntl.failed():
            return _grpc_error(_CODE_MAP.get(cntl.error_code, GRPC_UNKNOWN),
                               cntl.error_text)
        if deadline is not None and time.monotonic() >= deadline:
            # honored server-side: a response past the deadline is useless
            # to the peer (≙ grpc.cpp:208 deadline semantics)
            return _grpc_error(GRPC_DEADLINE_EXCEEDED,
                               "handler exceeded grpc-timeout")
        if isinstance(out, tuple):
            out = out[0]
        out = [out or b""]
        body = b"".join(b"\x00" + len(m).to_bytes(4, "big") + m
                        for m in out)
        return HttpResponse(
            200, {"content-type": "application/grpc"}, body,
            trailers={"grpc-status": "0"})

    return serve


def _pump_streaming(cntl, gen, deadline):
    """Drive a server/bidi-streaming handler's iterator through a
    progressive response: one length-prefixed frame per message, written
    (and flushed by the h2 layer) as it is produced; errors raised
    mid-stream land in the trailers like real gRPC servers do."""
    pa = HttpResponse.progressive(200,
                                  {"content-type": "application/grpc"})

    def pump():
        status, message = GRPC_OK, ""
        try:
            for m in gen:
                if deadline is not None and time.monotonic() >= deadline:
                    status = GRPC_DEADLINE_EXCEEDED
                    message = "handler exceeded grpc-timeout"
                    break
                pa.write(b"\x00" + len(m).to_bytes(4, "big") + m)
            else:
                if cntl.failed():
                    status = _CODE_MAP.get(cntl.error_code, GRPC_UNKNOWN)
                    message = cntl.error_text
        except errors.RpcError as e:
            status, message = _CODE_MAP.get(e.code, GRPC_UNKNOWN), e.text
        except BrokenPipeError:
            return  # peer reset the stream: no one left to trailer
        except TimeoutError:
            # live stream, but the peer stopped crediting flow control
            # for >30s: end it with a real status (the trailers queue
            # and flush whenever the window reopens or the stream dies)
            status, message = GRPC_UNAVAILABLE, "flow-control stall"
        except Exception as e:  # noqa: BLE001 — handler bug → INTERNAL
            status, message = GRPC_INTERNAL, str(e)
        trailers = {"grpc-status": str(status)}
        if status != GRPC_OK and message:
            trailers["grpc-message"] = _encode_grpc_message(message)
        pa.close(trailers=trailers)

    pa.on_bound = pump
    return pa


def install_grpc_service(server, service_name: str,
                         methods: Dict[str, Callable]) -> None:
    """Register `methods` under gRPC paths /<service_name>/<Method> on the
    server's shared port (h2 requests land there natively)."""
    for method_name, handler in methods.items():
        full = f"{service_name}/{method_name}"
        server.register_http("/" + full, _wrap(full, handler))
