"""Controller — per-RPC context and result carrier
(≙ brpc::Controller, reference controller.h:110: timeout/retry knobs on the
client side; method/peer/attachment context on the server side)."""

from __future__ import annotations

import threading
from typing import Optional

# Inherited-deadline context (ISSUE 19, ≙ the reference propagating the
# caller's remaining timeout in the baidu_std meta): the server
# dispatcher anchors the inbound tag-18 budget here as an ABSOLUTE
# monotonic-ns deadline for the handler's thread; Channel.call reads it
# to default a downstream call's timeout to the inherited remainder
# minus the per-hop reserve.  Thread-local because handlers own their
# usercode pthread for the callback's duration (the same contract the
# native TraceCtx rides).
_deadline_tls = threading.local()


def set_inherited_deadline_ns(abs_ns: Optional[int]) -> None:
    """Install (or clear, with None) the calling thread's inherited
    absolute deadline (time.monotonic_ns scale)."""
    _deadline_tls.abs_ns = abs_ns


def inherited_deadline_ns() -> Optional[int]:
    """The calling thread's inherited absolute deadline, or None."""
    return getattr(_deadline_tls, "abs_ns", None)


class Controller:
    """One RPC's mutable state.  Client side: set options before the call,
    read results after.  Server side: passed to the handler with request
    context; the handler sets response fields."""

    def __init__(self):
        # client options; None = inherit the ChannelOptions value
        # (≙ reference: unset Controller fields fall back to the channel's,
        # controller.cpp set_timeout_ms / ChannelOptions.timeout_ms)
        self.timeout_ms: Optional[float] = None
        self.max_retry: Optional[int] = None
        self.backup_request_ms: Optional[float] = None
        # shared state
        self.error_code: int = 0
        self.error_text: str = ""
        self.request_attachment: bytes = b""
        self.response_attachment: bytes = b""
        # compression (≙ set_request_compress_type/set_response_compress_type,
        # controller.h; codecs in rpc/compress.py): server side sees the
        # request's type and picks the response's; client side sets the
        # request's via ChannelOptions or this field
        self.request_compress_type: int = 0
        self.response_compress_type: int = 0
        # server-side context
        self.method: str = ""
        self.remote_side: str = ""
        self.log_id: int = 0
        # verified sender identity (rpc/auth.py AuthContext; ≙
        # Controller::auth_context(), controller.h), set by the server
        # dispatcher when ServerOptions.authenticator verified the
        # request's credential; None otherwise
        self.auth_context = None
        # tracing (rpcz): server side, the INBOUND trace/span ids from
        # meta tags 7/8 (≙ Controller::trace_id feeding span parentage)
        # — populated by the server dispatcher via trpc_token_trace;
        # 0/0 when the caller sent no trace context
        self.trace_id: int = 0
        self.span_id: int = 0
        # deadline-budget ingress (meta tag 18, ISSUE 19): the request's
        # remaining budget in µs as of dispatch, populated by the server
        # dispatcher via trpc_token_deadline_left_us; None when the
        # caller sent no budget.  May be <= 0 (already spent) — the
        # native layer normally sheds those before the handler runs.
        self.deadline_left_us: Optional[int] = None
        # populated after a call
        self.latency_us: int = 0
        self.retried_count: int = 0
        self.backup_fired: bool = False
        # per-call blacklist shared across this call's retry attempts
        # (≙ ExcludedServers, excluded_servers.h); cluster layer adds the
        # node of each failed attempt so retries go elsewhere
        self.excluded_nodes: set = set()
        # server-side streaming: the pending-call token, set by the server
        # dispatcher when the request carries a stream handshake
        self._stream_token: Optional[int] = None
        # client-side cancellation (≙ Controller::call_id + StartCancel,
        # controller.h:631,843): Channel.call attaches a ctypes buffer the
        # native layer fills with the in-flight call id before the request
        # hits the wire
        self._call_id_buf = None
        self._cancel_requested = False

    def has_stream(self) -> bool:
        """True if the client attached a stream to this request."""
        if self._stream_token is None:
            return False
        from brpc_tpu.rpc import stream as _stream
        return _stream.token_has_stream(self._stream_token)

    def accept_stream(self, window: Optional[int] = None):
        """Accept the request's stream (≙ StreamAccept, stream.cpp:802).
        Returns a rpc.stream.Stream usable from any thread; the handshake
        completes when the handler's response is sent."""
        if self._stream_token is None:
            return None
        from brpc_tpu.rpc import stream as _stream
        return _stream.accept_from_token(
            self._stream_token, window or _stream.DEFAULT_WINDOW)

    def start_cancel(self) -> None:
        """Cancel the in-flight call from ANY thread (≙ StartCancel,
        controller.h:631): the thread blocked in Channel.call returns
        ECANCELED immediately, the correlation slot is released safely,
        and a best-effort notice lets the server's handler observe it.
        The connection stays usable.  Idempotent; a no-op once the call
        completed."""
        self._cancel_requested = True
        buf = self._call_id_buf
        if buf is not None and buf.value:
            from brpc_tpu._native import lib
            lib().trpc_call_cancel(buf.value)

    def is_canceled(self) -> bool:
        """Server side (≙ Controller::IsCanceled): True once the peer
        canceled this call or its connection died — long handlers should
        poll this (or wait_cancel) and abort."""
        if self._stream_token is None:
            return False
        from brpc_tpu._native import lib
        return lib().trpc_call_canceled(self._stream_token) == 1

    def wait_cancel(self, timeout_s: Optional[float] = None) -> bool:
        """Server side (≙ NotifyOnCancel, controller.h:385-388): park
        until the peer cancels (True) or the timeout passes (False).
        Fiber/thread-cheap: rides the call's cancel butex."""
        if self._stream_token is None:
            return False
        from brpc_tpu._native import lib
        timeout_us = -1 if timeout_s is None else int(timeout_s * 1e6)
        return lib().trpc_call_wait_canceled(
            self._stream_token, timeout_us) == 1

    def trace_annotate(self, text: str) -> None:
        """TRACEPRINTF (≙ traceprintf.h): free text into the current rpcz
        span.  With a sampled Python span current (the normal handler
        case) the annotation lands there; otherwise it rides the native
        twin — the next native-captured span on this thread (e.g. the
        client-unary span of a downstream call made right after) carries
        it.  No-op when rpcz is off or the request wasn't sampled."""
        from brpc_tpu.rpc import span as _span
        if _span.current() is not None:
            _span.annotate(text)
        else:
            from brpc_tpu._native import lib
            lib().trpc_trace_annotate(text.encode("utf-8", "replace"))

    def failed(self) -> bool:
        return self.error_code != 0

    def set_failed(self, code: int, text: str = "") -> None:
        self.error_code = code
        self.error_text = text

    def reset(self) -> None:
        self.error_code = 0
        self.error_text = ""
        self.latency_us = 0
        self.retried_count = 0
        self._call_id_buf = None
        self._cancel_requested = False
        self.backup_fired = False
        self.excluded_nodes = set()
