"""Error codes shared with the native core (native/src/common.h) —
the capability of the reference's errno set (brpc/errno.proto)."""

from __future__ import annotations

OK = 0
ENOSERVICE = 1001
ENOMETHOD = 1002
ERPCTIMEDOUT = 1008
EFAILEDSOCKET = 1009
EBACKUPREQUEST = 1010
EREQUEST = 1011
ESTOP = 1012
ERESPONSE = 1013
EINTERNAL = 2001
EOVERCROWDED = 2004
ELIMIT = 2005
ESTREAMUNACCEPTED = 2006
ECANCELED = 2007
EAUTH = 2008
EDEADLINE = 2009

_TEXT = {
    OK: "OK",
    ENOSERVICE: "no such service",
    ENOMETHOD: "no such method",
    ERPCTIMEDOUT: "rpc call timed out",
    EFAILEDSOCKET: "the connection is broken",
    EBACKUPREQUEST: "backup request fired",
    EREQUEST: "bad request bytes",
    ESTOP: "server is stopping",
    ERESPONSE: "bad response bytes",
    EINTERNAL: "server-side exception",
    EOVERCROWDED: "too many buffered writes",
    ELIMIT: "rejected by concurrency limiter",
    ESTREAMUNACCEPTED: "server did not accept the stream",
    ECANCELED: "the rpc was canceled by the caller",
    EAUTH: "authentication failed",
    EDEADLINE: "deadline budget exhausted before dispatch",
}


def error_text(code: int) -> str:
    return _TEXT.get(code, f"error {code}")


class RpcError(Exception):
    def __init__(self, code: int, text: str = ""):
        self.code = code
        self.text = text or error_text(code)
        super().__init__(f"[E{code}] {self.text}")
