"""Memcache binary-protocol client with batched pipelining (≙
src/brpc/memcache.h:890 MemcacheRequest packing multiple operations into
one round trip + policy/memcache_binary_protocol.cpp framing).

Speaks the standard memcached binary protocol (24-byte header, magic
0x80/0x81), so it works against stock memcached.  Batching follows the
protocol's quiet-op idiom: a MemcacheBatch queues quiet variants
(GETKQ/SETQ/DELETEQ/...) and terminates the pipeline with NOOP, so one
write + one read round-trips N operations (what the reference's
pipelined_count achieves over its channel).
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["MemcacheClient", "MemcacheBatch", "MemcacheError", "Status"]

_HDR = struct.Struct("!BBHBBHIIQ")  # magic op keylen extlen dtype status bodylen opaque cas
_REQ_MAGIC = 0x80
_RES_MAGIC = 0x81


class Op:
    GET = 0x00
    SET = 0x01
    ADD = 0x02
    REPLACE = 0x03
    DELETE = 0x04
    INCREMENT = 0x05
    DECREMENT = 0x06
    QUIT = 0x07
    FLUSH = 0x08
    GETQ = 0x09
    NOOP = 0x0A
    VERSION = 0x0B
    GETK = 0x0C
    GETKQ = 0x0D
    APPEND = 0x0E
    PREPEND = 0x0F
    SETQ = 0x11
    ADDQ = 0x12
    REPLACEQ = 0x13
    DELETEQ = 0x14
    INCREMENTQ = 0x15
    DECREMENTQ = 0x16
    TOUCH = 0x1C


class Status:
    OK = 0x0000
    KEY_NOT_FOUND = 0x0001
    KEY_EXISTS = 0x0002
    VALUE_TOO_LARGE = 0x0003
    INVALID_ARGUMENTS = 0x0004
    ITEM_NOT_STORED = 0x0005
    NON_NUMERIC = 0x0006
    UNKNOWN_COMMAND = 0x0081
    OUT_OF_MEMORY = 0x0082


class MemcacheError(Exception):
    def __init__(self, status: int, message: str = ""):
        super().__init__(message or f"memcache status 0x{status:04x}")
        self.status = status


def _pack(op: int, key: bytes = b"", extras: bytes = b"", value: bytes = b"",
          opaque: int = 0, cas: int = 0) -> bytes:
    body = len(extras) + len(key) + len(value)
    return _HDR.pack(_REQ_MAGIC, op, len(key), len(extras), 0, 0, body,
                     opaque, cas) + extras + key + value


def _key(k) -> bytes:
    return k.encode("utf-8") if isinstance(k, str) else bytes(k)


class _Response:
    __slots__ = ("op", "status", "key", "extras", "value", "opaque", "cas")

    def __init__(self, op, status, key, extras, value, opaque, cas):
        self.op = op
        self.status = status
        self.key = key
        self.extras = extras
        self.value = value
        self.opaque = opaque
        self.cas = cas


class MemcacheClient:
    """Synchronous binary-protocol client.  Single connection; calls are
    serialized by a lock (use one client per thread, or MemcacheBatch for
    throughput — matching the reference's channel semantics)."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    # -- single ops ---------------------------------------------------------

    def get(self, key) -> Optional[bytes]:
        """Value bytes, or None if the key is absent."""
        r = self._round_trip(_pack(Op.GET, _key(key)))
        if r.status == Status.KEY_NOT_FOUND:
            return None
        self._raise_if(r)
        return r.value

    def gets(self, key) -> Tuple[Optional[bytes], int]:
        """(value, cas) — cas feeds compare-and-swap set(..., cas=...)."""
        r = self._round_trip(_pack(Op.GET, _key(key)))
        if r.status == Status.KEY_NOT_FOUND:
            return None, 0
        self._raise_if(r)
        return r.value, r.cas

    def set(self, key, value: bytes, flags: int = 0, exptime: int = 0,
            cas: int = 0) -> int:
        """Store unconditionally (or CAS-guarded when cas != 0).  Returns
        the new cas."""
        return self._store(Op.SET, key, value, flags, exptime, cas)

    def add(self, key, value: bytes, flags: int = 0, exptime: int = 0) -> int:
        """Store only if absent (raises KEY_EXISTS otherwise)."""
        return self._store(Op.ADD, key, value, flags, exptime, 0)

    def replace(self, key, value: bytes, flags: int = 0,
                exptime: int = 0) -> int:
        """Store only if present."""
        return self._store(Op.REPLACE, key, value, flags, exptime, 0)

    def append(self, key, value: bytes) -> int:
        r = self._round_trip(_pack(Op.APPEND, _key(key), b"", value))
        self._raise_if(r)
        return r.cas

    def prepend(self, key, value: bytes) -> int:
        r = self._round_trip(_pack(Op.PREPEND, _key(key), b"", value))
        self._raise_if(r)
        return r.cas

    def delete(self, key) -> bool:
        """True if the key existed."""
        r = self._round_trip(_pack(Op.DELETE, _key(key)))
        if r.status == Status.KEY_NOT_FOUND:
            return False
        self._raise_if(r)
        return True

    def incr(self, key, delta: int = 1, initial: int = 0,
             exptime: int = 0) -> int:
        return self._arith(Op.INCREMENT, key, delta, initial, exptime)

    def decr(self, key, delta: int = 1, initial: int = 0,
             exptime: int = 0) -> int:
        return self._arith(Op.DECREMENT, key, delta, initial, exptime)

    def touch(self, key, exptime: int) -> bool:
        r = self._round_trip(
            _pack(Op.TOUCH, _key(key), struct.pack("!I", exptime)))
        if r.status == Status.KEY_NOT_FOUND:
            return False
        self._raise_if(r)
        return True

    def flush_all(self, delay: int = 0) -> None:
        r = self._round_trip(_pack(Op.FLUSH, b"", struct.pack("!I", delay)))
        self._raise_if(r)

    def version(self) -> str:
        r = self._round_trip(_pack(Op.VERSION))
        self._raise_if(r)
        return r.value.decode("ascii", "replace")

    # -- batched pipeline ---------------------------------------------------

    def batch(self) -> "MemcacheBatch":
        return MemcacheBatch(self)

    def multi_get(self, keys) -> Dict[bytes, bytes]:
        """One round trip for N keys via quiet GETKQ + NOOP.  Absent keys
        produce no reply (the binary-protocol contract); a key whose
        lookup FAILED (server error, not a miss) raises, so callers never
        mistake a failure for a cache miss."""
        keys = [_key(k) for k in keys]
        with self._lock:
            out = bytearray()
            for i, k in enumerate(keys):
                out += _pack(Op.GETKQ, k, opaque=i)
            out += _pack(Op.NOOP, opaque=len(keys))
            self._sock.sendall(out)
            found: Dict[bytes, bytes] = {}
            failed: List[Tuple[bytes, int]] = []
            while True:
                r = self._read_response()
                if r.op == Op.NOOP:
                    break
                if r.status == Status.OK:
                    found[r.key] = r.value
                elif r.status != Status.KEY_NOT_FOUND:
                    k = keys[r.opaque] if r.opaque < len(keys) else r.key
                    failed.append((k, r.status))
        if failed:
            raise MemcacheError(
                failed[0][1],
                f"multi_get: {len(failed)} key(s) failed, first "
                f"{failed[0][0]!r} status 0x{failed[0][1]:04x}")
        return found

    # -- internals ----------------------------------------------------------

    def _store(self, op, key, value, flags, exptime, cas) -> int:
        extras = struct.pack("!II", flags, exptime)
        r = self._round_trip(_pack(op, _key(key), extras, value, cas=cas))
        self._raise_if(r)
        return r.cas

    def _arith(self, op, key, delta, initial, exptime) -> int:
        extras = struct.pack("!QQI", delta, initial, exptime)
        r = self._round_trip(_pack(op, _key(key), extras))
        self._raise_if(r)
        return struct.unpack("!Q", r.value)[0]

    def _raise_if(self, r: _Response) -> None:
        if r.status != Status.OK:
            raise MemcacheError(
                r.status, r.value.decode("ascii", "replace") if r.value
                else "")

    def _round_trip(self, req: bytes) -> _Response:
        with self._lock:
            self._sock.sendall(req)
            return self._read_response()

    def _read_response(self) -> _Response:
        hdr = self._recv_exact(_HDR.size)
        magic, op, klen, elen, _dt, status, blen, opaque, cas = \
            _HDR.unpack(hdr)
        if magic != _RES_MAGIC:
            raise MemcacheError(Status.UNKNOWN_COMMAND,
                                f"bad response magic 0x{magic:02x}")
        body = self._recv_exact(blen) if blen else b""
        extras = body[:elen]
        key = body[elen:elen + klen]
        value = body[elen + klen:]
        return _Response(op, status, key, extras, value, opaque, cas)

    def _recv_exact(self, n: int) -> bytes:
        from brpc_tpu.rpc._sockutil import recv_exact
        try:
            return recv_exact(self._sock, n)
        except ConnectionError:
            raise MemcacheError(Status.UNKNOWN_COMMAND,
                                "connection closed") from None

    def close(self) -> None:
        try:
            self._sock.sendall(_pack(Op.QUIT))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class MemcacheBatch:
    """Accumulates stores/deletes/gets, flushes them as one quiet
    pipeline (≙ MemcacheRequest's N-op batching, memcache.h:890).
    execute() returns {key: value} for the gets; store/delete errors
    surface as MemcacheError entries in .errors."""

    def __init__(self, client: MemcacheClient):
        self._c = client
        self._ops: List[bytes] = []
        self._keys: List[bytes] = []  # op index -> key, for .errors
        self.errors: List[Tuple[bytes, int]] = []  # (key, status)

    def _queue(self, op_bytes: bytes, key: bytes) -> "MemcacheBatch":
        self._ops.append(op_bytes)
        self._keys.append(key)
        return self

    def get(self, key) -> "MemcacheBatch":
        k = _key(key)
        return self._queue(_pack(Op.GETKQ, k, opaque=len(self._ops)), k)

    def set(self, key, value: bytes, flags: int = 0,
            exptime: int = 0) -> "MemcacheBatch":
        k = _key(key)
        return self._queue(
            _pack(Op.SETQ, k, struct.pack("!II", flags, exptime), value,
                  opaque=len(self._ops)), k)

    def delete(self, key) -> "MemcacheBatch":
        k = _key(key)
        return self._queue(_pack(Op.DELETEQ, k, opaque=len(self._ops)), k)

    def execute(self) -> Dict[bytes, bytes]:
        c = self._c
        with c._lock:
            out = bytearray()
            for op in self._ops:
                out += op
            out += _pack(Op.NOOP, opaque=len(self._ops))
            c._sock.sendall(out)
            found: Dict[bytes, bytes] = {}
            self.errors = []
            while True:
                r = c._read_response()
                if r.op == Op.NOOP:
                    break
                if r.status == Status.OK:
                    if r.key:
                        found[r.key] = r.value
                else:
                    # quiet stores/deletes only reply on error; error
                    # replies carry no key, so map back through the
                    # opaque each queued op was packed with
                    k = self._keys[r.opaque] \
                        if r.opaque < len(self._keys) else r.key
                    self.errors.append((k, r.status))
        self._ops = []
        self._keys = []
        return found
