"""Mongo wire-protocol head: OP_MSG framing + minimal BSON (≙
policy/mongo_protocol.cpp:298 + mongo_head.h — the reference also stops
at protocol parsing/dispatch; neither implements a database).

Mongo messages cannot ride the shared-port sniffer (they begin with a
little-endian length whose first byte is arbitrary), so the server here
owns its port — matching how the reference dedicates a mongo port via
ServerOptions.mongo_service_adaptor.

Wire format (OP_MSG, opcode 2013):
    u32 messageLength | u32 requestID | u32 responseTo | u32 opCode
    u32 flagBits | section kind 0x00 | BSON document
BSON subset: double, string, embedded doc, array, bool, null, int32,
int64 — the types the command surface (hello/ping/find-like commands)
needs.
"""

from __future__ import annotations

import socket
import struct
import threading

from brpc_tpu.rpc._sockutil import recv_exact
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = ["bson_encode", "bson_decode", "MongoService", "MongoClient",
           "MongoError"]

OP_MSG = 2013


class MongoError(Exception):
    pass


# ---------------------------------------------------------------------------
# BSON (subset)

def _enc_elem(out: bytearray, name: str, v: Any) -> None:
    key = name.encode("utf-8") + b"\x00"
    if isinstance(v, bool):  # before int: bool is an int subclass
        out += b"\x08" + key + (b"\x01" if v else b"\x00")
    elif isinstance(v, float):
        out += b"\x01" + key + struct.pack("<d", v)
    elif isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            out += b"\x10" + key + struct.pack("<i", v)
        else:
            out += b"\x12" + key + struct.pack("<q", v)
    elif isinstance(v, str):
        b = v.encode("utf-8") + b"\x00"
        out += b"\x02" + key + struct.pack("<i", len(b)) + b
    elif v is None:
        out += b"\x0a" + key
    elif isinstance(v, dict):
        out += b"\x03" + key + bson_encode(v)
    elif isinstance(v, (list, tuple)):
        doc = {str(i): x for i, x in enumerate(v)}
        out += b"\x04" + key + bson_encode(doc)
    else:
        raise MongoError(f"unsupported BSON value type {type(v).__name__}")


def bson_encode(doc: Dict[str, Any]) -> bytes:
    body = bytearray()
    for k, v in doc.items():
        _enc_elem(body, k, v)
    return struct.pack("<i", len(body) + 5) + bytes(body) + b"\x00"


def _dec_cstring(blob: bytes, off: int) -> Tuple[str, int]:
    end = blob.index(b"\x00", off)
    return blob[off:end].decode("utf-8"), end + 1


def bson_decode(blob: bytes, off: int = 0) -> Tuple[Dict[str, Any], int]:
    (total,) = struct.unpack_from("<i", blob, off)
    end = off + total
    i = off + 4
    out: Dict[str, Any] = {}
    while i < end - 1:
        t = blob[i]
        i += 1
        name, i = _dec_cstring(blob, i)
        if t == 0x01:
            (out[name],) = struct.unpack_from("<d", blob, i)
            i += 8
        elif t == 0x02:
            (n,) = struct.unpack_from("<i", blob, i)
            i += 4
            out[name] = blob[i:i + n - 1].decode("utf-8")
            i += n
        elif t in (0x03, 0x04):
            sub, j = bson_decode(blob, i)
            out[name] = list(sub.values()) if t == 0x04 else sub
            i = j
        elif t == 0x08:
            out[name] = blob[i] != 0
            i += 1
        elif t == 0x0A:
            out[name] = None
        elif t == 0x10:
            (out[name],) = struct.unpack_from("<i", blob, i)
            i += 4
        elif t == 0x12:
            (out[name],) = struct.unpack_from("<q", blob, i)
            i += 8
        else:
            raise MongoError(f"unsupported BSON type 0x{t:02x}")
    return out, end


# ---------------------------------------------------------------------------
# OP_MSG framing

def pack_op_msg(doc: Dict[str, Any], request_id: int,
                response_to: int = 0) -> bytes:
    body = struct.pack("<I", 0) + b"\x00" + bson_encode(doc)
    return struct.pack("<iiii", 16 + len(body), request_id, response_to,
                       OP_MSG) + body


MORE_TO_COME = 1 << 1  # OP_MSG flagBits: fire-and-forget, no reply


def parse_op_msg(frame: bytes) -> Tuple[int, int, Dict[str, Any]]:
    """frame = one whole wire message.  Returns (request_id, flags, doc)."""
    if len(frame) < 26:  # header + flags + kind + empty doc
        raise MongoError(f"frame too short ({len(frame)} bytes)")
    mlen, req_id, _resp_to, opcode = struct.unpack_from("<iiii", frame, 0)
    if mlen != len(frame):
        raise MongoError(f"length mismatch {mlen} != {len(frame)}")
    if opcode != OP_MSG:
        raise MongoError(f"unsupported opcode {opcode} (OP_MSG only)")
    (flags,) = struct.unpack_from("<I", frame, 16)
    kind = frame[20]
    if kind != 0:
        raise MongoError(f"unsupported section kind {kind}")
    try:
        doc, _ = bson_decode(frame, 21)
    except (struct.error, IndexError, ValueError) as e:
        raise MongoError(f"corrupt BSON: {e}") from None
    return req_id, flags, doc


# ---------------------------------------------------------------------------
# server / client heads

Handler = Callable[[Dict[str, Any]], Dict[str, Any]]


class MongoService:
    """Command dispatcher + its own listener (mongo cannot share the
    sniffed port — its frames have no magic).  Commands register by name
    (the first BSON key, per the OP_MSG convention); hello/ismaster/ping
    have defaults so stock drivers get through their handshake."""

    def __init__(self):
        self._commands: Dict[str, Handler] = {}
        self._srv: Optional[socket.socket] = None
        self._stop = False
        self.register("ping", lambda d: {"ok": 1})
        hello = {
            "ismaster": True, "isWritablePrimary": True,
            "maxBsonObjectSize": 16 * 1024 * 1024,
            "maxMessageSizeBytes": 48_000_000,
            "maxWireVersion": 17, "minWireVersion": 0, "ok": 1,
        }
        self.register("hello", lambda d: dict(hello))
        self.register("ismaster", lambda d: dict(hello))
        self.register("isMaster", lambda d: dict(hello))

    def register(self, command: str, handler: Handler) -> None:
        self._commands[command] = handler

    def dispatch(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        if not doc:
            return {"ok": 0, "errmsg": "empty command", "code": 22}
        cmd = next(iter(doc))
        h = self._commands.get(cmd)
        if h is None:
            return {"ok": 0, "errmsg": f"no such command: '{cmd}'",
                    "code": 59}
        try:
            return h(doc)
        except Exception as e:  # command bug → mongo-style error doc
            return {"ok": 0, "errmsg": repr(e), "code": 8}

    # -- listener -----------------------------------------------------------

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self.port

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            next_id = 1
            while True:
                hdr = _recv_exact(conn, 4)
                if hdr is None:
                    return
                (mlen,) = struct.unpack("<i", hdr)
                if not 16 <= mlen <= 48_000_000:
                    return  # corrupt framing: drop the connection
                rest = _recv_exact(conn, mlen - 4)
                if rest is None:
                    return
                req_id, flags, doc = parse_op_msg(hdr + rest)
                reply = self.dispatch(doc)
                if flags & MORE_TO_COME:
                    continue  # fire-and-forget: the contract is NO reply
                conn.sendall(pack_op_msg(reply, next_id, req_id))
                next_id += 1
        except Exception:
            pass  # corrupt peer: drop the connection, never the thread
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        if self._srv is not None:
            self._srv.close()


class MongoClient:
    """OP_MSG command client (the head of a driver: handshake + runCommand)."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._req = 0
        self._lock = threading.Lock()

    def command(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            self._req += 1
            sent_id = self._req
            self._sock.sendall(pack_op_msg(doc, sent_id))
            hdr = recv_exact(self._sock, 4)
            (mlen,) = struct.unpack("<i", hdr)
            if not 16 <= mlen <= 48_000_000:
                raise MongoError(f"bad reply length {mlen}")
            rest = recv_exact(self._sock, mlen - 4)
        frame = hdr + rest
        (_mlen, _rid, resp_to, _op) = struct.unpack_from("<iiii", frame, 0)
        if resp_to != sent_id:
            # a desynced stream (e.g. after a timeout left a reply
            # unread) must fail loudly, not return a stale answer
            raise MongoError(
                f"responseTo {resp_to} does not match request {sent_id}")
        _rid2, _flags, reply = parse_op_msg(frame)
        return reply

    def hello(self) -> Dict[str, Any]:
        return self.command({"hello": 1})

    def ping(self) -> bool:
        return self.command({"ping": 1}).get("ok") == 1

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    """Server-loop variant: None on EOF (a vanished client is normal)."""
    try:
        return recv_exact(conn, n)
    except ConnectionError:
        return None
