"""rpcz — sampled per-RPC spans (≙ the reference Span, span.h:47: created
per RPC client-side in Channel::CallMethod (channel.cpp:467-485) and
server-side in ProcessRpcRequest; free-text Annotate (span.h:80); sampling
throttled by bvar::Collector, collector.h:41 COLLECTOR_SAMPLING_BASE;
browsed through the /rpcz builtin service, builtin/rpcz_service.cpp).

TPU build differences: spans live in an in-process ring, and — when
``rpcz_persist_dir`` names a directory — finished spans additionally
spill to disk through the shared Collector (metrics/collector.py, the
≙ bvar::Collector background service) into length-prefixed recordio
files (utils/recordio.py) with size-based rotation, a time-keyed index
(index.txt: file min_ts max_ts count) and age-based expiry — the
capability of the reference persisting spans to leveldb with
span_db.cpp's time-indexed browsing (≙ span.cpp:476-494,672:
ForkAndSaveTo + the leveldb SpanDB).  ``/rpcz?time=<epoch>`` reads back
from disk, so sampled spans survive a process restart.  Sampling is a
plain token bucket refilled per second.  Span creation is off unless the
``enable_rpcz`` flag is on (≙ --enable_rpcz).
"""

from __future__ import annotations

import itertools
import json
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional

from brpc_tpu.utils import flags
from brpc_tpu.utils import recordio

def _push_rpcz(value) -> bool:
    """Flag validator doubling as the native push: the C++ span rings
    (native/src/metrics.h rpcz_*) capture fast-path spans only while the
    native half of the switch is on."""
    from brpc_tpu._native import lib
    lib().trpc_set_rpcz(1 if value else 0)
    return True


def _push_rpcz_budget(value) -> bool:
    if value < 0:
        return False
    from brpc_tpu._native import lib
    lib().trpc_set_rpcz_budget(int(value))
    return True


flags.define_bool("enable_rpcz", False, "collect rpcz spans",
                  validator=_push_rpcz)
flags.define_int32("rpcz_max_samples_per_second", 16384,
                   "span sampling budget (≙ COLLECTOR_SAMPLING_BASE); "
                   "shared by the Python spans and the native span rings",
                   validator=_push_rpcz_budget)
flags.define_int32("rpcz_keep_spans", 10000, "ring size of kept spans")
flags.define_string("rpcz_persist_dir", "",
                    "directory for rpcz span spill files (recordio, "
                    "rotated + time-indexed + expired); empty = spans "
                    "live only in the in-memory ring (≙ the reference "
                    "persisting spans via SpanDB/leveldb)")
flags.define_int32("rpcz_persist_rotate_bytes", 1 << 20,
                   "rotate the active span spill file past this size "
                   "(each rotation adds a time-keyed index entry)")
flags.define_int32("rpcz_persist_expiry_s", 24 * 3600,
                   "delete span spill files whose newest span is older "
                   "than this (checked at rotation and at read time; "
                   "≙ the reference's --span_keeping_seconds)")

_id_gen = itertools.count(random.getrandbits(48) << 8)
_tls = threading.local()


def _new_id() -> int:
    return next(_id_gen)


@dataclass
class Span:
    trace_id: int
    span_id: int
    parent_span_id: int = 0
    kind: str = "server"            # "server" | "client"
    method: str = ""
    remote_side: str = ""
    start_ts: float = 0.0           # wall clock
    latency_us: int = 0
    error_code: int = 0
    annotations: List[str] = field(default_factory=list)

    def annotate(self, text: str) -> None:
        """≙ TRACEPRINTF (traceprintf.h): free text with a timestamp."""
        dt_us = int((time.time() - self.start_ts) * 1e6)
        self.annotations.append(f"+{dt_us}us {text}")

    def describe(self) -> dict:
        return {
            "trace_id": f"{self.trace_id:x}",
            "span_id": f"{self.span_id:x}",
            "parent_span_id": f"{self.parent_span_id:x}",
            "kind": self.kind,
            "method": self.method,
            "remote_side": self.remote_side,
            "start": time.strftime("%Y-%m-%d %H:%M:%S",
                                   time.localtime(self.start_ts)),
            "latency_us": self.latency_us,
            "error_code": self.error_code,
            "annotations": self.annotations,
        }


class _Store:
    """Ring of finished spans + per-second sampling budget
    (the budget is the shared Collector primitive, ≙ bvar::Collector)."""

    def __init__(self):
        from brpc_tpu.metrics.collector import PerSecondBudget
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(flags.get_flag(
            "rpcz_keep_spans")))
        self._budget = PerSecondBudget("rpcz_max_samples_per_second")

    def try_sample(self) -> bool:
        return self._budget.try_take()

    def add(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)

    def recent(self, n: int, trace_id: Optional[int]) -> List[Span]:
        with self._lock:
            items = list(self._ring)
        if trace_id is not None:
            items = [s for s in items if s.trace_id == trace_id]
        return items[-n:][::-1]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_store = _Store()


# --- disk spill (≙ span.cpp:476-494,672: spans forked to the collector
# and persisted; browsed back by time) --------------------------------------


def _span_to_payload(s: Span) -> bytes:
    return json.dumps({
        "trace_id": s.trace_id, "span_id": s.span_id,
        "parent_span_id": s.parent_span_id, "kind": s.kind,
        "method": s.method, "remote_side": s.remote_side,
        "start_ts": s.start_ts, "latency_us": s.latency_us,
        "error_code": s.error_code, "annotations": s.annotations,
    }).encode()


def _span_from_payload(payload: bytes) -> Optional[Span]:
    try:
        d = json.loads(payload.decode())
        return Span(trace_id=int(d["trace_id"]), span_id=int(d["span_id"]),
                    parent_span_id=int(d.get("parent_span_id", 0)),
                    kind=d.get("kind", "server"),
                    method=d.get("method", ""),
                    remote_side=d.get("remote_side", ""),
                    start_ts=float(d.get("start_ts", 0.0)),
                    latency_us=int(d.get("latency_us", 0)),
                    error_code=int(d.get("error_code", 0)),
                    annotations=list(d.get("annotations", [])))
    except (ValueError, KeyError, TypeError):
        return None  # torn/foreign record: recordio already resynced


class _Persister:
    """Span spill files under ``rpcz_persist_dir``:

        spans-<ms>.rio   rotated recordio segments (utils/recordio.py)
        index.txt        one line per SEALED segment: name min max count

    Writes arrive on the Collector thread only (on_collected); reads
    (read_persisted) take the same lock, flush the active segment and
    scan index entries whose [min_ts, max_ts] window is relevant — the
    time-keyed lookup that makes /rpcz?time= skip cold segments."""

    def __init__(self):
        self._lock = threading.Lock()
        self._writer: Optional[recordio.RecordWriter] = None
        self._path = ""        # active segment (not yet in the index)
        self._min_ts = 0.0
        self._max_ts = 0.0
        self._count = 0
        self._seq = 0          # disambiguates same-millisecond rotations

    def _dir(self) -> str:
        d = str(flags.get_flag("rpcz_persist_dir") or "")
        # normalized: a trailing slash must not defeat the active-segment
        # dir comparison in write() (it would seal+reopen per span)
        return os.path.normpath(d) if d else ""

    def _index_path(self, d: str) -> str:
        return os.path.join(d, "index.txt")

    def _open_locked(self, d: str, first_ts: float) -> None:
        os.makedirs(d, exist_ok=True)
        self._seq += 1
        name = f"spans-{int(first_ts * 1000)}-{os.getpid()}-{self._seq}.rio"
        self._path = os.path.join(d, name)
        self._writer = recordio.RecordWriter(self._path)
        self._min_ts = first_ts
        self._max_ts = first_ts
        self._count = 0

    def _seal_locked(self, d: str) -> None:
        if self._writer is None:
            return
        self._writer.close()
        with open(self._index_path(d), "a", encoding="utf-8") as f:
            f.write(f"{os.path.basename(self._path)} {self._min_ts:.6f} "
                    f"{self._max_ts:.6f} {self._count}\n")
        self._writer = None
        self._path = ""

    def _expire_locked(self, d: str) -> None:
        """Drop sealed segments whose newest span aged out; rewrite the
        index without them."""
        idx = self._index_path(d)
        if not os.path.exists(idx):
            return
        horizon = time.time() - int(flags.get_flag("rpcz_persist_expiry_s"))
        keep, dropped = [], []
        with open(idx, encoding="utf-8") as f:
            for line in f:
                parts = line.split()
                if len(parts) != 4:
                    continue
                if float(parts[2]) >= horizon:
                    keep.append(line)
                else:
                    dropped.append(parts[0])
        if not dropped:
            return
        for name in dropped:
            try:
                os.unlink(os.path.join(d, name))
            except OSError:
                pass
        tmp = idx + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.writelines(keep)
        os.replace(tmp, idx)  # atomic: readers never see a half index

    def write(self, s: Span) -> None:
        d = self._dir()
        if not d:
            return
        with self._lock:
            if self._writer is not None and \
                    not self._path.startswith(d + os.sep):
                self._seal_locked(os.path.dirname(self._path))  # dir moved
            if self._writer is None:
                self._open_locked(d, s.start_ts)
            self._writer.write(_span_to_payload(s))
            self._min_ts = min(self._min_ts, s.start_ts)
            self._max_ts = max(self._max_ts, s.start_ts)
            self._count += 1
            if self._writer.tell() >= int(
                    flags.get_flag("rpcz_persist_rotate_bytes")):
                self._seal_locked(d)
                self._expire_locked(d)

    def read(self, at_ts: float, limit: int) -> List[Span]:
        """Spans with start_ts <= at_ts, newest first, from disk — the
        restart-surviving read path behind /rpcz?time=."""
        d = self._dir()
        if not d or not os.path.isdir(d):
            return []
        with self._lock:
            if self._writer is not None:
                self._writer.flush()  # the active segment is readable
            active = self._path
            self._expire_locked(d)
            candidates: List[str] = []
            sealed: set = set()  # EVERY indexed name, kept or time-skipped
            idx = self._index_path(d)
            if os.path.exists(idx):
                with open(idx, encoding="utf-8") as f:
                    for line in f:
                        parts = line.split()
                        if len(parts) != 4:
                            continue
                        sealed.add(parts[0])
                        # time-keyed skip: a segment strictly newer than
                        # the asked time can hold no matching span
                        if float(parts[1]) <= at_ts:
                            candidates.append(os.path.join(d, parts[0]))
            if active and self._min_ts <= at_ts:
                candidates.append(active)
            # crash recovery: an unsealed segment from a previous process
            # has no index entry — scan for orphans.  Exclusion must use
            # the FULL sealed set: a time-skipped sealed segment is not
            # an orphan, and re-adding it here would defeat the
            # time-keyed pruning (reading every cold segment anyway).
            for name in sorted(os.listdir(d)):
                if name.startswith("spans-") and name.endswith(".rio") \
                        and name not in sealed and \
                        os.path.join(d, name) != active:
                    candidates.append(os.path.join(d, name))
            candidates = list(dict.fromkeys(candidates))
        out: List[Span] = []
        for path in candidates:
            try:
                for payload in recordio.read_records(path):
                    s = _span_from_payload(payload)
                    if s is not None and s.start_ts <= at_ts:
                        out.append(s)
            except OSError:
                continue  # expired between listing and reading
        out.sort(key=lambda s: s.start_ts, reverse=True)
        return out[:limit]


_persister = _Persister()


class _SpanSample:
    """Collected adapter: the hot path pays one Collector submit; the
    recordio write happens on the collector thread (≙ bvar::Collected)."""

    def __init__(self, span: Span):
        self._span = span

    def on_collected(self) -> None:
        _persister.write(self._span)


def persisting() -> bool:
    return bool(flags.get_flag("rpcz_persist_dir"))


# --- native fast-path spans (native/src/metrics.h span rings) ---------------
# Inline-dispatched requests never enter Python, so their sampled spans
# live in per-shard native rings; drain_native() pulls them into the SAME
# store/persistence the Python spans use — /rpcz shows one merged view,
# and the recordio spill rides the shared Collector unchanged.

# presentation (kind, method label) per native family id (metrics.h
# TelemetryFamily); a family added natively falls back to its capi name
# with kind "server" instead of a blind "native" label
_NATIVE_FAMILY_VIEW = {
    0: ("server", "Echo (native inline)"),
    1: ("server", "HbmEcho (native)"),
    2: ("server", "redis_cache (native)"),
    3: ("server", "usercode (native)"),
    4: ("client", "client (native unary)"),
    5: ("client", "fanout (native group)"),
}


def _family_view(fam: int):
    view = _NATIVE_FAMILY_VIEW.get(fam)
    if view is not None:
        return view
    try:
        from brpc_tpu._native import lib
        return ("server",
                lib().trpc_telemetry_family_name(fam).decode() +
                " (native)")
    except Exception:
        return ("server", "native")


_drain_lock = threading.Lock()


def drain_native() -> int:
    """Move captured native spans into the span store (returns how many).
    Called on every /rpcz read and recent_spans() — reads happen at human
    frequency; the native side is lock-free for its writers."""
    if not enabled():
        return 0
    try:
        import ctypes
        from brpc_tpu._native import lib
    except Exception:
        return 0  # native core unavailable (exotic import contexts)
    moved = 0
    # rebase CLOCK_MONOTONIC capture stamps onto the wall clock once per
    # drain (both sides read the same kernel clocks on Linux)
    offset = time.time() - time.monotonic()
    with _drain_lock:
        buf = ctypes.create_string_buffer(1 << 20)
        while True:
            n = lib().trpc_rpcz_drain(buf, len(buf))
            if n == 0:
                break
            for line in buf.raw[:n].decode("utf-8", "replace").splitlines():
                parts = line.split("\t")
                if len(parts) < 8:
                    continue
                try:
                    fam = int(parts[3])
                    kind, method = _family_view(fam)
                    s = Span(
                        trace_id=int(parts[0]), span_id=int(parts[1]),
                        parent_span_id=int(parts[2]),
                        kind=kind, method=method,
                        start_ts=int(parts[6]) / 1e9 + offset,
                        latency_us=int(parts[7]),
                        error_code=int(parts[4]),
                        annotations=[a for a in parts[8].split("|") if a])
                except (ValueError, IndexError):
                    continue
                s.remote_side = f"shard{parts[5]}"
                _store.add(s)
                if persisting():
                    from brpc_tpu.metrics.collector import global_collector
                    global_collector().submit(_SpanSample(s))
                moved += 1
            if n < len(buf) - 256:
                break  # the rings are drained (not a buffer-full stop)
    return moved


def read_persisted(at_ts: Optional[float] = None,
                   limit: int = 100) -> List[Span]:
    """Disk read-back for /rpcz?time= (spans survive restarts)."""
    return _persister.read(at_ts if at_ts is not None else time.time(),
                           limit)


def enabled() -> bool:
    return bool(flags.get_flag("enable_rpcz"))


def start_span(kind: str, method: str, trace_id: int = 0,
               parent_span_id: int = 0) -> Optional[Span]:
    """Create a sampled span, or None (disabled / over budget).
    A zero trace_id starts a new trace (≙ Span::CreateServerSpan with no
    inherited ids)."""
    if not enabled() or not _store.try_sample():
        return None
    s = Span(trace_id=trace_id or _new_id(), span_id=_new_id(),
             parent_span_id=parent_span_id, kind=kind, method=method,
             start_ts=time.time())
    return s


def finish_span(span: Optional[Span], error_code: int = 0) -> None:
    if span is None:
        return
    span.latency_us = int((time.time() - span.start_ts) * 1e6)
    span.error_code = error_code
    _store.add(span)
    if persisting():
        # spill through the shared Collector (rate-limited background
        # service): the RPC path pays one budget check + deque append,
        # the recordio write runs on the collector thread
        from brpc_tpu.metrics.collector import global_collector
        global_collector().submit(_SpanSample(span))


def set_current(span: Optional[Span]) -> None:
    """TLS parent for annotate() (≙ tls_parent, span.h:115)."""
    _tls.span = span


def current() -> Optional[Span]:
    return getattr(_tls, "span", None)


def annotate(text: str) -> None:
    """≙ TRACEPRINTF into the current span; no-op when unsampled."""
    s = current()
    if s is not None:
        s.annotate(text)


def recent_spans(n: int = 100, trace_id: Optional[int] = None) -> List[Span]:
    drain_native()  # fast-path spans surface beside the Python ones
    return _store.recent(n, trace_id)


def clear() -> None:
    _store.clear()
