"""rpcz — sampled per-RPC spans (≙ the reference Span, span.h:47: created
per RPC client-side in Channel::CallMethod (channel.cpp:467-485) and
server-side in ProcessRpcRequest; free-text Annotate (span.h:80); sampling
throttled by bvar::Collector, collector.h:41 COLLECTOR_SAMPLING_BASE;
browsed through the /rpcz builtin service, builtin/rpcz_service.cpp).

TPU build differences: spans live in an in-process ring (the reference
persists to leveldb — operators here scrape /rpcz or read
``recent_spans()``), and sampling is a plain token bucket refilled per
second.  Span creation is off unless the ``enable_rpcz`` flag is on
(≙ --enable_rpcz).
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional

from brpc_tpu.utils import flags

flags.define_bool("enable_rpcz", False, "collect rpcz spans")
flags.define_int32("rpcz_max_samples_per_second", 16384,
                   "span sampling budget (≙ COLLECTOR_SAMPLING_BASE)")
flags.define_int32("rpcz_keep_spans", 10000, "ring size of kept spans")

_id_gen = itertools.count(random.getrandbits(48) << 8)
_tls = threading.local()


def _new_id() -> int:
    return next(_id_gen)


@dataclass
class Span:
    trace_id: int
    span_id: int
    parent_span_id: int = 0
    kind: str = "server"            # "server" | "client"
    method: str = ""
    remote_side: str = ""
    start_ts: float = 0.0           # wall clock
    latency_us: int = 0
    error_code: int = 0
    annotations: List[str] = field(default_factory=list)

    def annotate(self, text: str) -> None:
        """≙ TRACEPRINTF (traceprintf.h): free text with a timestamp."""
        dt_us = int((time.time() - self.start_ts) * 1e6)
        self.annotations.append(f"+{dt_us}us {text}")

    def describe(self) -> dict:
        return {
            "trace_id": f"{self.trace_id:x}",
            "span_id": f"{self.span_id:x}",
            "parent_span_id": f"{self.parent_span_id:x}",
            "kind": self.kind,
            "method": self.method,
            "remote_side": self.remote_side,
            "start": time.strftime("%Y-%m-%d %H:%M:%S",
                                   time.localtime(self.start_ts)),
            "latency_us": self.latency_us,
            "error_code": self.error_code,
            "annotations": self.annotations,
        }


class _Store:
    """Ring of finished spans + per-second sampling budget
    (the budget is the shared Collector primitive, ≙ bvar::Collector)."""

    def __init__(self):
        from brpc_tpu.metrics.collector import PerSecondBudget
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(flags.get_flag(
            "rpcz_keep_spans")))
        self._budget = PerSecondBudget("rpcz_max_samples_per_second")

    def try_sample(self) -> bool:
        return self._budget.try_take()

    def add(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)

    def recent(self, n: int, trace_id: Optional[int]) -> List[Span]:
        with self._lock:
            items = list(self._ring)
        if trace_id is not None:
            items = [s for s in items if s.trace_id == trace_id]
        return items[-n:][::-1]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_store = _Store()


def enabled() -> bool:
    return bool(flags.get_flag("enable_rpcz"))


def start_span(kind: str, method: str, trace_id: int = 0,
               parent_span_id: int = 0) -> Optional[Span]:
    """Create a sampled span, or None (disabled / over budget).
    A zero trace_id starts a new trace (≙ Span::CreateServerSpan with no
    inherited ids)."""
    if not enabled() or not _store.try_sample():
        return None
    s = Span(trace_id=trace_id or _new_id(), span_id=_new_id(),
             parent_span_id=parent_span_id, kind=kind, method=method,
             start_ts=time.time())
    return s


def finish_span(span: Optional[Span], error_code: int = 0) -> None:
    if span is None:
        return
    span.latency_us = int((time.time() - span.start_ts) * 1e6)
    span.error_code = error_code
    _store.add(span)


def set_current(span: Optional[Span]) -> None:
    """TLS parent for annotate() (≙ tls_parent, span.h:115)."""
    _tls.span = span


def current() -> Optional[Span]:
    return getattr(_tls, "span", None)


def annotate(text: str) -> None:
    """≙ TRACEPRINTF into the current span; no-op when unsampled."""
    s = current()
    if s is not None:
        s.annotate(text)


def recent_spans(n: int = 100, trace_id: Optional[int] = None) -> List[Span]:
    return _store.recent(n, trace_id)


def clear() -> None:
    _store.clear()
