"""Protobuf-typed services + JSON⇄pb transcoding (≙ json2pb,
SURVEY.md §2.5: json_to_pb.cpp / pb_to_json.cpp powering HTTP+JSON
access to pb services through http_rpc_protocol.cpp).

A pb service registers methods with their request/response message
classes.  Three access paths share one handler:

  * TRPC:       payload is the serialized request message; the response
                payload is the serialized response message.
  * HTTP JSON:  POST /rpc/<Service>.<Method> with a JSON body — fields
                transcode through google.protobuf.json_format exactly
                like the reference's rapidjson bridge.
  * HTTP pb:    POST with content-type application/proto(buf) passes
                serialized bytes straight through.

Handlers: handler(cntl, request_msg) -> response_msg.
"""

from __future__ import annotations

from typing import Dict, Tuple, Type

from google.protobuf import json_format
from google.protobuf.message import Message

__all__ = ["add_pb_service", "json_to_pb", "pb_to_json"]


def json_to_pb(data: bytes, msg_cls: Type[Message],
               ignore_unknown_fields: bool = False) -> Message:
    """JSON bytes -> message (≙ json_to_pb.cpp JsonToProtoMessage)."""
    msg = msg_cls()
    json_format.Parse(data.decode("utf-8"), msg,
                      ignore_unknown_fields=ignore_unknown_fields)
    return msg


def pb_to_json(msg: Message, always_print_fields_with_no_presence=False
               ) -> bytes:
    """Message -> JSON bytes (≙ pb_to_json.cpp ProtoMessageToJson)."""
    return json_format.MessageToJson(
        msg,
        always_print_fields_with_no_presence=(
            always_print_fields_with_no_presence),
        preserving_proto_field_name=True).encode("utf-8")


def add_pb_service(server, service_name: str,
                   methods: Dict[str, Tuple]) -> None:
    """Register a pb-typed service on `server`.

    methods: {method_name: (handler, RequestCls, ResponseCls)} with
    handler(cntl, request_msg) -> response_msg.  Each method serves as
    TRPC "<Service>.<Method>" and via the /rpc JSON bridge; the bridge
    learns the message types through server._pb_specs.
    """
    specs = getattr(server, "_pb_specs", None)
    if specs is None:
        specs = server._pb_specs = {}

    for method, (handler, req_cls, resp_cls) in methods.items():
        full = f"{service_name}.{method}"
        if not (isinstance(req_cls, type) and
                issubclass(req_cls, Message) and
                isinstance(resp_cls, type) and
                issubclass(resp_cls, Message)):
            raise TypeError(f"{full}: request/response must be pb classes")
        specs[full] = (req_cls, resp_cls)

        def wire_handler(cntl, payload, _h=handler, _rq=req_cls,
                         _rs=resp_cls, _full=full):
            req = _rq()
            req.ParseFromString(payload)
            resp = _h(cntl, req)
            if not isinstance(resp, _rs):
                raise TypeError(
                    f"{_full} handler returned {type(resp).__name__}, "
                    f"expected {_rs.__name__}")
            return resp.SerializeToString()

        server.add_service(full, wire_handler)


def pb_call(channel, method: str, request: Message,
            resp_cls: Type[Message], **kwargs) -> Message:
    """Typed client call: serialize request, call over the channel,
    parse the response (≙ a generated stub's CallMethod through
    Channel)."""
    raw = channel.call(method, request.SerializeToString(), **kwargs)
    resp = resp_cls()
    resp.ParseFromString(raw)
    return resp
