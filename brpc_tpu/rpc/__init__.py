"""rpc — Server/Channel/Controller public API (≙ reference src/brpc core:
server.h:343, channel.h:151, controller.h:110)."""

from brpc_tpu.rpc.errors import (  # noqa: F401
    RpcError, ERPCTIMEDOUT, EFAILEDSOCKET, ENOSERVICE, ENOMETHOD, EREQUEST,
    EINTERNAL, ELIMIT, ESTOP, error_text)
from brpc_tpu.rpc.controller import Controller  # noqa: F401
from brpc_tpu.rpc.channel import Channel, ChannelOptions  # noqa: F401
from brpc_tpu.rpc.server import Server, ServerOptions  # noqa: F401
from brpc_tpu.rpc.stream import (  # noqa: F401
    Stream, StreamClosed, StreamReset, StreamTimeout)
from brpc_tpu.rpc.auth import (  # noqa: F401
    AuthContext, AuthError, Authenticator, HmacNonceAuthenticator)
