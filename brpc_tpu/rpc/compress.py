"""Compression registry (≙ brpc compress.h:105 CompressHandler registry
keyed by CompressType; impls policy/gzip_compress.cpp + snappy).

The native core carries the compress_type meta tag (rpc.h tag 6) untouched;
codecs run here, on the usercode side of the boundary — requests are
compressed before entering the native write path, responses after leaving
it.  Type ids are part of the wire contract:
    0 = none    1 = gzip    2 = zlib (deflate)    3 = snappy
New codecs register with :func:`register` (≙ RegisterCompressHandler).
"""

from __future__ import annotations

import gzip as _gzip
import zlib as _zlib
from typing import Callable, Dict, Optional, Tuple

from brpc_tpu.utils import flags

COMPRESS_NONE = 0
COMPRESS_GZIP = 1
COMPRESS_ZLIB = 2
COMPRESS_SNAPPY = 3

# ≙ FLAGS_max_body_size bounding what a peer can make us materialize —
# applied to DECOMPRESSED size so a small zip bomb cannot OOM the process
flags.define_int32("max_decompressed_size", 512 * 1024 * 1024,
                   "cap on decompressed payload bytes")


def _bounded_inflate(data: bytes, wbits: int) -> bytes:
    limit = int(flags.get_flag("max_decompressed_size"))
    d = _zlib.decompressobj(wbits)
    out = d.decompress(data, limit)
    if d.unconsumed_tail or (not d.eof and d.decompress(b"", 1)):
        raise ValueError(
            f"decompressed payload exceeds {limit} bytes")
    return out

_handlers: Dict[int, Tuple[str, Callable[[bytes], bytes],
                           Callable[[bytes], bytes]]] = {}
_by_name: Dict[str, int] = {}


def register(type_id: int, name: str, compress_fn: Callable[[bytes], bytes],
             decompress_fn: Callable[[bytes], bytes]) -> None:
    """≙ RegisterCompressHandler (compress.cpp): type_id must be stable
    across every peer speaking the protocol."""
    if type_id == COMPRESS_NONE:
        raise ValueError("type 0 is reserved for 'none'")
    _handlers[type_id] = (name, compress_fn, decompress_fn)
    _by_name[name] = type_id


def type_of(name: str) -> int:
    if name in ("", "none"):
        return COMPRESS_NONE
    if name not in _by_name:
        raise KeyError(f"unknown compression {name!r}")
    return _by_name[name]


def name_of(type_id: int) -> str:
    if type_id == COMPRESS_NONE:
        return "none"
    h = _handlers.get(type_id)
    return h[0] if h else f"unknown({type_id})"


def compress(data: bytes, type_id: int) -> bytes:
    if type_id == COMPRESS_NONE:
        return data
    h = _handlers.get(type_id)
    if h is None:
        raise KeyError(f"no compress handler for type {type_id}")
    return h[1](data)


def decompress(data: bytes, type_id: int) -> bytes:
    if type_id == COMPRESS_NONE:
        return data
    h = _handlers.get(type_id)
    if h is None:
        raise KeyError(f"no decompress handler for type {type_id}")
    return h[2](data)


register(COMPRESS_GZIP, "gzip",
         lambda b: _gzip.compress(b, compresslevel=6),
         lambda b: _bounded_inflate(b, 16 + _zlib.MAX_WBITS))
register(COMPRESS_ZLIB, "zlib", _zlib.compress,
         lambda b: _bounded_inflate(b, _zlib.MAX_WBITS))


def _snappy_compress(data: bytes) -> bytes:
    """Native snappy block format (native/src/snappy.cc ≙ the snappy
    codec policy/snappy_compress.cpp wires in)."""
    import ctypes
    from brpc_tpu._native import lib
    L = lib()
    out = ctypes.create_string_buffer(
        int(L.trpc_snappy_max_compressed_length(len(data))))
    n = L.trpc_snappy_compress(data, len(data), out)
    return out.raw[:n]


def _snappy_decompress(data: bytes) -> bytes:
    import ctypes
    from brpc_tpu._native import lib
    L = lib()
    expect = int(L.trpc_snappy_uncompressed_length(data, len(data)))
    limit = int(flags.get_flag("max_decompressed_size"))
    if expect == (1 << 64) - 1 or expect > limit:
        raise ValueError("corrupt snappy stream or size over limit")
    out = ctypes.create_string_buffer(max(expect, 1))
    n = int(L.trpc_snappy_decompress(data, len(data), out, expect))
    if n != expect:
        raise ValueError("corrupt snappy stream")
    return out.raw[:n]


register(COMPRESS_SNAPPY, "snappy", _snappy_compress, _snappy_decompress)
