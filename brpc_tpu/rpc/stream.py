"""Streaming RPC — flow-controlled, ordered message streams riding an
established RPC connection (≙ reference StreamCreate/StreamAccept/
StreamWrite, stream.h:102-120 + policy/streaming_rpc_protocol.cpp;
re-designed: frames share the TRPC TLV framing, credit-based feedback
replaces the reference's Feedback frames, and the writer's throttle is a
butex — the same primitive a PJRT completion callback can wake, so a fiber
streaming tensors out of HBM parks for free while the window is full).

Client:
    resp, stream = channel.create_stream("Svc.Method", b"hello")
    stream.write(b"chunk")
    data = stream.read()        # None on EOF
    stream.close()

Server handler:
    def handler(cntl, req):
        stream = cntl.accept_stream()
        ...  # use it from any thread after returning the response
        return b"ok"
"""

from __future__ import annotations

import ctypes
import errno
from typing import Optional

from brpc_tpu._native import lib
from brpc_tpu.rpc import errors

DEFAULT_WINDOW = 2 << 20  # ≙ max_buf_size, reference StreamOptions stream.h:50


class StreamTimeout(Exception):
    """read/write exceeded its deadline while the stream stayed healthy."""


class StreamClosed(Exception):
    """operation on a closed or destroyed stream."""


class StreamProtocolError(Exception):
    """read kind mismatch: device frame vs host data (nothing consumed)."""


class StreamReset(Exception):
    """the stream was abortively reset (RST frame): queued data was
    discarded on both ends and `code` carries the wire error code —
    a reset NEVER surfaces as a clean EOF (≙ VERDICT Missing #3)."""

    def __init__(self, code: int):
        super().__init__(f"stream reset (error code {code})")
        self.code = code


class Stream:
    """One half of a bidirectional stream (native handle underneath)."""

    def __init__(self, handle: int):
        self._h = handle
        self._destroyed = False

    # -- data path ----------------------------------------------------------

    def write(self, data: bytes, timeout_s: Optional[float] = None) -> None:
        """Send one message.  Blocks while the peer's flow-control window
        is full (≙ StreamWrite returning EAGAIN + StreamWait, here folded
        into one blocking call on a butex)."""
        timeout_us = -1 if timeout_s is None else int(timeout_s * 1e6)
        rc = lib().trpc_stream_write(self._h, data, len(data), timeout_us)
        if rc == 0:
            return
        if rc == -errno.EAGAIN:
            raise StreamTimeout(f"write timed out after {timeout_s}s")
        if rc == -errno.EPIPE:
            raise StreamClosed("peer closed the stream")
        if rc == -errno.ECONNABORTED:
            raise StreamReset(self.rst_code)
        if rc == -errno.EINVAL:
            raise StreamClosed("stream destroyed")
        raise errors.RpcError(errors.EFAILEDSOCKET,
                              "stream connection failed")

    def try_write(self, data: bytes) -> bool:
        """Non-blocking write: queue one message if the peer's window has
        room RIGHT NOW, else return False without waiting.  A per-step
        producer (e.g. a decode loop fanning one token to N streams)
        uses this to detect a slow consumer without stalling the whole
        batch; stream failures still raise like write()."""
        try:
            self.write(data, timeout_s=0)
            return True
        except StreamTimeout:
            return False

    def read(self, timeout_s: Optional[float] = None) -> Optional[bytes]:
        """Receive one message; None on clean EOF (peer closed)."""
        timeout_us = -1 if timeout_s is None else int(timeout_s * 1e6)
        p = ctypes.POINTER(ctypes.c_uint8)()
        n = lib().trpc_stream_read(self._h, timeout_us, ctypes.byref(p))
        if n > 0:
            try:
                return ctypes.string_at(p, n)
            finally:
                lib().trpc_stream_buf_free(p)
        if n == 0:
            if p:
                lib().trpc_stream_buf_free(p)
            return None  # EOF
        if n == -errno.EAGAIN:
            raise StreamTimeout(f"read timed out after {timeout_s}s")
        if n == -errno.EPROTO:
            raise StreamProtocolError(
                "next stream message is a device frame (read_device() it)")
        if n == -errno.ECONNABORTED:
            raise StreamReset(self.rst_code)
        if n == -errno.EINVAL:
            raise StreamClosed("stream destroyed")
        raise errors.RpcError(errors.EFAILEDSOCKET,
                              "stream connection failed")

    def write_device(self, buf, timeout_s: Optional[float] = None) -> None:
        """Send one TENSOR (a tpu_plane.DeviceBuffer).  Ownership of
        ``buf`` transfers on success — do not free or reuse it.  When both
        stream ends share one PJRT client (equal plane uids from the
        tpu:// handshake) only the 17-byte handle rides the wire and the
        receiver copies device→device with no host landing; otherwise the
        frame carries one explicit d2h landing zone.  Window accounting
        uses the tensor's byte size either way."""
        timeout_us = -1 if timeout_s is None else int(timeout_s * 1e6)
        rc = lib().trpc_stream_write_device(self._h, buf.handle, timeout_us)
        if rc == 0:
            return
        if rc == -errno.EAGAIN:
            raise StreamTimeout(f"write timed out after {timeout_s}s")
        if rc == -errno.EPIPE:
            raise StreamClosed("peer closed the stream")
        if rc == -errno.EINVAL:
            raise StreamClosed("stream destroyed or bad buffer")
        raise errors.RpcError(errors.EFAILEDSOCKET,
                              "stream connection failed")

    def read_device(self, device: int = 0,
                    timeout_s: Optional[float] = None):
        """Receive one tensor onto ``device``; returns a NEW
        tpu_plane.DeviceBuffer (caller frees), or None on clean EOF.
        Raises StreamProtocolError if the next message is host data
        (read() it instead — nothing is consumed)."""
        from brpc_tpu import tpu_plane
        timeout_us = -1 if timeout_s is None else int(timeout_s * 1e6)
        out = ctypes.c_uint64()
        length = ctypes.c_uint64()
        rc = lib().trpc_stream_read_device(
            self._h, device, timeout_us, ctypes.byref(out),
            ctypes.byref(length))
        if rc == 0:
            return tpu_plane.DeviceBuffer(out.value, length.value)
        if rc == -errno.EPIPE:
            return None  # EOF
        if rc == -errno.ECONNABORTED:
            raise StreamReset(self.rst_code)
        if rc == -errno.EAGAIN:
            raise StreamTimeout(f"read timed out after {timeout_s}s")
        if rc == -errno.EPROTO:
            raise StreamProtocolError(
                "next stream message is not a device frame")
        if rc == -errno.EINVAL:
            raise StreamClosed("stream destroyed")
        if rc == -errno.EIO:
            raise IOError("device materialization failed")
        raise errors.RpcError(errors.EFAILEDSOCKET,
                              "stream connection failed")

    def __iter__(self):
        while True:
            msg = self.read()
            if msg is None:
                return
            yield msg

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Send CLOSE; reads still drain, writes are refused."""
        lib().trpc_stream_close(self._h)

    def rst(self, code: int = 0) -> None:
        """Abortive close (RST frame): discard queued data on both ends
        and surface `code` as the peer's read error — never a clean EOF
        (code 0 is coerced to ECANCELED natively).  An RPC cancel on a
        call with an accepted stream propagates as exactly this."""
        lib().trpc_stream_rst(self._h, code)

    def destroy(self) -> None:
        if not self._destroyed:
            self._destroyed = True
            lib().trpc_stream_destroy(self._h)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.destroy()

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass

    # -- state --------------------------------------------------------------

    @property
    def remote_closed(self) -> bool:
        return lib().trpc_stream_remote_closed(self._h) == 1

    @property
    def failed(self) -> bool:
        return lib().trpc_stream_failed(self._h) == 1

    @property
    def rst_code(self) -> int:
        """The error code carried by an RST (either direction); 0 when
        the stream was never reset."""
        return max(lib().trpc_stream_rst_code(self._h), 0)

    @property
    def pending_bytes(self) -> int:
        """Bytes received but not yet read."""
        return max(lib().trpc_stream_pending_bytes(self._h), 0)


def accept_from_token(token: int, window: int = DEFAULT_WINDOW
                      ) -> Optional[Stream]:
    """Server side: accept the stream attached to a pending request token
    (≙ StreamAccept, stream.cpp:802).  None if the request carried no
    stream or the token is stale."""
    h = lib().trpc_stream_accept(token, window)
    return Stream(h) if h else None


def token_has_stream(token: int) -> bool:
    return lib().trpc_token_stream_id(token) != 0
