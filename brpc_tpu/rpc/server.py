"""Server — service registry + lifecycle (≙ brpc::Server, reference
server.cpp:750 StartInternal: builds the acceptor, registers services and
builtin debug services, binds per-method status).

Data path is native: the acceptor, event dispatcher, frame parsing and the
native echo service never touch Python.  Python handlers run on the native
usercode pthread pool (≙ usercode_in_pthread,
details/usercode_backup_pool.cpp) and respond through trpc_respond.
"""

from __future__ import annotations

import ctypes
import os
import threading
import traceback
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple, Union

from brpc_tpu._native import HTTP_FN, lib
from brpc_tpu.metrics import bvar
from brpc_tpu.rpc import codec as codec_mod
from brpc_tpu.rpc import compress as compress_mod
from brpc_tpu.rpc import dump as dump_mod
from brpc_tpu.rpc import errors, span
from brpc_tpu.rpc import controller as controller_mod
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.http import (HttpDispatcher, HttpRequest, pack_headers,
                               parse_headers_blob)
from brpc_tpu.utils import flags, logging as log

flags.define_int32("event_dispatcher_num", 1,
                   "number of epoll dispatcher threads (the reference's "
                   "event_dispatcher_num); set before the first "
                   "server/channel starts")


def _parse_boot_int(env: str, default: int) -> int:
    try:
        return int(os.environ.get(env, "") or default)
    except ValueError:
        return default


def _push_shards(value) -> bool:
    if not 1 <= value <= 8:
        return False
    # boot-frozen natively: once the fiber runtime started, the native
    # side refuses the change (-EBUSY) — surface that as a flag error
    return lib().trpc_set_shards(int(value)) == 0


def _push_reuseport(value) -> bool:
    return lib().trpc_set_reuseport(1 if value else 0) == 0


flags.define_int32("shards", min(max(_parse_boot_int("TRPC_SHARDS", 1), 1), 8),
                   "runtime shard count (native/src/shard.h): N "
                   "independent reactors — per-shard io_uring/epoll, "
                   "SO_REUSEPORT listeners, shard-pinned fiber groups, "
                   "cross-shard mailbox.  Boot-time only (frozen at the "
                   "first fiber runtime init); 1 = the pre-shard runtime, "
                   "wire- and behavior-identical",
                   validator=_push_shards, reloadable=False)
flags.define_bool("reuseport",
                  os.environ.get("TRPC_REUSEPORT") != "0",
                  "with shards>1: every shard accepts on its own "
                  "SO_REUSEPORT fd (kernel hashes connections across "
                  "them); off = one listener, connections round-robin "
                  "across shards.  Boot-time only",
                  validator=_push_reuseport, reloadable=False)
flags.define_int32("usercode_workers", 4,
                   "pthreads running Python handlers")
flags.define_bool("use_io_uring", False,
                  "serve accepts + reads through io_uring (FORK "
                  "RingListener \u2259 socket.h:360); falls back to epoll "
                  "when the kernel refuses the ring")
flags.define_bool("use_sendzc", True,
                  "zero-copy egress on the io_uring transport: large "
                  "write-queue blocks leave as IORING_OP_SEND_ZC with "
                  "registered landing-zone buffers; falls back to writev "
                  "when the kernel lacks SEND_ZC or reports that it "
                  "copies anyway (no effect unless use_io_uring)")
flags.define_int32("sendzc_threshold_bytes", 16384,
                   "IOBuf blocks at least this large ride SEND_ZC; "
                   "smaller refs gather into linked SENDMSG ops")
def _push_usercode_cap(value) -> bool:
    """Flag validator doubling as the live-reload hook: every /flags set
    propagates straight into the native admission check."""
    if value < 0:
        return False
    lib().trpc_set_usercode_max_inflight(int(value))
    return True


flags.define_int32("usercode_max_inflight", 4096,
                   "TRPC requests queued+running in the usercode pool "
                   "before new ones get ELIMIT (0 = uncapped; "
                   "reloadable; the concurrency-limiter backstop)",
                   validator=_push_usercode_cap)


def _push_inline_dispatch(value) -> bool:
    lib().trpc_set_inline_dispatch(1 if value else 0)
    return True


def _push_inline_budget_requests(value) -> bool:
    if value < 1:
        return False
    lib().trpc_set_inline_budget_requests(int(value))
    return True


def _push_inline_budget_us(value) -> bool:
    if value < 1:
        return False
    lib().trpc_set_inline_budget_us(int(value))
    return True


flags.define_bool("inline_dispatch",
                  os.environ.get("TRPC_INLINE_DISPATCH") != "0",
                  "ingress fast path: short non-blocking handlers "
                  "(native echo, HbmEcho without a DMA wait, native "
                  "redis-cache commands, cached HTTP builtins) run to "
                  "completion on the connection's parse fiber, and each "
                  "drain's responses flush as one corked batch; off = "
                  "spawned-path A/B baseline (TRPC_INLINE_DISPATCH=0)",
                  validator=_push_inline_dispatch)
flags.define_int32("inline_budget_requests", 512,
                   "inline executions one parse drain may run before "
                   "falling back to the spawned path (fairness cap; "
                   "reloadable)", validator=_push_inline_budget_requests)
flags.define_int32("inline_budget_us", 500,
                   "µs of one parse drain spent inline before falling "
                   "back to the spawned path (reloadable)",
                   validator=_push_inline_budget_us)


def _push_accept_rate(value) -> bool:
    if value < 0:
        return False
    lib().trpc_set_accept_rate(int(value))
    return True


def _push_accept_burst(value) -> bool:
    if value < 1:
        return False
    lib().trpc_set_accept_burst(int(value))
    return True


def _push_accept_max_pending(value) -> bool:
    if value < 0:
        return False
    lib().trpc_set_accept_max_pending(int(value))
    return True


def _push_idle_kick_ms(value) -> bool:
    if value < 0:
        return False
    lib().trpc_set_idle_kick_ms(int(value))
    return True


flags.define_int32("accept_rate", _parse_boot_int("TRPC_ACCEPT_RATE", 0),
                   "accept-storm pacing: accepts/sec token bucket per "
                   "listener, 0 = unpaced (TRPC_ACCEPT_RATE; reloadable)",
                   validator=_push_accept_rate)
flags.define_int32("accept_burst", _parse_boot_int("TRPC_ACCEPT_BURST", 64),
                   "accept-storm pacing: token-bucket burst — accepts "
                   "one drain may take before the rate binds "
                   "(TRPC_ACCEPT_BURST; reloadable)",
                   validator=_push_accept_burst)
flags.define_int32("accept_max_pending",
                   _parse_boot_int("TRPC_ACCEPT_MAX_PENDING", 0),
                   "cap on accepted connections that have not yet sent "
                   "their first bytes; the listener parks at the cap and "
                   "the first-bytes decrement re-kicks it, 0 = uncapped "
                   "(TRPC_ACCEPT_MAX_PENDING; reloadable)",
                   validator=_push_accept_max_pending)
flags.define_int32("idle_kick_ms", _parse_boot_int("TRPC_IDLE_KICK_MS", 0),
                   "per-connection memory diet heartbeat: every interval "
                   "with no ingress, the connection's banked buffers "
                   "shrink back to the heap, 0 = off (TRPC_IDLE_KICK_MS; "
                   "reloadable)", validator=_push_idle_kick_ms)


def _push_telemetry(value) -> bool:
    lib().trpc_set_telemetry(1 if value else 0)
    return True


def _push_overload(value) -> bool:
    lib().trpc_set_overload(1 if value else 0)
    return True


def _push_overload_min(value) -> bool:
    if value < 1:
        return False
    lib().trpc_set_overload_min_concurrency(int(value))
    return True


def _push_overload_max(value) -> bool:
    if value < 1:
        return False
    lib().trpc_set_overload_max_concurrency(int(value))
    return True


def _push_overload_window(value) -> bool:
    if value < 1:
        return False
    lib().trpc_set_overload_window_ms(int(value))
    return True


flags.define_bool("overload_control",
                  os.environ.get("TRPC_OVERLOAD", "") not in ("", "0"),
                  "native overload-control plane (overload.h, ISSUE 11): "
                  "per-(shard, method-family) gradient concurrency "
                  "limiter with inline ELIMIT shedding on the parse "
                  "fiber (no decode, no spawn — the reject rides the "
                  "response cork).  Off (the default, TRPC_OVERLOAD "
                  "unset) the plane is inert and behavior-identical to "
                  "before (reloadable)", validator=_push_overload)
flags.define_int32("overload_min_concurrency", 16,
                   "floor the adaptive per-(shard,family) limit can "
                   "never drop below — the working limit for µs-scale "
                   "families whose gradient target sits under it "
                   "(TRPC_OVERLOAD_MIN_CONCURRENCY; reloadable)",
                   validator=_push_overload_min)
flags.define_int32("overload_max_concurrency", 4096,
                   "cap on the adaptive per-(shard,family) limit "
                   "(TRPC_OVERLOAD_MAX_CONCURRENCY; reloadable)",
                   validator=_push_overload_max)
flags.define_int32("overload_window_ms", 100,
                   "gradient sample-window length: one adaptation step "
                   "folds per window (TRPC_OVERLOAD_WINDOW_MS; "
                   "reloadable)", validator=_push_overload_window)


def _push_deadline_propagate(value) -> bool:
    lib().trpc_set_deadline_propagate(1 if value else 0)
    return True


def _push_deadline_reserve_us(value) -> bool:
    if value < 0:
        return False
    lib().trpc_set_deadline_reserve_us(int(value))
    return True


flags.define_bool("deadline_propagate",
                  os.environ.get("TRPC_DEADLINE_PROPAGATE", "")
                  not in ("", "0"),
                  "deadline-budget propagation (rpc.h, ISSUE 19): client "
                  "calls stamp their remaining budget into meta tag 18, "
                  "servers shed requests whose budget is already spent "
                  "(EDEADLINE on the parse fiber / at usercode dequeue) "
                  "and handlers' downstream calls default to the "
                  "inherited remainder minus deadline_reserve_us.  Off "
                  "(the default, TRPC_DEADLINE_PROPAGATE unset) the wire "
                  "is byte-identical to before (reloadable)",
                  validator=_push_deadline_propagate)
flags.define_int32("deadline_reserve_us",
                   _parse_boot_int("TRPC_DEADLINE_RESERVE_US", 2000),
                   "per-hop reserve subtracted when a handler's "
                   "downstream call inherits the remaining budget — the "
                   "slack this tier keeps for its own respond path "
                   "(TRPC_DEADLINE_RESERVE_US; reloadable)",
                   validator=_push_deadline_reserve_us)


flags.define_bool("telemetry",
                  os.environ.get("TRPC_TELEMETRY") != "0",
                  "native hot-path telemetry plane (metrics.h): per-shard "
                  "latency histograms + inflight gauges for the method "
                  "families that never leave the native core, and the "
                  "rpcz span rings; off = no histogram writes, no span "
                  "capture, no extra clock reads — the TRPC_TELEMETRY=0 "
                  "A/B baseline (reloadable)",
                  validator=_push_telemetry)

_HANDLER_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_uint64, ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t, ctypes.c_void_p)

# A handler returns bytes, (bytes, attachment_bytes), or None (then it must
# have set cntl fields / called cntl.set_failed).
Handler = Callable[[Controller, bytes], Union[bytes, Tuple[bytes, bytes], None]]


@dataclass
class ServerOptions:
    num_workers: int = 0           # fiber workers (0 = ncpu)
    max_concurrency: int = 0       # 0 = unlimited (limiters in cluster/)
    # The HTTP debug portal rides the main port (the native transport
    # sniffs HTTP beside TRPC — one-port-many-protocols)
    enable_builtin_services: bool = True
    # Require this credential on every TRPC request (≙ ServerOptions.auth,
    # verified natively before dispatch).  Channels send it via
    # ChannelOptions.auth.
    auth: Optional[bytes] = None
    # Pluggable authentication (≙ ServerOptions.auth as an Authenticator*,
    # authenticator.h:56-75; rpc/auth.py): verify_credential runs on the
    # usercode side per request (token_auth/token_peer feed it the raw
    # tag-13 credential + peer address) and the resulting AuthContext
    # lands on cntl.auth_context / request.auth_context.  HTTP requests
    # authenticate through the Authorization header when present; the
    # portal's /flags mutation additionally requires a verified context
    # with the "admin" role.  Mutually exclusive with `auth` (the static
    # native token): set one or the other.
    authenticator: Optional[object] = None
    # Allow state-mutating builtin endpoints (/flags?setvalue=) on the
    # portal.  Deviation from the reference (which allows flag writes by
    # default): unauthenticated remote flag mutation is too sharp a tool
    # to expose implicitly — opt in, or set `auth` which gates all HTTP.
    builtin_writable: bool = False
    # TLS on the shared port (≙ ServerOptions.ssl_options): PEM cert chain
    # + private key.  Sniffed per connection — TLS and plaintext clients
    # coexist on the one port.  tls_verify_ca requires client certs
    # signed by that CA (mutual TLS).
    tls_cert_file: Optional[str] = None
    tls_key_file: Optional[str] = None
    tls_verify_ca: Optional[str] = None
    # SNI certificate map (≙ ssl_options.h:30-41 sni_filters): list of
    # (pattern, cert_file, key_file); pattern is an exact hostname or a
    # one-label "*.domain" wildcard.  Unmatched names get the base cert.
    tls_sni: Optional[list] = None
    # Python-side admission hook (cluster/limiter.py Constant/Auto/
    # Timeout limiters, ≙ ServerOptions.method_max_concurrency taking a
    # ConcurrencyLimiter): consulted per usercode dispatch; rejected
    # requests answer ELIMIT.  The NATIVE overload plane (overload.h,
    # the `overload_control` flag) sheds before requests ever reach
    # Python — this hook is the slow-path override for custom policies.
    limiter: Optional[object] = None
    # Per-method max_concurrency overrides (≙ MaxConcurrencyOf(server,
    # "Service.Method") = n): {"Service.Method": n} pushed natively at
    # start() — beyond n queued+running requests of that method the
    # parse fiber sheds with ELIMIT before decode/dispatch.
    method_max_concurrency: Optional[Dict[str, int]] = None


class _MethodStatus:
    """Per-method metrics (≙ details/method_status.h + MethodStatus):
    a LatencyRecorder + error counter exposed as <service>_<method>_*."""

    def __init__(self, name: str):
        self.latency = bvar.LatencyRecorder()
        self.latency.expose(f"rpc_server_{name}")
        self.errors = bvar.Adder(f"rpc_server_{name}_errors")

    def close(self):
        self.latency.close()
        self.errors.hide()


class Server:
    def __init__(self, options: Optional[ServerOptions] = None):
        self.options = options or ServerOptions()
        self._handle = lib().trpc_server_create()
        self._services: Dict[str, Handler] = {}
        self._method_status: Dict[str, _MethodStatus] = {}
        self._cb_keepalive = []
        self._started = False
        self._port = 0
        # cluster.ConcurrencyLimiter: ServerOptions.limiter or
        # set_concurrency_limiter()
        self._limiter = self.options.limiter
        # dump context built eagerly (cheap: opens no file until the
        # rpc_dump flag turns on) so usercode threads never race a lazy init
        self._dump = dump_mod.RpcDumpContext()
        self.http = HttpDispatcher()
        self.http._server = self  # for the /rpc/<method> JSON bridge
        # paths whose GET responses are pre-rendered into the native
        # cached-response table at start() (ingress fast path: served
        # inline on the parse fiber, never entering Python)
        self._http_cacheable: list = []

    # -- registration (≙ Server::AddService) --------------------------------

    def add_echo_service(self) -> None:
        """Native echo service: requests never enter Python (hot path for
        benches, ≙ example/echo_c++)."""
        lib().trpc_server_add_echo(self._handle)

    def add_hbm_echo_service(self, name: str = "HbmEcho") -> None:
        """Device-plane echo: each request's attachment DMAs host->HBM and
        back into the response, entirely native (≙ example/rdma_performance
        retargeted at the PJRT data plane — the ici_performance workload).
        Requires tpu_plane.init(); without it requests fail with EINTERNAL
        "device plane unavailable" (explicit, never silent)."""
        lib().trpc_server_add_hbm_echo(self._handle, name.encode())

    def add_service(self, name: str, handler: Handler) -> None:
        if self._started:
            raise RuntimeError("add_service after start")
        self._services[name] = handler
        cb = _HANDLER_CB(self._make_dispatcher(name, handler))
        self._cb_keepalive.append(cb)
        lib().trpc_server_add_service(self._handle, name.encode(),
                                      ctypes.cast(cb, ctypes.c_void_p), None)

    def set_concurrency_limiter(self, limiter) -> None:
        """Admission control hook (cluster layer: constant/auto/timeout,
        ≙ ConcurrencyLimiter, concurrency_limiter.h:29)."""
        self._limiter = limiter

    def register_http(self, path: str, handler, prefix: bool = False) -> None:
        """RESTful mapping (≙ restful.cpp '/path => Service.Method'):
        handler(HttpRequest) -> HttpResponse|str|bytes|dict, served on the
        main port beside TRPC."""
        self.http.register(path, handler, prefix=prefix)

    def add_redis_service(self, service) -> None:
        """Make the shared port speak RESP (≙ a brpc server exposing a
        redis-compatible service, policy/redis_protocol.cpp).  `service`
        is a rpc.redis_service.RedisService; commands are sniffed natively
        and dispatched to it on the usercode pool."""
        from brpc_tpu.rpc import redis_service as rmod

        _REDIS_CB = ctypes.CFUNCTYPE(
            None, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t, ctypes.c_void_p)

        def on_command(token, blob_p, blob_len, _user):
            L = lib()
            try:
                argv = rmod.unpack_args(
                    ctypes.string_at(blob_p, blob_len) if blob_len
                    else b"\x00\x00\x00\x00")
                reply = service.dispatch(argv)
            except Exception:
                log.LOG(log.LOG_ERROR, "redis dispatch raised:\n%s",
                        traceback.format_exc())
                reply = b"-ERR internal error\r\n"
            L.trpc_redis_respond(token, reply, len(reply))

        cb = _REDIS_CB(on_command)
        self._cb_keepalive.append(cb)
        lib().trpc_server_set_redis_handler(
            self._handle, ctypes.cast(cb, ctypes.c_void_p), None)

    def enable_native_redis_cache(self) -> None:
        """Answer GET/SET/DEL/EXISTS/PING from a native in-memory store —
        run-to-completion on the connection's parse fiber when the
        ingress fast path grants it (the request never enters Python).
        Commands outside that table still dispatch to the Python
        RedisService if one is registered.  Call before start()."""
        if self._started:
            raise RuntimeError("enable_native_redis_cache after start")
        rc = lib().trpc_server_enable_redis_cache(self._handle)
        if rc != 0:
            raise RuntimeError(f"enable_native_redis_cache failed ({rc})")

    def cache_http_response(self, path: str) -> None:
        """Mark a GET route as a cached-response builtin: at start() its
        response is rendered ONCE through the normal dispatcher and
        registered natively, so live GETs are answered inline on the
        parse fiber with byte-identical framing.  Only for static
        responses (e.g. /health); auth-enabled servers skip the cache
        (the Python layer owns the credential check)."""
        if self._started:
            raise RuntimeError("cache_http_response after start")
        if path not in self._http_cacheable:
            self._http_cacheable.append(path)

    def add_thrift_service(self, service) -> None:
        """Make the shared port speak framed thrift (≙ brpc serving
        PROTOCOL_THRIFT, policy/thrift_protocol.cpp:763).  `service` is a
        rpc.thrift.ThriftService; frames are sniffed + cut natively and
        dispatched here on the usercode pool.  A oneway call releases its
        pipeline slot with an empty respond."""
        _THRIFT_CB = ctypes.CFUNCTYPE(
            None, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t, ctypes.c_void_p)

        def on_message(token, blob_p, blob_len, _user):
            L = lib()
            try:
                frame = ctypes.string_at(blob_p, blob_len) if blob_len else b""
                reply = service.dispatch(frame)
            except Exception:
                log.LOG(log.LOG_ERROR, "thrift dispatch raised:\n%s",
                        traceback.format_exc())
                from brpc_tpu.rpc import thrift as tmod
                exc = tmod.TApplicationException(
                    tmod.TApplicationException.INTERNAL_ERROR,
                    "internal dispatch error")
                reply = tmod.encode_message(
                    "", tmod.MessageType.EXCEPTION, 0, exc.encode())
            if reply is None:
                L.trpc_thrift_respond(token, b"", 0)
            else:
                L.trpc_thrift_respond(token, reply, len(reply))

        cb = _THRIFT_CB(on_message)
        self._cb_keepalive.append(cb)
        lib().trpc_server_set_thrift_handler(
            self._handle, ctypes.cast(cb, ctypes.c_void_p), None)

    def register_protocol(self, name: str, magic: bytes, parse, process
                          ) -> None:
        """Plug a user wire protocol into the shared port's sniffer (≙
        RegisterProtocol, protocol.h:186).  Must be called before
        start(); builtins sniff first.  `magic` (1-16 bytes) is a
        PER-FRAME prefix — every frame must start with it (like "TRPC" /
        RESP markers), not a one-time connection handshake.

        parse(buf: bytes) -> int: >0 total frame length, 0 incomplete,
        <0 corrupt (fails the connection).  buf is the buffered head,
        capped at 64KB — the frame length must be derivable within that.
        process(frame: bytes) -> bytes|None: raw reply bytes (None =
        one-way).  Replies release in request order like RESP/thrift
        pipelining."""
        if self._started:
            raise RuntimeError("register_protocol after start")
        if not 1 <= len(magic) <= 16:
            raise ValueError("magic must be 1-16 bytes")

        _PARSE_CB = ctypes.CFUNCTYPE(
            ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t, ctypes.c_void_p)
        _HANDLER_CB = ctypes.CFUNCTYPE(
            None, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t, ctypes.c_void_p)

        def on_parse(data_p, data_len, _user):
            try:
                buf = ctypes.string_at(data_p, data_len) if data_len else b""
                return int(parse(buf))
            except Exception:
                log.LOG(log.LOG_ERROR, "protocol %s parse raised:\n%s",
                        name, traceback.format_exc())
                return -1

        def on_frame(token, frame_p, frame_len, _user):
            L = lib()
            reply = b""
            try:
                frame = ctypes.string_at(frame_p, frame_len) \
                    if frame_len else b""
                out = process(frame)
                # coerce inside the try: a handler returning str/list/...
                # must not wedge the pipeline slot
                reply = b"" if out is None else bytes(out)
            except Exception:
                log.LOG(log.LOG_ERROR, "protocol %s handler raised:\n%s",
                        name, traceback.format_exc())
                reply = b""
            L.trpc_proto_respond(token, reply, len(reply))

        pcb = _PARSE_CB(on_parse)
        hcb = _HANDLER_CB(on_frame)
        self._cb_keepalive.extend((pcb, hcb))
        rc = lib().trpc_server_register_protocol(
            self._handle, name.encode(), magic, len(magic),
            ctypes.cast(pcb, ctypes.c_void_p),
            ctypes.cast(hcb, ctypes.c_void_p), None)
        if rc != 0:
            raise RuntimeError(f"register_protocol failed ({rc})")

    def add_pb_service(self, service_name: str, methods) -> None:
        """Protobuf-typed service (≙ a pb Service on a brpc server, with
        json2pb HTTP+JSON access): methods = {name: (handler, ReqCls,
        RespCls)}, handler(cntl, req_msg) -> resp_msg.  Callable via TRPC
        ("<Service>.<name>", pb payloads, see pb_service.pb_call) and
        POST /rpc/<Service>.<name> with a JSON body."""
        from brpc_tpu.rpc.pb_service import add_pb_service
        add_pb_service(self, service_name, methods)

    def add_grpc_service(self, service_name: str, methods) -> None:
        """Serve gRPC methods at /<service_name>/<Method> — real gRPC
        clients dial the same port (h2 + gRPC framing handled natively +
        rpc/grpc_service.py).  `methods`: {method_name: handler(cntl,
        bytes) -> bytes}."""
        from brpc_tpu.rpc.grpc_service import install_grpc_service
        install_grpc_service(self, service_name, methods)

    def _find_handler(self, method: str) -> Optional[Handler]:
        """Lookup with the native server's Service fallback."""
        h = self._services.get(method)
        if h is None and "." in method:
            h = self._services.get(method.split(".", 1)[0])
        return h

    def _make_dispatcher(self, name: str, handler: Handler):
        status = self._method_status.get(name)
        if status is None:
            status = self._method_status[name] = _MethodStatus(name)
        limiter_box = self  # read at call time so set_concurrency_limiter
        # works after registration

        def dispatch(token, method, req_p, req_len, att_p, att_len, _user):
            import time
            t0 = time.monotonic_ns()
            L = lib()
            limiter = limiter_box._limiter
            if limiter is not None and not limiter.on_request():
                L.trpc_respond(token, errors.ELIMIT,
                               errors.error_text(errors.ELIMIT).encode(),
                               None, 0, None, 0)
                status.errors.add(1)
                return
            cntl = Controller()
            cntl._stream_token = token
            cntl.method = method.decode() if method else name
            # cross-hop trace ingress: surface the INBOUND trace/span ids
            # (meta tags 7/8) on the Controller — the server span created
            # below parents at the caller's span, and the native TraceCtx
            # (already stamped by the usercode pool) carries the hop into
            # any downstream channel_call this handler makes
            tid = ctypes.c_uint64(0)
            sid = ctypes.c_uint64(0)
            if L.trpc_token_trace(token, ctypes.byref(tid),
                                  ctypes.byref(sid)) == 0:
                cntl.trace_id, cntl.span_id = tid.value, sid.value
            # deadline-budget ingress (meta tag 18, ISSUE 19): surface
            # the live remaining budget on the Controller and anchor the
            # thread's inherited absolute deadline — downstream calls
            # this handler makes default to the remainder minus the
            # per-hop reserve (Channel.call reads it back)
            dl = ctypes.c_int64(0)
            if L.trpc_token_deadline_left_us(token,
                                             ctypes.byref(dl)) == 1:
                cntl.deadline_left_us = dl.value
                controller_mod.set_inherited_deadline_ns(
                    t0 + dl.value * 1000)
            sp = None
            try:
                authn = limiter_box.options.authenticator
                if authn is not None:
                    # pluggable verify (≙ VerifyCredential before dispatch,
                    # authenticator.h:66): raw tag-13 credential + peer
                    # address per token; failure answers EAUTH
                    abuf = ctypes.create_string_buffer(4096)
                    alen = int(L.trpc_token_auth(token, abuf, len(abuf)))
                    if alen > len(abuf):
                        # token_auth reports the FULL length; re-read a
                        # large credential (JWT/cert chain) untruncated
                        abuf = ctypes.create_string_buffer(alen)
                        alen = int(L.trpc_token_auth(token, abuf,
                                                     len(abuf)))
                    raw = abuf.raw[:min(alen, len(abuf))] if alen else b""
                    plen = int(L.trpc_token_peer(token, abuf, len(abuf)))
                    peer = abuf.raw[:plen].decode() if plen else ""
                    try:
                        cntl.auth_context = authn.verify_credential(
                            raw, peer)
                    except Exception as e:
                        raise errors.RpcError(
                            errors.EAUTH, f"authentication failed: {e}")
                    if peer:
                        cntl.remote_side = peer
                req = ctypes.string_at(req_p, req_len) if req_len else b""
                cntl.request_compress_type = max(
                    L.trpc_token_compress(token), 0)
                if (flags.get_flag("rpc_dump")
                        and not L.trpc_dump_active()):
                    # sample the wire-form request (pre-decompression,
                    # ≙ rpc_dump capturing what arrived, rpc_dump.cpp) —
                    # same v2 record schema the native capture plane
                    # emits, so segments from either path interchange.
                    # Fallback only: when the native flight recorder is
                    # armed it already captured this frame at the parse
                    # fiber (pre-admission wire form) — sampling here
                    # too would double every record in the segments.
                    limiter_box._dump.sample(dump_mod.SampledRequest(
                        method=cntl.method, payload=req,
                        attachment=ctypes.string_at(att_p, att_len)
                        if att_len else b"",
                        compress_type=cntl.request_compress_type,
                        trace_id=cntl.trace_id, span_id=cntl.span_id))
                if cntl.request_compress_type:
                    try:
                        req = compress_mod.decompress(
                            req, cntl.request_compress_type)
                    except Exception:
                        cntl.error_code = errors.EREQUEST
                        L.trpc_respond(token, errors.EREQUEST,
                                       b"bad compressed payload", None, 0,
                                       None, 0)
                        status.errors.add(1)
                        return  # finally below still releases the limiter
                cntl.request_attachment = (
                    ctypes.string_at(att_p, att_len) if att_len else b"")
                # server span inherits the inbound trace: parent_span_id
                # = the caller's span (≙ Span::CreateServerSpan with
                # received ids) — /rpcz?trace_id= assembles the tree
                sp = span.start_span("server", cntl.method,
                                     trace_id=cntl.trace_id,
                                     parent_span_id=cntl.span_id)
                span.set_current(sp)
                if sp is not None:
                    # re-point the native hop at the sampled server span:
                    # downstream calls now parent HERE, not at the caller
                    L.trpc_trace_set_current(sp.trace_id, sp.span_id, 0)
                    # queue-inclusive arm stamp from the parse loop's
                    # coarse clock (one native clock read per drain):
                    # rpcz shows how long the request waited for a
                    # usercode worker before this handler ran
                    arm_ns = L.trpc_token_arm_ns(token)
                    if arm_ns > 0:
                        q_us = max(0, (t0 - arm_ns) // 1000)
                        sp.annotate(f"usercode queue {q_us}us "
                                    "(coarse-clock arm)")
                out = handler(cntl, req)
                resp, resp_att = b"", cntl.response_attachment
                if isinstance(out, tuple):
                    resp, resp_att = out
                elif out is not None:
                    resp = out
                if cntl.failed():
                    L.trpc_respond(token, cntl.error_code,
                                   cntl.error_text.encode(), None, 0, None, 0)
                    status.errors.add(1)
                else:
                    ct = cntl.response_compress_type
                    if ct:
                        resp = compress_mod.compress(resp, ct)
                    L.trpc_respond_compressed(
                        token, 0, None, resp, len(resp),
                        resp_att if resp_att else None, len(resp_att), ct)
            except errors.RpcError as e:
                cntl.error_code = e.code
                L.trpc_respond(token, e.code, e.text.encode(), None, 0,
                               None, 0)
                status.errors.add(1)
            except Exception:
                cntl.error_code = errors.EINTERNAL
                log.LOG(log.LOG_ERROR, "handler %s raised:\n%s", name,
                        traceback.format_exc())
                L.trpc_respond(token, errors.EINTERNAL,
                               traceback.format_exc(limit=3).encode(),
                               None, 0, None, 0)
                status.errors.add(1)
            finally:
                controller_mod.set_inherited_deadline_ns(None)
                span.set_current(None)
                span.finish_span(sp, cntl.error_code)
                if limiter is not None:
                    limiter.on_response((time.monotonic_ns() - t0) // 1000)
                status.latency.record((time.monotonic_ns() - t0) // 1000)

        return dispatch

    # -- lifecycle (≙ Server::Start/Stop/Join) ------------------------------

    def _install_http(self) -> None:
        """Native HTTP requests (sniffed on the main port) land here on the
        usercode pool; routed through self.http."""
        dispatcher = self.http
        auth = self.options.auth
        authenticator = self.options.authenticator

        def on_http(token, verb, path, query, hdr_p, hdr_len, body_p,
                    body_len, _user):
            import hmac
            L = lib()
            try:
                req = HttpRequest(
                    method=verb.decode() if verb else "GET",
                    path=path.decode() if path else "/",
                    query=query.decode() if query else "",
                    headers=parse_headers_blob(
                        ctypes.string_at(hdr_p, hdr_len) if hdr_len else b""),
                    body=ctypes.string_at(body_p, body_len)
                    if body_len else b"")
                if auth is not None:
                    # the TRPC credential also gates the HTTP surface —
                    # otherwise /rpc and /flags would bypass server auth
                    given = req.headers.get("authorization", "").encode()
                    if not hmac.compare_digest(given, auth):
                        L.trpc_http_respond(token, 401, None,
                                            b"unauthorized\n", 13)
                        return
                elif authenticator is not None:
                    # pluggable path: an Authorization header verifies
                    # into request.auth_context (mutating portal routes
                    # require it); a PRESENT-but-bad credential is 401,
                    # absence just leaves the context None
                    cred = req.headers.get("authorization", "")
                    if cred:
                        try:
                            req.auth_context = \
                                authenticator.verify_credential(
                                    cred.encode(), "")
                        except Exception:
                            L.trpc_http_respond(token, 401, None,
                                                b"unauthorized\n", 13)
                            return
                resp = dispatcher.dispatch(req)
                from brpc_tpu.rpc.http import ProgressiveAttachment
                if isinstance(resp, ProgressiveAttachment):
                    # streaming response: headers go out now (h1:
                    # sequenced chunked stream; h2: HEADERS on the
                    # request's stream), then either the handler's own
                    # writer thread streams chunks, or on_bound pumps
                    # them inline on this usercode thread (gRPC
                    # server-streaming — client flow control paces it)
                    handle = L.trpc_http_respond_progressive(
                        token, resp.status, pack_headers(resp.headers))
                    resp._bind(int(handle))
                    if not handle:
                        # dead connection / already-reset stream: the
                        # client must still get an answer, not a hang
                        log.LOG(log.LOG_ERROR,
                                "progressive respond failed (dead conn "
                                "or reset stream), %s", req.path)
                        msg = b"progressive response setup failed\n"
                        L.trpc_http_respond(token, 500, None, msg,
                                            len(msg))
                        return
                    if resp.on_bound is not None:
                        resp.on_bound()
                    return
                body = b"" if req.method == "HEAD" else resp.body
                if resp.trailers:
                    L.trpc_http_respond_trailers(
                        token, resp.status, pack_headers(resp.headers),
                        body, len(body), pack_headers(resp.trailers))
                else:
                    L.trpc_http_respond(token, resp.status,
                                        pack_headers(resp.headers), body,
                                        len(body))
            except Exception:
                log.LOG(log.LOG_ERROR, "http dispatch raised:\n%s",
                        traceback.format_exc())
                msg = b"internal error\n"
                L.trpc_http_respond(token, 500, None, msg, len(msg))

        cb = HTTP_FN(on_http)
        self._cb_keepalive.append(cb)
        lib().trpc_server_set_http_handler(
            self._handle, ctypes.cast(cb, ctypes.c_void_p), None)

    def start(self, address: str = "127.0.0.1:0") -> int:
        from brpc_tpu import fiber
        # push the shard config BEFORE the fiber runtime starts (the
        # count freezes at the first fiber_runtime_init); a later start
        # in the same process keeps the frozen value — trpc_set_shards
        # returning EBUSY with an unchanged flag is fine, only a CHANGE
        # after freeze is an error (the validator already rejected it)
        lib().trpc_set_shards(int(flags.get_flag("shards")))
        lib().trpc_set_reuseport(
            1 if flags.get_flag("reuseport") else 0)
        fiber.init(self.options.num_workers)
        lib().trpc_set_usercode_workers(
            int(flags.get_flag("usercode_workers")))
        lib().trpc_set_usercode_max_inflight(
            int(flags.get_flag("usercode_max_inflight")))
        lib().trpc_set_event_dispatcher_num(
            int(flags.get_flag("event_dispatcher_num")))
        lib().trpc_set_io_uring(
            1 if flags.get_flag("use_io_uring") else 0)
        lib().trpc_set_sendzc(
            1 if flags.get_flag("use_sendzc") else 0)
        lib().trpc_set_sendzc_threshold(
            int(flags.get_flag("sendzc_threshold_bytes")))
        lib().trpc_set_inline_dispatch(
            1 if flags.get_flag("inline_dispatch") else 0)
        lib().trpc_set_inline_budget_requests(
            int(flags.get_flag("inline_budget_requests")))
        lib().trpc_set_inline_budget_us(
            int(flags.get_flag("inline_budget_us")))
        # payload-codec rail (codec.h): push the resolved flag state so a
        # flags-file/env mix lands in the native atomics before traffic
        lib().trpc_set_payload_codec(
            codec_mod.id_of(flags.get_flag("payload_codec")))
        lib().trpc_set_codec_min_bytes(
            int(flags.get_flag("codec_min_bytes")))
        # hot-path telemetry plane (metrics.h): histograms + native rpcz
        # rings follow the resolved flags before the first request
        lib().trpc_set_telemetry(
            1 if flags.get_flag("telemetry") else 0)
        lib().trpc_set_rpcz(
            1 if flags.get_flag("enable_rpcz") else 0)
        lib().trpc_set_rpcz_budget(
            int(flags.get_flag("rpcz_max_samples_per_second")))
        # flight recorder (dump.h): the native capture rings follow the
        # resolved rpc_dump flags, and the drain pump starts so sampled
        # fast-path frames reach the recordio segments
        lib().trpc_set_dump(
            1 if flags.get_flag("rpc_dump") else 0)
        lib().trpc_set_dump_budget(
            int(flags.get_flag("rpc_dump_max_samples_per_second")))
        if flags.get_flag("rpc_dump"):
            dump_mod.ensure_native_drain()
        # overload-control plane (overload.h): resolved flag state lands
        # in the native atomics before traffic; off = the plane is inert
        lib().trpc_set_overload(
            1 if flags.get_flag("overload_control") else 0)
        lib().trpc_set_overload_min_concurrency(
            int(flags.get_flag("overload_min_concurrency")))
        lib().trpc_set_overload_max_concurrency(
            int(flags.get_flag("overload_max_concurrency")))
        lib().trpc_set_overload_window_ms(
            int(flags.get_flag("overload_window_ms")))
        # million-connection ingress (rpc.h/socket.h): accept pacing +
        # pending-handshake cap + idle-connection memory diet
        lib().trpc_set_accept_rate(
            int(flags.get_flag("accept_rate")))
        lib().trpc_set_accept_burst(
            int(flags.get_flag("accept_burst")))
        lib().trpc_set_accept_max_pending(
            int(flags.get_flag("accept_max_pending")))
        lib().trpc_set_idle_kick_ms(
            int(flags.get_flag("idle_kick_ms")))
        for meth, cap in (self.options.method_max_concurrency or {}).items():
            rc = lib().trpc_server_set_method_max_concurrency(
                self._handle, meth.encode(), int(cap))
            if rc != 0:
                raise ValueError(
                    f"method_max_concurrency[{meth!r}] rejected natively "
                    f"(rc={rc}; is the service registered?)")
        if self.options.enable_builtin_services:
            from brpc_tpu.builtin import install_builtin_services
            install_builtin_services(self, self.http)
        # the process block every server exposes on /vars (rusage, fds,
        # memory, threads ≙ bvar/default_variables.cpp:878)
        from brpc_tpu.metrics.default_vars import install_default_variables
        install_default_variables()
        # native core internals become live bvars (write-queue depth,
        # PendingCall occupancy, sequencer backlog, usercode queue, ...)
        from brpc_tpu.metrics.native import install_native_metrics
        install_native_metrics()
        # periodic bvar dump-to-file (≙ FLAGS_bvar_dump): idles unless
        # bvar_dump_file / TRPC_BVAR_DUMP_FILE names a target
        from brpc_tpu.metrics import dumper as _dumper
        _dumper.ensure_started()
        self._install_http()
        if self.options.auth and self.options.authenticator is not None:
            raise ValueError(
                "set ServerOptions.auth (static native token) OR "
                ".authenticator (pluggable), not both")
        if self.options.auth:
            lib().trpc_server_set_auth(self._handle, self.options.auth,
                                       len(self.options.auth))
        if self.options.tls_sni and not self.options.tls_cert_file:
            raise ValueError(
                "tls_sni requires tls_cert_file/tls_key_file (the base "
                "certificate is the fallback for unmatched SNI names)")
        if self.options.tls_cert_file:
            rc = lib().trpc_server_set_tls(
                self._handle, self.options.tls_cert_file.encode(),
                (self.options.tls_key_file or "").encode(),
                (self.options.tls_verify_ca or "").encode() or None)
            if rc != 0:
                reason = (lib().trpc_tls_error() or b"").decode()
                raise OSError(-rc, f"TLS setup failed: {reason}")
            for pattern, cert, key in (self.options.tls_sni or ()):
                rc = lib().trpc_server_add_tls_sni(
                    self._handle, pattern.encode(), cert.encode(),
                    key.encode())
                if rc != 0:
                    reason = (lib().trpc_tls_error() or b"").decode()
                    raise OSError(-rc,
                                  f"SNI cert for {pattern!r} failed: "
                                  f"{reason}")
        unix_path = None
        if address.startswith("unix:") or address.startswith("/"):
            # unix-domain listener (≙ brpc unix-socket EndPoint): the
            # path travels in the ip argument, port is meaningless
            unix_path = address[5:] if address.startswith("unix:") \
                else address
            if not unix_path:
                raise ValueError(f"empty unix path in {address!r}")
            ip, port = unix_path, 0
        else:
            ip, _, port = address.rpartition(":")
            port = int(port)
        if not self.options.auth:
            # pre-render the cached builtin responses through the normal
            # dispatcher: the native fast path then serves the exact
            # bytes the Python handler would have produced
            from brpc_tpu.rpc.http import ProgressiveAttachment
            for cpath in self._http_cacheable:
                try:
                    resp = self.http.dispatch(
                        HttpRequest(method="GET", path=cpath))
                    if isinstance(resp, ProgressiveAttachment) or \
                            resp.trailers or resp.status != 200:
                        continue  # not a cacheable static response
                    rc = lib().trpc_server_http_cache_put(
                        self._handle, cpath.encode(), resp.status,
                        pack_headers(resp.headers), resp.body,
                        len(resp.body))
                    if rc != 0:
                        log.LOG(log.LOG_ERROR,
                                "cache_http_response(%s) rejected by the "
                                "native table (rc=%d); the route falls "
                                "back to the Python dispatcher", cpath, rc)
                except Exception:
                    log.LOG(log.LOG_ERROR,
                            "cache_http_response(%s) skipped:\n%s",
                            cpath, traceback.format_exc())
        rc = lib().trpc_server_start(self._handle, ip.encode(), port)
        if rc != 0:
            raise OSError(-rc, f"server start failed on {address}")
        # recorded only on success: destroy() unlinks this path, and a
        # FAILED bind (EADDRINUSE) must never unlink the live owner's file
        self._unix_path = unix_path
        self._port = lib().trpc_server_port(self._handle)
        self._started = True
        flags.freeze_nonreloadable()
        if unix_path is not None:
            log.LOG(log.LOG_INFO, "Server started on unix:%s", unix_path)
        else:
            # log the REAL bind address (0.0.0.0 vs loopback matters when
            # diagnosing reachability); listen_address stays dialable
            log.LOG(log.LOG_INFO, "Server started on %s:%d",
                    ip or "0.0.0.0", self._port)
        return self._port

    @property
    def port(self) -> int:
        return self._port

    @property
    def listen_address(self) -> str:
        upath = getattr(self, "_unix_path", None)
        if upath is not None:
            return f"unix:{upath}"
        return f"127.0.0.1:{self._port}"

    def request_count(self) -> int:
        return lib().trpc_server_requests(self._handle)

    def stop(self) -> None:
        if self._started:
            lib().trpc_server_stop(self._handle)
            self._started = False

    def destroy(self) -> None:
        """Stop, fail live connections, drain, and free the native server.
        The Python object is unusable afterwards."""
        if self._handle:
            self.stop()
            lib().trpc_server_destroy(self._handle)
            self._handle = None
            upath = getattr(self, "_unix_path", None)
            if upath is not None:
                import os as _os
                try:
                    _os.unlink(upath)
                except OSError:
                    pass
        self._dump.close()
        for st in self._method_status.values():
            st.close()
        self._method_status.clear()

    def method_stats(self) -> Dict[str, dict]:
        """/status data: per-method qps/latency/errors."""
        out = {}
        for name, st in self._method_status.items():
            out[name] = {
                "qps": st.latency.qps(),
                "count": st.latency.count(),
                "latency_us": st.latency.latency(),
                "latency_99_us": st.latency.latency_percentile(0.99),
                "errors": st.errors.get_value(),
            }
        return out

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
