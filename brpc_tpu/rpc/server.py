"""Server — service registry + lifecycle (≙ brpc::Server, reference
server.cpp:750 StartInternal: builds the acceptor, registers services and
builtin debug services, binds per-method status).

Data path is native: the acceptor, event dispatcher, frame parsing and the
native echo service never touch Python.  Python handlers run on the native
usercode pthread pool (≙ usercode_in_pthread,
details/usercode_backup_pool.cpp) and respond through trpc_respond.
"""

from __future__ import annotations

import ctypes
import threading
import traceback
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple, Union

from brpc_tpu._native import lib
from brpc_tpu.metrics import bvar
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.utils import flags, logging as log

flags.define_int32("usercode_workers", 4,
                   "pthreads running Python handlers")

_HANDLER_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_uint64, ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t, ctypes.c_void_p)

# A handler returns bytes, (bytes, attachment_bytes), or None (then it must
# have set cntl fields / called cntl.set_failed).
Handler = Callable[[Controller, bytes], Union[bytes, Tuple[bytes, bytes], None]]


@dataclass
class ServerOptions:
    num_workers: int = 0           # fiber workers (0 = ncpu)
    max_concurrency: int = 0       # 0 = unlimited (limiters in cluster/)
    enable_builtin_services: bool = True
    builtin_port: Optional[int] = None  # HTTP debug portal port (None = off)


class _MethodStatus:
    """Per-method metrics (≙ details/method_status.h + MethodStatus):
    a LatencyRecorder + error counter exposed as <service>_<method>_*."""

    def __init__(self, name: str):
        self.latency = bvar.LatencyRecorder()
        self.latency.expose(f"rpc_server_{name}")
        self.errors = bvar.Adder(f"rpc_server_{name}_errors")

    def close(self):
        self.latency.close()
        self.errors.hide()


class Server:
    def __init__(self, options: Optional[ServerOptions] = None):
        self.options = options or ServerOptions()
        self._handle = lib().trpc_server_create()
        self._services: Dict[str, Handler] = {}
        self._method_status: Dict[str, _MethodStatus] = {}
        self._cb_keepalive = []
        self._started = False
        self._port = 0
        self._builtin = None
        self._limiter = None  # cluster.ConcurrencyLimiter, set via option

    # -- registration (≙ Server::AddService) --------------------------------

    def add_echo_service(self) -> None:
        """Native echo service: requests never enter Python (hot path for
        benches, ≙ example/echo_c++)."""
        lib().trpc_server_add_echo(self._handle)

    def add_service(self, name: str, handler: Handler) -> None:
        if self._started:
            raise RuntimeError("add_service after start")
        self._services[name] = handler
        cb = _HANDLER_CB(self._make_dispatcher(name, handler))
        self._cb_keepalive.append(cb)
        lib().trpc_server_add_service(self._handle, name.encode(),
                                      ctypes.cast(cb, ctypes.c_void_p), None)

    def set_concurrency_limiter(self, limiter) -> None:
        """Admission control hook (cluster layer: constant/auto/timeout,
        ≙ ConcurrencyLimiter, concurrency_limiter.h:29)."""
        self._limiter = limiter

    def _make_dispatcher(self, name: str, handler: Handler):
        status = self._method_status.get(name)
        if status is None:
            status = self._method_status[name] = _MethodStatus(name)
        limiter_box = self  # read at call time so set_concurrency_limiter
        # works after registration

        def dispatch(token, method, req_p, req_len, att_p, att_len, _user):
            import time
            t0 = time.monotonic_ns()
            L = lib()
            limiter = limiter_box._limiter
            if limiter is not None and not limiter.on_request():
                L.trpc_respond(token, errors.ELIMIT,
                               errors.error_text(errors.ELIMIT).encode(),
                               None, 0, None, 0)
                status.errors.add(1)
                return
            cntl = Controller()
            cntl._stream_token = token
            cntl.method = method.decode() if method else name
            req = ctypes.string_at(req_p, req_len) if req_len else b""
            cntl.request_attachment = (
                ctypes.string_at(att_p, att_len) if att_len else b"")
            try:
                out = handler(cntl, req)
                resp, resp_att = b"", cntl.response_attachment
                if isinstance(out, tuple):
                    resp, resp_att = out
                elif out is not None:
                    resp = out
                if cntl.failed():
                    L.trpc_respond(token, cntl.error_code,
                                   cntl.error_text.encode(), None, 0, None, 0)
                    status.errors.add(1)
                else:
                    L.trpc_respond(token, 0, None, resp, len(resp),
                                   resp_att if resp_att else None,
                                   len(resp_att))
            except errors.RpcError as e:
                L.trpc_respond(token, e.code, e.text.encode(), None, 0,
                               None, 0)
                status.errors.add(1)
            except Exception:
                log.LOG(log.LOG_ERROR, "handler %s raised:\n%s", name,
                        traceback.format_exc())
                L.trpc_respond(token, errors.EINTERNAL,
                               traceback.format_exc(limit=3).encode(),
                               None, 0, None, 0)
                status.errors.add(1)
            finally:
                if limiter is not None:
                    limiter.on_response((time.monotonic_ns() - t0) // 1000)
                status.latency.record((time.monotonic_ns() - t0) // 1000)

        return dispatch

    # -- lifecycle (≙ Server::Start/Stop/Join) ------------------------------

    def start(self, address: str = "127.0.0.1:0") -> int:
        from brpc_tpu import fiber
        fiber.init(self.options.num_workers)
        lib().trpc_set_usercode_workers(
            int(flags.get_flag("usercode_workers")))
        ip, _, port = address.rpartition(":")
        rc = lib().trpc_server_start(self._handle, ip.encode(), int(port))
        if rc != 0:
            raise OSError(-rc, f"server start failed on {address}")
        self._port = lib().trpc_server_port(self._handle)
        self._started = True
        flags.freeze_nonreloadable()
        if (self.options.enable_builtin_services
                and self.options.builtin_port is not None):
            from brpc_tpu.builtin.portal import BuiltinPortal
            self._builtin = BuiltinPortal(self)
            self._builtin.start(self.options.builtin_port)
        log.LOG(log.LOG_INFO, "Server started on %s:%d", ip or "0.0.0.0",
                self._port)
        return self._port

    @property
    def port(self) -> int:
        return self._port

    @property
    def listen_address(self) -> str:
        return f"127.0.0.1:{self._port}"

    def request_count(self) -> int:
        return lib().trpc_server_requests(self._handle)

    def stop(self) -> None:
        if self._started:
            lib().trpc_server_stop(self._handle)
            self._started = False
        if self._builtin is not None:
            self._builtin.stop()
            self._builtin = None

    def destroy(self) -> None:
        """Stop, fail live connections, drain, and free the native server.
        The Python object is unusable afterwards."""
        if self._handle:
            self.stop()
            lib().trpc_server_destroy(self._handle)
            self._handle = None
        for st in self._method_status.values():
            st.close()
        self._method_status.clear()

    def method_stats(self) -> Dict[str, dict]:
        """/status data: per-method qps/latency/errors."""
        out = {}
        for name, st in self._method_status.items():
            out[name] = {
                "qps": st.latency.qps(),
                "count": st.latency.count(),
                "latency_us": st.latency.latency(),
                "latency_99_us": st.latency.latency_percentile(0.99),
                "errors": st.errors.get_value(),
            }
        return out

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
