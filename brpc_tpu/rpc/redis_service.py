"""Redis protocol support — server-side services AND a client (capability
of the reference redis support: redis.{h,cpp} RedisCommand/RedisReply +
policy/redis_protocol.cpp:428, which lets a brpc server speak RESP and a
brpc channel dial real redis servers).

Server side: the native core sniffs RESP on the shared port and parses
command arrays (native/src/redis.cc); commands land here on the usercode
pool, dispatch by upper-cased command name, and handlers return replies
encoded with the helpers below.

    svc = RedisService()
    svc.register("GET", lambda args: bulk(store.get(args[0])))
    server.add_redis_service(svc)
    # then: redis-cli -p <port> GET key   (or RedisClient below)

Client side: RedisClient speaks RESP2 over a plain socket (works against
our servers and real redis).
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Dict, List, Optional, Union

Reply = bytes  # fully RESP-encoded


# --- RESP encoding helpers (server replies) --------------------------------


def simple(s: str) -> Reply:
    return f"+{s}\r\n".encode()


def error(msg: str) -> Reply:
    return f"-ERR {msg}\r\n".encode()


def integer(v: int) -> Reply:
    return f":{v}\r\n".encode()


def bulk(data: Optional[Union[bytes, str]]) -> Reply:
    if data is None:
        return b"$-1\r\n"  # null bulk
    if isinstance(data, str):
        data = data.encode()
    return b"$%d\r\n%s\r\n" % (len(data), data)


def array(items: Optional[List[Reply]]) -> Reply:
    if items is None:
        return b"*-1\r\n"
    return b"*%d\r\n%s" % (len(items), b"".join(items))


# --- server-side service ----------------------------------------------------


Handler = Callable[[List[bytes]], Reply]


class RedisService:
    """Command table: register("SET", handler(args) -> RESP bytes); args
    excludes the command name.  PING/ECHO/COMMAND are built in (override
    by registering)."""

    def __init__(self):
        self._commands: Dict[str, Handler] = {}
        self.register("PING", lambda args: simple("PONG") if not args
                      else bulk(args[0]))
        self.register("ECHO", lambda args: bulk(args[0]) if args
                      else error("wrong number of arguments"))
        self.register("COMMAND", lambda args: array([]))

    def register(self, name: str, handler: Handler) -> None:
        self._commands[name.upper()] = handler

    def dispatch(self, argv: List[bytes]) -> Reply:
        if not argv:
            return error("empty command")
        name = argv[0].decode("utf-8", "replace").upper()
        h = self._commands.get(name)
        if h is None:
            return error(f"unknown command '{name}'")
        try:
            return h(argv[1:])
        except Exception as e:  # noqa: BLE001 — handler bug → -ERR
            return error(str(e).replace("\r", " ").replace("\n", " "))


def unpack_args(blob: bytes) -> List[bytes]:
    """Native PackRedisArgs blob → argv."""
    (argc,) = struct.unpack_from("<I", blob, 0)
    off = 4
    out = []
    for _ in range(argc):
        (ln,) = struct.unpack_from("<I", blob, off)
        off += 4
        out.append(blob[off:off + ln])
        off += ln
    return out


# --- client -----------------------------------------------------------------


class RedisError(Exception):
    pass


class RedisClient:
    """Minimal RESP2 client (≙ the reference redis client capability —
    pipelining via call_pipeline, inline replies parsed)."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        self._buf = b""
        self._lock = threading.Lock()

    def call(self, *args: Union[bytes, str]):
        return self.call_pipeline([args])[0]

    def call_pipeline(self, commands):
        """Send all commands, then read all replies (ordered)."""
        out = bytearray()
        for cmd in commands:
            parts = [a.encode() if isinstance(a, str) else a for a in cmd]
            out += b"*%d\r\n" % len(parts)
            for p in parts:
                out += b"$%d\r\n%s\r\n" % (len(p), p)
        with self._lock:
            self._sock.sendall(bytes(out))
            return [self._read_reply() for _ in commands]

    # RESP reply parsing -----------------------------------------------------

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RedisError("connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RedisError("connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RedisError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n < 0 else self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            return None if n < 0 else [self._read_reply()
                                       for _ in range(n)]
        raise RedisError(f"bad reply type {kind!r}")

    def close(self):
        self._sock.close()
