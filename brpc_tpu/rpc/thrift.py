"""Thrift framed-transport TBinaryProtocol, service + client.

Speaks the strict TBinaryProtocol over the framed transport — the wire
format Apache Thrift's TFramedTransport + TBinaryProtocol produce — so a
stock generated client can call a brpc_tpu server and vice versa (≙
src/brpc/policy/thrift_protocol.cpp:763 ParseThriftMessage +
src/brpc/thrift_message.h ThriftFramedMessage).  The frame header is
stripped/added natively (native/src/rpc.cc thrift sniff + thrift_respond);
this module sees whole TBinaryProtocol messages.

No Thrift IDL compiler: values are described by compact runtime "specs"
mirroring what generated code carries:

    spec := TType.BOOL | .BYTE | .I16 | .I32 | .I64 | .DOUBLE | .STRING
          | (TType.LIST, elem_spec)
          | (TType.SET, elem_spec)
          | (TType.MAP, key_spec, val_spec)
          | (TType.STRUCT, {field_id: (name, spec), ...})

Struct values are plain dicts keyed by field name; unknown incoming
fields are skipped (forward compatibility, like generated readers).
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Any, Dict, Optional, Tuple

__all__ = [
    "TType", "MessageType", "TApplicationException", "ThriftError",
    "encode_message", "decode_message", "encode_struct", "decode_struct",
    "ThriftService", "ThriftClient",
]


class TType:
    STOP = 0
    VOID = 1
    BOOL = 2
    BYTE = 3
    DOUBLE = 4
    I16 = 6
    I32 = 8
    I64 = 10
    STRING = 11
    STRUCT = 12
    MAP = 13
    SET = 14
    LIST = 15


class MessageType:
    CALL = 1
    REPLY = 2
    EXCEPTION = 3
    ONEWAY = 4


VERSION_1 = 0x80010000


class ThriftError(Exception):
    pass


class TApplicationException(ThriftError):
    """Server-side failure carried in a MessageType.EXCEPTION reply
    (standard struct: 1:message string, 2:type i32)."""

    UNKNOWN = 0
    UNKNOWN_METHOD = 1
    INTERNAL_ERROR = 6

    def __init__(self, kind: int = UNKNOWN, message: str = ""):
        super().__init__(message or f"TApplicationException({kind})")
        self.kind = kind
        self.message = message

    SPEC = (TType.STRUCT, {1: ("message", TType.STRING),
                           2: ("type", TType.I32)})

    def encode(self) -> bytes:
        return encode_struct(
            {"message": self.message, "type": self.kind}, self.SPEC)

    @classmethod
    def decode(cls, blob: bytes, off: int = 0) -> "TApplicationException":
        d, _ = decode_struct(blob, off, cls.SPEC)
        return cls(d.get("type", cls.UNKNOWN), d.get("message", ""))


# ---------------------------------------------------------------------------
# encoding

def _spec_ttype(spec) -> int:
    return spec[0] if isinstance(spec, tuple) else spec


def _encode_value(out: bytearray, val, spec) -> None:
    t = _spec_ttype(spec)
    if t == TType.BOOL:
        out.append(1 if val else 0)
    elif t == TType.BYTE:
        out += struct.pack("!b", val)
    elif t == TType.I16:
        out += struct.pack("!h", val)
    elif t == TType.I32:
        out += struct.pack("!i", val)
    elif t == TType.I64:
        out += struct.pack("!q", val)
    elif t == TType.DOUBLE:
        out += struct.pack("!d", val)
    elif t == TType.STRING:
        b = val.encode("utf-8") if isinstance(val, str) else bytes(val)
        out += struct.pack("!i", len(b))
        out += b
    elif t == TType.STRUCT:
        out += encode_struct(val, spec)
    elif t in (TType.LIST, TType.SET):
        elem = spec[1]
        out += struct.pack("!bi", _spec_ttype(elem), len(val))
        for v in val:
            _encode_value(out, v, elem)
    elif t == TType.MAP:
        kspec, vspec = spec[1], spec[2]
        out += struct.pack("!bbi", _spec_ttype(kspec), _spec_ttype(vspec),
                           len(val))
        for k, v in val.items():
            _encode_value(out, k, kspec)
            _encode_value(out, v, vspec)
    else:
        raise ThriftError(f"cannot encode ttype {t}")


def encode_struct(value: Dict[str, Any], spec) -> bytes:
    """value: {field_name: python_value}; None fields are omitted
    (thrift optional semantics)."""
    assert _spec_ttype(spec) == TType.STRUCT
    fields = spec[1]
    out = bytearray()
    for fid, (name, fspec) in fields.items():
        v = value.get(name)
        if v is None:
            continue
        out += struct.pack("!bh", _spec_ttype(fspec), fid)
        _encode_value(out, v, fspec)
    out.append(TType.STOP)
    return bytes(out)


def encode_message(method: str, mtype: int, seqid: int, body: bytes) -> bytes:
    """Strict-binary message header + already-encoded struct body."""
    name = method.encode("utf-8")
    return (struct.pack("!Ii", VERSION_1 | mtype, len(name)) + name +
            struct.pack("!i", seqid) + body)


# ---------------------------------------------------------------------------
# decoding

def _decode_value(blob: bytes, off: int, ttype: int,
                  spec=None) -> Tuple[Any, int]:
    if ttype == TType.BOOL:
        return blob[off] != 0, off + 1
    if ttype == TType.BYTE:
        return struct.unpack_from("!b", blob, off)[0], off + 1
    if ttype == TType.I16:
        return struct.unpack_from("!h", blob, off)[0], off + 2
    if ttype == TType.I32:
        return struct.unpack_from("!i", blob, off)[0], off + 4
    if ttype == TType.I64:
        return struct.unpack_from("!q", blob, off)[0], off + 8
    if ttype == TType.DOUBLE:
        return struct.unpack_from("!d", blob, off)[0], off + 8
    if ttype == TType.STRING:
        (n,) = struct.unpack_from("!i", blob, off)
        off += 4
        raw = blob[off:off + n]
        try:
            return raw.decode("utf-8"), off + n
        except UnicodeDecodeError:
            return raw, off + n
    if ttype == TType.STRUCT:
        return decode_struct(blob, off, spec)
    if ttype in (TType.LIST, TType.SET):
        et, n = struct.unpack_from("!bi", blob, off)
        off += 5
        espec = spec[1] if spec is not None else None
        items = []
        for _ in range(n):
            v, off = _decode_value(blob, off, et, espec)
            items.append(v)
        return items, off
    if ttype == TType.MAP:
        kt, vt, n = struct.unpack_from("!bbi", blob, off)
        off += 6
        kspec = spec[1] if spec is not None else None
        vspec = spec[2] if spec is not None else None
        d = {}
        for _ in range(n):
            k, off = _decode_value(blob, off, kt, kspec)
            v, off = _decode_value(blob, off, vt, vspec)
            d[k] = v
        return d, off
    raise ThriftError(f"cannot decode ttype {ttype}")


def decode_struct(blob: bytes, off: int = 0,
                  spec=None) -> Tuple[Dict[Any, Any], int]:
    """Decode one struct.  With a spec, returns {field_name: value} and
    skips unknown fields; without, returns {field_id: value} schemaless."""
    fields = spec[1] if spec is not None else None
    out: Dict[Any, Any] = {}
    while True:
        ft = blob[off]
        off += 1
        if ft == TType.STOP:
            return out, off
        (fid,) = struct.unpack_from("!h", blob, off)
        off += 2
        fspec = None
        name = None
        if fields is not None and fid in fields:
            name, fspec = fields[fid]
            if _spec_ttype(fspec) != ft:
                name, fspec = None, None  # type mismatch: skip raw
        v, off = _decode_value(blob, off, ft, fspec)
        out[name if name is not None else fid] = v


def decode_message(blob: bytes) -> Tuple[str, int, int, int]:
    """Return (method, mtype, seqid, body_offset).  Strict binary only —
    the native sniffer already guaranteed the 0x80 0x01 version bytes."""
    (ver,) = struct.unpack_from("!I", blob, 0)
    if ver & 0xFFFF0000 != VERSION_1:
        raise ThriftError(f"bad thrift version 0x{ver:08x}")
    mtype = ver & 0xFF
    (nlen,) = struct.unpack_from("!i", blob, 4)
    name = blob[8:8 + nlen].decode("utf-8")
    (seqid,) = struct.unpack_from("!i", blob, 8 + nlen)
    return name, mtype, seqid, 12 + nlen


# ---------------------------------------------------------------------------
# service (server side)

class ThriftService:
    """Dispatches framed-thrift calls on the shared port.

    register("Echo", handler, args_spec=..., result_spec=...) — handler
    receives the decoded args dict and returns the success value (encoded
    as field 0 of the standard result struct).  Raising
    TApplicationException (or anything else) produces an EXCEPTION reply.
    Specs default to schemaless dicts keyed by field id / value packed
    with a caller-provided spec.
    """

    def __init__(self):
        self._methods: Dict[str, Tuple[Any, Any, Any]] = {}

    def register(self, method: str, handler, args_spec=None,
                 result_spec=None) -> None:
        self._methods[method] = (handler, args_spec, result_spec)

    def dispatch(self, frame: bytes) -> Optional[bytes]:
        """One TBinaryProtocol message in → one out (None for oneway)."""
        try:
            method, mtype, seqid, off = decode_message(frame)
        except Exception as e:
            # can't even parse the header: synthesize a seqid-0 exception
            exc = TApplicationException(
                TApplicationException.INTERNAL_ERROR, f"bad message: {e}")
            return encode_message("", MessageType.EXCEPTION, 0, exc.encode())
        oneway = mtype == MessageType.ONEWAY
        ent = self._methods.get(method)
        if ent is None:
            if oneway:
                return None
            exc = TApplicationException(
                TApplicationException.UNKNOWN_METHOD,
                f"unknown method {method!r}")
            return encode_message(method, MessageType.EXCEPTION, seqid,
                                  exc.encode())
        handler, args_spec, result_spec = ent
        try:
            args, _ = decode_struct(frame, off, args_spec)
            ret = handler(args)
            if oneway:
                return None
            if result_spec is None:
                body = b"\x00"  # void result: empty struct
            else:
                body = encode_struct(
                    {"success": ret},
                    (TType.STRUCT, {0: ("success", result_spec)}))
            return encode_message(method, MessageType.REPLY, seqid, body)
        except TApplicationException as exc:
            if oneway:
                return None
            return encode_message(method, MessageType.EXCEPTION, seqid,
                                  exc.encode())
        except Exception as e:
            if oneway:
                return None
            exc = TApplicationException(
                TApplicationException.INTERNAL_ERROR, repr(e))
            return encode_message(method, MessageType.EXCEPTION, seqid,
                                  exc.encode())


# ---------------------------------------------------------------------------
# client

class ThriftClient:
    """Framed-transport strict-binary client (≙ a brpc Channel with
    PROTOCOL_THRIFT, policy/thrift_protocol.cpp client half).  Thread-safe:
    one in-flight call at a time per connection, guarded by a lock."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._seq = 0
        self._lock = threading.Lock()

    def call(self, method: str, args: Dict[str, Any], args_spec,
             result_spec=None):
        """Synchronous call; returns the success value (None for void).
        Raises TApplicationException on an EXCEPTION reply."""
        with self._lock:
            self._seq += 1
            seqid = self._seq
            body = encode_struct(args, args_spec) if args_spec is not None \
                else b"\x00"
            msg = encode_message(method, MessageType.CALL, seqid, body)
            self._send_frame(msg)
            reply = self._recv_frame()
        rmethod, mtype, rseq, off = decode_message(reply)
        # EXCEPTION first: server-synthesized failures (unparseable header)
        # carry seqid 0 and must surface as the real error, not a mismatch
        if mtype == MessageType.EXCEPTION:
            raise TApplicationException.decode(reply, off)
        if rseq != seqid:
            raise ThriftError(f"seqid mismatch: sent {seqid} got {rseq}")
        if mtype != MessageType.REPLY:
            raise ThriftError(f"unexpected message type {mtype}")
        spec = (TType.STRUCT, {0: ("success", result_spec)}) \
            if result_spec is not None else None
        result, _ = decode_struct(reply, off, spec)
        return result.get("success") if result_spec is not None else None

    def call_oneway(self, method: str, args: Dict[str, Any],
                    args_spec) -> None:
        with self._lock:
            self._seq += 1
            body = encode_struct(args, args_spec) if args_spec is not None \
                else b"\x00"
            self._send_frame(
                encode_message(method, MessageType.ONEWAY, self._seq, body))

    def _send_frame(self, payload: bytes) -> None:
        self._sock.sendall(struct.pack("!I", len(payload)) + payload)

    def _recv_frame(self) -> bytes:
        hdr = self._recv_exact(4)
        (n,) = struct.unpack("!I", hdr)
        return self._recv_exact(n)

    def _recv_exact(self, n: int) -> bytes:
        from brpc_tpu.rpc._sockutil import recv_exact
        try:
            return recv_exact(self._sock, n)
        except ConnectionError:
            raise ThriftError("connection closed mid-frame") from None

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
