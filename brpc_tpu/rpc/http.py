"""HTTP service layer over the shared port (capability of the reference's
HTTP support: policy/http_rpc_protocol.cpp — pb services callable as
/Service/Method with JSON bodies via json2pb, plus raw HTTP services with
restful mappings, restful.cpp).

The native core sniffs HTTP on the same listening port as TRPC
(native/src/http.cc; ≙ one-port-many-protocols, input_messenger.cpp:77),
parses requests, and hands them to one dispatcher callback per server on
the usercode pthread pool.  This module is that dispatcher: an exact+prefix
route table plus the /rpc/<Service.Method> JSON bridge into registered TRPC
services (≙ json2pb: HTTP+JSON access to binary services).
"""

from __future__ import annotations

import json
import urllib.parse
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple, Union

from brpc_tpu.rpc import errors


@dataclass
class HttpRequest:
    method: str = "GET"
    path: str = "/"
    query: str = ""                 # raw query string
    headers: Dict[str, str] = field(default_factory=dict)  # lower-case keys
    body: bytes = b""
    # verified sender identity (rpc/auth.py AuthContext) when the server
    # has an Authenticator and the Authorization header verified; None
    # otherwise.  Gates mutating portal endpoints (/flags?setvalue=).
    auth_context: object = None

    def query_params(self) -> Dict[str, str]:
        return {k: v[-1] for k, v in
                urllib.parse.parse_qs(self.query, keep_blank_values=True)
                .items()}


@dataclass
class HttpResponse:
    status: int = 200
    headers: Dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    # HTTP/2 trailers (gRPC status rides here); ignored on HTTP/1.x
    trailers: Dict[str, str] = field(default_factory=dict)

    @staticmethod
    def text(s: str, status: int = 200) -> "HttpResponse":
        return HttpResponse(status, {"Content-Type": "text/plain"},
                            s.encode())

    @staticmethod
    def html(s: str, status: int = 200) -> "HttpResponse":
        return HttpResponse(status, {"Content-Type": "text/html"},
                            s.encode())

    @staticmethod
    def json(obj, status: int = 200) -> "HttpResponse":
        return HttpResponse(status, {"Content-Type": "application/json"},
                            json.dumps(obj, indent=1).encode())

    @staticmethod
    def progressive(status: int = 200,
                    headers: Optional[Dict[str, str]] = None
                    ) -> "ProgressiveAttachment":
        """Chunked streaming response (≙ ProgressiveAttachment,
        progressive_attachment.h:32): return this from a handler, keep a
        reference, and write()/close() from any thread — even long after
        the handler returned (infinite responses)."""
        return ProgressiveAttachment(status, dict(headers or {}))


class ProgressiveAttachment:
    """Server half of a streaming response (h1: chunked encoding; h2:
    open DATA frames on the request's stream, client flow control pacing
    blocked writes).  The HTTP dispatch layer binds it to the native
    PaState right after the handler returns; write() blocks until then,
    so background writer threads can start immediately.  Set `on_bound`
    to a callable to drive the stream INLINE on the dispatch thread right
    after binding (gRPC server-streaming pumps generators this way)."""

    def __init__(self, status: int, headers: Dict[str, str]):
        import threading as _t
        self.status = status
        self.headers = headers
        self.on_bound = None  # optional: called after _bind, same thread
        self._handle = None
        self._bound = _t.Event()
        self._closed = False

    def _bind(self, handle: int) -> None:
        self._handle = handle  # 0 = native setup failed; write() raises
        self._bound.set()

    def write(self, data: bytes) -> None:
        """One chunk onto the wire.  Raises BrokenPipeError once the
        peer is gone, so infinite writers terminate.  On h2 this blocks
        while the client's flow-control windows are exhausted."""
        if not self._bound.wait(timeout=30):
            raise RuntimeError("progressive response never bound")
        if self._closed or not self._handle:
            raise BrokenPipeError("progressive response closed")
        from brpc_tpu._native import lib
        import errno as _errno
        rc = lib().trpc_pa_write(self._handle, data, len(data))
        if rc == -_errno.ETIMEDOUT:
            # h2 flow-control stall: the stream is alive, the peer just
            # stopped crediting it for >30s.  Not a broken pipe — the
            # caller decides (retry, or close with a real status).
            raise TimeoutError("peer flow control stalled the stream")
        if rc != 0:
            # close the NATIVE side before marking closed: without it a
            # dead h2 stream would leak its PaState slot and H2Conn
            # reference forever (close() below early-returns on _closed,
            # and no teardown abort path exists for h2 attachments)
            self._closed = True
            lib().trpc_pa_close(self._handle)
            raise BrokenPipeError(f"chunk write failed ({rc})")

    def close(self, trailers: Optional[Dict[str, str]] = None) -> None:
        """End the stream.  h1: final chunk, then the connection closes.
        h2: trailing HEADERS carrying `trailers` (gRPC status) — or a
        bare END_STREAM — and the connection keeps multiplexing."""
        if not self._bound.wait(timeout=30):
            return
        if self._closed or not self._handle:
            return
        self._closed = True
        from brpc_tpu._native import lib
        if trailers:
            blob = "".join(f"{k}: {v}\r\n"
                           for k, v in trailers.items()).encode()
            lib().trpc_pa_close_trailers(self._handle, blob)
        else:
            lib().trpc_pa_close(self._handle)


# A handler returns HttpResponse | str (text/plain) | bytes (octet-stream) |
# dict/list (JSON).
HttpHandler = Callable[[HttpRequest], Union[HttpResponse, str, bytes, dict,
                                            list]]


def _coerce(out) -> HttpResponse:
    if isinstance(out, (HttpResponse, ProgressiveAttachment)):
        return out
    if isinstance(out, str):
        return HttpResponse.text(out)
    if isinstance(out, bytes):
        return HttpResponse(200, {"Content-Type":
                                  "application/octet-stream"}, out)
    if isinstance(out, (dict, list)):
        return HttpResponse.json(out)
    return HttpResponse.text(str(out))


def parse_headers_blob(blob: bytes) -> Dict[str, str]:
    """Native layer hands headers as 'lower-key: value\\n' lines."""
    out: Dict[str, str] = {}
    for line in blob.decode("utf-8", "replace").split("\n"):
        if not line:
            continue
        k, _, v = line.partition(": ")
        out[k] = v
    return out


class HttpDispatcher:
    """Route table: exact paths first, then longest registered prefix
    (≙ restful mapping '/path => Service.Method', restful.cpp), then the
    /rpc JSON bridge, then 404."""

    def __init__(self):
        self._exact: Dict[str, HttpHandler] = {}
        self._prefix: List[Tuple[str, HttpHandler]] = []  # sorted, longest 1st
        self._server = None  # set by Server for the /rpc bridge

    def register(self, path: str, handler: HttpHandler,
                 prefix: bool = False) -> None:
        if prefix:
            self._prefix.append((path, handler))
            self._prefix.sort(key=lambda kv: -len(kv[0]))
        else:
            self._exact[path] = handler

    def dispatch(self, req: HttpRequest) -> HttpResponse:
        h = self._exact.get(req.path)
        if h is None:
            for p, ph in self._prefix:
                if req.path.startswith(p):
                    h = ph
                    break
        if h is None and req.path.startswith("/rpc/"):
            return self._rpc_bridge(req)
        if h is None:
            return HttpResponse.text(f"no handler for {req.path}\n", 404)
        try:
            return _coerce(h(req))
        except Exception as e:  # handler bug → 500 (≙ EINTERNAL)
            import traceback
            return HttpResponse.text(
                f"handler raised: {e}\n{traceback.format_exc(limit=5)}", 500)

    # -- /rpc/<Service.Method> — JSON/raw access to TRPC services -----------
    # (≙ json2pb powering HTTP+JSON access to pb services,
    #  http_rpc_protocol.cpp + json_to_pb.cpp)

    def _rpc_bridge(self, req: HttpRequest) -> HttpResponse:
        if self._server is None:
            return HttpResponse.text("no TRPC services attached\n", 503)
        method = req.path[len("/rpc/"):]
        handler = self._server._find_handler(method)
        if handler is None:
            return HttpResponse.text(f"no such method {method}\n", 404)
        from brpc_tpu.rpc.controller import Controller
        cntl = Controller()
        cntl.method = method
        is_json = "json" in req.headers.get("content-type", "")
        body = req.body
        # pb-typed methods transcode JSON⇄message (≙ json2pb giving pb
        # services an HTTP+JSON face); raw/proto bodies pass through.
        # The invoke/error path below is shared; only the body decode
        # here and the response encode at the end differ.
        pb_spec = getattr(self._server, "_pb_specs", {}).get(method)
        if pb_spec is not None and is_json:
            from brpc_tpu.rpc.pb_service import json_to_pb
            try:
                body = json_to_pb(body or b"{}",
                                  pb_spec[0]).SerializeToString()
            except Exception as e:
                return HttpResponse.text(f"bad JSON request: {e}\n", 400)
        elif is_json and body:
            # JSON envelope: {"payload": "...", ...} or raw string body
            try:
                obj = json.loads(body)
                if isinstance(obj, dict) and "payload" in obj:
                    body = str(obj["payload"]).encode()
                elif isinstance(obj, str):
                    body = obj.encode()
            except ValueError:
                return HttpResponse.text("bad JSON body\n", 400)
        try:
            out = handler(cntl, body)
        except errors.RpcError as e:
            return HttpResponse.json(
                {"error_code": e.code, "error_text": e.text}, 500)
        except Exception as e:
            return HttpResponse.json(
                {"error_code": errors.EINTERNAL, "error_text": str(e)}, 500)
        resp = out[0] if isinstance(out, tuple) else (out or b"")
        if cntl.failed():
            return HttpResponse.json({"error_code": cntl.error_code,
                                      "error_text": cntl.error_text}, 500)
        if pb_spec is not None:
            if is_json:
                from brpc_tpu.rpc.pb_service import pb_to_json
                msg = pb_spec[1]()
                msg.ParseFromString(resp)
                return HttpResponse(200,
                                    {"Content-Type": "application/json"},
                                    pb_to_json(msg))
            return HttpResponse(200, {"Content-Type": "application/proto"},
                                resp)
        if is_json:
            return HttpResponse.json(
                {"payload": resp.decode("utf-8", "replace")})
        return HttpResponse(200, {"Content-Type":
                                  "application/octet-stream"}, resp)


def pack_headers(headers: Dict[str, str]) -> bytes:
    """To the native response blob: 'Key: Value\\r\\n' lines."""
    return "".join(f"{k}: {v}\r\n" for k, v in headers.items()).encode()
