"""TRPC meta TLV wire tags — the Python mirror of the registry.

The registry of record is tools/wire_tags_manifest.txt; the C++ side is
the kMetaTag* enum in native/src/rpc.h.  The `wiretags` analyzer rule
(tools/analyze/wiretags.py, tier-1 via tests/test_lint.py) checks all
three against each other BOTH ways, so adding/renaming a tag in one
place fails the gate until the other two agree.

Python never encodes the TRPC meta itself (framing is native), but
tooling that inspects frames — dump utilities, tests asserting
byte-identical wire, future debug decoders — must name tags from here,
never from numeric literals.
"""

METHOD = 1
CORRELATION_ID = 2
ERROR_CODE = 3
ERROR_TEXT = 4
ATTACHMENT_SIZE = 5
COMPRESS_TYPE = 6
TRACE_ID = 7
SPAN_ID = 8
FLAGS = 9
STREAM_ID = 10
STREAM_FRAME_TYPE = 11
FEEDBACK_BYTES = 12
AUTH = 13
DEVICE_CAPS = 14
PLANE_UID = 15
PAYLOAD_CODEC = 16
ATTACH_CODEC = 17
DEADLINE_LEFT_US = 18
