"""Channel — the client stub (≙ brpc::Channel, reference channel.cpp:407
CallMethod is the whole client pipeline: serialize → pick server → pack →
write → wait, with timeout/retry/backup orchestration from
Controller::OnVersionedRPCReturned, controller.cpp:575-670).

The per-connection data path (correlation ids, butex-woken pending calls,
wait-free socket writes) is native (native/src/rpc.cc); this layer adds what
sits above a single connection: retries with backoff, backup requests,
naming+load-balancing (cluster layer), and circuit-breaker feedback.
"""

from __future__ import annotations

import ctypes
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from brpc_tpu._native import lib
from brpc_tpu.metrics import bvar
from brpc_tpu.rpc import codec as _codec  # noqa: F401 — registers the
# payload_codec / codec_min_bytes flags (native/src/codec.h rail)
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller, inherited_deadline_ns
from brpc_tpu.utils import flags
from brpc_tpu.utils import logging as log
from brpc_tpu.utils.endpoint import EndPoint, str2endpoint


def _push_client_cork(value) -> bool:
    lib().trpc_set_client_cork(1 if value else 0)
    return True


flags.define_bool("client_cork",
                  os.environ.get("TRPC_CLIENT_CORK") != "0",
                  "client egress fast path: requests hold the socket "
                  "doorbell (Socket::Cork/Uncork) around the write, so "
                  "concurrent callers sharing one connection leave as a "
                  "single writev/SEND_ZC chain; off = plain per-request "
                  "writes, the TRPC_CLIENT_CORK=0 A/B baseline",
                  validator=_push_client_cork)


@dataclass
class ChannelOptions:
    timeout_ms: float = 1000.0
    max_retry: int = 3
    backup_request_ms: Optional[float] = None
    connect_timeout_ms: float = 500.0
    # cluster mode (set via Channel(naming_url, load_balancer=...))
    load_balancer: str = ""
    retry_policy: Optional["RetryPolicy"] = None
    # request payload compression: 0 none, 1 gzip, 2 zlib, 3 snappy
    # (rpc/compress.py; ≙ ChannelOptions request_compress_type)
    request_compress_type: int = 0
    # credential sent in every request meta (≙ ChannelOptions.auth +
    # Authenticator::GenerateCredential); verified natively by the server
    auth: Optional[bytes] = None
    # pluggable credential source (rpc/auth.py Authenticator): when set
    # (and `auth` is not), generate_credential() runs once per Channel —
    # the per-connection analog — and the result rides meta tag 13; the
    # server's Authenticator verifies it into an AuthContext
    authenticator: Optional[object] = None
    # "single" (default: one SocketMap-shared connection), "pooled"
    # (exclusive connection per in-flight call, parked between calls),
    # "short" (one call per connection)
    # (≙ ChannelOptions.connection_type, controller.cpp:1112-1114)
    connection_type: str = "single"
    # TLS (≙ ChannelOptions.ssl_options): handshake at dial time, before
    # the first frame.  tls_verify=False accepts any server certificate
    # (self-signed/test); tls_ca pins the trust root.  tls_cert/tls_key
    # present a client certificate (mutual TLS).
    tls: bool = False
    tls_verify: bool = True
    tls_ca: Optional[str] = None
    tls_cert: Optional[str] = None
    tls_key: Optional[str] = None


class RetryPolicy:
    """≙ brpc::RetryPolicy (retry_policy.h): DoRetry decides, backoff_time_us
    spaces the attempts."""

    # ≙ reference DefaultRetryPolicy (retry_policy.cpp): connection-level
    # and server-unavailable errors retry; ESTOP maps to ELOGOFF
    RETRIABLE = {errors.EFAILEDSOCKET, errors.EOVERCROWDED,
                 errors.EINTERNAL, errors.ESTOP}

    def do_retry(self, cntl: Controller) -> bool:
        if cntl.error_code == errors.ELIMIT:
            # the server SHED this request before executing it (the
            # overload plane's inline fast-reject, overload.h) — a retry
            # is at-most-once-safe even for non-idempotent methods, but
            # only useful on a DIFFERENT replica (≙ ExcludedServers: the
            # shedding node is excluded for this call's later attempts).
            # Single-server channels don't retry ELIMIT: hammering the
            # one saturated server is exactly what shedding exists to
            # stop.
            return getattr(cntl, "retry_elsewhere", False)
        return cntl.error_code in self.RETRIABLE

    def backoff_us(self, attempt: int) -> int:
        return 0  # no backoff by default (≙ reference default policy)


def _unpack_result(L, rc: int, result) -> Tuple[int, str, bytes, bytes]:
    """Drain and free a native CallResult (decompressing the response if
    the server compressed it — meta tag 6 rides back on the wire)."""
    try:
        code = L.trpc_result_error_code(result)
        text = L.trpc_result_error_text(result).decode(
            "utf-8", "replace") if code else ""
        p = ctypes.POINTER(ctypes.c_uint8)()
        n = L.trpc_result_data(result, ctypes.byref(p))
        data = ctypes.string_at(p, n) if n else b""
        ct = L.trpc_result_compress(result)
        if ct > 0 and data:
            from brpc_tpu.rpc import compress as compress_mod
            try:
                data = compress_mod.decompress(data, ct)
            except Exception as e:
                # undecodable response stays inside the RpcError contract
                return errors.ERESPONSE, f"bad compressed response: {e}", \
                    b"", b""
        n2 = L.trpc_result_attachment(result, ctypes.byref(p))
        att = ctypes.string_at(p, n2) if n2 else b""
        return (rc if rc else code), text, data, att
    finally:
        L.trpc_result_destroy(result)


class _NativeCall:
    """One sync call against one native channel handle."""

    __slots__ = ("handle",)

    def __init__(self, handle):
        self.handle = handle

    def call(self, method: bytes, payload: bytes, attachment: bytes,
             timeout_us: int, stream_handle: int = 0,
             compress: int = 0, cancel_buf=None
             ) -> Tuple[int, str, bytes, bytes]:
        L = lib()
        result = ctypes.c_void_p()
        if cancel_buf is not None:
            # publishes the call id into cancel_buf before the request is
            # written, so Controller.start_cancel works from any thread
            rc = L.trpc_channel_call_cancelable(
                self.handle, method, payload, len(payload),
                attachment if attachment else None, len(attachment),
                timeout_us, stream_handle, compress,
                ctypes.byref(cancel_buf), ctypes.byref(result))
        elif stream_handle:
            rc = L.trpc_channel_call_stream(
                self.handle, method, payload, len(payload),
                attachment if attachment else None, len(attachment),
                timeout_us, stream_handle, ctypes.byref(result))
        elif compress:
            rc = L.trpc_channel_call_compressed(
                self.handle, method, payload, len(payload),
                attachment if attachment else None, len(attachment),
                timeout_us, compress, ctypes.byref(result))
        else:
            rc = L.trpc_channel_call(
                self.handle, method, payload, len(payload),
                attachment if attachment else None, len(attachment),
                timeout_us, ctypes.byref(result))
        return _unpack_result(L, rc, result)

    def call_raw(self, method: bytes, payload: bytes, attachment: bytes,
                 timeout_us: int, compress: int, payload_codec: int,
                 attach_codec: int) -> Tuple[int, str, bytes, bytes]:
        """Replay rail (native/src/dump.h): payload/attachment are
        WIRE-form bytes from a captured sample — the native layer skips
        its codec encode and stamps the captured tag-16/17 ids verbatim,
        so the frame leaving here is byte-identical to the captured one."""
        L = lib()
        result = ctypes.c_void_p()
        rc = L.trpc_channel_call_raw(
            self.handle, method, payload, len(payload),
            attachment if attachment else None, len(attachment),
            timeout_us, compress, payload_codec, attach_codec,
            ctypes.byref(result))
        return _unpack_result(L, rc, result)


def native_fanout(subs: Sequence["SubChannel"], method: bytes,
                  payload: bytes, attachment: bytes, timeout_us: int
                  ) -> List[Tuple[int, str, bytes, bytes]]:
    """Serialize-once fan-out: ONE native call issues len(subs) sub-calls
    whose frames share a single serialization of `payload`/`attachment`
    as refcounted IOBuf blocks (rpc.cc channel_fanout_call; counted by
    native_fanout_shared_serializations).  Responses complete on the
    arriving parse fibers and are harvested here by one thread — no pool
    thread per sub-call.  Returns one (code, text, data, attachment)
    tuple per sub, in order.  Raises RpcError if any sub is closed."""
    L = lib()
    n = len(subs)
    if n == 0:
        return []
    acquired = []
    results = (ctypes.c_void_p * n)()
    try:
        # in-flight accounting on every member, so a concurrent close()
        # cannot free a native handle under the group call
        for s in subs:
            with s._lock:
                if s._closed:
                    raise errors.RpcError(errors.EFAILEDSOCKET,
                                          "channel closed")
                s._inflight += 1
            acquired.append(s)
        handles = (ctypes.c_void_p * n)(*[s._handle for s in subs])
        L.trpc_fanout_call(handles, n, method, payload, len(payload),
                           attachment if attachment else None,
                           len(attachment), timeout_us, results)
    finally:
        for s in acquired:
            with s._lock:
                s._inflight -= 1
                if s._inflight == 0:
                    s._drained.notify_all()
    return [_unpack_result(L, 0, results[i]) for i in range(n)]


class SubChannel:
    """A channel to a single server endpoint (native connection underneath).

    ≙ the single-server brpc::Channel (SocketMap entry, channel.cpp:317).
    """

    _CONN_TYPES = {"single": 0, "": 0, "pooled": 1, "short": 2}

    def __init__(self, endpoint: EndPoint,
                 connect_timeout_ms: float = 500.0,
                 auth: Optional[bytes] = None,
                 connection_type: str = "single",
                 device_plane: bool = False,
                 tls: bool = False, tls_verify: bool = True,
                 tls_ca: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None):
        self.endpoint = endpoint
        L = lib()
        self._handle = L.trpc_channel_create(
            endpoint.ip.encode(), endpoint.port)
        L.trpc_channel_set_connect_timeout(
            self._handle, int(connect_timeout_ms * 1000))
        if auth:
            L.trpc_channel_set_auth(self._handle, auth, len(auth))
        ct = self._CONN_TYPES.get(connection_type)
        if ct is None:
            raise ValueError(f"unknown connection_type {connection_type!r}")
        if ct:
            L.trpc_channel_set_connection_type(self._handle, ct)
        if device_plane:
            L.trpc_channel_request_device_plane(self._handle, 1)
        if tls:
            rc = L.trpc_channel_set_tls(
                self._handle, 1 if tls_verify else 0,
                tls_ca.encode() if tls_ca else None,
                tls_cert.encode() if tls_cert else None,
                tls_key.encode() if tls_key else None)
            if rc != 0:
                reason = (L.trpc_tls_error() or b"").decode()
                # the native handle was created above: don't leak it
                L.trpc_channel_destroy(self._handle)
                self._handle = None
                raise OSError(-rc, f"client TLS setup failed: {reason}")
        self._native = _NativeCall(self._handle)
        self._lock = threading.Lock()
        self._drained = threading.Condition(self._lock)
        self._inflight = 0
        self._closed = False

    def transport_state(self) -> str:
        """State of the connection the most recent call rode: "tcp",
        "handshaking", "device", or "fallback_tcp"."""
        from brpc_tpu.tpu_plane import TRANSPORT_STATES
        return TRANSPORT_STATES.get(
            lib().trpc_channel_transport_state(self._handle), "tcp")

    def call_once(self, method: bytes, payload: bytes, attachment: bytes,
                  timeout_us: int, stream_handle: int = 0,
                  compress: int = 0, cancel_buf=None):
        """One attempt.  A nonzero stream_handle makes this the streaming
        handshake (≙ StreamCreate riding CallMethod via stream_settings,
        baidu_rpc_meta.proto:16)."""
        # in-flight accounting so close() can't free the native handle
        # under a concurrent (e.g. async-pool) caller
        with self._lock:
            if self._closed:
                return (errors.EFAILEDSOCKET, "channel closed", b"", b"")
            self._inflight += 1
        try:
            return self._native.call(method, payload, attachment,
                                     timeout_us, stream_handle, compress,
                                     cancel_buf)
        finally:
            with self._lock:
                self._inflight -= 1
                if self._inflight == 0:
                    self._drained.notify_all()

    def call_raw_once(self, method: bytes, payload: bytes,
                      attachment: bytes, timeout_us: int, compress: int,
                      payload_codec: int, attach_codec: int):
        """One byte-for-byte replay attempt (wire-form bytes from a
        captured sample, codec/compress tags stamped verbatim)."""
        with self._lock:
            if self._closed:
                return (errors.EFAILEDSOCKET, "channel closed", b"", b"")
            self._inflight += 1
        try:
            return self._native.call_raw(method, payload, attachment,
                                         timeout_us, compress,
                                         payload_codec, attach_codec)
        finally:
            with self._lock:
                self._inflight -= 1
                if self._inflight == 0:
                    self._drained.notify_all()

    def close(self):
        """Blocks until in-flight calls drain (each bounded by its own RPC
        timeout), then frees the native handle."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            while self._inflight > 0:
                self._drained.wait()
        lib().trpc_channel_destroy(self._handle)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class Channel:
    """Client stub.  ``Channel("127.0.0.1:8000")`` dials a single server;
    ``Channel("list://h1:80,h2:80", load_balancer="rr")`` goes through the
    cluster layer (naming service + LB + circuit breaker) — see
    brpc_tpu/cluster/cluster_channel.py.
    """

    _latency = None  # class-wide client latency recorder, lazily exposed
    _hedge_canceled = None  # losing hedge attempts canceled (ISSUE 19)

    def __init__(self, address: str,
                 options: Optional[ChannelOptions] = None, **kw):
        self.options = options or ChannelOptions(**kw)
        self._cred_born = None
        if (self.options.authenticator is not None
                and self.options.auth is None):
            # per-connection generate (≙ GenerateCredential writing the
            # auth string once per connection): resolved per Channel,
            # then carried on every request meta by the native layer.
            # Time-boxed credentials (HmacNonceAuthenticator.max_skew_s)
            # rotate on a live channel — see _maybe_refresh_credential.
            # The options object is COPIED first: a caller sharing one
            # ChannelOptions across Channels must not have channel A's
            # credential leak into (and stop rotation for) channel B.
            import dataclasses as _dc
            self.options = _dc.replace(
                self.options,
                auth=self.options.authenticator.generate_credential())
            self._cred_born = time.monotonic()
        self._cluster = None
        self._device_requested = False
        if "://" in address and not address.startswith("tpu://"):
            from brpc_tpu.cluster.cluster_channel import ClusterChannel
            self._cluster = ClusterChannel(address, self.options)
            self._sub = None
        else:
            ep = str2endpoint(address)
            self._device_requested = ep.is_device
            if ep.is_device:
                # tpu:// endpoint — the control plane rides DCN/TCP and
                # the connection runs the device-plane handshake on its
                # first call (≙ RdmaEndpoint's TCP-assisted bring-up with
                # an EXPLICIT FALLBACK_TCP state, rdma_endpoint.h:95-110;
                # never a silent downgrade).  Bring the local plane up
                # eagerly so the handshake can settle into "device".
                from brpc_tpu import tpu_plane
                if not tpu_plane.init():
                    log.LOG(log.LOG_WARNING,
                            "tpu://%s: local device plane unavailable "
                            "(%s); connection will settle in fallback_tcp",
                            address, tpu_plane.error())
                ep = EndPoint(ip=ep.ip, port=ep.port)
            self._sub = SubChannel(ep, self.options.connect_timeout_ms,
                                   self.options.auth,
                                   self.options.connection_type,
                                   device_plane=self._device_requested,
                                   tls=self.options.tls,
                                   tls_verify=self.options.tls_verify,
                                   tls_ca=self.options.tls_ca,
                                   tls_cert=self.options.tls_cert,
                                   tls_key=self.options.tls_key)
        if Channel._latency is None:
            Channel._latency = bvar.LatencyRecorder()
            Channel._latency.expose("rpc_client")
            Channel._hedge_canceled = bvar.Adder(
                "rpc_client_hedge_canceled")
        self._fallback_warned = False

    def _maybe_refresh_credential(self) -> None:
        """Rotate a time-boxed credential before it exits the server's
        replay window: a long-lived channel must not start failing EAUTH
        at max_skew_s.  Regenerates at HALF the window and pushes the
        new credential into the live native channel(s) —
        trpc_channel_set_auth is rotation-safe (Channel::auth_mu)."""
        a = self.options.authenticator
        if a is None or self._cred_born is None:
            return
        skew = getattr(a, "max_skew_s", None)
        if not skew or time.monotonic() - self._cred_born <= skew / 2:
            return
        cred = a.generate_credential()
        self.options.auth = cred  # new (incl. cluster) subchannels
        self._cred_born = time.monotonic()
        if self._sub is not None and self._sub._handle:
            lib().trpc_channel_set_auth(self._sub._handle, cred, len(cred))
        if self._cluster is not None:
            self._cluster.refresh_auth(cred)

    # -- the client pipeline (≙ Channel::CallMethod, channel.cpp:407) -------

    def call(self, method: str, payload: bytes = b"",
             attachment: bytes = b"",
             cntl: Optional[Controller] = None,
             timeout_ms: Optional[float] = None) -> bytes:
        """Synchronous call.  Raises RpcError on failure; returns response
        payload (attachment lands on cntl.response_attachment).
        `timeout_ms` overrides both cntl and ChannelOptions for this call
        only (used by call_async's queue-time accounting)."""
        cntl = cntl or Controller()
        cntl.reset()
        # effective knobs: Controller overrides, else ChannelOptions —
        # computed into locals so a reused Controller keeps None = inherit
        if timeout_ms is None:
            if cntl.timeout_ms is not None:
                timeout_ms = cntl.timeout_ms
            else:
                timeout_ms = self.options.timeout_ms
                # deadline-budget inheritance (ISSUE 19, ≙ the reference
                # shrinking the baidu_std meta timeout_ms hop by hop): a
                # call with NO explicit timeout made from inside a server
                # handler defaults to the caller's remaining budget minus
                # the per-hop reserve (TRPC_DEADLINE_RESERVE_US), so a
                # mesh's tail work is bounded by the root's deadline
                # instead of each tier's full ChannelOptions.timeout_ms.
                # Explicit timeouts (Controller or per-call) still win.
                inh = inherited_deadline_ns()
                if inh is not None:
                    left_ms = ((inh - time.monotonic_ns()) / 1e6
                               - lib().trpc_deadline_reserve_us() / 1e3)
                    if left_ms < 1.0:
                        left_ms = 1.0  # let the server-side shed decide
                    if left_ms < timeout_ms:
                        timeout_ms = left_ms
        self._maybe_refresh_credential()
        mb = method.encode()
        start = time.monotonic_ns()
        deadline = start + int(timeout_ms * 1e6)
        policy = self.options.retry_policy or _default_retry
        max_retry = cntl.max_retry if cntl.max_retry is not None \
            else self.options.max_retry
        backup_ms = (cntl.backup_request_ms
                     if cntl.backup_request_ms is not None
                     else self.options.backup_request_ms)

        # request compression happens once, before the attempt loop
        # (≙ compress in CallMethod before IssueRPC, channel.cpp:527)
        compress_type = (cntl.request_compress_type
                         or self.options.request_compress_type)
        if compress_type:
            from brpc_tpu.rpc import compress as compress_mod
            payload = compress_mod.compress(payload, compress_type)

        from brpc_tpu.rpc import span as span_mod
        # cross-hop inheritance: a client span created INSIDE a server
        # handler parents at the current server span, continuing the
        # caller's trace (≙ Channel::CallMethod inheriting tls_parent,
        # channel.cpp:467-485)
        parent = span_mod.current()
        sp = span_mod.start_span(
            "client", method,
            trace_id=parent.trace_id if parent is not None else 0,
            parent_span_id=parent.span_id if parent is not None else 0)
        saved_trace = None
        if sp is not None:
            # push this span into the thread's native TraceCtx so the
            # wire (TLV tags 7/8) carries it — the server parents its
            # span here.  python_owned=1 stops the native layer from
            # capturing a duplicate client-unary span for this call.
            # Saved/restored so a handler's LATER downstream calls still
            # parent at the server span.  (Backup-request hedge attempts
            # run on their own threads and skip propagation.)
            L = lib()
            _t, _s = ctypes.c_uint64(0), ctypes.c_uint64(0)
            owned = L.trpc_trace_current(ctypes.byref(_t),
                                         ctypes.byref(_s))
            saved_trace = (_t.value, _s.value, owned)
            L.trpc_trace_set_current(sp.trace_id, sp.span_id, 1)

        # arm the cancellation window (≙ Controller::call_id being valid
        # from IssueRPC on): start_cancel from another thread claims the
        # published id; between attempts the flag stops the retry loop
        cntl._call_id_buf = ctypes.c_uint64(0)

        # ELIMIT retry-elsewhere gate: a shed request may retry only
        # when another replica exists to land on (cluster mode, >1
        # resolved servers — the shedding node joins excluded_nodes)
        cntl.retry_elsewhere = (
            self._cluster is not None
            and len(self._cluster.lb.servers()) > 1)

        try:
            attempt = 0
            while True:
                if cntl._cancel_requested:
                    cntl.set_failed(errors.ECANCELED,
                                    "canceled before the attempt")
                    break
                remaining_us = (deadline - time.monotonic_ns()) // 1000
                if remaining_us <= 0:
                    cntl.set_failed(errors.ERPCTIMEDOUT)
                    break
                code, text, data, att = self._call_attempt(
                    mb, payload, attachment, remaining_us, backup_ms, cntl,
                    compress_type)
                cntl.error_code, cntl.error_text = code, text
                if code == errors.ELIMIT:
                    # refresh the elsewhere gate against THIS call's
                    # exclusions: once every replica has shed, the
                    # cluster's all-excluded fallback would re-pick a
                    # saturated node — stop retrying instead of
                    # hammering servers that just told us to back off
                    cntl.retry_elsewhere = (
                        self._cluster is not None
                        and any(n not in cntl.excluded_nodes
                                for n in self._cluster.lb.servers()))
                if code == 0:
                    cntl.response_attachment = att
                    cntl.latency_us = (time.monotonic_ns() - start) // 1000
                    Channel._latency.record(cntl.latency_us)
                    if sp is not None:
                        sp.remote_side = cntl.remote_side
                        span_mod.finish_span(sp, 0)
                    self._check_transport_settled()
                    return data
                if attempt >= max_retry or not policy.do_retry(cntl):
                    break
                attempt += 1
                cntl.retried_count = attempt
                if sp is not None:
                    sp.annotate(f"retry #{attempt} after E{code}")
                backoff = policy.backoff_us(attempt)
                if backoff > 0:
                    time.sleep(backoff / 1e6)
            cntl.latency_us = (time.monotonic_ns() - start) // 1000
            if sp is not None:
                sp.remote_side = cntl.remote_side
                span_mod.finish_span(sp, cntl.error_code)
            raise errors.RpcError(cntl.error_code, cntl.error_text)
        finally:
            if saved_trace is not None:
                lib().trpc_trace_set_current(*saved_trace)

    @property
    def transport_state(self) -> str:
        """Transport of the most recent call's connection: "tcp",
        "handshaking", "device", or "fallback_tcp" (≙ the RdmaEndpoint
        state machine's observable states, rdma_endpoint.h:95-110)."""
        if self._sub is None:
            return "tcp"
        return self._sub.transport_state()

    def _check_transport_settled(self) -> None:
        """tpu:// channels announce (once) when the handshake settled in
        FALLBACK_TCP — an explicit, logged downgrade."""
        if (not getattr(self, "_device_requested", False)
                or self._fallback_warned):
            return
        st = self.transport_state
        if st == "fallback_tcp":
            self._fallback_warned = True
            log.LOG(log.LOG_WARNING,
                    "tpu:// channel settled in FALLBACK_TCP (peer or "
                    "local device plane unavailable); attachments ride "
                    "TCP without the device data plane")
        elif st == "device":
            self._fallback_warned = True  # settled: stop checking
            log.LOG(log.LOG_INFO, "tpu:// channel established DEVICE "
                    "transport (PJRT data plane active on both sides)")

    def _call_attempt(self, method: bytes, payload: bytes, attachment: bytes,
                      timeout_us: int, backup_ms: Optional[float],
                      cntl: Controller, compress: int = 0):
        hedged = backup_ms is not None and timeout_us > backup_ms * 1000
        if self._cluster is not None:
            # cluster hedging (ISSUE 19): the backup attempt goes back
            # through the LB, so it statistically lands on a DIFFERENT
            # replica than the straggling primary — the mixer-tier
            # "hedged scatter" leg of the churn story
            def call_fn(budget_us, buf):
                return self._cluster.call_once(
                    method, payload, attachment, budget_us, cntl,
                    compress=compress, cancel_buf=buf)
        else:
            def call_fn(budget_us, buf):
                return self._sub.call_once(
                    method, payload, attachment, budget_us,
                    compress=compress, cancel_buf=buf)
        if not hedged:
            return call_fn(timeout_us, getattr(cntl, "_call_id_buf", None))
        return self._backup_race(call_fn, timeout_us, backup_ms, cntl)

    @staticmethod
    def _backup_race(call_fn, timeout_us: int, backup_ms: float,
                     cntl: Controller):
        """Backup request (≙ reference channel.cpp:551-560,
        controller.cpp:601-634): if no response within backup_ms, race a
        second attempt; first success wins — and CANCELS the loser
        (≙ the reference's CallId cancel of the superseded attempt) so
        its server-side work stops instead of running to completion on a
        node that no longer has a waiter.  Canceled-loser count rides
        the rpc_client_hedge_canceled bvar."""
        result = []  # (attempt_idx, (code, text, data, att))
        cond = threading.Condition()
        deadline = time.monotonic() + timeout_us / 1e6  # from attempt start
        # per-attempt cancel cells: the winner needs the LOSER's call id,
        # so the two attempts cannot share one buffer.  External
        # start_cancel still claims whichever armed last via
        # cntl._call_id_buf (same window the shared cell gave it).
        bufs = [ctypes.c_uint64(0), ctypes.c_uint64(0)]
        done = [False, False]

        def attempt(idx, budget_us):
            cntl._call_id_buf = bufs[idx]
            r = call_fn(budget_us, bufs[idx])
            with cond:
                done[idx] = True
                result.append((idx, r))
                cond.notify_all()

        t1 = threading.Thread(
            target=attempt, args=(0, timeout_us), daemon=True)
        t1.start()
        with cond:
            cond.wait(backup_ms / 1000.0)
            if not result:
                cntl.backup_fired = True
        if cntl.backup_fired:
            remaining = timeout_us - int(backup_ms * 1000)
            t2 = threading.Thread(
                target=attempt, args=(1, remaining), daemon=True)
            t2.start()

        def cancel_loser(winner_idx):
            loser = 1 - winner_idx
            if not cntl.backup_fired or done[loser]:
                return
            call_id = bufs[loser].value
            if call_id:
                lib().trpc_call_cancel(call_id)
                Channel._hedge_canceled.add(1)

        with cond:
            while True:
                for idx, r in result:
                    if r[0] == 0:
                        cancel_loser(idx)
                        return r
                expected = 2 if cntl.backup_fired else 1
                if len(result) >= expected:
                    return result[0][1]
                left = deadline - time.monotonic()
                if left <= 0:
                    return (errors.ERPCTIMEDOUT, "", b"", b"")
                cond.wait(left)

    def call_raw(self, method: str, payload: bytes = b"",
                 attachment: bytes = b"",
                 timeout_ms: Optional[float] = None,
                 compress_type: int = 0, payload_codec: int = 0,
                 attach_codec: int = 0) -> bytes:
        """Byte-for-byte replay call (tools/rpc_replay): payload and
        attachment are WIRE-form bytes from a captured sample; the
        captured codec ids (meta tags 16/17) and compress type (tag 6)
        are stamped verbatim and the client-side encode is skipped.
        Single-server channels only, no retries — the replay cannon
        measures offered load, sheds included.  Raises RpcError on
        failure; returns the response payload."""
        if self._sub is None:
            raise errors.RpcError(
                errors.EINTERNAL,
                "call_raw requires a single-server channel")
        if timeout_ms is None:
            timeout_ms = self.options.timeout_ms
        self._maybe_refresh_credential()
        code, text, data, _att = self._sub.call_raw_once(
            method.encode(), payload, attachment, int(timeout_ms * 1000),
            compress_type, payload_codec, attach_codec)
        if code != 0:
            raise errors.RpcError(code, text)
        return data

    # -- streaming (≙ StreamCreate + CallMethod handshake, stream.cpp:773) --

    # -- async call (≙ CallMethod with done != NULL: the call returns
    # immediately and done->Run() fires on completion,
    # docs/en/client.md "Asynchronous call") -------------------------------

    _async_pool = None
    _async_pool_lock = threading.Lock()

    @classmethod
    def _pool(cls):
        if cls._async_pool is None:
            with cls._async_pool_lock:
                if cls._async_pool is None:
                    from concurrent.futures import ThreadPoolExecutor
                    cls._async_pool = ThreadPoolExecutor(
                        max_workers=32, thread_name_prefix="rpc_async")
        return cls._async_pool

    def call_async(self, method: str, payload: bytes = b"",
                   attachment: bytes = b"",
                   cntl: Optional[Controller] = None,
                   done: Optional[Callable[[Controller,
                                            Optional[bytes]], None]] = None):
        """Asynchronous call: returns a Future of the response bytes.
        `done(cntl, response_or_None)` runs exactly once on completion
        (response is None when cntl.failed()); the Future raises RpcError
        on failure.  The timeout clock starts NOW (≙ the reference timer
        arming in CallMethod), not when a pool thread picks the call up."""
        cntl = cntl or Controller()
        timeout_ms = (cntl.timeout_ms if cntl.timeout_ms is not None
                      else self.options.timeout_ms)
        deadline = time.monotonic() + timeout_ms / 1e3

        def run():
            resp = None
            try:
                remaining_ms = (deadline - time.monotonic()) * 1e3
                if remaining_ms <= 0:
                    # queued past its deadline behind other async calls
                    cntl.set_failed(errors.ERPCTIMEDOUT)
                    raise errors.RpcError(errors.ERPCTIMEDOUT,
                                          "timed out in async queue")
                resp = self.call(method, payload, attachment, cntl,
                                 timeout_ms=remaining_ms)
                return resp
            finally:
                if done is not None:
                    try:
                        done(cntl, resp)
                    except Exception:
                        from brpc_tpu.utils import logging as _log
                        import traceback as _tb
                        _log.LOG(_log.LOG_ERROR,
                                 "async done callback raised:\n%s",
                                 _tb.format_exc())

        return self._pool().submit(run)

    def create_stream(self, method: str, payload: bytes = b"",
                      attachment: bytes = b"", window: Optional[int] = None,
                      cntl: Optional[Controller] = None):
        """Issue `method` with a stream attached.  Returns
        ``(response_bytes, Stream)``; the server handler must call
        ``cntl.accept_stream()``.  The stream is pinned to the chosen
        connection for its whole life (no retries across servers)."""
        from brpc_tpu.rpc import stream as _stream
        cntl = cntl or Controller()
        cntl.reset()
        self._maybe_refresh_credential()
        timeout_ms = (cntl.timeout_ms if cntl.timeout_ms is not None
                      else self.options.timeout_ms)
        timeout_us = int(timeout_ms * 1000)
        handle = lib().trpc_stream_create(window or _stream.DEFAULT_WINDOW)
        # arm the cancellation window like call() does: start_cancel from
        # another thread claims the handshake's published id, and the
        # server propagates the cancel to the accepted stream as an RST
        cntl._call_id_buf = ctypes.c_uint64(0)
        # the cluster path keeps its LB/breaker/health bookkeeping (the
        # handshake is a normal one-attempt call with a stream attached)
        if self._cluster is not None:
            code, text, data, att = self._cluster.call_once(
                method.encode(), payload, attachment, timeout_us, cntl,
                stream_handle=handle)
        else:
            code, text, data, att = self._sub.call_once(
                method.encode(), payload, attachment, timeout_us, handle,
                cancel_buf=cntl._call_id_buf)
        cntl.error_code, cntl.error_text = code, text
        cntl.response_attachment = att
        if code != 0:
            lib().trpc_stream_destroy(handle)
            raise errors.RpcError(code, text)
        return data, _stream.Stream(handle)

    def close(self):
        if self._sub is not None:
            self._sub.close()
        if self._cluster is not None:
            self._cluster.close()


_default_retry = RetryPolicy()
