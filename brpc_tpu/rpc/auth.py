"""Pluggable authentication — Authenticator + AuthContext
(≙ reference authenticator.h:30-75: the client's GenerateCredential
writes an auth string into the first message of each connection; the
server's VerifyCredential checks it and fills an AuthContext — user,
group, roles, starter, is_service — that handlers read off the
Controller).

TPU-build mapping: the credential rides meta tag 13 on EVERY request
(the native layer attaches it per channel, channel_set_auth), so
"per-connection" generate happens once per Channel and verify runs per
request on the usercode side (token_auth/token_peer surface the raw
credential + peer address per token).  Cheap-verifier impls (HMAC) make
per-request verify a non-issue; the verified AuthContext lands on
``cntl.auth_context`` for TRPC handlers and ``request.auth_context``
for HTTP handlers, and gates the portal's /flags mutation.

The legacy static-token path (ServerOptions.auth bytes, compared
natively before dispatch) is unchanged; an Authenticator replaces it
with Python-side verification and a real identity.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import time
from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass
class AuthContext:
    """Verified identity of a request's sender (≙ AuthContext,
    authenticator.h:30-54)."""
    user: str = ""
    group: str = ""
    roles: Tuple[str, ...] = ()
    starter: str = ""
    is_service: bool = False
    # where the credential came from (ip:port), for audit lines
    client_addr: str = ""

    def has_role(self, role: str) -> bool:
        return role in self.roles


class AuthError(Exception):
    """Verification failed — the server answers EAUTH / HTTP 401."""


class Authenticator:
    """Interface (≙ Authenticator, authenticator.h:56-75).  Subclass and
    pass to ServerOptions.authenticator / ChannelOptions.authenticator."""

    def generate_credential(self) -> bytes:
        """Client side: the credential attached to requests (meta tag 13).
        Called once per Channel (the per-connection analog)."""
        raise NotImplementedError

    def verify_credential(self, auth: bytes,
                          client_addr: str) -> AuthContext:
        """Server side: verify and build the identity.  Raise
        :class:`AuthError` to reject (the caller sees EAUTH)."""
        raise NotImplementedError


class HmacNonceAuthenticator(Authenticator):
    """HMAC-of-nonce credential: ``hmac1 <user> <nonce> <mac>`` where
    ``mac = HMAC_SHA256(secret, user + " " + nonce)`` and the nonce
    carries the client's clock (ns) + 8 random bytes.  Verify recomputes
    the MAC (constant-time compare) and bounds the clock skew, so a
    captured credential cannot be replayed outside ``max_skew_s``.

    One shared secret, many identities: the user/group/roles the client
    CLAIMS are authenticated by the MAC (whoever holds the secret vouches
    for them) — the reference's GenerateCredential embeds identity the
    same way.
    """

    def __init__(self, secret: bytes, user: str = "anon",
                 group: str = "", roles: Tuple[str, ...] = (),
                 max_skew_s: float = 600.0):
        if not secret:
            raise ValueError("empty HMAC secret")
        self.secret = secret
        self.user = user
        self.group = group
        self.roles = tuple(roles)
        self.max_skew_s = max_skew_s

    def _mac(self, user: str, nonce: str, group: str,
             roles_csv: str) -> str:
        msg = " ".join((user, nonce, group, roles_csv)).encode()
        return _hmac.new(self.secret, msg, hashlib.sha256).hexdigest()

    def generate_credential(self) -> bytes:
        nonce = f"{time.time_ns()}.{os.urandom(8).hex()}"
        roles_csv = ",".join(self.roles)
        mac = self._mac(self.user, nonce, self.group, roles_csv)
        return " ".join(("hmac1", self.user, nonce, self.group or "-",
                         roles_csv or "-", mac)).encode()

    def verify_credential(self, auth: bytes,
                          client_addr: str) -> AuthContext:
        try:
            parts = auth.decode("utf-8", "strict").split(" ")
        except UnicodeDecodeError:
            raise AuthError("malformed credential") from None
        if len(parts) != 6 or parts[0] != "hmac1":
            raise AuthError("malformed credential")
        _, user, nonce, group, roles_csv, mac = parts
        group = "" if group == "-" else group
        roles_csv = "" if roles_csv == "-" else roles_csv
        want = self._mac(user, nonce, group, roles_csv)
        if not _hmac.compare_digest(mac, want):
            raise AuthError("bad MAC")
        try:
            sent_ns = int(nonce.split(".", 1)[0])
        except ValueError:
            raise AuthError("malformed nonce") from None
        if abs(time.time_ns() - sent_ns) > self.max_skew_s * 1e9:
            raise AuthError("stale credential (replay window exceeded)")
        return AuthContext(
            user=user, group=group,
            roles=tuple(r for r in roles_csv.split(",") if r),
            client_addr=client_addr)
