"""Payload-codec rail — the Python face of native/src/codec.h (ISSUE 8;
≙ the reference compress-handler registry policy/gzip_compress.cpp,
extended TPU-natively with quantizing tensor codecs per EQuARX,
arXiv 2506.17615).

Unlike rpc/compress.py (whose codecs run in Python on the usercode side
and ride meta tag 6), this rail is NATIVE and per-part: channel_call /
channel_fanout_call encode the request's payload and attachment on the
way into the socket (fan-out groups encode ONCE and share the encoded
refcounted blocks), the server decodes on the owning shard's parse
fiber, and responses mirror the request's codec (meta tags 16/17).

Codec ids are wire contract:
    0 none      1 snappy (lossless)
    2 bf16      3 int8 per-256-float-block scale   (lossy, f32 streams)

The `payload_codec` flag (seeded from TRPC_PAYLOAD_CODEC) picks what
THIS process's clients send; "none" is byte-identical wire to a build
without the rail.  int8's error bound: |err| <= max|block| / 127.
"""

from __future__ import annotations

import ctypes
import os
from typing import Tuple

from brpc_tpu._native import lib
from brpc_tpu.utils import flags

CODEC_NONE = 0
CODEC_SNAPPY = 1
CODEC_BF16 = 2
CODEC_INT8 = 3

_NAMES = {CODEC_NONE: "none", CODEC_SNAPPY: "snappy",
          CODEC_BF16: "bf16", CODEC_INT8: "int8"}
_IDS = {v: k for k, v in _NAMES.items()}

# int8 quantization block (floats per scale) — must match codec.h
INT8_BLOCK_FLOATS = 256


def name_of(codec_id: int) -> str:
    return _NAMES.get(codec_id, f"unknown({codec_id})")


def id_of(name: str) -> int:
    if name in ("", "none", "0"):
        return CODEC_NONE
    if name not in _IDS:
        raise KeyError(f"unknown payload codec {name!r}")
    return _IDS[name]


def _push_payload_codec(value: str) -> bool:
    try:
        cid = id_of(value)
    except KeyError:
        return False
    lib().trpc_set_payload_codec(cid)
    return True


def _push_codec_min_bytes(value: int) -> bool:
    if value < 0:
        return False
    lib().trpc_set_codec_min_bytes(int(value))
    return True


flags.define_string(
    "payload_codec",
    os.environ.get("TRPC_PAYLOAD_CODEC", "none") or "none",
    "native payload codec for client-issued requests "
    "(none/snappy/bf16/int8; native/src/codec.h): encode once per call "
    "— once per FAN-OUT GROUP — on the way into the socket; the server "
    "mirrors it on responses.  'none' is byte-identical wire "
    "(the TRPC_PAYLOAD_CODEC A/B)", validator=_push_payload_codec)
flags.define_int32(
    "codec_min_bytes", int(os.environ.get("TRPC_CODEC_MIN_BYTES", "") or 256),
    "payload/attachment parts smaller than this ride plain (encoding a "
    "16-byte echo costs more than it saves); reloadable",
    validator=_push_codec_min_bytes)


def active() -> str:
    """Name of the codec the native layer currently applies to requests."""
    return name_of(int(lib().trpc_payload_codec()))


def encode(data: bytes, codec: str | int) -> Tuple[bytes, int]:
    """Encode bytes through the native rail (tests/tools surface; the
    RPC paths encode natively, not through here).  Returns
    (encoded, applied_id) — applied_id 0 means the codec declined
    (ineligible part / incompressible) and `data` came back unchanged."""
    cid = id_of(codec) if isinstance(codec, str) else int(codec)
    L = lib()
    p = ctypes.POINTER(ctypes.c_uint8)()
    applied = ctypes.c_int(0)
    n = L.trpc_codec_encode(cid, data, len(data), ctypes.byref(p),
                            ctypes.byref(applied))
    n = int(n)
    if n < 0:
        raise ValueError(f"codec {name_of(cid)} encode failed")
    if n == 0 or applied.value == 0:
        return data, 0
    try:
        return ctypes.string_at(p, n), int(applied.value)
    finally:
        L.trpc_codec_buf_free(p)


def decode(data: bytes, codec: str | int) -> bytes:
    """Inverse of :func:`encode` (codec id 0 = identity)."""
    cid = id_of(codec) if isinstance(codec, str) else int(codec)
    if cid == CODEC_NONE:
        return data
    L = lib()
    p = ctypes.POINTER(ctypes.c_uint8)()
    n = int(L.trpc_codec_decode(cid, data, len(data), ctypes.byref(p)))
    if n < 0:
        raise ValueError(f"codec {name_of(cid)} decode failed (corrupt "
                         f"input)")
    try:
        return ctypes.string_at(p, n)
    finally:
        L.trpc_codec_buf_free(p)


def roundtrip_chained(data: bytes, codec: str | int,
                      chunk: int) -> Tuple[int, float]:
    """Property-test hook: encode+decode `data` through a CHAINED native
    IOBuf built from `chunk`-byte appends (multi-block seams).  Returns
    (rc, max_f32_err): rc 0 = byte-exact, 1 = lossy, -1 = failure."""
    cid = id_of(codec) if isinstance(codec, str) else int(codec)
    err = ctypes.c_double(0.0)
    rc = int(lib().trpc_codec_roundtrip_chained(
        cid, data, len(data), chunk, ctypes.byref(err)))
    return rc, float(err.value)
# (int8's documented per-element bound — max|block|/127 — lives with the
# tensor-side mirror, brpc_tpu/parallel/quantize.int8_error_bound.)
