"""HTTP client — the framework's OWN client (≙ accessing HTTP services
via brpc::Channel with PROTOCOL_HTTP, docs/en/http_client.md and the
client half of policy/http_rpc_protocol.cpp; NOT a urllib wrapper).

The data path is native: requests serialize and responses parse in C++
over the same Socket/EventDispatcher/TLS stack every other protocol
uses; responses correlate FIFO per connection; `stream=` delivers body
bytes progressively as they arrive (≙ ProgressiveReader,
progressive_reader.h:36).
"""

from __future__ import annotations

import ctypes
import threading
from typing import Callable, Dict, Optional, Tuple

from brpc_tpu._native import HTTP_CHUNK_CB as _CHUNK_CB, lib
from brpc_tpu.rpc import errors
from brpc_tpu.utils.endpoint import str2endpoint


class HttpResponse:
    __slots__ = ("status", "headers", "body")

    def __init__(self, status: int, headers: Dict[str, str], body: bytes):
        self.status = status
        self.headers = headers
        self.body = body


class HttpChannel:
    """Client channel to one HTTP/1.1 server.

    connection_type: "pooled" (default — one exclusive connection per
    in-flight request, parked between calls), "single" (one shared
    pipelined connection), or "short".  TLS via tls=True (+ tls_ca /
    tls_verify / client certs), sharing the channel TLS stack.
    """

    _CONN_TYPES = {"single": 0, "pooled": 1, "short": 2}

    def __init__(self, address: str, connection_type: str = "pooled",
                 connect_timeout_ms: float = 1000.0,
                 host: Optional[str] = None,
                 tls: bool = False, tls_verify: bool = True,
                 tls_ca: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None):
        ep = str2endpoint(address)
        L = lib()
        self._handle = L.trpc_channel_create(ep.ip.encode(), ep.port)
        L.trpc_channel_set_connect_timeout(
            self._handle, int(connect_timeout_ms * 1000))
        ct = self._CONN_TYPES.get(connection_type)
        if ct is None:
            raise ValueError(f"unknown connection_type {connection_type!r}")
        if ct:
            L.trpc_channel_set_connection_type(self._handle, ct)
        L.trpc_channel_set_http(self._handle,
                                host.encode() if host else None)
        if tls:
            rc = L.trpc_channel_set_tls(
                self._handle, 1 if tls_verify else 0,
                tls_ca.encode() if tls_ca else None,
                tls_cert.encode() if tls_cert else None,
                tls_key.encode() if tls_key else None)
            if rc != 0:
                reason = (L.trpc_tls_error() or b"").decode()
                L.trpc_channel_destroy(self._handle)
                self._handle = None
                raise OSError(-rc, f"TLS setup failed: {reason}")
        self._lock = threading.Lock()
        self._closed = False
        # ctypes trampolines for in-flight streaming callbacks: the native
        # side may still deliver chunks after a local timeout (until the
        # connection sweep runs), so a trampoline must outlive its call —
        # kept here until the call completes cleanly or the channel closes
        self._cb_refs: list = []

    def request(self, method: str, target: str = "/",
                headers: Optional[Dict[str, str]] = None,
                body: bytes = b"", timeout_ms: float = 10_000.0,
                stream: Optional[Callable[[bytes], None]] = None
                ) -> HttpResponse:
        """One HTTP call.  `stream` (optional) receives body chunks as
        they arrive; the returned body is then empty."""
        if self._closed:
            raise errors.RpcError(errors.EFAILEDSOCKET, "channel closed")
        L = lib()
        blob = None
        if headers:
            blob = "".join(f"{k}: {v}\r\n" for k, v in headers.items()
                           ).encode()
        cb = _CHUNK_CB()  # NULL function pointer (no streaming)
        keepalive = None
        if stream is not None:
            def _cb(_user, data, n):
                stream(ctypes.string_at(data, n))
            keepalive = _CHUNK_CB(_cb)
            cb = keepalive
            with self._lock:
                self._cb_refs.append(keepalive)
        result = ctypes.c_void_p()
        try:
            rc = L.trpc_http_client_call(
                self._handle, method.encode(), target.encode(), blob,
                body if body else None, len(body), int(timeout_ms * 1000),
                cb, None, ctypes.byref(result))
        except BaseException:
            raise  # trampoline stays in _cb_refs (freed at close())
        else:
            if keepalive is not None and rc == 0:
                # clean completion: the native side is done with the
                # trampoline (response fully parsed)
                with self._lock:
                    try:
                        self._cb_refs.remove(keepalive)
                    except ValueError:
                        pass
        try:
            if rc != 0:
                text = (L.trpc_http_result_error_text(result)
                        or b"").decode()
                raise errors.RpcError(rc, text or f"http error {rc}")
            status = L.trpc_http_result_status(result)
            p = ctypes.POINTER(ctypes.c_uint8)()
            n = L.trpc_http_result_headers(result, ctypes.byref(p))
            hdr_blob = ctypes.string_at(p, n).decode(
                "latin-1") if n else ""
            n2 = L.trpc_http_result_body(result, ctypes.byref(p))
            rbody = ctypes.string_at(p, n2) if n2 else b""
        finally:
            L.trpc_http_result_destroy(result)
        hdrs: Dict[str, str] = {}
        for line in hdr_blob.splitlines():
            k, _, v = line.partition(": ")
            if k:
                hdrs[k] = v
        return HttpResponse(status, hdrs, rbody)

    def get(self, target: str = "/", **kw) -> HttpResponse:
        return self.request("GET", target, **kw)

    def post(self, target: str, body: bytes = b"",
             **kw) -> HttpResponse:
        return self.request("POST", target, body=body, **kw)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        # destroy waits out the native connections, after which no chunk
        # callback can fire — trampolines are safe to drop
        lib().trpc_channel_destroy(self._handle)
        self._handle = None
        self._cb_refs.clear()
