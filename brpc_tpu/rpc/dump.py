"""rpc_dump — sampled request snapshotting + replay iteration
(≙ the reference rpc_dump.{h,cpp}: SampledRequest rpc_dump.h:50 throttled
by the bvar Collector :69, written to butil::recordio files with rotation,
RpcDumpContext rpc_dump.cpp:68,150; read back by SampleIterator rpc_dump.h:81
and replayed by tools/rpc_replay).

Enable with the ``rpc_dump`` flag; sampled inbound requests are serialized
(method, payload, attachment, compress/codec meta) into recordio files under
``rpc_dump_dir``, rotated by size.  ``SampleIterator`` yields them back for
tools.rpc_replay.

Two capture paths feed ONE record schema, but each request lands in
the segments exactly ONCE:

- Native path (canonical): the C++ flight recorder (native/src/dump.h)
  samples wire-form frames on the parse fibers — everything inbound,
  including the fast paths (inline echo, HbmEcho, redis-cache,
  stream/token frames) Python never sees — and ``drain_native()``
  pumps them through the same rotating writer.
- Python path (fallback): ``RpcDumpContext.sample()`` on the usercode
  dispatch, taken only while the native recorder is NOT armed
  (``trpc_dump_active() == 0``); the parse-fiber seam already captured
  the same frame otherwise, and sampling twice would double the
  segments — a doubled segment replays 2x the incident's traffic.

Records carry a leading schema-version byte (``0x02``); version-1 records
(no version byte, no meta) still deserialize, so old segments replay.
"""

from __future__ import annotations

import itertools
import json
import os
import struct
import threading
import time
from dataclasses import dataclass
from typing import Iterator, Optional

from brpc_tpu.utils import flags, recordio

SCHEMA_V2 = 0x02


def _push_dump(value) -> bool:
    """Flag validator doubling as the native push: the C++ flight
    recorder (native/src/dump.h) samples fast-path wire frames only
    while the native half of the switch is on.  Turning the flag on
    also arms the drain pump, so a runtime toggle (e.g. via /flags on
    a live server) never captures into rings nobody empties."""
    from brpc_tpu._native import lib
    lib().trpc_set_dump(1 if value else 0)
    if value:
        ensure_native_drain()
    return True


def _push_dump_budget(value) -> bool:
    if value < 0:
        return False
    from brpc_tpu._native import lib
    lib().trpc_set_dump_budget(int(value))
    return True


flags.define_bool("rpc_dump",
                  os.environ.get("TRPC_DUMP", "") not in ("", "0"),
                  "sample inbound requests to disk (TRPC_DUMP seeds the "
                  "boot default; the native capture rings follow this "
                  "switch through the validator)",
                  validator=_push_dump)
flags.define_string("rpc_dump_dir", "./rpc_dump",
                    "directory of rpc_dump sample files")
flags.define_int32("rpc_dump_max_requests_in_one_file", 1000,
                   "rotate after this many samples per file")
flags.define_int32("rpc_dump_max_files", 32,
                   "keep at most this many rotated files")
flags.define_int32("rpc_dump_max_samples_per_second",
                   int(os.environ.get("TRPC_DUMP_BUDGET", "") or 1024),
                   "sampling budget (≙ collector speed limit); shared "
                   "by the Python path and the native capture rings "
                   "(TRPC_DUMP_BUDGET seeds the boot default)",
                   validator=_push_dump_budget)


@dataclass
class SampledRequest:
    """One captured inbound request (≙ SampledRequest, rpc_dump.h:50).

    ``payload``/``attachment`` hold the WIRE form: still codec-encoded
    (``payload_codec``/``attach_codec``, meta tags 16/17) and/or
    compressed (``compress_type``, tag 6) — replay re-sends the exact
    bytes, stamping the captured tags verbatim."""
    method: str
    payload: bytes
    attachment: bytes = b""
    compress_type: int = 0
    timestamp: float = 0.0
    trace_id: int = 0
    span_id: int = 0
    payload_codec: int = 0
    attach_codec: int = 0
    stream_id: int = 0
    stream_frame_type: int = 0  # 0 = unary request

    def serialize(self) -> bytes:
        head = json.dumps({
            "method": self.method,
            "compress_type": self.compress_type,
            "timestamp": self.timestamp,
            "payload_len": len(self.payload),
            "attachment_len": len(self.attachment),
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "payload_codec": self.payload_codec,
            "attach_codec": self.attach_codec,
            "stream_id": self.stream_id,
            "stream_frame_type": self.stream_frame_type,
        }).encode()
        return b"%c%d\n%s%s%s" % (SCHEMA_V2, len(head), head,
                                  self.payload, self.attachment)

    @staticmethod
    def deserialize(blob: bytes) -> "SampledRequest":
        # version sniff: v2 leads with 0x02; v1 led straight with the
        # ASCII head-length digits — old segments keep deserializing
        if blob[:1] == bytes([SCHEMA_V2]):
            blob = blob[1:]
        elif not blob[:1].isdigit():
            raise ValueError("unknown sample schema version")
        nl = blob.index(b"\n")
        head_len = int(blob[:nl])
        head = json.loads(blob[nl + 1:nl + 1 + head_len])
        rest = blob[nl + 1 + head_len:]
        pl = head["payload_len"]
        return SampledRequest(
            method=head["method"],
            payload=rest[:pl],
            attachment=rest[pl:pl + head["attachment_len"]],
            compress_type=head["compress_type"],
            timestamp=head["timestamp"],
            trace_id=head.get("trace_id", 0),
            span_id=head.get("span_id", 0),
            payload_codec=head.get("payload_codec", 0),
            attach_codec=head.get("attach_codec", 0),
            stream_id=head.get("stream_id", 0),
            stream_frame_type=head.get("stream_frame_type", 0))


# Per-process writer discriminator: two contexts rotating in the same
# second (e.g. the server's Python-path context and the native-drain
# context) must never open the SAME segment file.
_ctx_ids = itertools.count()


class RpcDumpContext:
    """Per-server dump state: sampling budget + rotating writer
    (≙ RpcDumpContext, rpc_dump.cpp:68)."""

    def __init__(self, dir_path: Optional[str] = None):
        from brpc_tpu.metrics.collector import PerSecondBudget
        # dir resolved lazily at first rotate so a context constructed at
        # server init still honors a later rpc_dump_dir flag change
        self._dir_override = dir_path
        self._lock = threading.Lock()
        self._writer: Optional[recordio.RecordWriter] = None
        self._in_file = 0
        self._seq = 0
        self._tag = "%x-%d" % (os.getpid(), next(_ctx_ids))
        self._budget = PerSecondBudget("rpc_dump_max_samples_per_second")

    def _try_sample(self) -> bool:
        return self._budget.try_take()

    @property
    def _dir(self) -> str:
        return self._dir_override or str(flags.get_flag("rpc_dump_dir"))

    def _rotate(self) -> None:
        if self._writer is not None:
            self._writer.close()
        os.makedirs(self._dir, exist_ok=True)
        path = os.path.join(
            self._dir,
            f"requests.{int(time.time())}.{self._tag}.{self._seq:06d}")
        self._seq += 1
        self._writer = recordio.RecordWriter(path)
        self._in_file = 0
        # prune old files (reference keeps a bounded set of rotated files)
        keep = int(flags.get_flag("rpc_dump_max_files"))
        files = sorted(f for f in os.listdir(self._dir)
                       if f.startswith("requests."))
        for f in files[:-keep] if len(files) > keep else []:
            try:
                os.unlink(os.path.join(self._dir, f))
            except OSError:
                pass

    def _write_locked(self, blob: bytes) -> None:
        if (self._writer is None or self._in_file >=
                int(flags.get_flag("rpc_dump_max_requests_in_one_file"))):
            self._rotate()
        self._writer.write(blob)
        self._writer.flush()
        self._in_file += 1

    def sample(self, req: SampledRequest) -> bool:
        """Called on the server hot path; cheap no-op unless enabled and
        under budget."""
        if not flags.get_flag("rpc_dump"):
            return False
        with self._lock:
            if not self._try_sample():
                return False
            req.timestamp = time.time()
            self._write_locked(req.serialize())
            return True

    def write_blob(self, blob: bytes) -> None:
        """Write one already-serialized sample record (the native drain
        path: budget + meta were applied at capture time in C++)."""
        with self._lock:
            self._write_locked(blob)

    def close(self) -> None:
        with self._lock:
            if self._writer is not None:
                self._writer.close()
                self._writer = None


class SampleIterator:
    """Iterate every sample under a dump dir (≙ SampleIterator,
    rpc_dump.h:81)."""

    def __init__(self, dir_path: Optional[str] = None):
        self._dir = dir_path or str(flags.get_flag("rpc_dump_dir"))

    def __iter__(self) -> Iterator[SampledRequest]:
        if not os.path.isdir(self._dir):
            return
        for name in sorted(os.listdir(self._dir)):
            if not name.startswith("requests."):
                continue
            for blob in recordio.read_records(
                    os.path.join(self._dir, name)):
                try:
                    yield SampledRequest.deserialize(blob)
                except (ValueError, KeyError, IndexError):
                    continue  # skip corrupt sample


# --- native capture drain ---------------------------------------------------
# The C++ rings (native/src/dump.cc) hold sampled wire frames already
# serialized at drain time into the v2 record schema; this side only
# splits the length-prefixed batch and appends through the rotating
# writer.  One module-level context so concurrent drains share one
# segment sequence (its filename tag keeps it apart from any
# server-owned Python-path context).

_native_ctx: Optional[RpcDumpContext] = None
_native_lock = threading.Lock()
_drain_lock = threading.Lock()
_pump_started = False


def _native_context() -> RpcDumpContext:
    global _native_ctx
    with _native_lock:
        if _native_ctx is None:
            _native_ctx = RpcDumpContext()
        return _native_ctx


def drain_native() -> int:
    """Move natively captured frames (dump.cc rings) into recordio
    segments under rpc_dump_dir (returns how many).  Runs at human /
    pump frequency; the native side is lock-free for its writers."""
    try:
        import ctypes
        from brpc_tpu._native import lib
    except Exception:
        return 0  # native core unavailable (exotic import contexts)
    ctx = _native_context()
    moved = 0
    with _drain_lock:
        buf = ctypes.create_string_buffer(1 << 20)
        while True:
            n = lib().trpc_dump_drain(buf, len(buf))
            if n == 0:
                break  # rings drained (a buffer-full stop returns > 0)
            raw = buf.raw[:n]
            off = 0
            while off + 4 <= len(raw):
                (blen,) = struct.unpack_from("<I", raw, off)
                off += 4
                if off + blen > len(raw):
                    break  # torn batch tail: impossible by construction
                ctx.write_blob(raw[off:off + blen])
                off += blen
                moved += 1
    return moved


def ensure_native_drain() -> None:
    """Start the background pump flushing the native capture rings to
    disk (idempotent; daemon thread).  Servers call this when rpc_dump
    turns on — without a pump the 64-slot rings would just lap."""
    global _pump_started
    with _native_lock:
        if _pump_started:
            return
        _pump_started = True

    def _pump() -> None:
        while True:
            time.sleep(0.25)
            try:
                # only pump while the FLAG holds the recorder on: a
                # harness arming the native switch directly (tests, the
                # stress child) drains by hand, and a background steal
                # between its captures and its own drain would race it
                if flags.get_flag("rpc_dump"):
                    drain_native()
            except Exception:
                return  # interpreter teardown

    threading.Thread(target=_pump, name="rpc-dump-drain",
                     daemon=True).start()
