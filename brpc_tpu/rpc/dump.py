"""rpc_dump — sampled request snapshotting + replay iteration
(≙ the reference rpc_dump.{h,cpp}: SampledRequest rpc_dump.h:50 throttled
by the bvar Collector :69, written to butil::recordio files with rotation,
RpcDumpContext rpc_dump.cpp:68,150; read back by SampleIterator rpc_dump.h:81
and replayed by tools/rpc_replay).

Enable with the ``rpc_dump`` flag; sampled inbound requests are serialized
(method, payload, attachment, compress type) into recordio files under
``rpc_dump_dir``, rotated by size.  ``SampleIterator`` yields them back for
tools.rpc_replay.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Iterator, Optional

from brpc_tpu.utils import flags, recordio

flags.define_bool("rpc_dump", False, "sample inbound requests to disk")
flags.define_string("rpc_dump_dir", "./rpc_dump",
                    "directory of rpc_dump sample files")
flags.define_int32("rpc_dump_max_requests_in_one_file", 1000,
                   "rotate after this many samples per file")
flags.define_int32("rpc_dump_max_files", 32,
                   "keep at most this many rotated files")
flags.define_int32("rpc_dump_max_samples_per_second", 1024,
                   "sampling budget (≙ collector speed limit)")


@dataclass
class SampledRequest:
    """One captured inbound request (≙ SampledRequest, rpc_dump.h:50)."""
    method: str
    payload: bytes
    attachment: bytes = b""
    compress_type: int = 0
    timestamp: float = 0.0

    def serialize(self) -> bytes:
        head = json.dumps({
            "method": self.method,
            "compress_type": self.compress_type,
            "timestamp": self.timestamp,
            "payload_len": len(self.payload),
            "attachment_len": len(self.attachment),
        }).encode()
        return b"%d\n%s%s%s" % (len(head), head, self.payload,
                                self.attachment)

    @staticmethod
    def deserialize(blob: bytes) -> "SampledRequest":
        nl = blob.index(b"\n")
        head_len = int(blob[:nl])
        head = json.loads(blob[nl + 1:nl + 1 + head_len])
        rest = blob[nl + 1 + head_len:]
        pl = head["payload_len"]
        return SampledRequest(
            method=head["method"],
            payload=rest[:pl],
            attachment=rest[pl:pl + head["attachment_len"]],
            compress_type=head["compress_type"],
            timestamp=head["timestamp"])


class RpcDumpContext:
    """Per-server dump state: sampling budget + rotating writer
    (≙ RpcDumpContext, rpc_dump.cpp:68)."""

    def __init__(self, dir_path: Optional[str] = None):
        from brpc_tpu.metrics.collector import PerSecondBudget
        # dir resolved lazily at first rotate so a context constructed at
        # server init still honors a later rpc_dump_dir flag change
        self._dir_override = dir_path
        self._lock = threading.Lock()
        self._writer: Optional[recordio.RecordWriter] = None
        self._in_file = 0
        self._seq = 0
        self._budget = PerSecondBudget("rpc_dump_max_samples_per_second")

    def _try_sample(self) -> bool:
        return self._budget.try_take()

    @property
    def _dir(self) -> str:
        return self._dir_override or str(flags.get_flag("rpc_dump_dir"))

    def _rotate(self) -> None:
        if self._writer is not None:
            self._writer.close()
        os.makedirs(self._dir, exist_ok=True)
        path = os.path.join(
            self._dir, f"requests.{int(time.time())}.{self._seq:06d}")
        self._seq += 1
        self._writer = recordio.RecordWriter(path)
        self._in_file = 0
        # prune old files (reference keeps a bounded set of rotated files)
        keep = int(flags.get_flag("rpc_dump_max_files"))
        files = sorted(f for f in os.listdir(self._dir)
                       if f.startswith("requests."))
        for f in files[:-keep] if len(files) > keep else []:
            try:
                os.unlink(os.path.join(self._dir, f))
            except OSError:
                pass

    def sample(self, req: SampledRequest) -> bool:
        """Called on the server hot path; cheap no-op unless enabled and
        under budget."""
        if not flags.get_flag("rpc_dump"):
            return False
        with self._lock:
            if not self._try_sample():
                return False
            if (self._writer is None or self._in_file >=
                    int(flags.get_flag("rpc_dump_max_requests_in_one_file"))):
                self._rotate()
            req.timestamp = time.time()
            self._writer.write(req.serialize())
            self._writer.flush()
            self._in_file += 1
            return True

    def close(self) -> None:
        with self._lock:
            if self._writer is not None:
                self._writer.close()
                self._writer = None


class SampleIterator:
    """Iterate every sample under a dump dir (≙ SampleIterator,
    rpc_dump.h:81)."""

    def __init__(self, dir_path: Optional[str] = None):
        self._dir = dir_path or str(flags.get_flag("rpc_dump_dir"))

    def __iter__(self) -> Iterator[SampledRequest]:
        if not os.path.isdir(self._dir):
            return
        for name in sorted(os.listdir(self._dir)):
            if not name.startswith("requests."):
                continue
            for blob in recordio.read_records(
                    os.path.join(self._dir, name)):
                try:
                    yield SampledRequest.deserialize(blob)
                except (ValueError, KeyError, IndexError):
                    continue  # skip corrupt sample
