"""Shared socket read helpers for the pure-python protocol clients."""

from __future__ import annotations

import socket


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes or raise ConnectionError on EOF."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed mid-message")
        buf += chunk
    return bytes(buf)
