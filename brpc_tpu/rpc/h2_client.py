"""HTTP/2 client + gRPC client (≙ the client half of
policy/http2_rpc_protocol.cpp and grpc.h:208 semantics).

The connection (native h2.cc client section) multiplexes concurrent
calls over one socket with HPACK request encoding and send-side flow
control; gRPC layers its 5-byte message framing and grpc-status
trailers on top — so brpc_tpu services exposed via add_grpc_service are
callable without grpcio.
"""

from __future__ import annotations

import ctypes
import struct
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from brpc_tpu._native import lib
from brpc_tpu.rpc import errors

__all__ = ["H2Response", "H2Channel", "GrpcError", "GrpcChannel"]


@dataclass
class H2Response:
    status: int
    headers: Dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    trailers: Dict[str, str] = field(default_factory=dict)


def _parse_lines(blob: bytes) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for line in blob.decode("latin-1").splitlines():
        k, _, v = line.partition(": ")
        if k:
            out[k] = v
    return out


class H2Channel:
    """h2c (prior-knowledge) client connection.  Calls are thread-safe
    and multiplex concurrently on one socket."""

    def __init__(self, target: str, connect_timeout_ms: float = 1000.0,
                 tls: bool = False, tls_verify: bool = True,
                 tls_ca_file: Optional[str] = None):
        import socket as _socket
        host, _, port = target.rpartition(":")
        # the native side takes IPv4 literals only; resolve names here
        ip = _socket.gethostbyname(host or "127.0.0.1")
        rc = ctypes.c_int()
        if tls:
            self._handle = lib().trpc_h2_client_create_tls(
                ip.encode(), int(port), int(connect_timeout_ms * 1000),
                1 if tls_verify else 0,
                tls_ca_file.encode() if tls_ca_file else None,
                ctypes.byref(rc))
        else:
            self._handle = lib().trpc_h2_client_create(
                ip.encode(), int(port), int(connect_timeout_ms * 1000),
                ctypes.byref(rc))
        if not self._handle:
            raise errors.RpcError(rc.value, f"h2 connect to {target} failed")

    def request(self, method: str, path: str,
                headers: Optional[Dict[str, str]] = None,
                body: bytes = b"",
                timeout_ms: float = 10_000.0) -> H2Response:
        if self._handle is None:
            raise errors.RpcError(errors.EFAILEDSOCKET, "channel closed")
        L = lib()
        blob = None
        if headers:
            blob = "".join(f"{k}: {v}\r\n"
                           for k, v in headers.items()).encode()
        result = ctypes.c_void_p()
        rc = L.trpc_h2_client_call(
            self._handle, method.encode(), path.encode(), blob,
            body if body else None, len(body), int(timeout_ms * 1000),
            ctypes.byref(result))
        try:
            if rc != 0:
                raise errors.RpcError(rc, f"h2 call failed ({rc})")
            status = L.trpc_h2_result_status(result)
            p = ctypes.POINTER(ctypes.c_uint8)()
            n = L.trpc_h2_result_headers(result, ctypes.byref(p))
            hdrs = _parse_lines(ctypes.string_at(p, n) if n else b"")
            n = L.trpc_h2_result_body(result, ctypes.byref(p))
            rbody = ctypes.string_at(p, n) if n else b""
            n = L.trpc_h2_result_trailers(result, ctypes.byref(p))
            trls = _parse_lines(ctypes.string_at(p, n) if n else b"")
        finally:
            L.trpc_h2_result_destroy(result)
        return H2Response(status, hdrs, rbody, trls)

    def get(self, path: str, **kw) -> H2Response:
        return self.request("GET", path, **kw)

    def post(self, path: str, body: bytes = b"", **kw) -> H2Response:
        return self.request("POST", path, body=body, **kw)

    def close(self) -> None:
        if self._handle is not None:
            lib().trpc_h2_client_destroy(self._handle)
            self._handle = None


class GrpcError(Exception):
    def __init__(self, code: int, message: str = ""):
        super().__init__(f"grpc-status {code}: {message}")
        self.code = code
        self.message = message


class GrpcChannel:
    """gRPC unary calls over the framework's own h2 client (no grpcio):
    POST /<Service>/<Method>, content-type application/grpc, 5-byte
    length-prefixed messages, grpc-status in the trailers."""

    def __init__(self, target: str, **kw):
        self._h2 = H2Channel(target, **kw)

    def call(self, service: str, method: str, request: bytes,
             timeout_ms: float = 10_000.0) -> bytes:
        framed = b"\x00" + struct.pack("!I", len(request)) + request
        resp = self._h2.post(
            f"/{service}/{method}", body=framed,
            headers={"content-type": "application/grpc", "te": "trailers"},
            timeout_ms=timeout_ms)
        status_map = dict(resp.trailers)
        if "grpc-status" not in status_map:
            status_map.update(resp.headers)  # trailers-only responses
        code = int(status_map.get("grpc-status", "2"))
        if code != 0:
            raise GrpcError(code, status_map.get("grpc-message", ""))
        if len(resp.body) < 5:
            return b""
        compressed, mlen = resp.body[0], struct.unpack("!I",
                                                       resp.body[1:5])[0]
        if compressed:
            raise GrpcError(12, "compressed grpc frames unsupported")
        return resp.body[5:5 + mlen]

    def close(self) -> None:
        self._h2.close()
