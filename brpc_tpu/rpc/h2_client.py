"""HTTP/2 client + gRPC client (≙ the client half of
policy/http2_rpc_protocol.cpp and grpc.h:208 semantics).

The connection (native h2.cc client section) multiplexes concurrent
calls over one socket with HPACK request encoding and send-side flow
control; gRPC layers its 5-byte message framing and grpc-status
trailers on top — so brpc_tpu services exposed via add_grpc_service are
callable without grpcio.
"""

from __future__ import annotations

import ctypes
import struct
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from brpc_tpu._native import lib
from brpc_tpu.rpc import errors

__all__ = ["H2Response", "H2Channel", "H2Stream", "GrpcError",
           "GrpcChannel", "GrpcStream"]


@dataclass
class H2Response:
    status: int
    headers: Dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    trailers: Dict[str, str] = field(default_factory=dict)


def _parse_lines(blob: bytes) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for line in blob.decode("latin-1").splitlines():
        k, _, v = line.partition(": ")
        if k:
            out[k] = v
    return out


class H2Channel:
    """h2c (prior-knowledge) client connection.  Calls are thread-safe
    and multiplex concurrently on one socket."""

    def __init__(self, target: str, connect_timeout_ms: float = 1000.0,
                 tls: bool = False, tls_verify: bool = True,
                 tls_ca_file: Optional[str] = None):
        import socket as _socket
        host, _, port = target.rpartition(":")
        # the native side takes IPv4 literals only; resolve names here
        ip = _socket.gethostbyname(host or "127.0.0.1")
        rc = ctypes.c_int()
        if tls:
            self._handle = lib().trpc_h2_client_create_tls(
                ip.encode(), int(port), int(connect_timeout_ms * 1000),
                1 if tls_verify else 0,
                tls_ca_file.encode() if tls_ca_file else None,
                ctypes.byref(rc))
        else:
            self._handle = lib().trpc_h2_client_create(
                ip.encode(), int(port), int(connect_timeout_ms * 1000),
                ctypes.byref(rc))
        if not self._handle:
            raise errors.RpcError(rc.value, f"h2 connect to {target} failed")

    def request(self, method: str, path: str,
                headers: Optional[Dict[str, str]] = None,
                body: bytes = b"",
                timeout_ms: float = 10_000.0) -> H2Response:
        if self._handle is None:
            raise errors.RpcError(errors.EFAILEDSOCKET, "channel closed")
        L = lib()
        blob = None
        if headers:
            blob = "".join(f"{k}: {v}\r\n"
                           for k, v in headers.items()).encode()
        result = ctypes.c_void_p()
        rc = L.trpc_h2_client_call(
            self._handle, method.encode(), path.encode(), blob,
            body if body else None, len(body), int(timeout_ms * 1000),
            ctypes.byref(result))
        try:
            if rc != 0:
                raise errors.RpcError(rc, f"h2 call failed ({rc})")
            status = L.trpc_h2_result_status(result)
            p = ctypes.POINTER(ctypes.c_uint8)()
            n = L.trpc_h2_result_headers(result, ctypes.byref(p))
            hdrs = _parse_lines(ctypes.string_at(p, n) if n else b"")
            n = L.trpc_h2_result_body(result, ctypes.byref(p))
            rbody = ctypes.string_at(p, n) if n else b""
            n = L.trpc_h2_result_trailers(result, ctypes.byref(p))
            trls = _parse_lines(ctypes.string_at(p, n) if n else b"")
        finally:
            L.trpc_h2_result_destroy(result)
        return H2Response(status, hdrs, rbody, trls)

    def open_stream(self, method: str, path: str,
                    headers: Optional[Dict[str, str]] = None) -> "H2Stream":
        """Open a streaming request (HEADERS only): write body chunks
        incrementally, half-close, and read the response body as chunks
        while the server is still sending (≙ ProgressiveReader both
        ways on one h2 stream)."""
        if self._handle is None:
            raise errors.RpcError(errors.EFAILEDSOCKET, "channel closed")
        blob = None
        if headers:
            blob = "".join(f"{k}: {v}\r\n"
                           for k, v in headers.items()).encode()
        rc = ctypes.c_int()
        h = lib().trpc_h2_stream_open(self._handle, method.encode(),
                                      path.encode(), blob, ctypes.byref(rc))
        if not h:
            raise errors.RpcError(rc.value, "h2 stream open failed")
        return H2Stream(h)

    def get(self, path: str, **kw) -> H2Response:
        return self.request("GET", path, **kw)

    def post(self, path: str, body: bytes = b"", **kw) -> H2Response:
        return self.request("POST", path, body=body, **kw)

    def close(self) -> None:
        if self._handle is not None:
            lib().trpc_h2_client_destroy(self._handle)
            self._handle = None


class H2Stream:
    """One streaming h2 request: incremental body out, incremental body
    in (chunks arrive while the server still streams)."""

    def __init__(self, handle):
        self._h = handle

    def write(self, data: bytes, timeout_ms: float = 10_000.0) -> None:
        rc = lib().trpc_h2_stream_write(self._h, data, len(data),
                                        int(timeout_ms * 1000))
        if rc != 0:
            raise errors.RpcError(rc, f"h2 stream write failed ({rc})")

    def close_send(self) -> None:
        rc = lib().trpc_h2_stream_close_send(self._h)
        if rc != 0:
            raise errors.RpcError(rc, f"h2 stream half-close failed ({rc})")

    def read(self, timeout_ms: float = 10_000.0) -> Optional[bytes]:
        """Next response-body chunk; None at EOF (status/headers/trailers
        are final then)."""
        L = lib()
        p = ctypes.POINTER(ctypes.c_uint8)()
        n = L.trpc_h2_stream_read(self._h, int(timeout_ms * 1000),
                                  ctypes.byref(p))
        if n > 0:
            try:
                return ctypes.string_at(p, n)
            finally:
                L.trpc_h2_stream_chunk_free(p)
        if n == 0:
            return None
        raise errors.RpcError(int(n), f"h2 stream read failed ({n})")

    @property
    def status(self) -> int:
        return lib().trpc_h2_stream_status(self._h)

    def headers(self) -> Dict[str, str]:
        p = ctypes.POINTER(ctypes.c_uint8)()
        n = lib().trpc_h2_stream_headers(self._h, ctypes.byref(p))
        return _parse_lines(ctypes.string_at(p, n) if n else b"")

    def trailers(self) -> Dict[str, str]:
        p = ctypes.POINTER(ctypes.c_uint8)()
        n = lib().trpc_h2_stream_trailers(self._h, ctypes.byref(p))
        return _parse_lines(ctypes.string_at(p, n) if n else b"")

    def destroy(self) -> None:
        if self._h is not None:
            lib().trpc_h2_stream_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.destroy()


def _grpc_timeout_value(timeout_ms: float) -> str:
    """gRPC TimeoutValue is at most 8 digits: escalate the unit when
    milliseconds overflow (the spec's own coarsening rule)."""
    ms = max(int(timeout_ms), 1)
    if ms <= 99_999_999:
        return f"{ms}m"
    seconds = ms // 1000
    if seconds <= 99_999_999:
        return f"{seconds}S"
    return f"{min(seconds // 3600, 99_999_999)}H"


class GrpcError(Exception):
    def __init__(self, code: int, message: str = ""):
        super().__init__(f"grpc-status {code}: {message}")
        self.code = code
        self.message = message


class GrpcChannel:
    """gRPC unary calls over the framework's own h2 client (no grpcio):
    POST /<Service>/<Method>, content-type application/grpc, 5-byte
    length-prefixed messages, grpc-status in the trailers."""

    def __init__(self, target: str, **kw):
        self._h2 = H2Channel(target, **kw)

    def call(self, service: str, method: str, request: bytes,
             timeout_ms: float = 10_000.0) -> bytes:
        framed = b"\x00" + struct.pack("!I", len(request)) + request
        resp = self._h2.post(
            f"/{service}/{method}", body=framed,
            headers={"content-type": "application/grpc", "te": "trailers",
                     # deadline propagation (≙ grpc.cpp:208 both ways)
                     "grpc-timeout": _grpc_timeout_value(timeout_ms)},
            timeout_ms=timeout_ms)
        status_map = dict(resp.trailers)
        if "grpc-status" not in status_map:
            status_map.update(resp.headers)  # trailers-only responses
        code = int(status_map.get("grpc-status", "2"))
        if code != 0:
            raise GrpcError(code, status_map.get("grpc-message", ""))
        if len(resp.body) < 5:
            return b""
        compressed, mlen = resp.body[0], struct.unpack("!I",
                                                       resp.body[1:5])[0]
        if compressed:
            raise GrpcError(12, "compressed grpc frames unsupported")
        return resp.body[5:5 + mlen]

    def streaming_call(self, service: str, method: str,
                       timeout_ms: float = 10_000.0) -> "GrpcStream":
        """Open a streaming gRPC call (client/server/bidi): send_message
        incrementally, done_sending to half-close, recv_message while the
        server still streams (None = end; grpc-status then checked)."""
        st = self._h2.open_stream(
            "POST", f"/{service}/{method}",
            headers={"content-type": "application/grpc", "te": "trailers",
                     "grpc-timeout": _grpc_timeout_value(timeout_ms)})
        return GrpcStream(st, timeout_ms)

    def close(self) -> None:
        self._h2.close()


class GrpcStream:
    """gRPC message framing over one streaming h2 call."""

    def __init__(self, h2_stream: H2Stream, timeout_ms: float):
        self._st = h2_stream
        self._timeout_ms = timeout_ms
        self._buf = b""
        self._eof = False

    def send_message(self, message: bytes) -> None:
        framed = b"\x00" + struct.pack("!I", len(message)) + message
        self._st.write(framed, timeout_ms=self._timeout_ms)

    def done_sending(self) -> None:
        self._st.close_send()

    def recv_message(self) -> Optional[bytes]:
        """Next response message; None when the server finished (then
        grpc-status from the trailers is raised if nonzero)."""
        while True:
            if len(self._buf) >= 5:
                compressed, mlen = self._buf[0], struct.unpack(
                    "!I", self._buf[1:5])[0]
                if len(self._buf) >= 5 + mlen:
                    if compressed:
                        raise GrpcError(12,
                                        "compressed grpc frames unsupported")
                    msg = self._buf[5:5 + mlen]
                    self._buf = self._buf[5 + mlen:]
                    return msg
            if self._eof:
                if self._buf:
                    raise GrpcError(13, "truncated grpc frame at EOF")
                status_map = self._st.trailers() or self._st.headers()
                code = int(status_map.get("grpc-status", "2"))
                if code != 0:
                    raise GrpcError(code,
                                    status_map.get("grpc-message", ""))
                return None
            chunk = self._st.read(timeout_ms=self._timeout_ms)
            if chunk is None:
                self._eof = True
            else:
                self._buf += chunk

    def __iter__(self):
        while True:
            m = self.recv_message()
            if m is None:
                return
            yield m

    def destroy(self) -> None:
        self._st.destroy()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.destroy()
