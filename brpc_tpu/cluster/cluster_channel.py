"""ClusterChannel — the cluster-mode guts of rpc.Channel
(≙ LoadBalancerWithNaming, details/load_balancer_with_naming.cpp, plus the
per-node fault tolerance from controller.cpp OnVersionedRPCReturned:
circuit breaking, exclusion, health-check revival).

One ClusterChannel = one naming URL + one LB + per-node native connections,
circuit breakers and health checking.  rpc.Channel owns retries/backup; this
layer owns "which server does this attempt go to".
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from brpc_tpu.cluster.circuit_breaker import CircuitBreaker
from brpc_tpu.cluster.health_check import HealthChecker
from brpc_tpu.cluster.load_balancer import (
    NoServerError,
    create_load_balancer,
)
from brpc_tpu.cluster.naming import (ServerNode, Watcher,
                                     acquire_naming_watcher)
from brpc_tpu.metrics import bvar
from brpc_tpu.rpc import errors


class _LBWatcher(Watcher):
    def __init__(self, channel: "ClusterChannel"):
        self.channel = channel

    def on_servers(self, added, removed, all_nodes):
        if added:
            self.channel.lb.add_servers_in_batch(added)
        if removed:
            self.channel.lb.remove_servers_in_batch(removed)
            self.channel._prune(removed)


class ClusterChannel:
    def __init__(self, address: str, options):
        from brpc_tpu.rpc.channel import SubChannel  # cycle: rpc ↔ cluster
        self._SubChannel = SubChannel
        self.options = options
        self.lb = create_load_balancer(options.load_balancer or "rr")
        self._subs: Dict[ServerNode, object] = {}
        self._breakers: Dict[ServerNode, CircuitBreaker] = {}
        self._lock = threading.Lock()
        self._health = HealthChecker(on_revive=self._on_revive)
        self._watcher = _LBWatcher(self)
        self._ns = acquire_naming_watcher(address, self._watcher)
        self._ns.wait_first_resolve()
        self._closed = False

    def _prune(self, removed) -> None:
        """Drop per-node state for ex-members so membership churn (DNS
        rotation etc.) doesn't leak native channels/fds."""
        with self._lock:
            subs = [self._subs.pop(n) for n in removed if n in self._subs]
            for n in removed:
                self._breakers.pop(n, None)
        for n in removed:
            self._health.discard(n)
        for s in subs:
            s.close()

    # -- node plumbing ------------------------------------------------------

    def _sub(self, node: ServerNode):
        with self._lock:
            sub = self._subs.get(node)
            if sub is None:
                sub = self._subs[node] = self._SubChannel(
                    node.endpoint, self.options.connect_timeout_ms,
                    getattr(self.options, "auth", None),
                    getattr(self.options, "connection_type", "single"))
            return sub

    def refresh_auth(self, cred: bytes) -> None:
        """Push a rotated credential (rpc/auth.py time-boxed HMAC) into
        every live member subchannel; new members pick it up from
        options.auth at creation."""
        from brpc_tpu._native import lib
        with self._lock:
            subs = list(self._subs.values())
        for s in subs:
            if getattr(s, "_handle", None):
                lib().trpc_channel_set_auth(s._handle, cred, len(cred))

    def _breaker(self, node: ServerNode) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(node)
            if br is None:
                br = self._breakers[node] = CircuitBreaker()
            return br

    def _on_revive(self, node: ServerNode) -> None:
        self._breaker(node).mark_recovered()

    def _isolated_nodes(self):
        with self._lock:
            items = list(self._breakers.items())
        return {n for n, br in items if br.is_isolated()}

    # -- one attempt (rpc.Channel drives retries around this) ---------------

    def call_once(self, method: bytes, payload: bytes, attachment: bytes,
                  timeout_us: int, cntl, stream_handle: int = 0,
                  compress: int = 0,
                  cancel_buf=None) -> Tuple[int, str, bytes, bytes]:
        # breaker-isolated nodes + nodes that already failed THIS call's
        # earlier attempts (≙ ExcludedServers): without the latter, sticky
        # LBs (c_md5) would re-pick the same dead node on every retry
        excluded = self._isolated_nodes() | cntl.excluded_nodes
        try:
            node = self.lb.select(request_code=cntl.log_id,
                                  excluded=excluded)
        except NoServerError:
            if not excluded:
                return (errors.ENOSERVICE, "no servers resolved", b"", b"")
            # every node isolated: pick through the breaker anyway rather
            # than failing hard (≙ ClusterRecoverPolicy letting probes in)
            try:
                node = self.lb.select(request_code=cntl.log_id)
            except NoServerError:
                return (errors.ENOSERVICE, "no servers resolved", b"", b"")
        sub = self._sub(node)
        t0 = time.monotonic_ns()
        if cancel_buf is None:  # hedged attempts pass their own cell
            cancel_buf = getattr(cntl, "_call_id_buf", None)
        code, text, data, att = sub.call_once(
            method, payload, attachment, timeout_us, stream_handle,
            compress, cancel_buf=cancel_buf)
        latency_us = (time.monotonic_ns() - t0) // 1000
        failed = code != 0
        shed = code == errors.ELIMIT
        # the client half of the overload survival loop (overload.h,
        # ISSUE 11): a server-side ELIMIT means the replica shed BEFORE
        # executing — (a) the LB leg treats it as a failure so the EWMA
        # weights (`la`) steer new traffic away from the saturated
        # replica, (b) the breaker records it as SOFT pressure that can
        # never trip isolation by itself (a shedding node is alive —
        # isolating it would dogpile the survivors), and (c) the
        # excluded set makes THIS call's retry land on a different
        # replica (≙ ExcludedServers), which is safe precisely because
        # a shed request never executed.
        self.lb.feedback(node, latency_us, failed)
        br = self._breaker(node)
        br.on_call_end(latency_us, failed and not shed, shed=shed)
        # pressure-steered LB (ISSUE 19): push the breaker's shed-rate
        # EMA into the LB after EVERY attempt, so `la`/`wrr` bleed
        # traffic off a slow-but-alive replica while its breaker is
        # still closed (soft steering before hard isolation).
        self.lb.set_pressure(node, br.pressure())
        if failed:
            cntl.excluded_nodes.add(node)
        if code == errors.EFAILEDSOCKET:
            self._health.mark_broken(node)
        cntl.remote_side = str(node.endpoint)
        return code, text, data, att

    def node_pressure(self):
        """Per-node shed-rate EMA (the breaker-fed EWMA signal): the
        health/LB view of which replicas are saturated right now."""
        with self._lock:
            items = list(self._breakers.items())
        return {n: br.pressure() for n, br in items}

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._ns.remove_watcher(self._watcher)
        self._health.stop()
        with self._lock:
            subs = list(self._subs.values())
            self._subs.clear()
        for s in subs:
            s.close()
