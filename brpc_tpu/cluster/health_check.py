"""Health checking — periodic probe of isolated/failed nodes, revive on
success (≙ details/health_check.cpp:146-241 HealthCheckTask: periodic
reconnect probe + optional app-level RPC check via health_check_path).

Probe pacing (ISSUE 19): each probe is jittered ±25% around its due time
so a mesh of clients that lost the same leaf at the same instant does not
re-probe it in lockstep, and a node that STAYS dead backs off
exponentially (interval × 2^fails, capped) — a long-dead leaf costs a
trickle of SYNs instead of a steady drumbeat (≙ the reference's
HealthCheckTask rescheduling at health_check_interval_s, plus the
defer-with-backoff idiom of its reconnect path).
"""

from __future__ import annotations

import random
import socket as pysocket
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from brpc_tpu.cluster.naming import ServerNode
from brpc_tpu.utils import logging as log


def tcp_probe(node: ServerNode, timeout_s: float = 0.5) -> bool:
    """Default probe: can we (re)connect? (≙ the reconnect probe)."""
    try:
        with pysocket.create_connection((node.endpoint.ip,
                                         node.endpoint.port),
                                        timeout=timeout_s):
            return True
    except OSError:
        return False


@dataclass
class _BrokenState:
    since: float        # when the node was first marked broken
    fails: int = 0      # consecutive failed probes (drives backoff)
    next_due: float = 0.0  # monotonic time of the next probe


class HealthChecker:
    """Watches broken nodes, revives them via on_revive when the probe
    passes.  `rpc_probe` (≙ health_check_path) upgrades the TCP probe to an
    application-level call."""

    # ±25% uniform jitter applied to every scheduling decision
    JITTER = 0.25

    def __init__(self, interval_s: float = 0.2,
                 probe: Callable[[ServerNode], bool] = tcp_probe,
                 on_revive: Optional[Callable[[ServerNode], None]] = None,
                 max_backoff_s: Optional[float] = None):
        self.interval_s = interval_s
        # backoff ceiling: a dead node is probed at least this often
        self.max_backoff_s = (max_backoff_s if max_backoff_s is not None
                              else interval_s * 16)
        self.probe = probe
        self.on_revive = on_revive
        self._broken: Dict[ServerNode, _BrokenState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rng = random.Random()

    def _jittered(self, base_s: float) -> float:
        return base_s * (1.0 - self.JITTER
                         + 2.0 * self.JITTER * self._rng.random())

    def mark_broken(self, node: ServerNode) -> None:
        with self._lock:
            if node not in self._broken:
                now = time.monotonic()
                # first probe after one jittered interval (not instantly:
                # the breaker just saw the failure, give the node a beat)
                self._broken[node] = _BrokenState(
                    since=now, next_due=now + self._jittered(self.interval_s))
            self._ensure_thread_locked()

    def discard(self, node: ServerNode) -> None:
        with self._lock:
            self._broken.pop(node, None)

    def broken_nodes(self):
        with self._lock:
            return list(self._broken)

    def probe_backlog(self):
        """Diagnostic view: node -> consecutive failed probes."""
        with self._lock:
            return {n: st.fails for n, st in self._broken.items()}

    def stop(self) -> None:
        self._stop.set()

    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="health_check", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        # tick faster than interval_s so jittered due-times are honored
        # with reasonable resolution; each node still probes only when
        # its own (jittered, backed-off) due time arrives
        tick = max(self.interval_s / 4.0, 0.01)
        while not self._stop.wait(tick):
            now = time.monotonic()
            with self._lock:
                if not self._broken:
                    return  # exit when idle; restarted on next mark_broken
                due = [n for n, st in self._broken.items()
                       if st.next_due <= now]
            for node in due:
                if self.probe(node):
                    with self._lock:
                        st = self._broken.pop(node, None)
                    if st is not None:
                        log.LOG(log.LOG_INFO,
                                "health check revived %s after %.1fs "
                                "(%d failed probes)",
                                node, time.monotonic() - st.since, st.fails)
                        if self.on_revive is not None:
                            self.on_revive(node)
                else:
                    with self._lock:
                        st = self._broken.get(node)
                        if st is not None:
                            st.fails += 1
                            backoff = min(
                                self.interval_s * (2.0 ** st.fails),
                                self.max_backoff_s)
                            st.next_due = (time.monotonic()
                                           + self._jittered(backoff))
