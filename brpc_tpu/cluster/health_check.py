"""Health checking — periodic probe of isolated/failed nodes, revive on
success (≙ details/health_check.cpp:146-241 HealthCheckTask: periodic
reconnect probe + optional app-level RPC check via health_check_path).
"""

from __future__ import annotations

import socket as pysocket
import threading
import time
from typing import Callable, Dict, Optional

from brpc_tpu.cluster.naming import ServerNode
from brpc_tpu.utils import logging as log


def tcp_probe(node: ServerNode, timeout_s: float = 0.5) -> bool:
    """Default probe: can we (re)connect? (≙ the reconnect probe)."""
    try:
        with pysocket.create_connection((node.endpoint.ip,
                                         node.endpoint.port),
                                        timeout=timeout_s):
            return True
    except OSError:
        return False


class HealthChecker:
    """Watches broken nodes, revives them via on_revive when the probe
    passes.  `rpc_probe` (≙ health_check_path) upgrades the TCP probe to an
    application-level call."""

    def __init__(self, interval_s: float = 0.2,
                 probe: Callable[[ServerNode], bool] = tcp_probe,
                 on_revive: Optional[Callable[[ServerNode], None]] = None):
        self.interval_s = interval_s
        self.probe = probe
        self.on_revive = on_revive
        self._broken: Dict[ServerNode, float] = {}  # node -> since
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def mark_broken(self, node: ServerNode) -> None:
        with self._lock:
            if node not in self._broken:
                self._broken[node] = time.monotonic()
            self._ensure_thread_locked()

    def discard(self, node: ServerNode) -> None:
        with self._lock:
            self._broken.pop(node, None)

    def broken_nodes(self):
        with self._lock:
            return list(self._broken)

    def stop(self) -> None:
        self._stop.set()

    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="health_check", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            with self._lock:
                nodes = list(self._broken)
            if not nodes:
                return  # exit when idle; restarted on next mark_broken
            for node in nodes:
                if self.probe(node):
                    with self._lock:
                        since = self._broken.pop(node, None)
                    if since is not None:
                        log.LOG(log.LOG_INFO,
                                "health check revived %s after %.1fs",
                                node, time.monotonic() - since)
                        if self.on_revive is not None:
                            self.on_revive(node)
