"""CircuitBreaker — per-node error isolation.

≙ reference circuit_breaker.h:25-88: two EMA windows (long + short) over
error rate; when either window's error count exceeds its budget the node is
isolated; the isolation duration doubles on repeated isolation within
`window_s` and resets after a quiet period.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass


@dataclass
class CircuitBreakerOptions:
    # max error RATE the long/short windows tolerate (reference flags
    # circuit_breaker_{long,short}_window_error_percent)
    long_window: int = 128          # samples
    long_error_percent: int = 50
    short_window: int = 32
    short_error_percent: int = 75
    min_isolation_s: float = 0.1
    max_isolation_s: float = 30.0
    # after this long without isolation, the doubling resets
    reset_after_s: float = 60.0


class _EmaWindow:
    """EMA over a nominal sample window: error fraction with decay
    alpha = 1/window (≙ circuit_breaker.cpp EmaErrorRecorder)."""

    def __init__(self, window: int, max_error_percent: int):
        self.alpha = 1.0 / window
        self.limit = max_error_percent / 100.0
        self.ema = 0.0
        self.samples = 0
        self.window = window

    def record(self, failed: bool) -> bool:
        """Returns False when the node should be isolated."""
        self.samples += 1
        self.ema += self.alpha * ((1.0 if failed else 0.0) - self.ema)
        if self.samples < self.window // 2:
            return True  # not enough signal yet
        return self.ema < self.limit

    def reset(self):
        self.ema = 0.0
        self.samples = 0


class CircuitBreaker:
    def __init__(self, options: CircuitBreakerOptions = None):
        self.opt = options or CircuitBreakerOptions()
        self._long = _EmaWindow(self.opt.long_window,
                                self.opt.long_error_percent)
        self._short = _EmaWindow(self.opt.short_window,
                                 self.opt.short_error_percent)
        self._lock = threading.Lock()
        self._isolated_until = 0.0
        self._isolation_s = self.opt.min_isolation_s
        self._last_isolation = 0.0
        self._pressure = 0.0  # shed-rate EMA (soft ELIMIT feedback)
        self.isolated_times = 0

    # EMA decay of the shed-pressure signal: ~32 calls of memory — fast
    # enough to clear once a replica stops shedding, slow enough that
    # the EWMA LB leg sees sustained pressure, not single rejects
    PRESSURE_ALPHA = 1.0 / 32.0

    def on_call_end(self, latency_us: int, failed: bool,
                    shed: bool = False) -> bool:
        """Record one call (≙ OnCallEnd, circuit_breaker.h:38).
        Returns False if the node just tripped into isolation.

        `shed` marks a server-side ELIMIT (the overload plane rejected
        before executing): SOFT feedback only — it feeds the pressure
        EMA that weights the LB away from the saturated replica, but it
        never counts toward the error windows, so shedding alone can
        never trip isolation (a shedding server is alive and healthy;
        isolating it would dogpile the survivors)."""
        with self._lock:
            if shed:
                self._pressure += self.PRESSURE_ALPHA * (1.0 - self._pressure)
                return True
            self._pressure += self.PRESSURE_ALPHA * (0.0 - self._pressure)
            ok = self._long.record(failed) and self._short.record(failed)
            if not ok:
                self._isolate_locked()
            return ok

    def pressure(self) -> float:
        """EMA fraction of recent calls the node shed with ELIMIT
        (0.0-1.0) — the breaker-fed signal the EWMA LB leg steers on."""
        with self._lock:
            return self._pressure

    def is_isolated(self) -> bool:
        with self._lock:
            return time.monotonic() < self._isolated_until

    def remaining_isolation_s(self) -> float:
        with self._lock:
            return max(0.0, self._isolated_until - time.monotonic())

    def mark_recovered(self) -> None:
        """Health check succeeded: close the breaker
        (≙ Reset on revive)."""
        with self._lock:
            self._isolated_until = 0.0
            self._long.reset()
            self._short.reset()

    def _isolate_locked(self) -> None:
        now = time.monotonic()
        if now - self._last_isolation > self.opt.reset_after_s:
            self._isolation_s = self.opt.min_isolation_s
        self._isolated_until = now + self._isolation_s
        self._last_isolation = now
        self._isolation_s = min(self._isolation_s * 2,
                                self.opt.max_isolation_s)
        self.isolated_times += 1
        self._long.reset()
        self._short.reset()
