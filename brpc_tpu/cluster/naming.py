"""Naming services — cluster membership sources.

≙ reference naming_service.h:36-61 (`RunNamingService` pushing `ResetServers`
diffs) + details/naming_service_thread.h:58,136 (one shared thread per URL).

A NamingService yields full server lists; the NamingServiceThread diffs them
and notifies watchers (load balancers) with add/remove batches, so LBs apply
membership changes without stopping traffic (DoublyBufferedData underneath).

URLs: ``list://ip:port[ tag][,ip:port...]`` (inline),
``file:///path`` (one "ip:port [tag]" per line, # comments),
``dns://host:port`` (re-resolved every poll).
Partition tags "N/M" are parsed by PartitionChannel (parallel/channels.py).
"""

from __future__ import annotations

import os
import socket as pysocket
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from brpc_tpu.utils import logging as log
from brpc_tpu.utils.endpoint import EndPoint, str2endpoint


@dataclass(frozen=True)
class ServerNode:
    """One cluster member (≙ reference ServerNode: EndPoint + tag)."""
    endpoint: EndPoint
    tag: str = ""
    weight: int = 1

    def __str__(self):
        return f"{self.endpoint}" + (f" {self.tag}" if self.tag else "")


class NamingService:
    """Subclass and implement get_servers(); poll-style services set
    poll_interval_s (≙ PeriodicNamingService)."""

    poll_interval_s: float = 5.0

    def __init__(self, param: str):
        self.param = param

    def get_servers(self) -> List[ServerNode]:
        raise NotImplementedError

    @staticmethod
    def parse_nodes(lines: Sequence[str]) -> List[ServerNode]:
        nodes = []
        for raw in lines:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split(None, 1)
            ep = str2endpoint(parts[0])
            tag = parts[1].strip() if len(parts) > 1 else ""
            nodes.append(ServerNode(ep, tag))
        return nodes


class ListNamingService(NamingService):
    """list://ip:port[ tag],ip:port[ tag],...  — static inline membership."""

    poll_interval_s = 0.0  # static: resolve once

    def get_servers(self) -> List[ServerNode]:
        return self.parse_nodes(self.param.split(","))


class FileNamingService(NamingService):
    """file:///path — re-read when mtime changes (reference
    policy/file_naming_service.cpp watches the file)."""

    poll_interval_s = 0.5

    def get_servers(self) -> List[ServerNode]:
        with open(self.param) as f:
            return self.parse_nodes(f.readlines())


class DNSNamingService(NamingService):
    """dns://host:port — getaddrinfo on every poll."""

    poll_interval_s = 5.0

    def get_servers(self) -> List[ServerNode]:
        host, _, port = self.param.rpartition(":")
        infos = pysocket.getaddrinfo(host, int(port), pysocket.AF_INET,
                                     pysocket.SOCK_STREAM)
        nodes = []
        seen = set()
        for info in infos:
            ip = info[4][0]
            if ip not in seen:
                seen.add(ip)
                nodes.append(ServerNode(EndPoint(ip=ip, port=int(port))))
        return nodes


_NS_REGISTRY: Dict[str, type] = {
    "list": ListNamingService,
    "file": FileNamingService,
    "dns": DNSNamingService,
}


def register_naming_service(scheme: str, cls: type) -> None:
    """Extension point (≙ RegisterNamingService, global.cpp:352)."""
    _NS_REGISTRY[scheme] = cls


# ---------------------------------------------------------------------------
# NamingServiceThread — shared per URL, diffs lists, fans out to watchers


class Watcher:
    """Receives membership diffs (≙ NamingServiceActions)."""

    def on_servers(self, added: List[ServerNode],
                   removed: List[ServerNode],
                   all_nodes: List[ServerNode]) -> None:
        raise NotImplementedError


class NamingServiceThread:
    def __init__(self, url: str,
                 ns_filter: Optional[Callable[[ServerNode], bool]] = None):
        scheme, _, param = url.partition("://")
        if scheme not in _NS_REGISTRY:
            raise ValueError(f"unknown naming scheme '{scheme}://' "
                             f"(known: {sorted(_NS_REGISTRY)})")
        self.url = url
        self.ns = _NS_REGISTRY[scheme](param)
        self.filter = ns_filter
        self._watchers: List[Watcher] = []
        self._nodes: List[ServerNode] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._resolved_once = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"ns:{url}", daemon=True)
        self._thread.start()

    def add_watcher(self, w: Watcher) -> None:
        with self._lock:
            self._watchers.append(w)
            nodes = list(self._nodes)
        if nodes:
            w.on_servers(nodes, [], nodes)

    def remove_watcher(self, w: Watcher) -> None:
        with self._lock:
            if w in self._watchers:
                self._watchers.remove(w)

    def wait_first_resolve(self, timeout_s: float = 5.0) -> bool:
        return self._resolved_once.wait(timeout_s)

    def nodes(self) -> List[ServerNode]:
        with self._lock:
            return list(self._nodes)

    def stop(self) -> None:
        self._stop.set()

    def _poll_once(self) -> None:
        try:
            fresh = self.ns.get_servers()
        except Exception as e:  # naming outage: keep the last good list
            log.LOG(log.LOG_WARNING, "naming %s failed: %s", self.url, e)
            self._resolved_once.set()
            return
        if self.filter is not None:
            fresh = [n for n in fresh if self.filter(n)]
        with self._lock:
            old = set(self._nodes)
            new = set(fresh)
            added = [n for n in fresh if n not in old]
            removed = [n for n in self._nodes if n not in new]
            self._nodes = fresh
            watchers = list(self._watchers)
        if added or removed:
            for w in watchers:
                w.on_servers(added, removed, fresh)
        self._resolved_once.set()

    def _run(self) -> None:
        self._poll_once()
        interval = self.ns.poll_interval_s
        if interval <= 0:
            return  # static list
        while not self._stop.wait(interval):
            self._poll_once()


_threads: Dict[str, NamingServiceThread] = {}
_threads_lock = threading.Lock()


def get_naming_thread(url: str) -> NamingServiceThread:
    """Shared per URL (≙ GetNamingServiceThread,
    details/naming_service_thread.h:136)."""
    with _threads_lock:
        t = _threads.get(url)
        if t is None or not t._thread.is_alive():
            t = NamingServiceThread(url)
            _threads[url] = t
        return t
