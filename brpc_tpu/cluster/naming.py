"""Naming services — cluster membership sources.

≙ reference naming_service.h:36-61 (`RunNamingService` pushing `ResetServers`
diffs) + details/naming_service_thread.h:58,136 (one shared thread per URL).

A NamingService yields full server lists; the NamingServiceThread diffs them
and notifies watchers (load balancers) with add/remove batches, so LBs apply
membership changes without stopping traffic (DoublyBufferedData underneath).

URLs: ``list://ip:port[ tag][,ip:port...]`` (inline),
``file:///path`` (one "ip:port [tag]" per line, # comments),
``dns://host:port`` (re-resolved every poll).
Partition tags "N/M" are parsed by PartitionChannel (parallel/channels.py).
"""

from __future__ import annotations

import os
import socket as pysocket
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from brpc_tpu.utils import logging as log
from brpc_tpu.utils.endpoint import EndPoint, str2endpoint


@dataclass(frozen=True)
class ServerNode:
    """One cluster member (≙ reference ServerNode: EndPoint + tag)."""
    endpoint: EndPoint
    tag: str = ""
    weight: int = 1

    def __str__(self):
        return f"{self.endpoint}" + (f" {self.tag}" if self.tag else "")


class NamingService:
    """Subclass and implement get_servers(); poll-style services set
    poll_interval_s (≙ PeriodicNamingService)."""

    poll_interval_s: float = 5.0

    def __init__(self, param: str):
        self.param = param

    def get_servers(self) -> List[ServerNode]:
        raise NotImplementedError

    @staticmethod
    def parse_nodes(lines: Sequence[str]) -> List[ServerNode]:
        nodes = []
        for raw in lines:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split(None, 1)
            ep = str2endpoint(parts[0])
            tag = parts[1].strip() if len(parts) > 1 else ""
            nodes.append(ServerNode(ep, tag))
        return nodes


class ListNamingService(NamingService):
    """list://ip:port[ tag],ip:port[ tag],...  — static inline membership."""

    poll_interval_s = 0.0  # static: resolve once

    def get_servers(self) -> List[ServerNode]:
        return self.parse_nodes(self.param.split(","))


class FileNamingService(NamingService):
    """file:///path — re-read when mtime changes (reference
    policy/file_naming_service.cpp watches the file)."""

    poll_interval_s = 0.5

    def get_servers(self) -> List[ServerNode]:
        with open(self.param) as f:
            return self.parse_nodes(f.readlines())


class DNSNamingService(NamingService):
    """dns://host:port — getaddrinfo on every poll."""

    poll_interval_s = 5.0

    def get_servers(self) -> List[ServerNode]:
        host, _, port = self.param.rpartition(":")
        infos = pysocket.getaddrinfo(host, int(port), pysocket.AF_INET,
                                     pysocket.SOCK_STREAM)
        nodes = []
        seen = set()
        for info in infos:
            ip = info[4][0]
            if ip not in seen:
                seen.add(ip)
                nodes.append(ServerNode(EndPoint(ip=ip, port=int(port))))
        return nodes


class DomainListNamingService(NamingService):
    """dlist://host1:port1,host2:port2 — every entry DNS-resolved each
    poll (≙ policy/domain_naming_service.cpp over a list — the
    reference's dlist scheme); a name that fails to resolve drops out
    this round instead of failing the whole refresh."""

    poll_interval_s = 5.0

    def get_servers(self) -> List[ServerNode]:
        nodes = []
        seen = set()
        for entry in self.param.split(","):
            entry = entry.strip()
            if not entry:
                continue
            host, _, port = entry.rpartition(":")
            try:
                infos = pysocket.getaddrinfo(
                    host, int(port), pysocket.AF_INET, pysocket.SOCK_STREAM)
            except (OSError, ValueError):
                continue  # one dead name must not empty the cluster
            for info in infos:
                ip = info[4][0]
                if (ip, port) not in seen:
                    seen.add((ip, port))
                    nodes.append(ServerNode(EndPoint(ip=ip, port=int(port))))
        return nodes


class _HttpNamingBase(NamingService):
    """Shared plumbing for HTTP-backed naming (the framework's own HTTP
    client underneath): "host:port/path" param parsing and channel
    lifecycle — close() breaks any in-flight fetch and frees the native
    channel (called by NamingServiceThread at teardown)."""

    def __init__(self, param: str):
        super().__init__(param)
        hostport, slash, path = param.partition("/")
        self._target = "/" + path if slash else "/"
        from brpc_tpu.rpc.http_client import HttpChannel
        self._ch = HttpChannel(hostport, connection_type="pooled")

    def close(self) -> None:
        self._ch.close()


class RemoteFileNamingService(_HttpNamingBase):
    """remote_file://host:port/path — the membership file lives on an HTTP
    server and is fetched with the framework's own client
    (≙ policy/remote_file_naming_service.cpp, which pulls via brpc's
    http channel)."""

    poll_interval_s = 5.0

    def get_servers(self) -> List[ServerNode]:
        r = self._ch.get(self._target, timeout_ms=5000)
        if r.status != 200:
            raise IOError(f"remote_file fetch: HTTP {r.status}")
        return self.parse_nodes(r.body.decode().splitlines())


class WatchNamingService(_HttpNamingBase):
    """watch://host:port/path — PUSH-style membership via HTTP long-poll
    (≙ policy/consul_naming_service.cpp's blocking queries: the server
    holds the request until the list changes, so updates propagate
    immediately instead of waiting out a poll interval).

    Protocol (served by cluster.membership.MembershipRegistry):
      GET /path?index=N&wait_s=S
        -> 200 with the list + "x-list-index: M" once index != N (or on
           first call), or 304 if nothing changed within S seconds.
    """

    # wait budget per long-poll round; the server answers sooner on change
    wait_s = 20.0

    def __init__(self, param: str):
        super().__init__(param)
        self._index = 0

    @staticmethod
    def _index_of(resp) -> int:
        """A 200 MUST carry a numeric x-list-index — a server without it
        (plain file server, header-stripping proxy) would otherwise reset
        the index and turn the long-poll into a zero-delay busy loop."""
        raw = resp.headers.get("x-list-index")
        if raw is None:
            raise IOError("response missing x-list-index "
                          "(not a long-poll membership server)")
        try:
            return int(raw)
        except ValueError:
            raise IOError(f"bad x-list-index {raw!r}")

    def get_servers(self) -> List[ServerNode]:
        # non-blocking form for the initial resolve
        r = self._ch.get(f"{self._target}?index=0", timeout_ms=5000)
        if r.status != 200:
            raise IOError(f"watch fetch: HTTP {r.status}")
        self._index = self._index_of(r)
        return self.parse_nodes(r.body.decode().splitlines())

    def watch(self, emit: Callable[[List[ServerNode]], None],
              stop) -> None:
        """Blocking push loop: emit(list) on every change, immediately."""
        backoff = 0.05
        while not stop.is_set():
            try:
                r = self._ch.get(
                    f"{self._target}?index={self._index}"
                    f"&wait_s={self.wait_s}",
                    timeout_ms=(self.wait_s + 10.0) * 1000)
                if r.status == 200:
                    self._index = self._index_of(r)
                    emit(self.parse_nodes(r.body.decode().splitlines()))
                    backoff = 0.05
                elif r.status == 304:
                    continue  # no change within the wait budget
                else:
                    raise IOError(f"HTTP {r.status}")
            except Exception as e:
                if stop.is_set():
                    return
                log.LOG(log.LOG_WARNING, "watch %s: %s (retry in %.2fs)",
                        self.param, e, backoff)
                stop.wait(backoff)
                backoff = min(backoff * 2, 5.0)


_NS_REGISTRY: Dict[str, type] = {
    "list": ListNamingService,
    "file": FileNamingService,
    "dns": DNSNamingService,
    "dlist": DomainListNamingService,
    "remote_file": RemoteFileNamingService,
    "watch": WatchNamingService,
}


def register_naming_service(scheme: str, cls: type) -> None:
    """Extension point (≙ RegisterNamingService, global.cpp:352)."""
    _NS_REGISTRY[scheme] = cls


# ---------------------------------------------------------------------------
# NamingServiceThread — shared per URL, diffs lists, fans out to watchers


class Watcher:
    """Receives membership diffs (≙ NamingServiceActions)."""

    def on_servers(self, added: List[ServerNode],
                   removed: List[ServerNode],
                   all_nodes: List[ServerNode]) -> None:
        raise NotImplementedError


class NamingServiceThread:
    def __init__(self, url: str,
                 ns_filter: Optional[Callable[[ServerNode], bool]] = None):
        scheme, _, param = url.partition("://")
        if scheme not in _NS_REGISTRY:
            raise ValueError(f"unknown naming scheme '{scheme}://' "
                             f"(known: {sorted(_NS_REGISTRY)})")
        self.url = url
        self.ns = _NS_REGISTRY[scheme](param)
        self.filter = ns_filter
        self._watchers: List[Watcher] = []
        self._nodes: List[ServerNode] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._resolved_once = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"ns:{url}", daemon=True)
        self._thread.start()

    def add_watcher(self, w: Watcher) -> None:
        """Prefer acquire_naming_watcher(): it registers atomically with
        the shared-thread lookup, closing the window where the last
        watcher's removal stops the thread a new watcher just got."""
        with self._lock:
            self._watchers.append(w)
            nodes = list(self._nodes)
        if nodes:
            w.on_servers(nodes, [], nodes)

    def remove_watcher(self, w: Watcher) -> None:
        # under the global lock so it can't interleave with a concurrent
        # acquire_naming_watcher() on the same URL
        with _threads_lock:
            with self._lock:
                if w in self._watchers:
                    self._watchers.remove(w)
                last = not self._watchers
            if last:
                # nobody listening: stop the thread (matters for push-
                # style services, whose watch loop would otherwise
                # reconnect forever) and let the next lookup start fresh
                self.stop()
                if _threads.get(self.url) is self:
                    del _threads[self.url]

    def wait_first_resolve(self, timeout_s: float = 5.0) -> bool:
        return self._resolved_once.wait(timeout_s)

    def nodes(self) -> List[ServerNode]:
        with self._lock:
            return list(self._nodes)

    def stop(self) -> None:
        self._stop.set()
        # break any in-flight fetch/long-poll and free the native channel
        close = getattr(self.ns, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass

    def _apply(self, fresh: List[ServerNode]) -> None:
        """Diff a fresh full list against the current one and fan out
        add/remove batches to watchers (≙ ResetServers)."""
        if self.filter is not None:
            fresh = [n for n in fresh if self.filter(n)]
        with self._lock:
            old = set(self._nodes)
            new = set(fresh)
            added = [n for n in fresh if n not in old]
            removed = [n for n in self._nodes if n not in new]
            self._nodes = fresh
            watchers = list(self._watchers)
        if added or removed:
            for w in watchers:
                w.on_servers(added, removed, fresh)
        self._resolved_once.set()

    def _poll_once(self) -> None:
        try:
            fresh = self.ns.get_servers()
        except Exception as e:  # naming outage: keep the last good list
            log.LOG(log.LOG_WARNING, "naming %s failed: %s", self.url, e)
            self._resolved_once.set()
            return
        self._apply(fresh)

    def _run(self) -> None:
        self._poll_once()
        if hasattr(self.ns, "watch"):
            # push-style service: its blocking loop emits every change the
            # moment the remote side reports it (long-poll / streaming),
            # no poll interval involved
            self.ns.watch(self._apply, self._stop)
            return
        interval = self.ns.poll_interval_s
        if interval <= 0:
            return  # static list
        while not self._stop.wait(interval):
            self._poll_once()


_threads: Dict[str, NamingServiceThread] = {}
_threads_lock = threading.Lock()


def get_naming_thread(url: str) -> NamingServiceThread:
    """Shared per URL (≙ GetNamingServiceThread,
    details/naming_service_thread.h:136)."""
    with _threads_lock:
        return _get_locked(url)


def _get_locked(url: str) -> NamingServiceThread:
    t = _threads.get(url)
    if t is None or not t._thread.is_alive() or t._stop.is_set():
        t = NamingServiceThread(url)
        _threads[url] = t
    return t


def acquire_naming_watcher(url: str, w: Watcher) -> NamingServiceThread:
    """Atomically look up (or start) the URL's shared thread AND register
    the watcher — a concurrent last-watcher removal can't stop the thread
    in between (both paths hold _threads_lock)."""
    with _threads_lock:
        t = _get_locked(url)
        with t._lock:
            t._watchers.append(w)
            nodes = list(t._nodes)
    if nodes:
        w.on_servers(nodes, [], nodes)
    return t
