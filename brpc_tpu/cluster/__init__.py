"""cluster/ — naming services, load balancers, fault tolerance, admission
control (≙ the reference's policy/ + details/ client-cluster machinery,
SURVEY.md §2.4 "Load balancers"/"Naming services"/"Client fault tolerance"/
"Server admission control" rows).
"""

from brpc_tpu.cluster.naming import (  # noqa: F401
    NamingService,
    ServerNode,
    get_naming_thread,
    register_naming_service,
)
from brpc_tpu.cluster.load_balancer import (  # noqa: F401
    LoadBalancer,
    create_load_balancer,
    register_load_balancer,
)
from brpc_tpu.cluster.circuit_breaker import CircuitBreaker  # noqa: F401
from brpc_tpu.cluster.limiter import (  # noqa: F401
    AutoConcurrencyLimiter,
    ConstantConcurrencyLimiter,
    Interceptor,
    TimeoutConcurrencyLimiter,
)
from brpc_tpu.cluster.health_check import HealthChecker  # noqa: F401
