"""Server admission control (≙ concurrency_limiter.h:29-44 + policy/
{constant,auto,timeout}_concurrency_limiter.cpp + interceptor.h:26).

A limiter sees on_request (admit or reject with ELIMIT) and on_response
(with latency) — exactly the reference's OnRequest/OnResponded contract.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class ConcurrencyLimiter:
    def on_request(self) -> bool:
        raise NotImplementedError

    def on_response(self, latency_us: int, error: bool = False) -> None:
        raise NotImplementedError


class ConstantConcurrencyLimiter(ConcurrencyLimiter):
    """max N in-flight (≙ constant_concurrency_limiter.cpp)."""

    def __init__(self, max_concurrency: int):
        self.max = max_concurrency
        self._inflight = 0
        self._lock = threading.Lock()

    def on_request(self) -> bool:
        with self._lock:
            if self.max > 0 and self._inflight >= self.max:
                return False
            self._inflight += 1
            return True

    def on_response(self, latency_us: int, error: bool = False) -> None:
        with self._lock:
            self._inflight = max(self._inflight - 1, 0)

    @property
    def inflight(self) -> int:
        return self._inflight


class AutoConcurrencyLimiter(ConcurrencyLimiter):
    """Gradient limiter (≙ auto_concurrency_limiter.cpp, doc
    docs/cn/auto_concurrency_limiter.md):

      max_concurrency = max_qps * ((2+alpha) * min_latency - latency)

    where min_latency is an EMA of the best observed (no-load) latency and
    max_qps the peak measured throughput.  Periodically the limit is lowered
    to re-sample min_latency (the exploration step).
    """

    ALPHA = 0.3
    SAMPLE_WINDOW_S = 0.1
    MIN_SAMPLES = 10
    EXPLORE_EVERY = 20  # windows

    def __init__(self, max_concurrency: int = 40):
        self._limit = max_concurrency
        self._inflight = 0
        self._lock = threading.Lock()
        self._win_start = time.monotonic()
        self._win_count = 0
        self._win_lat_sum = 0
        self._min_latency_us: Optional[float] = None
        self._max_qps = 0.0
        self._windows = 0

    @property
    def max_concurrency(self) -> int:
        return int(self._limit)

    def on_request(self) -> bool:
        with self._lock:
            if self._inflight >= max(int(self._limit), 1):
                return False
            self._inflight += 1
            return True

    def on_response(self, latency_us: int, error: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            self._inflight = max(self._inflight - 1, 0)
            if error:
                return
            self._win_count += 1
            self._win_lat_sum += latency_us
            dt = now - self._win_start
            if dt >= self.SAMPLE_WINDOW_S and \
                    self._win_count >= self.MIN_SAMPLES:
                self._end_window_locked(dt)

    def _end_window_locked(self, dt: float) -> None:
        avg_lat = self._win_lat_sum / self._win_count
        qps = self._win_count / dt
        if self._min_latency_us is None:
            self._min_latency_us = avg_lat
        else:
            # fast decay downward, slow upward: track the no-load floor
            if avg_lat < self._min_latency_us:
                self._min_latency_us = avg_lat
            else:
                self._min_latency_us += 0.1 * (avg_lat
                                               - self._min_latency_us)
        self._max_qps = max(self._max_qps * 0.98, qps)
        self._windows += 1
        if self._windows % self.EXPLORE_EVERY == 0:
            # exploration: drop concurrency so min_latency can re-sample
            self._limit = max(self._limit * 0.75, 1)
        else:
            target = (self._max_qps / 1e6) * \
                ((2 + self.ALPHA) * self._min_latency_us - avg_lat)
            if target > 0:
                self._limit = 0.5 * self._limit + 0.5 * max(target, 1.0)
        self._win_start = time.monotonic()
        self._win_count = 0
        self._win_lat_sum = 0


class TimeoutConcurrencyLimiter(ConcurrencyLimiter):
    """Admit while the queue's expected wait stays under max_wait_ms
    (≙ timeout_concurrency_limiter.cpp: estimated latency * inflight
    vs the deadline)."""

    def __init__(self, max_wait_ms: float = 100.0):
        self.max_wait_us = max_wait_ms * 1000
        self._inflight = 0
        self._lat_ema_us = 1000.0
        self._lock = threading.Lock()

    def on_request(self) -> bool:
        with self._lock:
            expected_wait = self._lat_ema_us * self._inflight
            if expected_wait > self.max_wait_us:
                return False
            self._inflight += 1
            return True

    def on_response(self, latency_us: int, error: bool = False) -> None:
        with self._lock:
            self._inflight = max(self._inflight - 1, 0)
            if not error:
                self._lat_ema_us += 0.125 * (latency_us - self._lat_ema_us)


class Interceptor:
    """Global accept/reject hook before user code
    (≙ interceptor.h:26-37)."""

    def __init__(self, fn: Callable[[object], Optional[str]]):
        """fn(controller) -> None to accept, or an error string to reject."""
        self.fn = fn

    def process(self, cntl) -> Optional[str]:
        return self.fn(cntl)
