"""Membership registry — the server half of push-style naming.

Serves a cluster member list over HTTP with long-poll semantics (the
protocol `watch://` consumes, ≙ the consul blocking-query contract the
reference's consul_naming_service speaks):

    GET /members?index=N&wait_s=S
      200 + body "ip:port [tag]" lines + "x-list-index: M"   (list at
          version M != N — answered immediately, or the moment the list
          changes within the wait budget)
      304  (no change within S seconds)

Install on any Server; publishers call update() and every long-polling
watcher is answered at once — membership changes reach live load
balancers without waiting out a poll interval.
"""

from __future__ import annotations

import threading
from typing import Iterable, List

from brpc_tpu.cluster.naming import ServerNode


class MembershipRegistry:
    def __init__(self, initial: Iterable[ServerNode] = ()):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._nodes: List[ServerNode] = list(initial)
        self._index = 1

    def update(self, nodes: Iterable[ServerNode]) -> int:
        """Replace the list; wakes every parked long-poll immediately."""
        with self._cond:
            self._nodes = list(nodes)
            self._index += 1
            self._cond.notify_all()
            return self._index

    def nodes(self) -> List[ServerNode]:
        with self._lock:
            return list(self._nodes)

    def install(self, server, path: str = "/members",
                max_wait_s: float = 25.0) -> None:
        """Register the long-poll endpoint on `server`.

        NOTE: a parked long-poll occupies a usercode-pool thread for up
        to its wait budget; size usercode_workers for the number of
        concurrent watchers (the reference's consul agent has the same
        property per blocking query).
        """
        from brpc_tpu.rpc.http import HttpResponse

        def handler(req):
            q = req.query_params()
            try:
                index = int(q.get("index", "0"))
                wait_s = min(float(q.get("wait_s", "0") or 0), max_wait_s)
            except ValueError:
                return HttpResponse.text("bad index/wait_s\n", 400)
            with self._cond:
                if index == self._index and wait_s > 0:
                    self._cond.wait_for(lambda: self._index != index,
                                        timeout=wait_s)
                if index == self._index:
                    return HttpResponse.text("", 304)
                body = "\n".join(str(n) for n in self._nodes) + "\n"
                resp = HttpResponse.text(body)
                resp.headers["x-list-index"] = str(self._index)
                return resp

        server.register_http(path, handler)
