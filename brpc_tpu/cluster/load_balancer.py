"""Load balancers (≙ reference load_balancer.h:35-98 + policy/ LBs,
registered in global.cpp:368-377).

Server lists live in DoublyBufferedData so SelectServer is lock-free against
concurrent membership updates (the reference's stated reason for DBD,
load_balancer.h:72).  Feedback (latency/errors) flows back per node for
locality-aware weighting and circuit-breaker accounting.
"""

from __future__ import annotations

import bisect
import hashlib
import random
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from brpc_tpu.cluster.naming import ServerNode
from brpc_tpu.utils.doubly_buffered import DoublyBufferedData


class NoServerError(Exception):
    pass


class LoadBalancer:
    """AddServer/RemoveServer(+batch)/SelectServer/Feedback
    (≙ load_balancer.h:35-98)."""

    name = "base"

    def __init__(self):
        self._dbd: DoublyBufferedData[List[ServerNode]] = \
            DoublyBufferedData(list)

    # membership -----------------------------------------------------------
    def add_server(self, node: ServerNode) -> None:
        self.add_servers_in_batch([node])

    def remove_server(self, node: ServerNode) -> None:
        self.remove_servers_in_batch([node])

    def add_servers_in_batch(self, nodes: Sequence[ServerNode]) -> None:
        def mod(lst: List[ServerNode]):
            have = set(lst)
            lst.extend(n for n in nodes if n not in have)
            return True
        self._dbd.modify(mod)
        self._on_membership()

    def remove_servers_in_batch(self, nodes: Sequence[ServerNode]) -> None:
        gone = set(nodes)

        def mod(lst: List[ServerNode]):
            lst[:] = [n for n in lst if n not in gone]
            return True
        self._dbd.modify(mod)
        self._on_membership()

    def servers(self) -> List[ServerNode]:
        with self._dbd.read() as lst:
            return list(lst)

    # selection ------------------------------------------------------------
    def select(self, request_code: int = 0,
               excluded: Optional[set] = None) -> ServerNode:
        """≙ SelectServer; excluded = per-call blacklist
        (excluded_servers.h)."""
        with self._dbd.read() as lst:
            if not lst:
                raise NoServerError(f"no servers in {self.name} LB")
            node = self._pick(lst, request_code, excluded or ())
            if node is None:
                raise NoServerError("all servers excluded")
            return node

    def feedback(self, node: ServerNode, latency_us: int,
                 failed: bool) -> None:
        """≙ LoadBalancer::Feedback — only LA uses it by default."""

    def set_pressure(self, node: ServerNode, pressure: float) -> None:
        """Per-node soft-pressure hint (ISSUE 19): the circuit breaker's
        shed-rate EMA (0.0-1.0, CircuitBreaker.pressure()) pushed after
        every attempt so a slow-but-alive replica bleeds traffic BEFORE
        its breaker trips.  Pressure-aware LBs (la, wrr) override;
        default is a no-op so sticky/hashing LBs keep their placement
        contract."""

    # subclass hooks -------------------------------------------------------
    def _pick(self, lst, request_code, excluded) -> Optional[ServerNode]:
        raise NotImplementedError

    def _on_membership(self) -> None:
        pass


def _first_not_excluded(ordered, excluded):
    for n in ordered:
        if n not in excluded:
            return n
    return None


class RoundRobinLB(LoadBalancer):
    name = "rr"

    def __init__(self):
        super().__init__()
        self._i = 0
        self._lock = threading.Lock()

    def _pick(self, lst, request_code, excluded):
        with self._lock:
            start = self._i
            self._i += 1
        n = len(lst)
        return _first_not_excluded(
            (lst[(start + k) % n] for k in range(n)), excluded)


class WeightedRoundRobinLB(LoadBalancer):
    """Smooth WRR (the nginx algorithm): per-node current weight grows by
    its static weight each round; the max is picked and decremented by the
    total (≙ policy/weighted_round_robin_load_balancer.cpp semantics)."""

    name = "wrr"
    # pressure scaling resolution: static weights ride ×100 so the
    # (1 - pressure) scale keeps fractional precision in the smooth-WRR
    # integer arithmetic; a fully-pressured node keeps a trickle (never
    # drops to zero — its shed/latency signal must keep refreshing)
    PRESSURE_SCALE = 100

    def __init__(self):
        super().__init__()
        self._cw: Dict[ServerNode, int] = {}
        self._pressure: Dict[ServerNode, float] = {}
        self._lock = threading.Lock()

    def set_pressure(self, node: ServerNode, pressure: float) -> None:
        with self._lock:
            self._pressure[node] = min(max(pressure, 0.0), 1.0)

    def _pick(self, lst, request_code, excluded):
        with self._lock:
            total = 0
            best = None
            for n in lst:
                if n in excluded:
                    continue
                p = min(self._pressure.get(n, 0.0), 0.99)
                w = max(int(max(n.weight, 1)
                            * self.PRESSURE_SCALE * (1.0 - p)), 1)
                total += w
                self._cw[n] = self._cw.get(n, 0) + w
                if best is None or self._cw[n] > self._cw[best]:
                    best = n
            if best is not None:
                self._cw[best] -= total
            return best

    def _on_membership(self):
        with self._lock:
            live = set(self.servers())
            self._cw = {n: w for n, w in self._cw.items() if n in live}
            self._pressure = {n: p for n, p in self._pressure.items()
                              if n in live}


class RandomizedLB(LoadBalancer):
    name = "random"

    def _pick(self, lst, request_code, excluded):
        n = len(lst)
        start = random.randrange(n)
        return _first_not_excluded(
            (lst[(start + k) % n] for k in range(n)), excluded)


class WeightedRandomLB(LoadBalancer):
    name = "wrandom"

    def _pick(self, lst, request_code, excluded):
        cand = [n for n in lst if n not in excluded]
        if not cand:
            return None
        return random.choices(cand,
                              [max(n.weight, 1) for n in cand])[0]


def _hash_md5(data: bytes) -> int:
    return int.from_bytes(hashlib.md5(data).digest()[:8], "little")


def _hash_murmur(data: bytes) -> int:
    # 64-bit FNV-1a stand-in for murmurhash (same role: cheap, well-mixed;
    # the reference offers md5/murmur/ketama hashers, policy/hasher.cpp)
    h = 0xcbf29ce484222325
    for b in data:
        h = ((h ^ b) * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
    return h


class ConsistentHashLB(LoadBalancer):
    """Ketama-style ring: `replicas` virtual nodes per server; requests with
    the same request_code stick to the same server across membership churn
    (≙ policy/consistent_hashing_load_balancer.cpp, 3 hasher variants)."""

    name = "c_md5"
    replicas = 100

    def __init__(self, hasher: Callable[[bytes], int] = _hash_md5):
        super().__init__()
        self._hasher = hasher
        self._ring: List[int] = []
        self._ring_nodes: List[ServerNode] = []
        self._ring_lock = threading.Lock()

    def _on_membership(self):
        ring = []
        for node in self.servers():
            base = str(node.endpoint).encode()
            for r in range(self.replicas * max(node.weight, 1)):
                ring.append((self._hasher(base + b"#%d" % r), node))
        ring.sort(key=lambda t: t[0])
        with self._ring_lock:
            self._ring = [h for h, _ in ring]
            self._ring_nodes = [n for _, n in ring]

    def _pick(self, lst, request_code, excluded):
        with self._ring_lock:
            ring, nodes = self._ring, self._ring_nodes
        if not ring:
            return None
        i = bisect.bisect_left(ring, self._hasher(
            request_code.to_bytes(8, "little", signed=False)))
        n = len(ring)
        for k in range(n):
            node = nodes[(i + k) % n]
            if node not in excluded:
                return node
        return None


class ConsistentHashMurmurLB(ConsistentHashLB):
    name = "c_murmurhash"

    def __init__(self):
        super().__init__(hasher=_hash_murmur)


def _ketama_point(digest: bytes, j: int) -> int:
    """32-bit continuum point j (0..3) of one MD5 digest — libketama's
    byte order (≙ policy/hasher.cpp ketama points: four little-endian
    u32 points carved from each 16-byte digest)."""
    return ((digest[3 + j * 4] << 24) | (digest[2 + j * 4] << 16)
            | (digest[1 + j * 4] << 8) | digest[j * 4])


def _hash_ketama(data: bytes) -> int:
    # request-code side of the continuum: point 0 of the MD5 digest
    return _ketama_point(hashlib.md5(data).digest(), 0)


class KetamaLB(ConsistentHashLB):
    """Ketama continuum (reference replica-point semantics ≙
    policy/hasher.cpp + the c_ketama arm of
    consistent_hashing_load_balancer.cpp): each endpoint contributes
    virtual points in groups of FOUR per MD5 digest of "endpoint-i", and
    request codes land on the ring through the same 32-bit point formula
    — so our placements agree with other libketama-compatible rings.
    The ring walk itself is the base class's (_pick via _hash_ketama);
    only the replica-point generation differs."""

    name = "c_ketama"
    replicas = 100  # rounded up to whole 4-point digest groups

    def __init__(self):
        super().__init__(hasher=_hash_ketama)

    def _on_membership(self):
        ring = []
        for node in self.servers():
            base = str(node.endpoint).encode()
            groups = (self.replicas * max(node.weight, 1) + 3) // 4
            for i in range(groups):
                digest = hashlib.md5(base + b"-%d" % i).digest()
                for j in range(4):
                    ring.append((_ketama_point(digest, j), node))
        ring.sort(key=lambda t: t[0])
        with self._ring_lock:
            self._ring = [h for h, _ in ring]
            self._ring_nodes = [n for _, n in ring]


@dataclass
class _NodeStat:
    # EMA of latency + inflight count (≙ locality_aware_load_balancer.cpp
    # weight = 1 / (latency * inflight); doc docs/cn/lalb.md)
    latency_ema_us: float = 1000.0
    inflight: int = 0
    errors: int = 0
    pressure: float = 0.0  # breaker shed-rate EMA (ISSUE 19)


class LocalityAwareLB(LoadBalancer):
    """Weight ∝ 1 / (latency_ema * (inflight + 1) * (1 + k·pressure));
    feedback-driven, with the breaker's shed-rate EMA as a third leg
    (ISSUE 19) so a replica that sheds (or crawls behind a saturated
    NIC) bleeds traffic before its latency EMA fully catches up."""

    name = "la"
    DECAY = 0.85
    PRESSURE_K = 8.0  # pressure 1.0 → node costs 9× its unpressured self

    def __init__(self):
        super().__init__()
        self._stats: Dict[ServerNode, _NodeStat] = {}
        self._lock = threading.Lock()

    def set_pressure(self, node: ServerNode, pressure: float) -> None:
        with self._lock:
            st = self._stats.setdefault(node, _NodeStat())
            st.pressure = min(max(pressure, 0.0), 1.0)

    def _pick(self, lst, request_code, excluded):
        cand = [n for n in lst if n not in excluded]
        if not cand:
            return None
        with self._lock:
            weights = []
            for n in cand:
                st = self._stats.setdefault(n, _NodeStat())
                weights.append(1.0 / (max(st.latency_ema_us, 1.0)
                                      * (st.inflight + 1)
                                      * (1.0 + self.PRESSURE_K
                                         * st.pressure)))
            chosen = random.choices(cand, weights)[0]
            self._stats[chosen].inflight += 1
            return chosen

    def feedback(self, node: ServerNode, latency_us: int,
                 failed: bool) -> None:
        with self._lock:
            st = self._stats.setdefault(node, _NodeStat())
            st.inflight = max(st.inflight - 1, 0)
            if failed:
                st.errors += 1
                # punish: treat a failure as a slow response
                latency_us = max(latency_us, int(st.latency_ema_us * 4), 1)
            st.latency_ema_us = (self.DECAY * st.latency_ema_us
                                 + (1 - self.DECAY) * latency_us)

    def _on_membership(self):
        with self._lock:
            live = set(self.servers())
            self._stats = {n: s for n, s in self._stats.items() if n in live}


_LB_REGISTRY: Dict[str, Callable[[], LoadBalancer]] = {
    "rr": RoundRobinLB,
    "wrr": WeightedRoundRobinLB,
    "random": RandomizedLB,
    "wrandom": WeightedRandomLB,
    "c_md5": ConsistentHashLB,
    "c_murmurhash": ConsistentHashMurmurLB,
    "c_ketama": KetamaLB,
    "la": LocalityAwareLB,
}


def register_load_balancer(name: str,
                           factory: Callable[[], LoadBalancer]) -> None:
    """Extension point (≙ RegisterLoadBalancer, global.cpp:368)."""
    _LB_REGISTRY[name] = factory


def create_load_balancer(name: str) -> LoadBalancer:
    if name not in _LB_REGISTRY:
        raise ValueError(f"unknown load balancer '{name}' "
                         f"(known: {sorted(_LB_REGISTRY)})")
    return _LB_REGISTRY[name]()
