"""Collector — shared sampling budget (≙ bvar::Collector, collector.h:41:
one global sampling service with a speed limit, shared by rpcz spans and
rpc_dump in the reference; COLLECTOR_SAMPLING_BASE=16384/s).

A ``PerSecondBudget`` refills from its flag once per wall second, on a
monotonic clock so NTP steps can't double-refill it.
"""

from __future__ import annotations

import threading
import time

from brpc_tpu.utils import flags


class PerSecondBudget:
    """Token bucket refilled to ``flags[flag_name]`` each second."""

    def __init__(self, flag_name: str):
        self._flag = flag_name
        self._lock = threading.Lock()
        self._budget = 0
        self._sec = -1

    def try_take(self) -> bool:
        now = int(time.monotonic())
        with self._lock:
            if now != self._sec:
                self._sec = now
                self._budget = int(flags.get_flag(self._flag))
            if self._budget <= 0:
                return False
            self._budget -= 1
            return True


class Collected:
    """Base for objects that want asynchronous, rate-limited processing
    (≙ bvar::Collected, collector.h:81): call ``submit()`` on the hot
    path; ``on_collected()`` runs later on the collector thread."""

    def on_collected(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def submit(self, collector: "Collector" = None) -> bool:
        """Queue for background processing.  False = over budget or the
        queue is saturated (the sample is simply dropped, matching the
        reference's shed-on-overload behavior)."""
        return (collector or global_collector()).submit(self)


class Collector:
    """Background sampling service (≙ bvar::Collector, collector.cpp:75:
    grab-all consumer loop + COLLECTOR_SAMPLING_BASE global speed limit).

    The hot path pays one budget check and one deque append; processing
    (on_collected) happens on a single daemon thread.  The budget flag is
    shared by every sample type routed through this collector, like the
    reference's global sampling speed."""

    MAX_PENDING = 4096  # backstop if on_collected stalls

    def __init__(self, budget_flag: str = "collector_max_samples_per_second"):
        self._budget = PerSecondBudget(budget_flag)
        self._lock = threading.Lock()
        self._pending = []
        self._wake = threading.Condition(self._lock)
        self._thread = None
        self.collected = 0   # processed samples (observable via bvar)
        self.dropped = 0     # budget/queue sheds

    def submit(self, obj: "Collected") -> bool:
        if not self._budget.try_take():
            with self._lock:
                self.dropped += 1
            return False
        with self._wake:
            if len(self._pending) >= self.MAX_PENDING:
                self.dropped += 1
                return False
            self._pending.append(obj)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="bvar_collector", daemon=True)
                self._thread.start()
            self._wake.notify()
        return True

    def _run(self) -> None:
        while True:
            with self._wake:
                while not self._pending:
                    self._wake.wait()
                batch, self._pending = self._pending, []
            for obj in batch:  # grab-all then process outside the lock
                try:
                    obj.on_collected()
                except Exception:
                    pass  # a broken sample must not kill the collector
                with self._lock:
                    self.collected += 1

    def stats(self) -> dict:
        with self._lock:
            return {"collected": self.collected, "dropped": self.dropped,
                    "pending": len(self._pending)}


flags.define_int32("collector_max_samples_per_second", 16384,
                   "global budget shared by samples routed through the "
                   "default Collector (≙ COLLECTOR_SAMPLING_BASE)")

_global = None
_global_lock = threading.Lock()


def global_collector() -> Collector:
    global _global
    if _global is None:
        with _global_lock:
            if _global is None:
                _global = Collector()
    return _global
