"""Collector — shared sampling budget (≙ bvar::Collector, collector.h:41:
one global sampling service with a speed limit, shared by rpcz spans and
rpc_dump in the reference; COLLECTOR_SAMPLING_BASE=16384/s).

A ``PerSecondBudget`` refills from its flag once per wall second, on a
monotonic clock so NTP steps can't double-refill it.
"""

from __future__ import annotations

import threading
import time

from brpc_tpu.utils import flags


class PerSecondBudget:
    """Token bucket refilled to ``flags[flag_name]`` each second."""

    def __init__(self, flag_name: str):
        self._flag = flag_name
        self._lock = threading.Lock()
        self._budget = 0
        self._sec = -1

    def try_take(self) -> bool:
        now = int(time.monotonic())
        with self._lock:
            if now != self._sec:
                self._sec = now
                self._budget = int(flags.get_flag(self._flag))
            if self._budget <= 0:
                return False
            self._budget -= 1
            return True
