"""Native metrics seam — publishes the C++ core's internals into the bvar
registry (the real counterpart of the reference's self-instrumenting
bvars: socket/write-queue/usercode/pipelining state that previously ran
unobservable at ~300k QPS).

Source of truth is ``native/src/metrics.h``: single relaxed atomics
updated on the hot paths, dumped as "name value" lines by
``trpc_native_metrics_dump`` and exposed here as PassiveStatus variables
(value computed on read — /vars, /metrics and dumps all see live data).
"""

from __future__ import annotations

import ctypes
import threading
import time
from typing import Dict

from brpc_tpu._native import lib
from brpc_tpu.metrics import bvar

_installed = False
_install_lock = threading.Lock()


def read_native_metrics() -> Dict[str, int]:
    """One snapshot of every native counter."""
    buf = ctypes.create_string_buffer(1 << 14)
    n = lib().trpc_native_metrics_dump(buf, len(buf))
    out: Dict[str, int] = {}
    for line in buf.raw[:n].decode().splitlines():
        name, _, value = line.partition(" ")
        if value:
            out[name] = int(value)
    return out


def native_prometheus_text() -> str:
    """The native histogram exposition (real cumulative ``_bucket{le=}``
    series per method family + ``_sum``/``_count``) — appended to the
    portal's /metrics output beside the bvar gauges."""
    buf = ctypes.create_string_buffer(1 << 18)
    n = lib().trpc_telemetry_prom_dump(buf, len(buf))
    return buf.raw[:n].decode()


def native_families() -> tuple:
    """Method-family names of native/src/metrics.h TelemetryFamily, in
    id order — derived from the C++ table through capi so a family added
    natively surfaces here without a Python edit."""
    L = lib()
    return tuple(L.trpc_telemetry_family_name(f).decode()
                 for f in range(int(L.trpc_telemetry_families())))


# lazy per-family rate window for /status qps: (monotonic_ts, count)
# samples appended at READ time — /status is scraped at human frequency,
# so the window self-assembles from consecutive scrapes; a single scrape
# falls back to count/uptime-since-install
_rate_lock = threading.Lock()
_rate_hist: Dict[int, list] = {}
_rate_t0 = None


def native_family_stats() -> Dict[str, dict]:
    """Per-family qps / percentiles / inflight from the native histograms
    — the /status block for the methods Python never sees (the
    inline-dispatched fast path finally has a latency story)."""
    global _rate_t0
    L = lib()
    now = time.monotonic()
    out: Dict[str, dict] = {}
    with _rate_lock:
        if _rate_t0 is None:
            _rate_t0 = now
        for f, name in enumerate(native_families()):
            count = int(L.trpc_telemetry_count(f))
            hist = _rate_hist.setdefault(f, [])
            hist.append((now, count))
            # keep ~60s of scrape samples
            while len(hist) > 2 and now - hist[0][0] > 60.0:
                hist.pop(0)
            t_old, c_old = hist[0]
            if now - t_old >= 0.5 and count >= c_old:
                qps = (count - c_old) / (now - t_old)
            else:
                # first scrape: average since the plane started observing
                qps = count / max(now - _rate_t0, 1e-9) \
                    if now > _rate_t0 else 0.0
            out[name] = {
                "qps": round(qps, 1),
                "count": count,
                "latency_50_us": int(L.trpc_telemetry_percentile_us(f, 0.5)),
                "latency_99_us": int(
                    L.trpc_telemetry_percentile_us(f, 0.99)),
                "latency_999_us": int(
                    L.trpc_telemetry_percentile_us(f, 0.999)),
                "inflight": int(L.trpc_telemetry_inflight(f)),
            }
    return out


def native_overload_stats() -> dict:
    """The overload-control plane's /status block (overload.h, ISSUE
    11): master-switch state plus the per-family limit / inflight /
    rejects triple, folded across shards by the native read side.  Only
    the server-ingress families are gated; the others report the inert
    defaults."""
    L = lib()
    fams = {}
    for f, name in enumerate(native_families()):
        fams[name] = {
            "limit": int(L.trpc_overload_limit(f)),
            "inflight": int(L.trpc_overload_inflight(f)),
            "admits": int(L.trpc_overload_admits(f)),
            "rejects": int(L.trpc_overload_rejects(f)),
        }
    return {"enabled": bool(L.trpc_overload_active()), "families": fams}


def install_native_metrics() -> None:
    """Expose every native counter as a PassiveStatus bvar (idempotent).
    Called from Server.start(); safe to call standalone."""
    global _installed, _rate_t0
    with _install_lock:
        if _installed:
            return
        _installed = True
        # anchor the /status qps fallback window at server start: the
        # FIRST scrape after load then reports count/elapsed instead of 0
        with _rate_lock:
            if _rate_t0 is None:
                _rate_t0 = time.monotonic()
        for name in read_native_metrics():
            # each var re-reads the full dump: reads happen at human
            # frequency (portal/dump), writes stay single-atomic
            bvar.PassiveStatus(
                lambda n=name: read_native_metrics().get(n, 0), name)
