"""Native metrics seam — publishes the C++ core's internals into the bvar
registry (the real counterpart of the reference's self-instrumenting
bvars: socket/write-queue/usercode/pipelining state that previously ran
unobservable at ~300k QPS).

Source of truth is ``native/src/metrics.h``: single relaxed atomics
updated on the hot paths, dumped as "name value" lines by
``trpc_native_metrics_dump`` and exposed here as PassiveStatus variables
(value computed on read — /vars, /metrics and dumps all see live data).
"""

from __future__ import annotations

import ctypes
import threading
from typing import Dict

from brpc_tpu._native import lib
from brpc_tpu.metrics import bvar

_installed = False
_install_lock = threading.Lock()


def read_native_metrics() -> Dict[str, int]:
    """One snapshot of every native counter."""
    buf = ctypes.create_string_buffer(1 << 14)
    n = lib().trpc_native_metrics_dump(buf, len(buf))
    out: Dict[str, int] = {}
    for line in buf.raw[:n].decode().splitlines():
        name, _, value = line.partition(" ")
        if value:
            out[name] = int(value)
    return out


def install_native_metrics() -> None:
    """Expose every native counter as a PassiveStatus bvar (idempotent).
    Called from Server.start(); safe to call standalone."""
    global _installed
    with _install_lock:
        if _installed:
            return
        _installed = True
        for name in read_native_metrics():
            # each var re-reads the full dump: reads happen at human
            # frequency (portal/dump), writes stay single-atomic
            bvar.PassiveStatus(
                lambda n=name: read_native_metrics().get(n, 0), name)
