"""metrics — lock-minimal metrics (≙ reference src/bvar, SURVEY.md §2.2)."""
