"""bvar — write-side per-thread, read-side merge-on-demand metrics.

Capability map to the reference (src/bvar):
  Variable registry + expose/dump  — variable.h:102,133 ``expose_as``,
                                     ``dump_exposed`` with filters
  Adder/Maxer/Miner                — reducer.h:69,224,258,308 over per-thread
                                     agents (detail/combiner.h): each writer
                                     thread mutates only its own agent; reads
                                     merge all agents
  Window / PerSecond               — window.h over per-second sampled series
                                     (detail/sampler.cpp: one global sampler
                                     thread ticks every second)
  IntRecorder + Percentile         — average + reservoir percentile estimator
                                     (detail/percentile.h:134)
  LatencyRecorder                  — composite per-method server metric
                                     (latency_recorder.h:32-75)
  PassiveStatus / Status           — value computed on read / settable value
  MultiDimension                   — labeled metrics for Prometheus export
                                     (multi_dimension.h:35)
  GFlag bridge                     — flags mirrored as variables (bvar/gflag.cpp)

Python writers on the hot path touch only their own thread's agent (a plain
attribute store), so there is no cross-thread contention; the native C++ core
mirrors this design for its internal counters and publishes them through the
same registry (see native/src/metrics.h).
"""

from __future__ import annotations

import threading
import time
import traceback as _traceback
import random
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from brpc_tpu.utils import flags as _flags
from brpc_tpu.utils import logging as _log

# ---------------------------------------------------------------------------
# Registry


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._vars: Dict[str, "Variable"] = {}

    def expose(self, name: str, var: "Variable") -> bool:
        with self._lock:
            if name in self._vars:
                return False
            self._vars[name] = var
            return True

    def hide(self, name: str) -> bool:
        with self._lock:
            return self._vars.pop(name, None) is not None

    def get(self, name: str) -> Optional["Variable"]:
        with self._lock:
            return self._vars.get(name)

    def items(self) -> List[Tuple[str, "Variable"]]:
        with self._lock:
            return sorted(self._vars.items())


_registry = _Registry()


def describe_exposed(name: str) -> Optional[str]:
    v = _registry.get(name)
    return None if v is None else v.describe()


def dump_exposed(filter_fn: Optional[Callable[[str], bool]] = None
                 ) -> List[Tuple[str, str]]:
    """≙ Variable::dump_exposed (variable.h:153)."""
    out = []
    for name, var in _registry.items():
        if filter_fn is None or filter_fn(name):
            out.append((name, var.describe()))
    return out


def series_of(name: str) -> Optional[List[Tuple[float, Any]]]:
    """Per-second sample history of an exposed windowed variable
    (≙ the reference's /vars plots reading bvar::detail::Series) — the
    data behind a trend graph: [(monotonic_ts, per-second value), ...].
    None when the variable doesn't exist or keeps no history."""
    var = _registry.get(name)
    if var is None:
        return None
    sampler = getattr(var, "_sampler", None)
    if sampler is None:
        inner = getattr(var, "_win", None)  # PerSecond wraps a Window
        sampler = getattr(inner, "_sampler", None)
    if sampler is None:
        return None
    return sampler.samples()


class Variable:
    """Base of everything exposable (≙ bvar::Variable, variable.h:102)."""

    def __init__(self):
        self._name: Optional[str] = None

    def get_value(self) -> Any:
        raise NotImplementedError

    def describe(self) -> str:
        v = self.get_value()
        if isinstance(v, float):
            return f"{v:.6g}"
        return str(v)

    def expose(self, name: str) -> bool:
        name = name.strip().replace(" ", "_")
        # register the new name first: a failed re-expose must not
        # unregister the old name
        ok = _registry.expose(name, self)
        if ok:
            if self._name is not None and self._name != name:
                _registry.hide(self._name)
            self._name = name
        return ok

    def expose_as(self, prefix: str, name: str) -> bool:
        return self.expose(f"{prefix}_{name}" if prefix else name)

    def hide(self) -> bool:
        if self._name is None:
            return False
        ok = _registry.hide(self._name)
        self._name = None
        return ok

    @property
    def name(self) -> Optional[str]:
        return self._name


# ---------------------------------------------------------------------------
# Reducers over per-thread agents


class _Agent:
    __slots__ = ("value", "last")

    def __init__(self, identity):
        self.value = identity
        self.last = None  # sampler-thread-private cumulative snapshot


class _AgentHolder:
    """Lives in a thread's TLS dict; its collection (at thread exit) folds
    the agent's contribution into the reducer's residuals."""

    __slots__ = ("reducer", "agent")

    def __init__(self, reducer, agent):
        self.reducer = reducer
        self.agent = agent

    def __del__(self):
        try:
            self.reducer._on_agent_death(self.agent)
        except Exception:
            pass


class _Reducer(Variable):
    """Per-thread-agent combiner (≙ detail::AgentCombiner, detail/combiner.h)."""

    def __init__(self, identity, op: Callable[[Any, Any], Any]):
        super().__init__()
        self._identity = identity
        self._op = op
        self._agents_lock = threading.Lock()
        self._agents: List[_Agent] = []
        self._tls = threading.local()
        self._window_sampler: Optional["_WindowSampler"] = None
        # contributions of exited threads (≙ the reference combiner merging
        # dead agents into a global residual so _agents stays bounded by
        # *live* threads): _residual feeds lifetime reads, _residual_unsampled
        # holds the dead agents' not-yet-sampled remainder for the next tick.
        self._residual = identity
        self._residual_unsampled = identity

    def _shared_window_sampler(self) -> "_WindowSampler":
        """All Windows over one reducer share one sampler — a second
        independent sampler would also call reset() and the two would split
        the per-second deltas between them."""
        with self._agents_lock:
            if self._window_sampler is None:
                self._window_sampler = _WindowSampler(self, _MAX_WINDOW, True)
            self._window_sampler.refs += 1
            return self._window_sampler

    def _release_window_sampler(self):
        with self._agents_lock:
            s = self._window_sampler
            if s is not None:
                s.refs -= 1
                if s.refs <= 0:
                    s.destroy()
                    self._window_sampler = None

    def _my_agent(self) -> _Agent:
        holder = getattr(self._tls, "holder", None)
        if holder is None:
            a = _Agent(self._identity)
            with self._agents_lock:
                self._agents.append(a)
            # when the thread dies, its TLS dict drops the holder and the
            # finalizer folds the agent into the residuals
            self._tls.holder = _AgentHolder(self, a)
            return a
        return holder.agent

    def _on_agent_death(self, a: _Agent) -> None:
        with self._agents_lock:
            try:
                self._agents.remove(a)
            except ValueError:
                return
            cur = a.value
            self._residual = self._op(self._residual, cur)
            last = a.last
            d = cur if last is None else self._sub_or_whole(cur, last)
            self._residual_unsampled = self._op(self._residual_unsampled, d)

    def _sub_or_whole(self, cur, last):
        return self._sub(cur, last) if self._samples_as_delta else cur

    def get_value(self):
        with self._agents_lock:
            agents = list(self._agents)
            v = self._residual
        for a in agents:
            v = self._op(v, a.value)
        return v

    # Sampling. Adder/IntRecorder sample per-tick *deltas* without writing to
    # agents at all (writers do non-atomic read-modify-write under the GIL, so
    # a sampler store could double-count an in-flight increment; tracking the
    # last-seen cumulative value per agent is race-free because only the
    # single sampler thread reads/writes `last`). Maxer/Miner have no delta
    # form, so their sample resets the agent (a max racing the reset may slip
    # into the adjacent second — same tolerance as the reference's
    # agent-exchange).
    _samples_as_delta = False

    def reset(self):
        """Take one per-interval sample (called by the sampler thread only)."""
        with self._agents_lock:
            agents = list(self._agents)
            v = self._residual_unsampled
            self._residual_unsampled = self._identity
        if self._samples_as_delta:
            for a in agents:
                cur = a.value
                last = a.last
                if last is None:
                    delta = cur
                else:
                    delta = self._sub(cur, last)
                a.last = cur
                v = self._op(v, delta)
        else:
            for a in agents:
                v = self._op(v, a.value)
                a.value = self._identity
        return v

    @staticmethod
    def _sub(cur, last):
        return cur - last

    def _unsampled_remainder(self):
        """Value accumulated since the last sampler tick (read-only)."""
        with self._agents_lock:
            agents = list(self._agents)
            v = self._residual_unsampled
        if self._samples_as_delta:
            for a in agents:
                cur, last = a.value, a.last
                v = self._op(v, cur if last is None else self._sub(cur, last))
        else:
            for a in agents:
                v = self._op(v, a.value)
        return v


class Adder(_Reducer):
    """≙ bvar::Adder (reducer.h:224)."""

    _samples_as_delta = True

    def __init__(self, name: Optional[str] = None):
        super().__init__(0, lambda a, b: a + b)
        if name:
            self.expose(name)

    def add(self, v=1):
        self._my_agent().value += v

    def __lshift__(self, v):
        self.add(v)
        return self


class Maxer(_Reducer):
    """≙ bvar::Maxer (reducer.h:258)."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(None, lambda a, b: b if a is None else
                         (a if b is None else max(a, b)))
        if name:
            self.expose(name)

    def update(self, v):
        a = self._my_agent()
        if a.value is None or v > a.value:
            a.value = v

    __lshift__ = lambda self, v: (self.update(v), self)[1]

    def get_value(self):
        v = super().get_value()
        return 0 if v is None else v


class Miner(_Reducer):
    """≙ bvar::Miner (reducer.h:308)."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(None, lambda a, b: b if a is None else
                         (a if b is None else min(a, b)))
        if name:
            self.expose(name)

    def update(self, v):
        a = self._my_agent()
        if a.value is None or v < a.value:
            a.value = v

    __lshift__ = lambda self, v: (self.update(v), self)[1]

    def get_value(self):
        v = super().get_value()
        return 0 if v is None else v


# ---------------------------------------------------------------------------
# Status / PassiveStatus


class Status(Variable):
    """Settable value (≙ bvar::Status, status.h)."""

    def __init__(self, name: Optional[str] = None, value: Any = 0):
        super().__init__()
        self._value = value
        if name:
            self.expose(name)

    def set_value(self, v):
        self._value = v

    def get_value(self):
        return self._value


class PassiveStatus(Variable):
    """Value computed on read (≙ bvar::PassiveStatus, status.h; used for
    worker_usage / run-queue sizes, reference task_control.h:123-129)."""

    def __init__(self, fn: Callable[[], Any], name: Optional[str] = None):
        super().__init__()
        self._fn = fn
        if name:
            self.expose(name)

    def get_value(self):
        return self._fn()


class GFlag(PassiveStatus):
    """Flag mirrored as a variable (≙ bvar::GFlag, bvar/gflag.cpp)."""

    def __init__(self, flag_name: str, expose_name: Optional[str] = None):
        _flags.get_flag(flag_name)  # fail at definition site, not at dump time
        super().__init__(lambda: _flags.get_flag(flag_name),
                         expose_name or flag_name)


# ---------------------------------------------------------------------------
# Sampler thread + Window / PerSecond

_MAX_WINDOW = 600


class _SamplerCollector(threading.Thread):
    """One global thread sampling every second
    (≙ detail::SamplerCollector, detail/sampler.cpp)."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        super().__init__(name="bvar_sampler", daemon=True)
        self._lock = threading.Lock()
        self._samplers: List["_WindowSampler"] = []
        self._stop = threading.Event()

    @classmethod
    def instance(cls) -> "_SamplerCollector":
        with cls._instance_lock:
            if cls._instance is None or not cls._instance.is_alive():
                cls._instance = cls()
                cls._instance.start()
            return cls._instance

    def schedule(self, s: "_WindowSampler"):
        with self._lock:
            self._samplers.append(s)

    def unschedule(self, s: "_WindowSampler"):
        with self._lock:
            try:
                self._samplers.remove(s)
            except ValueError:
                pass

    def run(self):
        while not self._stop.wait(1.0):
            with self._lock:
                samplers = list(self._samplers)
            for s in samplers:
                try:
                    s.take_sample()
                except Exception:
                    _log.LOG(_log.LOG_ERROR,
                             "bvar sampler failed on %r: %s",
                             getattr(s.owner, "name", s.owner),
                             _traceback.format_exc())


class _WindowSampler:
    """Keeps last N per-second samples of a reducer."""

    def __init__(self, owner, window_size: int, reset_each_second: bool):
        self.owner = owner
        self.window_size = min(window_size, _MAX_WINDOW)
        self.reset_each_second = reset_each_second
        self.refs = 0
        self._lock = threading.Lock()
        self._q: deque = deque(maxlen=self.window_size + 1)
        _SamplerCollector.instance().schedule(self)

    def take_sample(self):
        if self.reset_each_second:
            v = self.owner.reset()
        else:
            v = self.owner.get_value()
        with self._lock:
            self._q.append((time.monotonic(), v))

    def samples(self) -> List[Tuple[float, Any]]:
        with self._lock:
            return list(self._q)

    def destroy(self):
        """Stop sampling (≙ reference samplers destroyed with their Variable —
        without this every Window/Percentile ever created leaks into the
        collector and its reducer is pinned forever)."""
        _SamplerCollector.instance().unschedule(self)


class Window(Variable):
    """Value of a reducer over the last ``window_size`` seconds
    (≙ bvar::Window, window.h).

    Every sample is a *per-second* value (Adder/IntRecorder: the delta
    accumulated that second; Maxer/Miner: the extremum seen that second,
    agents reset per tick as the reference's Window<Maxer> does); the window
    value folds the samples with the reducer's own op, plus the live partial
    second, so a spike ages out of the window instead of sticking forever.
    """

    def __init__(self, reducer: _Reducer, window_size: int = 10,
                 name: Optional[str] = None):
        super().__init__()
        self._reducer = reducer
        self._window = window_size
        self._sampler = reducer._shared_window_sampler()
        if name:
            self.expose(name)

    def get_value(self):
        if self._sampler is None:
            return 0  # closed
        op = self._reducer._op
        samples = self._sampler.samples()[-self._window:]
        acc = self._reducer._identity
        for _, v in samples:
            acc = op(acc, v)
        # include the not-yet-sampled partial second. For delta reducers the
        # live value is (current - last-sampled) per agent; approximating with
        # get_value() would re-count already-sampled history, so compute the
        # unsampled remainder explicitly.
        acc = op(acc, self._reducer._unsampled_remainder())
        if acc is None:  # Maxer/Miner identity with no data
            return 0
        return acc

    def close(self):
        if self._sampler is None:
            return  # double-close must not drop a sibling Window's sampler
        self._sampler = None
        self._reducer._release_window_sampler()
        self.hide()


class PerSecond(Variable):
    """Windowed rate (≙ bvar::PerSecond, window.h)."""

    def __init__(self, adder: Adder, window_size: int = 10,
                 name: Optional[str] = None):
        super().__init__()
        self._win = Window(adder, window_size)
        self._window_size = window_size
        if name:
            self.expose(name)

    def get_value(self):
        samples = self._win._sampler.samples()
        if len(samples) < 2:
            return 0
        # each sample is the delta accumulated over one 1s sampler tick; rate
        # is their mean over the window (the live partial second is excluded —
        # including it would overcount the denominator's whole seconds)
        use = samples[-min(self._window_size, len(samples)):]
        total = 0
        for _, v in use:
            total += v
        return total / len(use)

    def close(self):
        self._win.close()
        self.hide()


# ---------------------------------------------------------------------------
# IntRecorder + Percentile + LatencyRecorder


class IntRecorder(_Reducer):
    """Average recorder: per-thread (sum, count) agents
    (≙ bvar::IntRecorder, recorder.h)."""

    _samples_as_delta = True

    def __init__(self, name: Optional[str] = None):
        super().__init__((0, 0), lambda a, b: (a[0] + b[0], a[1] + b[1]))
        if name:
            self.expose(name)

    @staticmethod
    def _sub(cur, last):
        return (cur[0] - last[0], cur[1] - last[1])

    def record(self, v: int):
        a = self._my_agent()
        s, c = a.value
        a.value = (s + v, c + 1)

    __lshift__ = lambda self, v: (self.record(v), self)[1]

    def average(self) -> float:
        s, c = self.get_value()
        return (s / c) if c else 0.0

    def describe(self) -> str:
        return f"{self.average():.6g}"


_RESERVOIR = 254  # samples kept per interval (≙ percentile.h SAMPLE_SIZE)


class _PercentileInterval:
    __slots__ = ("samples", "count")

    def __init__(self):
        self.samples: List[int] = []
        self.count = 0

    def add(self, v: int):
        self.count += 1
        if len(self.samples) < _RESERVOIR:
            self.samples.append(v)
        else:
            # reservoir sampling keeps the kept set uniform over all added
            i = random.randrange(self.count)
            if i < _RESERVOIR:
                self.samples[i] = v


class Percentile:
    """Randomized-reservoir percentile estimator over a sliding window
    (≙ bvar::detail::Percentile, detail/percentile.h:134)."""

    def __init__(self, window_size: int = 10):
        self._lock = threading.Lock()
        self._window = window_size
        self._current = _PercentileInterval()
        self._q: deque = deque(maxlen=window_size)
        self._sampler = _WindowSampler(self, window_size, True)

    # duck-typed reducer API for the sampler
    def reset(self):
        with self._lock:
            iv = self._current
            self._current = _PercentileInterval()
            self._q.append(iv)
        return iv

    def get_value(self):
        return None

    def record(self, v: int):
        with self._lock:
            self._current.add(v)

    def get_number(self, ratio: float) -> int:
        with self._lock:
            intervals = list(self._q) + [self._current]
        merged: List[int] = []
        for iv in intervals:
            merged.extend(iv.samples)
        if not merged:
            return 0
        merged.sort()
        idx = min(len(merged) - 1, int(ratio * len(merged)))
        return merged[idx]

    def close(self):
        self._sampler.destroy()


class LatencyRecorder(Variable):
    """Composite latency/qps metric: avg, p50/p90/p99/p999/p9999, max, qps,
    count (≙ bvar::LatencyRecorder, latency_recorder.h:32-75).

    ``expose(prefix)`` publishes the same sub-variable names the reference
    does: <prefix>_latency, _max_latency, _qps, _count, _latency_percentiles.
    """

    def __init__(self, window_size: int = 10):
        super().__init__()
        self._latency = IntRecorder()
        self._latency_window = Window(self._latency, window_size)
        self._max = Maxer()
        self._max_window = Window(self._max, window_size)
        self._count = Adder()
        self._qps = PerSecond(self._count, window_size)
        self._percentile = Percentile(window_size)

    def record(self, latency_us: int):
        self._latency.record(latency_us)
        self._max.update(latency_us)
        self._count.add(1)
        self._percentile.record(latency_us)

    __lshift__ = lambda self, v: (self.record(v), self)[1]

    def latency(self) -> float:
        v = self._latency_window.get_value()
        if isinstance(v, tuple):
            s, c = v
            return s / c if c else 0.0
        return 0.0

    def latency_percentile(self, ratio: float) -> int:
        return self._percentile.get_number(ratio)

    def max_latency(self) -> int:
        return self._max_window.get_value() or 0

    def qps(self) -> float:
        return self._qps.get_value()

    def count(self) -> int:
        return self._count.get_value()

    def get_value(self):
        return self.latency()

    def expose(self, prefix: str) -> bool:  # type: ignore[override]
        self.hide()
        self._name = prefix
        # the qps var is the PerSecond ITSELF (not a PassiveStatus over
        # it) so /vars?series= can reach its per-second sample history
        self._qps.expose(f"{prefix}_qps")
        self._sub_vars = [
            PassiveStatus(self.latency, f"{prefix}_latency"),
            PassiveStatus(self.max_latency, f"{prefix}_max_latency"),
            self._qps,
            PassiveStatus(self.count, f"{prefix}_count"),
        ]
        for p, nm in ((0.5, "50"), (0.9, "90"), (0.99, "99"),
                      (0.999, "999"), (0.9999, "9999")):
            self._sub_vars.append(
                PassiveStatus(lambda p=p: self.latency_percentile(p),
                              f"{prefix}_latency_{nm}"))
        return True

    def hide(self) -> bool:  # type: ignore[override]
        for v in getattr(self, "_sub_vars", []):
            v.hide()
        self._sub_vars = []
        self._name = None
        return True

    def close(self):
        """Unregister and stop all samplers (call when the method/connection
        this recorder instruments goes away)."""
        self.hide()
        self._latency_window.close()
        self._max_window.close()
        self._qps.close()
        self._percentile.close()


# ---------------------------------------------------------------------------
# MultiDimension (labeled metrics)


class MultiDimension(Variable):
    """Labeled family of variables (≙ bvar::MultiDimension, multi_dimension.h:35);
    exported with labels by the Prometheus dumper."""

    def __init__(self, name: str, labels: Sequence[str],
                 factory: Callable[[], Variable] = Adder):
        super().__init__()
        self._labels = tuple(labels)
        self._factory = factory
        self._lock = threading.Lock()
        self._stats: Dict[Tuple[str, ...], Variable] = {}
        self.expose(name)

    @property
    def labels(self) -> Tuple[str, ...]:
        return self._labels

    def get_stats(self, label_values: Sequence[str]) -> Variable:
        key = tuple(str(v) for v in label_values)
        if len(key) != len(self._labels):
            raise ValueError(f"expected {len(self._labels)} label values")
        with self._lock:
            v = self._stats.get(key)
            if v is None:
                v = self._factory()
                self._stats[key] = v
            return v

    def items(self) -> List[Tuple[Tuple[str, ...], Variable]]:
        with self._lock:
            return list(self._stats.items())

    def count_stats(self) -> int:
        with self._lock:
            return len(self._stats)

    def get_value(self):
        return self.count_stats()


# ---------------------------------------------------------------------------
# Prometheus text export (≙ builtin/prometheus_metrics_service.cpp)


def _prom_name(name: str) -> str:
    return "".join(c if (c.isalnum() or c == "_") else "_" for c in name)


def _prom_label_value(v: str) -> str:
    """Escape per the Prometheus text exposition format."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def dump_prometheus() -> str:
    lines: List[str] = []
    for name, var in _registry.items():
        pname = _prom_name(name)
        if isinstance(var, MultiDimension):
            lines.append(f"# TYPE {pname} gauge")
            for key, sub in var.items():
                lbl = ",".join(f'{k}="{_prom_label_value(v)}"'
                               for k, v in zip(var.labels, key))
                val = sub.get_value()
                if isinstance(val, tuple):
                    val = (val[0] / val[1]) if val[1] else 0
                if isinstance(val, (int, float)):
                    lines.append(f"{pname}{{{lbl}}} {val}")
            continue
        val = var.get_value()
        if isinstance(val, tuple):
            val = (val[0] / val[1]) if val[1] else 0
        if isinstance(val, (int, float)):
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {val}")
    return "\n".join(lines) + "\n"
