"""Periodic bvar dump-to-file (≙ the reference's FLAGS_bvar_dump family,
bvar/variable.cpp dumping_thread: a background thread snapshots every
exposed variable to a file on an interval, so operators get metrics from
processes with no scrape path — batch jobs, crashed-before-scrape
servers, offline analysis).

Driven by two RELOADABLE flags (env-seeded, live-settable via /flags):

    bvar_dump_file        TRPC_BVAR_DUMP_FILE        "" = disabled
    bvar_dump_interval_s  TRPC_BVAR_DUMP_INTERVAL_S  seconds per snapshot

Each snapshot is written ATOMICALLY (tmp file + os.replace) so a reader
never observes a torn dump; the format is the /vars portal's
"name : value" lines.
"""

from __future__ import annotations

import os
import threading
import time

from brpc_tpu.utils import flags

_lock = threading.Lock()
_thread: "threading.Thread | None" = None
# bumped per completed snapshot; tests key on it via dump_count()
_dumps = 0
# set by the flag validator so a disabled dumper parks instead of
# polling; the loop wakes promptly on any live reconfiguration
_wake = threading.Event()


def _maybe_start(_value) -> bool:
    """Flag validator doubling as the live-start hook: setting a dump
    file via /flags starts the dumper without a server restart."""
    ensure_started()
    _wake.set()
    return True


def _positive(v) -> bool:
    return v > 0


flags.define_string(
    "bvar_dump_file", os.environ.get("TRPC_BVAR_DUMP_FILE", ""),
    "periodically write the /vars snapshot to this file, atomically "
    "(empty = disabled; reloadable — the dumper starts/stops live)",
    validator=_maybe_start)
flags.define_double(
    "bvar_dump_interval_s",
    float(os.environ.get("TRPC_BVAR_DUMP_INTERVAL_S", "10")),
    "seconds between bvar dump snapshots (reloadable)",
    validator=_positive)


def dump_count() -> int:
    """Completed snapshots since process start (test observability)."""
    return _dumps


def _snapshot_text() -> str:
    from brpc_tpu.metrics import bvar
    lines = [f"{name} : {val}" for name, val in bvar.dump_exposed()]
    return "\n".join(lines) + "\n"


def _write_atomic(path: str, text: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # readers see the old dump or the new: never torn


def _loop() -> None:
    global _dumps
    last = 0.0
    while True:
        try:
            path = flags.get_flag("bvar_dump_file")
            interval = max(float(flags.get_flag("bvar_dump_interval_s")),
                           0.05)
        except Exception:
            path, interval = "", 10.0
        if not path:
            # disabled: park until a validator reconfigures us (bounded,
            # so a direct set_flag bypassing the validator still lands)
            woke = _wake.wait(timeout=30.0)
            if woke:
                # the validator signals BEFORE Flag.set assigns the new
                # value — give the assignment a beat before consuming
                # the event, or this loop could re-read the OLD empty
                # path and park another full window
                time.sleep(0.05)
                _wake.clear()
            continue
        now = time.monotonic()
        if now - last >= interval:
            try:
                # broad except: ONE failing user gauge (a PassiveStatus
                # callback raising) or an unwritable target must not
                # kill the dumper thread for the process lifetime
                _write_atomic(path, _snapshot_text())
                _dumps += 1
            except Exception:
                pass  # retry next interval
            last = now
        # fine-grained tick so a live interval/file reload takes effect
        # promptly (the reference's dumping thread polls its gflags too)
        time.sleep(min(interval, 0.2))


def ensure_started() -> None:
    """Start the dumper thread once (idempotent; thread is a daemon)."""
    global _thread
    with _lock:
        if _thread is not None and _thread.is_alive():
            return
        _thread = threading.Thread(target=_loop, name="bvar_dumper",
                                   daemon=True)
        _thread.start()
