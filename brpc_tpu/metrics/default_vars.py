"""Process-level default variables (≙ bvar/default_variables.cpp:878 —
rusage, fd count, memory, threads, io — the block every brpc process
exposes on /vars without registering anything).

install_default_variables() is idempotent and called by Server.start();
importing applications can also call it directly.  Every variable is a
PassiveStatus reading /proc/self (this is Linux; TPU hosts are Linux) or
the `resource` module, so values are live at dump time with zero
background cost.
"""

from __future__ import annotations

import os
import resource
import threading
import time
from typing import Optional

from brpc_tpu.metrics.bvar import PassiveStatus

_installed_lock = threading.Lock()
_installed = False
_START_TIME = time.time()

_CLK_TCK = os.sysconf("SC_CLK_TCK")
_PAGE = os.sysconf("SC_PAGE_SIZE")


def _proc_stat_fields():
    # /proc/self/stat: field 2 is "(comm)" which may contain spaces —
    # split after the closing paren
    with open("/proc/self/stat") as f:
        raw = f.read()
    return raw[raw.rindex(")") + 2:].split()


def _cpu_seconds() -> float:
    ru_self = resource.getrusage(resource.RUSAGE_SELF)
    return ru_self.ru_utime + ru_self.ru_stime


def _cpu_user_seconds() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_utime


def _cpu_system_seconds() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_stime


def _memory_resident() -> int:
    # statm field 1 = resident pages
    with open("/proc/self/statm") as f:
        return int(f.read().split()[1]) * _PAGE


def _memory_virtual() -> int:
    with open("/proc/self/statm") as f:
        return int(f.read().split()[0]) * _PAGE


def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def _thread_count() -> int:
    # field 17 (0-based from after comm) of /proc/self/stat = num_threads
    return int(_proc_stat_fields()[17])


def _io_counter(tag: str) -> int:
    try:
        with open("/proc/self/io") as f:
            for line in f:
                if line.startswith(tag + ":"):
                    return int(line.split(":")[1])
    except OSError:
        pass
    return -1


def _loadavg_1m() -> float:
    return os.getloadavg()[0]


def _faults_major() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_majflt


def _ctx_switches_voluntary() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_nvcsw


def _ctx_switches_involuntary() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_nivcsw


class _CpuUsage:
    """process_cpu_usage: cores consumed over the last sampling gap
    (≙ default_variables.cpp deriving usage from rusage deltas)."""

    def __init__(self):
        self._last_t = time.monotonic()
        self._last_cpu = _cpu_seconds()
        self._value = 0.0

    def __call__(self) -> float:
        now = time.monotonic()
        cpu = _cpu_seconds()
        dt = now - self._last_t
        if dt >= 0.5:  # keep readings stable under rapid dumps
            self._value = max(0.0, (cpu - self._last_cpu) / dt)
            self._last_t = now
            self._last_cpu = cpu
        return round(self._value, 4)


def install_default_variables() -> None:
    """Expose the process block.  Idempotent; name collisions with an
    earlier install are impossible by construction."""
    global _installed
    with _installed_lock:
        if _installed:
            return
        _install_locked()
        # only after every variable registered: a concurrent caller must
        # not observe a half-installed block, and a failure must retry
        _installed = True


def _install_locked() -> None:
    PassiveStatus(lambda: round(time.time() - _START_TIME, 1),
                  "process_uptime_s")
    PassiveStatus(lambda: os.getpid(), "process_pid")
    PassiveStatus(_CpuUsage(), "process_cpu_usage")
    PassiveStatus(lambda: round(_cpu_user_seconds(), 3),
                  "process_cpu_usage_user_s")
    PassiveStatus(lambda: round(_cpu_system_seconds(), 3),
                  "process_cpu_usage_system_s")
    PassiveStatus(_memory_resident, "process_memory_resident_bytes")
    PassiveStatus(_memory_virtual, "process_memory_virtual_bytes")
    PassiveStatus(_fd_count, "process_fd_count")
    PassiveStatus(_thread_count, "process_thread_count")
    PassiveStatus(lambda: _io_counter("read_bytes"),
                  "process_io_read_bytes")
    PassiveStatus(lambda: _io_counter("write_bytes"),
                  "process_io_write_bytes")
    PassiveStatus(_faults_major, "process_faults_major")
    PassiveStatus(_ctx_switches_voluntary, "process_ctx_switches_voluntary")
    PassiveStatus(_ctx_switches_involuntary,
                  "process_ctx_switches_involuntary")
    PassiveStatus(_loadavg_1m, "system_loadavg_1m")
