"""fiber — Python facade over the native M:N fiber runtime
(≙ reference src/bthread, SURVEY.md §2.3; implementation native/src/fiber.cc).

Python-side usage is control-plane only (starting the runtime, introspecting
stats, waiting on butexes from host threads or PJRT completion callbacks);
the scheduler and all hot-path fibers live in C++.
"""

from __future__ import annotations

import ctypes
import os
from typing import Callable, Dict, Optional

from brpc_tpu._native import FIBER_FN, lib
from brpc_tpu.metrics import bvar
from brpc_tpu.utils import flags

_started = False
_stats_vars = []


def _push_sched_seed(value) -> bool:
    if int(value) < 0:
        return False
    lib().trpc_sched_set_seed(int(value))
    return True


def _env_seed() -> int:
    # base-0 like the C side's strtoull (hex/octal seeds mean the same
    # thing on both surfaces), and garbage degrades to 0 like strtoull
    # instead of crashing every brpc_tpu.fiber import
    try:
        return int(os.environ.get("TRPC_SCHED_SEED", "0") or "0", 0)
    except ValueError:
        return 0


flags.define_int64("sched_seed", _env_seed(),
                   "schedule perturbation seed (native/src/sched_perturb"
                   ".h): nonzero arms seeded yield injection + steal/wake "
                   "shuffles in the fiber runtime so schedule-dependent "
                   "bugs replay from the seed (BENCH_NOTES.md 'Schedule "
                   "replay'); 0 = off — REQUIRED off for bench-of-record",
                   validator=_push_sched_seed)


def sched_seed() -> int:
    """The active schedule-perturbation seed (0 = perturbation off)."""
    return int(lib().trpc_sched_seed())


def sched_trace_hash() -> int:
    """Replay fingerprint of the worker lanes' decision streams."""
    return int(lib().trpc_sched_trace_hash())


def sched_trace_dump() -> str:
    """Per-lane decision counters + event-ring tails (diagnostics)."""
    buf = ctypes.create_string_buffer(1 << 14)
    n = lib().trpc_sched_trace_dump(buf, len(buf))
    return buf.raw[:n].decode(errors="replace")


def init(num_workers: int = 0) -> int:
    """Start worker pthreads (idempotent, ≙ bthread concurrency setup)."""
    global _started
    n = lib().trpc_init(num_workers)
    if not _started:
        _started = True
        _expose_stats()
    return n


def workers() -> int:
    return lib().trpc_workers()


def _raw_stats():
    buf = (ctypes.c_uint64 * 5)()
    lib().trpc_runtime_stats(buf)
    return {
        "fibers_created": buf[0],
        "context_switches": buf[1],
        "steals": buf[2],
        "parks": buf[3],
        "workers": buf[4],
    }


def stats() -> Dict[str, int]:
    return _raw_stats()


def _expose_stats() -> None:
    # ≙ bthread's bvars: worker_usage, switch_per_second (task_control.h:120)
    for key in ("fibers_created", "context_switches", "steals", "parks"):
        _stats_vars.append(
            bvar.PassiveStatus(lambda k=key: _raw_stats()[k], f"fiber_{key}"))


# live references so ctypes callbacks outlive their fibers
_live_callbacks: Dict[int, object] = {}
_cb_seq = [0]


def _start_impl(starter, what: str) -> int:
    """Shared trampoline/keepalive/error plumbing for the start variants:
    the ctypes callback must outlive its fiber, and a failed native start
    must not leak the keepalive entry."""
    init()
    key = _cb_seq[0] = _cb_seq[0] + 1
    holder = {}

    def tramp(_arg):
        try:
            holder["fn"]()
        finally:
            _live_callbacks.pop(key, None)

    cfn = FIBER_FN(tramp)
    _live_callbacks[key] = cfn
    fid = ctypes.c_uint64()
    rc = starter(holder, ctypes.byref(fid), cfn)
    if rc != 0:
        _live_callbacks.pop(key, None)
        raise OSError(rc, f"{what} failed")
    return fid.value


def start(fn: Callable[[], None]) -> int:
    """Run fn() on a fiber.  For tests/tools — handlers on the RPC hot path
    are dispatched natively, not through here."""
    def starter(holder, fid_ref, cfn):
        holder["fn"] = fn
        return lib().trpc_fiber_start(fid_ref, cfn, None)
    return _start_impl(starter, "fiber_start")


def start_bound(group: int, fn: Callable[[], None]) -> int:
    """Run fn() on a fiber PINNED to worker `group` — never stolen (≙
    the fork's bound task queues / start_from_dispatcher).  Per-core
    state needs no locks inside such fibers.

    Note: the fork's jump_group (mid-fiber migration) is NATIVE-ONLY
    (fiber_jump_group in fiber.h): a Python frame cannot move between
    OS threads under the GIL, so no Python facade exists for it."""
    def starter(holder, fid_ref, cfn):
        holder["fn"] = fn
        return lib().trpc_fiber_start_bound(group, fid_ref, cfn, None)
    return _start_impl(starter, "fiber_start_bound")


def worker_index() -> int:
    """Worker running the caller, or -1 off-worker."""
    return int(lib().trpc_fiber_worker_index())


def shards() -> int:
    """Boot-frozen runtime shard count (native/src/shard.h; 1 = the
    unsharded pre-shard runtime)."""
    return int(lib().trpc_shard_count())


def current_shard() -> int:
    """Shard of the calling context (-1 off-worker — Python control
    threads are off-worker unless running inside a fiber)."""
    return int(lib().trpc_current_shard())


def cross_shard_hops() -> int:
    """Cross-shard mailbox hops so far; the echo hot path keeps this
    near zero (hops are naming/teardown/aggregation traffic)."""
    return int(lib().trpc_cross_shard_hops())


def join(fid: int) -> None:
    lib().trpc_fiber_join(fid)


class Butex:
    """32-bit wait/wake word shared between fibers and pthreads
    (≙ bthread butex, reference butex.h:36-72).  The TPU hook: a jax host
    callback on transfer completion calls wake_all() to resume fibers
    awaiting device data (BASELINE.json north star)."""

    def __init__(self):
        init()
        self._b = lib().trpc_butex_create()

    def close(self):
        if self._b:
            lib().trpc_butex_destroy(self._b)
            self._b = None

    @property
    def value(self) -> int:
        return lib().trpc_butex_load(self._b)

    @value.setter
    def value(self, v: int) -> None:
        lib().trpc_butex_store(self._b, v)

    def add(self, v: int = 1) -> int:
        return lib().trpc_butex_add(self._b, v)

    def wait(self, expected: int, timeout_us: Optional[int] = None) -> int:
        """0 = woken; -EWOULDBLOCK value differs; -ETIMEDOUT on timeout."""
        t = -1 if timeout_us is None else timeout_us
        return lib().trpc_butex_wait(self._b, expected, t)

    def wake(self) -> int:
        return lib().trpc_butex_wake(self._b)

    def wake_all(self) -> int:
        return lib().trpc_butex_wake_all(self._b)


# -- sync primitives on butex (≙ bthread mutex/cond/rwlock/countdown) -------
# All of these park a fiber without consuming a thread and work equally
# from plain pthreads (native/src/fiber_sync.h).


class Mutex:
    """≙ bthread_mutex (src/bthread/mutex.cpp): futex-style 0/1/2 states,
    one CAS on the uncontended path."""

    def __init__(self):
        init()
        self._m = lib().trpc_mutex_create()

    def close(self):
        if self._m:
            lib().trpc_mutex_destroy(self._m)
            self._m = None

    def acquire(self) -> None:
        lib().trpc_mutex_lock(self._m)

    def try_acquire(self) -> bool:
        return bool(lib().trpc_mutex_trylock(self._m))

    def release(self) -> None:
        lib().trpc_mutex_unlock(self._m)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


class Cond:
    """≙ bthread_cond (condition_variable.cpp): sequence-counter wait over
    a Mutex; no missed wakeups."""

    def __init__(self):
        init()
        self._c = lib().trpc_cond_create()

    def close(self):
        if self._c:
            lib().trpc_cond_destroy(self._c)
            self._c = None

    def wait(self, mutex: "Mutex", timeout_us: Optional[int] = None) -> bool:
        """mutex must be held; re-held on return.  False on timeout."""
        t = -1 if timeout_us is None else timeout_us
        return lib().trpc_cond_wait(self._c, mutex._m, t) == 0

    def notify_one(self) -> None:
        lib().trpc_cond_notify_one(self._c)

    def notify_all(self) -> None:
        lib().trpc_cond_notify_all(self._c)


class CountdownEvent:
    """≙ bthread CountdownEvent (countdown_event.cpp): init N, workers
    signal(), waiters park until the count reaches zero."""

    def __init__(self, initial: int = 1):
        init()
        self._e = lib().trpc_countdown_create(initial)

    def close(self):
        if self._e:
            lib().trpc_countdown_destroy(self._e)
            self._e = None

    def signal(self, n: int = 1) -> None:
        lib().trpc_countdown_signal(self._e, n)

    def add(self, n: int = 1) -> None:
        lib().trpc_countdown_add(self._e, n)

    def wait(self, timeout_us: Optional[int] = None) -> bool:
        """False on timeout."""
        t = -1 if timeout_us is None else timeout_us
        return lib().trpc_countdown_wait(self._e, t) == 0


class RWLock:
    """≙ bthread_rwlock: write-preferring reader/writer lock."""

    def __init__(self):
        init()
        self._l = lib().trpc_rwlock_create()

    def close(self):
        if self._l:
            lib().trpc_rwlock_destroy(self._l)
            self._l = None

    def rdlock(self) -> None:
        lib().trpc_rwlock_rdlock(self._l)

    def rdunlock(self) -> None:
        lib().trpc_rwlock_rdunlock(self._l)

    def wrlock(self) -> None:
        lib().trpc_rwlock_wrlock(self._l)

    def wrunlock(self) -> None:
        lib().trpc_rwlock_wrunlock(self._l)


class FiberLocal:
    """Fiber-local storage slot (≙ bthread_key_t, bthread/key.cpp).

    Each fiber (or plain thread, via the pthread fallback) sees its own
    value.  Values are Python objects; the native layer stores an opaque
    integer token into the fiber's key slot and this class keeps the
    object alive in a side table until the slot is overwritten, the key
    closed, or the owning fiber exits (native destructor callback).
    """

    def __init__(self):
        import ctypes as _c
        init()
        L = lib()
        # native destructor: drop the side-table reference when a fiber
        # holding a value exits
        self._DTOR = _c.CFUNCTYPE(None, _c.c_void_p)(self._on_fiber_exit)
        self._values = {}
        self._next_token = 1
        self._vlock = __import__("threading").Lock()
        key = _c.c_uint64()
        rc = L.trpc_fiber_key_create(
            _c.byref(key), _c.cast(self._DTOR, _c.c_void_p))
        if rc != 0:
            raise RuntimeError(f"fiber key space exhausted ({rc})")
        self._key = key.value

    def _on_fiber_exit(self, token):
        with self._vlock:
            self._values.pop(int(token or 0), None)

    def set(self, value) -> None:
        L = lib()
        old = int(L.trpc_fiber_getspecific(self._key) or 0)
        with self._vlock:
            if old:
                self._values.pop(old, None)
            if value is None:
                token = 0
            else:
                token = self._next_token
                self._next_token += 1
                self._values[token] = value
        L.trpc_fiber_setspecific(self._key, token)

    def get(self, default=None):
        token = int(lib().trpc_fiber_getspecific(self._key) or 0)
        if not token:
            return default
        with self._vlock:
            return self._values.get(token, default)

    def close(self) -> None:
        if self._key is not None:
            lib().trpc_fiber_key_delete(self._key)
            self._key = None
            with self._vlock:
                self._values.clear()

    def __del__(self):
        # without this, a dropped FiberLocal leaves the native key alive
        # pointing at a freed ctypes trampoline — the next fiber exit
        # holding a value would call through it.  key_delete bumps the
        # version so the native sweep never invokes the dead pointer.
        try:
            self.close()
        except Exception:
            pass  # interpreter teardown: the library may be gone
