"""Backup request: if no response within backup_request_ms, race a second
attempt; first success wins (≙ example/backup_request — the tail-latency
killer, reference channel.cpp:551)."""
import _bootstrap  # noqa: F401

import random
import time

from brpc_tpu.rpc.channel import Channel, ChannelOptions
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.server import Server


def main():
    server = Server()

    def sometimes_slow(cntl, req):
        if random.random() < 0.5:
            time.sleep(0.2)  # the 200ms tail
        return b"ok"

    server.add_service("Slow", sometimes_slow)
    port = server.start("127.0.0.1:0")

    ch = Channel(f"127.0.0.1:{port}",
                 ChannelOptions(timeout_ms=1000, backup_request_ms=30))
    lat, fired = [], 0
    for _ in range(20):
        cntl = Controller()
        ch.call("Slow", b"", cntl=cntl)
        lat.append(cntl.latency_us / 1000)
        fired += cntl.backup_fired
    lat.sort()
    print(f"backup fired {fired}/20; p50={lat[10]:.1f}ms max={lat[-1]:.1f}ms"
          f" (tail would be 200ms without backup)")
    ch.close()
    server.destroy()


if __name__ == "__main__":
    main()
