"""Progressive (chunked) HTTP response — server keeps writing after the
handler returns (≙ ProgressiveAttachment, progressive_attachment.h:32 +
example/http's streaming mode), read by the framework's own progressive
client (≙ ProgressiveReader)."""
import _bootstrap  # noqa: F401

import threading
import time

from brpc_tpu.rpc.http import HttpResponse
from brpc_tpu.rpc.http_client import HttpChannel
from brpc_tpu.rpc.server import Server


def main():
    def stream(req):
        pa = HttpResponse.progressive(
            200, {"Content-Type": "text/event-stream"})

        def writer():
            try:
                for i in range(4):
                    pa.write(f"data: chunk {i}\n\n".encode())
                    time.sleep(0.05)
            finally:
                pa.close()

        threading.Thread(target=writer, daemon=True).start()
        return pa  # handler is done; the writer streams on

    server = Server()
    server.register_http("/events", stream)
    port = server.start("127.0.0.1:0")

    c = HttpChannel(f"127.0.0.1:{port}")
    resp = c.request("GET", "/events",
                     stream=lambda b: print("<-", b.decode().strip()))
    print("status:", resp.status)
    c.close()
    server.destroy()


if __name__ == "__main__":
    main()
