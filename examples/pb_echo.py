"""Protobuf-typed service: pb over TRPC + JSON over HTTP, one handler
(≙ example/echo_c++'s pb EchoService + json2pb HTTP access)."""
import _bootstrap  # noqa: F401

import json
import urllib.request

from google.protobuf import proto_builder
from google.protobuf.descriptor_pb2 import FieldDescriptorProto as F

from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.pb_service import pb_call
from brpc_tpu.rpc.server import Server

EchoRequest = proto_builder.MakeSimpleProtoClass(
    {"message": F.TYPE_STRING}, full_name="example.EchoRequest")
EchoResponse = proto_builder.MakeSimpleProtoClass(
    {"message": F.TYPE_STRING, "length": F.TYPE_INT32},
    full_name="example.EchoResponse")


def main():
    def echo(cntl, req):
        resp = EchoResponse()
        resp.message = req.message
        resp.length = len(req.message)
        return resp

    server = Server()
    server.add_pb_service("EchoService",
                          {"Echo": (echo, EchoRequest, EchoResponse)})
    port = server.start("127.0.0.1:0")

    # typed pb call over TRPC
    ch = Channel(f"127.0.0.1:{port}")
    req = EchoRequest()
    req.message = "hello pb"
    resp = pb_call(ch, "EchoService.Echo", req, EchoResponse)
    print("pb over TRPC  ->", resp.message, f"(length={resp.length})")
    ch.close()

    # the same method over HTTP with a JSON body (json2pb transcoding)
    hreq = urllib.request.Request(
        f"http://127.0.0.1:{port}/rpc/EchoService.Echo",
        data=json.dumps({"message": "hello json"}).encode(),
        headers={"Content-Type": "application/json"})
    print("json over HTTP->",
          json.load(urllib.request.urlopen(hreq, timeout=5)))
    server.destroy()


if __name__ == "__main__":
    main()
