"""RPC cancellation: a client abandons a slow call from another thread;
the blocked caller returns ECANCELED immediately, the server's handler
observes the cancel and aborts its work, and the connection keeps
serving (≙ example/cancel_c++ + Controller::StartCancel/NotifyOnCancel,
controller.h:631,385)."""
import _bootstrap  # noqa: F401

import threading
import time

from brpc_tpu.rpc import errors
from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.server import Server


def main():
    server = Server()

    def long_job(cntl, req):
        # a "10 second" job that parks on the cancel butex while working
        # (≙ NotifyOnCancel): the moment the peer cancels, abort
        if cntl.wait_cancel(timeout_s=10.0):
            print("server: peer canceled — aborting the job")
            raise errors.RpcError(errors.EINTERNAL, "aborted")
        return b"finished"

    server.add_service("LongJob", long_job)
    server.add_service("Echo", lambda cntl, req: req)
    port = server.start("127.0.0.1:0")

    ch = Channel(f"127.0.0.1:{port}")
    cntl = Controller()
    threading.Thread(target=lambda: (time.sleep(0.3), cntl.start_cancel()),
                     daemon=True).start()
    t0 = time.monotonic()
    try:
        ch.call("LongJob", b"work", cntl=cntl, timeout_ms=30_000)
        raise SystemExit("the call should have been canceled")
    except errors.RpcError as e:
        assert e.code == errors.ECANCELED, e
        print(f"client: canceled after {time.monotonic() - t0:.2f}s "
              f"(the job had 10s to go)")
    # the connection survives the canceled call
    assert ch.call("Echo", b"still here") == b"still here"
    print("connection still usable after cancel")
    ch.close()
    server.destroy()


if __name__ == "__main__":
    main()
