"""N caller threads sharing one channel (≙ example/multi_threaded_echo:
channels are thread-safe; one connection multiplexes all callers)."""
import _bootstrap  # noqa: F401

import threading
import time

from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.server import Server

THREADS, SECONDS = 8, 1.0


def main():
    server = Server()
    server.add_echo_service()
    port = server.start("127.0.0.1:0")
    ch = Channel(f"127.0.0.1:{port}")

    counts = [0] * THREADS
    stop = threading.Event()

    def worker(i):
        while not stop.is_set():
            assert ch.call("Echo.echo", b"x" * 64) == b"x" * 64
            counts[i] += 1

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(THREADS)]
    for t in threads:
        t.start()
    time.sleep(SECONDS)
    stop.set()
    for t in threads:
        t.join()
    total = sum(counts)
    print(f"{THREADS} threads, {SECONDS}s: {total} echos "
          f"({total / SECONDS:.0f} qps) per-thread={counts}")
    ch.close()
    server.destroy()


if __name__ == "__main__":
    main()
