"""ici_performance — device data plane bandwidth benchmark
(≙ example/rdma_performance/{client,server}.cpp retargeted at TPU:
throughput of RPC attachments that round-trip host<->HBM through the PJRT
plane, plus raw plane H2D/D2H bandwidth).

Run on a host with a PJRT plugin (TPU VM, or anywhere TRPC_PJRT_PLUGIN
points at one).  Without a plugin it reports the explicit FALLBACK_TCP
path instead of silently degrading.

Usage: python examples/ici_performance.py [--size MB] [--seconds S]
"""
import _bootstrap  # noqa: F401

import argparse
import json
import time

from brpc_tpu import tpu_plane
from brpc_tpu.rpc.channel import Channel, ChannelOptions
from brpc_tpu.rpc.server import Server


def bench_raw(size: int, seconds: float) -> dict:
    """Raw plane H2D+D2H bandwidth (no RPC framing)."""
    data = bytes(bytearray(range(256)) * (size // 256 + 1))[:size]
    deadline = time.monotonic() + seconds
    rounds = 0
    t0 = time.monotonic()
    while time.monotonic() < deadline:
        buf = tpu_plane.h2d(data)
        buf.wait()
        back = buf.to_host()
        buf.free()
        assert back == data
        rounds += 1
    dt = time.monotonic() - t0
    return {
        "rounds": rounds,
        "h2d_gbps": rounds * size / dt / 1e9,
        "roundtrip_gbps": 2 * rounds * size / dt / 1e9,
    }


def bench_rpc(size: int, seconds: float) -> dict:
    """Attachment round-trips through a real RPC whose server half DMAs
    host->HBM->host (the HbmEcho service)."""
    server = Server()
    server.add_hbm_echo_service()
    port = server.start("127.0.0.1:0")
    ch = Channel(f"tpu://0/0@127.0.0.1:{port}",
                 ChannelOptions(timeout_ms=60_000, max_retry=0))
    payload = bytes(size)
    before = tpu_plane.stats()
    deadline = time.monotonic() + seconds
    rounds = 0
    t0 = time.monotonic()
    from brpc_tpu.rpc.controller import Controller
    while time.monotonic() < deadline:
        cntl = Controller()
        ch.call("HbmEcho", b"x", attachment=payload, cntl=cntl)
        assert cntl.response_attachment == payload
        rounds += 1
    dt = time.monotonic() - t0
    after = tpu_plane.stats()
    state = ch.transport_state
    ch.close()
    server.destroy()
    return {
        "rounds": rounds,
        "transport": state,
        # each round moves size bytes H2D and size bytes D2H on the server
        "device_gbps": 2 * rounds * size / dt / 1e9,
        "zero_copy_sends": after["zero_copy_sends"] - before["zero_copy_sends"],
        "gather_copies": after["gather_copies"] - before["gather_copies"],
    }


def bench_stream(size: int, seconds: float) -> dict:
    """Tensor stream dev0->dev1: device-payload frames over a stream on a
    tpu:// channel.  Both ends share this process's PJRT client, so each
    frame is ONE CopyToDevice on the local rail — the counters prove no
    host landing happened (≙ 'tensor streams overlapping compute',
    SURVEY §2.9)."""
    if tpu_plane.device_count() < 2:
        return {"skipped": "needs 2 addressable devices"}
    server = Server()
    accepted = []
    server.add_service("TensorSink",
                       lambda cntl, req: (accepted.append(
                           cntl.accept_stream()), b"ok")[1])
    port = server.start("127.0.0.1:0")
    ch = Channel(f"tpu://0/0@127.0.0.1:{port}",
                 ChannelOptions(timeout_ms=60_000, max_retry=0))
    _, st = ch.create_stream("TensorSink", b"")
    sink = accepted[0]
    payload = bytes(size)
    # pure device-to-device rate first: one resident source, repeated
    # CopyToDevice (the source stays valid — d2d doesn't consume it)
    src = tpu_plane.h2d(payload, device=0)
    src.wait()
    deadline = time.monotonic() + seconds / 2
    hops = 0
    t0 = time.monotonic()
    while time.monotonic() < deadline:
        dst = tpu_plane.d2d(src, 1)
        dst.wait()  # a hop isn't done until the copy completed
        dst.free()
        hops += 1
    d2d_dt = time.monotonic() - t0
    src.free()
    # then end-to-end tensor frames (h2d source + stream + d2d per frame)
    before = tpu_plane.stats()
    deadline = time.monotonic() + seconds / 2
    frames = 0
    t0 = time.monotonic()
    while time.monotonic() < deadline:
        buf = tpu_plane.h2d(payload, device=0)
        st.write_device(buf)  # ownership transfers
        got = sink.read_device(device=1, timeout_s=60)
        got.free()
        frames += 1
    dt = time.monotonic() - t0
    after = tpu_plane.stats()
    st.destroy()
    sink.destroy()
    ch.close()
    server.destroy()
    return {
        "d2d_hops": hops,
        "d2d_gbps": hops * size / d2d_dt / 1e9,
        "frames": frames,
        # end-to-end: includes the per-frame source h2d + RPC framing
        "frame_gbps": frames * size / dt / 1e9,
        "d2d_transfers": after["d2d_transfers"] - before["d2d_transfers"],
        "gather_copies": after["gather_copies"] - before["gather_copies"],
        # host landings beyond the unavoidable source h2d per frame
        "extra_host_copies": (after["d2h_transfers"] -
                              before["d2h_transfers"]),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=float, default=8.0)
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--stream", action="store_true",
                    help="also run the dev0->dev1 tensor-stream bench")
    args = ap.parse_args()
    size = int(args.size_mb * 1024 * 1024)

    if not tpu_plane.init():
        print(json.dumps({
            "available": False,
            "fallback": "tcp",
            "reason": tpu_plane.error(),
        }))
        return
    out = {
        "available": True,
        "platform": tpu_plane.platform(),
        "devices": tpu_plane.device_count(),
        "size_mb": args.size_mb,
        "raw": bench_raw(size, args.seconds),
        "rpc": bench_rpc(size, args.seconds),
    }
    if args.stream:
        out["stream"] = bench_stream(size, args.seconds)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
