"""Async call with done callback (≙ example/asynchronous_echo: CallMethod
with a done closure; the call returns immediately)."""
import _bootstrap  # noqa: F401

import threading

from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.server import Server


def main():
    server = Server()
    server.add_echo_service()
    port = server.start("127.0.0.1:0")
    ch = Channel(f"127.0.0.1:{port}")

    finished = threading.Event()

    def on_done(cntl, response):
        if response is None:
            print("failed:", cntl.error_code, cntl.error_text)
        else:
            print(f"done callback: {response!r} latency={cntl.latency_us}us")
        finished.set()

    fut = ch.call_async("Echo.echo", b"async hello", done=on_done)
    print("call issued; doing other work...")
    print("future result:", fut.result(timeout=5))
    finished.wait(5)
    ch.close()
    server.destroy()


if __name__ == "__main__":
    main()
