"""Sync echo — server + client (≙ example/echo_c++)."""
import _bootstrap  # noqa: F401

from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.server import Server


def main():
    server = Server()
    server.add_echo_service()  # native hot path

    def upper(cntl, req):
        cntl.response_attachment = b"meta"
        return req.upper()

    server.add_service("Upper", upper)
    port = server.start("127.0.0.1:0")
    print(f"server on :{port} — portal at http://127.0.0.1:{port}/")

    ch = Channel(f"127.0.0.1:{port}")
    print("Echo.echo  ->", ch.call("Echo.echo", b"hello world"))
    from brpc_tpu.rpc.controller import Controller
    cntl = Controller()
    print("Upper      ->", ch.call("Upper", b"hello world", cntl=cntl),
          "attachment:", cntl.response_attachment,
          f"latency={cntl.latency_us}us")
    ch.close()
    server.destroy()


if __name__ == "__main__":
    main()
