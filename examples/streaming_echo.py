"""Streaming RPC: flow-controlled ordered messages on an accepted stream
(≙ example/streaming_echo — StreamCreate on the client, StreamAccept in
the handler, credit-based flow control underneath)."""
import _bootstrap  # noqa: F401

import threading

from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.server import Server


def main():
    server = Server()

    def open_stream(cntl, req):
        st = cntl.accept_stream()

        def pump():
            for msg in st:          # iterate until remote close
                st.write(b"echo:" + msg)
            st.close()

        threading.Thread(target=pump, daemon=True).start()
        return b"stream accepted"

    server.add_service("OpenStream", open_stream)
    port = server.start("127.0.0.1:0")

    ch = Channel(f"127.0.0.1:{port}")
    resp, stream = ch.create_stream("OpenStream")
    print("handshake response:", resp)
    for i in range(5):
        stream.write(f"msg-{i}".encode())
    for i in range(5):
        print("got:", stream.read(timeout_s=2.0))
    stream.close()
    ch.close()
    server.destroy()


if __name__ == "__main__":
    main()
