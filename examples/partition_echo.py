"""PartitionChannel: "i/n"-tagged cluster members, one call fans to every
partition (≙ example/partition_echo)."""
import _bootstrap  # noqa: F401

from brpc_tpu.parallel.channels import PartitionChannel
from brpc_tpu.rpc.server import Server


def make_server(name: bytes):
    s = Server()
    s.add_service("Who", lambda cntl, req, n=name: n + b":" + req)
    s.start("127.0.0.1:0")
    return s


def main():
    parts = [make_server(f"part{i}".encode()) for i in range(3)]
    url = ",".join(f"127.0.0.1:{s.port} {i}/3"
                   for i, s in enumerate(parts))
    pch = PartitionChannel("list://" + url, partition_count=3)
    print("partitions ready:", pch.partitions_ready())
    print("fan to all 3:    ", pch.call("Who", b"x"))
    pch.close()
    for s in parts:
        s.destroy()


if __name__ == "__main__":
    main()
