"""HTTP on the RPC port: RESTful routes, the JSON bridge, and the builtin
portal (≙ example/http — one port speaks TRPC and HTTP simultaneously)."""
import _bootstrap  # noqa: F401

import json
import urllib.request

from brpc_tpu.rpc.http import HttpRequest, HttpResponse
from brpc_tpu.rpc.server import Server


def main():
    server = Server()
    server.add_service("Upper", lambda cntl, req: req.upper())

    def greet(req: HttpRequest) -> HttpResponse:
        name = req.query_params().get("name", "world")
        return HttpResponse.json({"hello": name})

    server.register_http("/greet", greet)
    port = server.start("127.0.0.1:0")
    base = f"http://127.0.0.1:{port}"

    print("GET /greet?name=tpu ->",
          urllib.request.urlopen(f"{base}/greet?name=tpu").read())
    req = urllib.request.Request(
        f"{base}/rpc/Upper", data=json.dumps({"payload": "json in"}).encode(),
        headers={"Content-Type": "application/json"})
    print("POST /rpc/Upper     ->", urllib.request.urlopen(req).read())
    print("GET /status         ->",
          urllib.request.urlopen(f"{base}/status").read()[:80], "...")
    server.destroy()


if __name__ == "__main__":
    main()
