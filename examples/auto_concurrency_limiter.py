"""Adaptive admission control (≙ example/auto_concurrency_limiter: the
"auto" gradient limiter adjusts max_concurrency from noload-latency vs
measured latency; overload sheds with ELIMIT instead of queueing)."""
import _bootstrap  # noqa: F401

import threading
import time

from brpc_tpu.cluster.limiter import AutoConcurrencyLimiter
from brpc_tpu.rpc.channel import Channel, ChannelOptions
from brpc_tpu.rpc.errors import ELIMIT, RpcError
from brpc_tpu.rpc.server import Server


def main():
    server = Server()

    def work(cntl, req):
        time.sleep(0.02)  # 20ms of "work"
        return b"done"

    server.add_service("Work", work)
    server.set_concurrency_limiter(AutoConcurrencyLimiter())
    port = server.start("127.0.0.1:0")

    ok, shed = 0, 0
    lock = threading.Lock()

    def flood():
        nonlocal ok, shed
        ch = Channel(f"127.0.0.1:{port}",
                     ChannelOptions(timeout_ms=2000, max_retry=0))
        for _ in range(20):
            try:
                ch.call("Work", b"")
                with lock:
                    ok += 1
            except RpcError as e:
                with lock:
                    shed += e.code == ELIMIT
        ch.close()

    threads = [threading.Thread(target=flood) for _ in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    print(f"flood of 640 calls: {ok} served, {shed} shed with ELIMIT "
          f"(limiter keeps latency bounded instead of queueing)")
    server.destroy()


if __name__ == "__main__":
    main()
