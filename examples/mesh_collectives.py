"""Combo channels lowered to XLA collectives (SURVEY.md §2.9: when the
member set is a mesh axis, ParallelChannel fan-out+merge IS one collective
riding ICI — no per-member RPCs)."""
# JAX_PLATFORMS must be set BEFORE _bootstrap: its force_cpu_platform
# hang guard (dead-tunnel protection) only fires when the env says cpu
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "") +
     " --xla_force_host_platform_device_count=8").strip())

import _bootstrap  # noqa: F401,E402

import os  # noqa: E402  (env set before _bootstrap below)

import jax  # noqa: E402

if len(jax.devices()) < 8:
    # single real chip (or axon forced the TPU platform): fall back to a
    # virtual 8-device CPU mesh, same as the test conftest
    from jax.extend import backend as _jex_backend
    _jex_backend.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

import jax.numpy as jnp  # noqa: E402

from brpc_tpu.parallel.channels import (MeshParallelChannel,  # noqa: E402
                                        MeshPartitionChannel)
from brpc_tpu.parallel.collectives import bus_bandwidth_gbps  # noqa: E402
from brpc_tpu.parallel.mesh import make_mesh  # noqa: E402


def main():
    mesh = make_mesh({"dp": 2, "tp": 4})
    print("mesh:", dict(mesh.shape), "on", jax.devices()[0].platform)

    # ParallelChannel whose members are the tp axis: merge = all_reduce
    # (dim 0 is sharded over the axis — one shard per member)
    pch = MeshParallelChannel(mesh, "tp", merger="add")
    x = jnp.ones((8, 8))
    print("allreduce-merged fan-out:", pch.call_tensor(x)[0, 0],
          f"(= {mesh.shape['tp']} members summed)")

    # PartitionChannel on the axis: gather / reduce-scatter are the merges
    part = MeshPartitionChannel(mesh, "tp")
    print("all_gather merge shape:", part.call_gather(x).shape)
    print("reduce_scatter merge shape:",
          part.call_reduce_scatter(jnp.ones((16, 8))).shape)

    # the driver's ICI bus-bandwidth metric (BASELINE.json)
    gbps = bus_bandwidth_gbps(mesh, "tp", mbytes_per_shard=8)
    print(f"allreduce bus bandwidth over tp: {gbps:.2f} GB/s "
          f"(virtual CPU mesh — real number comes from TPU chips)")


if __name__ == "__main__":
    main()
