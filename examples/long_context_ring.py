"""Long-context forward with ring attention: sequence sharded over sp,
K/V blocks rotating on the ICI ring, O(S/n) HBM per chip
(parallel/ring_attention.py)."""
# JAX_PLATFORMS must be set BEFORE _bootstrap: its force_cpu_platform
# hang guard (dead-tunnel protection) only fires when the env says cpu
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "") +
     " --xla_force_host_platform_device_count=8").strip())

import _bootstrap  # noqa: F401,E402

import os  # noqa: E402  (env set before _bootstrap below)

import jax  # noqa: E402

if len(jax.devices()) < 8:
    # single real chip (or axon forced the TPU platform): fall back to a
    # virtual 8-device CPU mesh, same as the test conftest
    from jax.extend import backend as _jex_backend
    _jex_backend.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from brpc_tpu.models import ModelConfig, apply, init  # noqa: E402
from brpc_tpu.models.transformer import param_specs  # noqa: E402
from brpc_tpu.parallel.mesh import make_mesh  # noqa: E402


def main():
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    cfg = ModelConfig(vocab=256, d_model=64, n_heads=4, n_layers=2,
                      d_ff=128, max_seq=4096, attn_impl="ring",
                      dtype=jnp.float32)
    params = init(jax.random.key(0), cfg)
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, param_specs(cfg), is_leaf=lambda x: isinstance(x, P))
    S = 4096  # 2048 per sp shard; K/V never materialize full-S per chip
    tokens = jax.device_put(jnp.zeros((2, S), jnp.int32),
                            NamedSharding(mesh, P("dp", None)))
    logits = jax.jit(lambda p, t: apply(p, t, cfg, mesh))(params, tokens)
    logits.block_until_ready()
    print(f"ring-attention forward: seq={S} over sp={mesh.shape['sp']} "
          f"→ logits {logits.shape} ok")


if __name__ == "__main__":
    main()
