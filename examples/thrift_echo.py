"""Framed-thrift service + client on the shared port (≙
example/thrift_extension): the same listener speaks TRPC and thrift
side by side."""
import _bootstrap  # noqa: F401

from brpc_tpu.rpc import thrift as t
from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.server import Server

ECHO_ARGS = (t.TType.STRUCT, {1: ("message", t.TType.STRING)})


def main():
    svc = t.ThriftService()
    svc.register("Echo", lambda a: a["message"],
                 args_spec=ECHO_ARGS, result_spec=t.TType.STRING)

    def fails(_a):
        raise t.TApplicationException(
            t.TApplicationException.INTERNAL_ERROR, "as requested")
    svc.register("Fail", fails, args_spec=None, result_spec=t.TType.I32)

    server = Server()
    server.add_echo_service()
    server.add_thrift_service(svc)
    port = server.start("127.0.0.1:0")

    c = t.ThriftClient("127.0.0.1", port)
    print("thrift Echo ->", c.call("Echo", {"message": "hello thrift"},
                                   ECHO_ARGS, result_spec=t.TType.STRING))
    try:
        c.call("Fail", {}, None, result_spec=t.TType.I32)
    except t.TApplicationException as e:
        print("thrift Fail ->", f"TApplicationException({e.message})")

    # TRPC lives on the very same port
    ch = Channel(f"127.0.0.1:{port}")
    print("TRPC Echo   ->", ch.call("Echo.echo", b"same port"))
    ch.close()
    c.close()
    server.destroy()


if __name__ == "__main__":
    main()
