"""SelectiveChannel: LB over heterogeneous sub-channels with failover
(≙ example/selective_echo — each sub-channel can itself be a cluster)."""
import _bootstrap  # noqa: F401

from brpc_tpu.parallel.channels import SelectiveChannel
from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.server import Server


def make_server(name: bytes):
    s = Server()
    s.add_service("Who", lambda cntl, req, n=name: n)
    s.start("127.0.0.1:0")
    return s


def main():
    a, b = make_server(b"cluster-a"), make_server(b"cluster-b")
    sch = SelectiveChannel(max_retry=2)
    sch.add_channel(Channel(f"127.0.0.1:{a.port}"))
    sch.add_channel(Channel(f"127.0.0.1:{b.port}"))
    print("round-robin:", [sch.call("Who", b"").decode() for _ in range(4)])

    a.destroy()  # cluster-a dies: calls fail over to b and a is isolated
    print("after a down:", [sch.call("Who", b"").decode()
                            for _ in range(3)])
    b.destroy()


if __name__ == "__main__":
    main()
