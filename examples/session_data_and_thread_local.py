"""Session-local data + fiber-local storage (≙
example/session_data_and_thread_local: SimpleDataPool reusing expensive
per-request session objects, bthread-local values surviving handler
hops)."""
import _bootstrap  # noqa: F401

import itertools
import queue

from brpc_tpu import fiber
from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.server import Server


class SessionDataPool:
    """Reusable session objects (≙ SimpleDataPool + data_factory.h):
    expensive state is constructed once and recycled across requests
    instead of per-call."""

    def __init__(self, factory):
        self._factory = factory
        self._pool = queue.LifoQueue()
        self.created = 0

    def get(self):
        try:
            return self._pool.get_nowait()
        except queue.Empty:
            self.created += 1
            return self._factory()

    def put(self, obj):
        self._pool.put(obj)


def main():
    counter = itertools.count(1)
    pool = SessionDataPool(lambda: {"id": next(counter), "uses": 0})
    request_local = fiber.FiberLocal()  # ≙ bthread_key_t value per task

    def handler(cntl, req):
        session = pool.get()
        try:
            session["uses"] += 1
            request_local.set(req.decode())
            # ... deeper code reads the value without plumbing it through
            tag = request_local.get()
            return (f"session={session['id']} uses={session['uses']} "
                    f"tag={tag}").encode()
        finally:
            pool.put(session)

    server = Server()
    server.add_service("Session", handler)
    port = server.start("127.0.0.1:0")

    ch = Channel(f"127.0.0.1:{port}")
    for i in range(6):
        print(ch.call("Session", f"req-{i}".encode()).decode())
    print(f"sessions created: {pool.created} (recycled across 6 requests)")
    ch.close()
    request_local.close()
    server.destroy()


if __name__ == "__main__":
    main()
