"""Echo served through the io_uring transport (the FORK's RingListener
≙ socket.h:360 + provided-buffer recv): multishot ACCEPT adopts
connections, multishot RECV feeds the parse path — ~19% over epoll on
the echo bench.  Falls back to epoll transparently when the kernel
refuses the ring (the flag is safe to leave on)."""
import _bootstrap  # noqa: F401

from brpc_tpu._native import lib
from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.server import Server
from brpc_tpu.utils import flags


def main():
    available = bool(lib().trpc_io_uring_available())
    print("io_uring available:", available)
    flags.set_flag("use_io_uring", True)

    server = Server()
    server.add_echo_service()
    port = server.start("127.0.0.1:0")

    ch = Channel(f"127.0.0.1:{port}")
    for i in range(5):
        assert ch.call("Echo.echo", f"ring-{i}".encode()) == \
            f"ring-{i}".encode()
    print("5 echoes over", "io_uring" if available else "epoll (fallback)")

    # the engine's internals are live bvars (also on /vars)
    import ctypes
    buf = ctypes.create_string_buffer(1 << 16)
    n = lib().trpc_native_metrics_dump(buf, len(buf))
    for line in buf.raw[:n].decode().splitlines():
        if line.startswith("native_uring_"):
            print(" ", line)
    ch.close()
    server.destroy()


if __name__ == "__main__":
    main()
