"""Memcache binary-protocol client with batched quiet-op pipelining (≙
example/memcache).  No memcached daemon in this image, so the demo
serves the binary protocol from a tiny in-process store — the client
bytes on the wire are exactly what stock memcached speaks."""
import _bootstrap  # noqa: F401

import os
import sys

from brpc_tpu.rpc.memcache import MemcacheClient

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tests"))
from test_memcache import MiniMemcached  # noqa: E402  (spec-faithful store)


def main():
    srv = MiniMemcached()
    c = MemcacheClient("127.0.0.1", srv.port)

    cas = c.set("greeting", b"hello memcache", flags=1)
    print("set   -> cas", cas)
    print("get   ->", c.get("greeting"))
    print("incr  ->", c.incr("hits", 1, initial=41))
    print("incr  ->", c.incr("hits", 1))

    # one round trip for many keys (quiet GETKQ + NOOP batching,
    # ≙ MemcacheRequest packing N operations)
    b = c.batch()
    for i in range(5):
        b.set(f"key-{i}", f"value-{i}".encode())
    b.execute()
    got = c.multi_get([f"key-{i}" for i in range(5)] + ["missing"])
    print("multi_get ->", {k.decode(): v.decode() for k, v in got.items()})

    val, cas = c.gets("greeting")
    c.set("greeting", b"compare-and-swapped", cas=cas)
    print("cas   ->", c.get("greeting"))

    c.close()
    srv.close()


if __name__ == "__main__":
    main()
