"""ParallelChannel fan-out + merge (≙ example/parallel_echo: one logical
call broadcast to N servers, responses merged; fail_limit tolerance)."""
import _bootstrap  # noqa: F401

from brpc_tpu.parallel.channels import (CallMapper, ParallelChannel,
                                        ResponseMerger, SubCall)
from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.server import Server


def make_server(name: bytes):
    s = Server()
    s.add_service("Who", lambda cntl, req, n=name: n + b"(" + req + b")")
    s.start("127.0.0.1:0")
    return s


class ShardMapper(CallMapper):
    """Give each member its slice of the payload (scatter, not broadcast)."""

    def map(self, i, n, method, payload, attachment):
        chunk = (len(payload) + n - 1) // n
        return SubCall(method, payload[i * chunk:(i + 1) * chunk])


def main():
    servers = [make_server(f"s{i}".encode()) for i in range(3)]
    pch = ParallelChannel(fail_limit=1)
    for s in servers:
        pch.add_channel(Channel(f"127.0.0.1:{s.port}"))

    print("broadcast:", pch.call("Who", b"hi"))

    scatter = ParallelChannel()
    for s in servers:
        scatter.add_channel(Channel(f"127.0.0.1:{s.port}"), ShardMapper())
    print("scatter:  ", scatter.call("Who", b"abcdef"))

    # fail_limit tolerance: kill one member, broadcast still succeeds
    servers[1].destroy()
    print("1 member down, fail_limit=1:", pch.call("Who", b"degraded"))
    for s in (servers[0], servers[2]):
        s.destroy()


if __name__ == "__main__":
    main()
