"""Shared path bootstrap so examples run from any cwd."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Honor an explicit JAX_PLATFORMS=cpu even under the axon sitecustomize
# (which force-selects the tunneled-TPU platform; a dead tunnel then
# hangs jax initialization).  A cpu-forced example also must not inherit
# the tunnel pool config: with it present, even `import jax` can hang on
# a dead tunnel (same reason tests/conftest.py pops it).  Examples that
# use the NATIVE device plane (ici_performance) don't force cpu, so
# their relay contract is untouched.
if os.environ.get("JAX_PLATFORMS") == "cpu":
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

# Examples pin shards=1 (ISSUE 7): they are single-connection demos whose
# output tests/test_examples.py asserts on — an inherited TRPC_SHARDS
# from a sharded-suite sweep must not change their runtime shape.
os.environ["TRPC_SHARDS"] = "1"

from brpc_tpu.utils.jaxenv import force_cpu_platform  # noqa: E402

force_cpu_platform()
