"""Shared path bootstrap so examples run from any cwd."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Honor an explicit JAX_PLATFORMS=cpu even under the axon sitecustomize
# (which force-selects the tunneled-TPU platform; a dead tunnel then
# hangs jax initialization).
from brpc_tpu.utils.jaxenv import force_cpu_platform  # noqa: E402

force_cpu_platform()
