"""Cascading RPC: a middle server whose handler calls a downstream server
(≙ example/cascade_echo — latency composes, portals show both hops)."""
import _bootstrap  # noqa: F401

from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.server import Server


def main():
    backend = Server()
    backend.add_service("Deep", lambda cntl, req: b"deep(" + req + b")")
    backend.start("127.0.0.1:0")

    middle = Server()
    down = Channel(f"127.0.0.1:{backend.port}")

    def relay(cntl, req):
        inner = down.call("Deep", req)  # handler issues its own RPC
        return b"relay(" + inner + b")"

    middle.add_service("Relay", relay)
    middle.start("127.0.0.1:0")

    ch = Channel(f"127.0.0.1:{middle.port}")
    print("cascaded:", ch.call("Relay", b"x"))
    ch.close()
    down.close()
    middle.destroy()
    backend.destroy()


if __name__ == "__main__":
    main()
