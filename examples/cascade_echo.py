"""Cascading RPC: a middle server whose handler calls a downstream server
(≙ example/cascade_echo — latency composes, portals show both hops).

With deadline-budget propagation on (ISSUE 19), each tier also sees how
much of the ROOT caller's budget it inherited: the root stamps its
timeout as meta tag 18, the middle tier's downstream call (made with no
explicit timeout) defaults to the inherited remainder minus the per-hop
reserve (TRPC_DEADLINE_RESERVE_US), so the budget visibly SHRINKS hop by
hop instead of every tier re-arming its own full timeout.
"""
import _bootstrap  # noqa: F401

from brpc_tpu.rpc.channel import Channel
from brpc_tpu.rpc.server import Server
from brpc_tpu.utils import flags


def main():
    flags.set_flag("deadline_propagate", True)

    backend = Server()

    def deep(cntl, req):
        print(f"  backend inherited deadline_left_us={cntl.deadline_left_us}")
        return b"deep(" + req + b")"

    backend.add_service("Deep", deep)
    backend.start("127.0.0.1:0")

    middle = Server()
    down = Channel(f"127.0.0.1:{backend.port}")

    def relay(cntl, req):
        print(f"  middle  inherited deadline_left_us={cntl.deadline_left_us}")
        # no explicit timeout: the downstream attempt's budget defaults
        # to the inherited remainder minus the per-hop reserve, so the
        # backend prints a strictly smaller number than this tier saw
        inner = down.call("Deep", req)  # handler issues its own RPC
        return b"relay(" + inner + b")"

    middle.add_service("Relay", relay)
    middle.start("127.0.0.1:0")

    ch = Channel(f"127.0.0.1:{middle.port}")
    print("root sends timeout_ms=500 (the whole cascade's budget)")
    print("cascaded:", ch.call("Relay", b"x", timeout_ms=500))
    ch.close()
    down.close()
    middle.destroy()
    backend.destroy()


if __name__ == "__main__":
    main()
