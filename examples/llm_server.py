"""LLM serving flagship: continuous batching + KV block plane + token
streaming (ISSUE 14's end-to-end proof; ≙ the role example/
rdma_performance plays for the reference's RDMA path — the workload that
earns the transport).

One process hosts everything: a pjit decode loop over the 8-device CPU
mesh, the KV-cache block plane on the (fake-plugin) PJRT device plane,
and a TRPC server streaming one token per decode step to each client.

    * N_CLIENTS concurrent clients stream full generations (retrying
      ELIMIT sheds like real clients, so every one of them finishes)
    * one client cancels MID-STREAM via Controller.start_cancel — the
      engine evicts the sequence and frees its blocks
    * a no-retry burst offered beyond the block budget is SHED with
      ELIMIT (never queued) by the scheduler + the native per-method cap
    * prefill→decode KV migration rides the tpu_d2d local rail
      (stats()["d2d_transfers"] delta printed in the proof line)

The last stdout line is a JSON proof block tests/test_examples.py
asserts on (balanced accounting, local-rail migrations, sheds, cancel)."""
import _bootstrap  # noqa: F401

import os

os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "") +
     " --xla_force_host_platform_device_count=8").strip())
# the device plane wants a PJRT plugin; default to the fake one the
# native build installs next to the core .so (real TPU VMs override)
_FAKE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "brpc_tpu", "_native", "libpjrt_fake.so")
if os.path.exists(_FAKE):
    os.environ.setdefault("TRPC_PJRT_PLUGIN", _FAKE)

import json           # noqa: E402
import struct         # noqa: E402
import threading      # noqa: E402
import time           # noqa: E402

from brpc_tpu import tpu_plane                       # noqa: E402
from brpc_tpu.parallel.mesh import make_mesh         # noqa: E402
from brpc_tpu.rpc import errors                      # noqa: E402
from brpc_tpu.rpc.channel import Channel, ChannelOptions  # noqa: E402
from brpc_tpu.rpc.server import Server, ServerOptions     # noqa: E402
from brpc_tpu.serving import ServingEngine           # noqa: E402
from brpc_tpu.serving.engine import TOKEN_FMT, tiny_config  # noqa: E402
from brpc_tpu.serving.kv_cache import KvBlockPlane   # noqa: E402

N_CLIENTS = 8      # full-generation streamers (the acceptance floor)
N_BURST = 12       # no-retry offered load beyond the budget (shed bait)
MAX_NEW = 8


def _pct(xs, p):
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(len(s) - 1, int(p * len(s)))]


def main():
    plane_up = tpu_plane.init()
    stats0 = tpu_plane.stats() if plane_up else {}
    mesh = make_mesh({"dp": 2, "tp": 4})
    engine = ServingEngine(cfg=tiny_config(), mesh=mesh,
                           kv=KvBlockPlane(block_bytes=4096, n_blocks=48),
                           n_slots=4, max_waiting=4)
    server = Server(ServerOptions(
        # the PR-11 native gate in front of the scheduler: anything past
        # what the batcher could even hold sheds on the parse fiber
        method_max_concurrency={"LLM.Generate": engine.method_cap}))
    engine.register(server)
    port = server.start("127.0.0.1:0")
    engine.start()
    addr = f"127.0.0.1:{port}"
    print(f"serving on {addr} (plane={'up' if plane_up else 'DOWN'}, "
          f"slots=4, blocks=48)")

    lock = threading.Lock()
    out = {"streamed": 0, "tokens": 0, "shed": 0, "errors": 0,
           "cancel_reset": 0, "ttft_ms": [], "gap_ms": []}

    def generate(i, retry=True):
        """One full-generation client; retries sheds so it always
        finishes (the burst clients don't)."""
        ch = Channel(addr, ChannelOptions(timeout_ms=60000, max_retry=0))
        req = json.dumps({"prompt_len": 10 + i % 4,
                          "max_new_tokens": MAX_NEW}).encode()
        try:
            while True:
                t0 = time.monotonic()
                try:
                    _, st = ch.create_stream("LLM.Generate", req)
                    break
                except errors.RpcError as e:
                    if e.code != errors.ELIMIT or not retry:
                        with lock:
                            out["shed" if e.code == errors.ELIMIT
                                else "errors"] += 1
                        return
                    with lock:
                        out["shed"] += 1
                    time.sleep(0.1)
            n, last = 0, None
            while True:
                msg = st.read(timeout_s=120)
                if msg is None:
                    break
                now = time.monotonic()
                tok = struct.unpack(TOKEN_FMT, msg)[0]
                assert tok < 128, tok
                with lock:
                    out["tokens"] += 1
                    if n == 0:
                        out["ttft_ms"].append((now - t0) * 1e3)
                    else:
                        out["gap_ms"].append((now - last) * 1e3)
                n, last = n + 1, now
            st.destroy()
            with lock:
                out["streamed"] += 1 if n == MAX_NEW else 0
        finally:
            ch.close()

    def cancel_client():
        """Reads two tokens, then RSTs the stream mid-decode (the wire
        form every cancel takes once the handshake response is out): the
        engine must evict the sequence and free its blocks."""
        ch = Channel(addr, ChannelOptions(timeout_ms=60000, max_retry=0))
        try:
            while True:
                try:
                    _, st = ch.create_stream(
                        "LLM.Generate",
                        json.dumps({"prompt_len": 12,
                                    "max_new_tokens": 32}).encode())
                    break
                except errors.RpcError as e:
                    if e.code != errors.ELIMIT:
                        raise
                    time.sleep(0.1)
            for _ in range(2):
                st.read(timeout_s=120)
            st.rst(errors.ECANCELED)
            with lock:
                out["cancel_reset"] += 1
            st.destroy()
        finally:
            ch.close()

    threads = [threading.Thread(target=generate, args=(i,))
               for i in range(N_CLIENTS)]
    threads.append(threading.Thread(target=cancel_client))
    for t in threads:
        t.start()
        time.sleep(0.02)
    # wait until the batch is hot, then offer a no-retry burst the
    # budget cannot hold — the plane must SHED it, not queue it
    deadline = time.monotonic() + 60
    while engine.stats()["running"] < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    burst = [threading.Thread(target=generate, args=(100 + i, False))
             for i in range(N_BURST)]
    for t in burst:
        t.start()
    for t in threads + burst:
        t.join(180)
    # the decode loop notices the mid-stream RST on its next write to
    # that sequence; wait for the eviction to land before draining
    deadline = time.monotonic() + 60
    while engine.stats()["canceled"] < 1 and time.monotonic() < deadline:
        time.sleep(0.05)

    engine.stop()
    engine.assert_drained()
    es = engine.stats()
    stats1 = tpu_plane.stats() if plane_up else {}
    d2d = stats1.get("d2d_transfers", 0) - stats0.get("d2d_transfers", 0)
    print(f"engine: {json.dumps({k: v for k, v in es.items() if v})}")
    proof = {
        "metric": "llm_server",
        "clients": N_CLIENTS,
        "streamed": out["streamed"],
        "tokens": out["tokens"],
        "tokens_out": es["tokens_out"],
        "shed_client": out["shed"],
        "shed_server": es["shed"],
        "canceled": es["canceled"],
        "cancel_reset": out["cancel_reset"],
        "finished": es["finished"],
        "rail_local": es["rail_local"],
        "d2d_delta": d2d,
        "plane": plane_up,
        "live_buffers_end": stats1.get("live_buffers", 0),
        "balanced": True,  # assert_drained() above would have thrown
        "ttft_ms_p50": round(_pct(out["ttft_ms"], .5), 1),
        "ttft_ms_p99": round(_pct(out["ttft_ms"], .99), 1),
        "itl_ms_p50": round(_pct(out["gap_ms"], .5), 1),
        "itl_ms_p99": round(_pct(out["gap_ms"], .99), 1),
    }
    server.destroy()
    print(json.dumps(proof))


if __name__ == "__main__":
    main()
