"""Parameter-server gradient allreduce — the BASELINE.md stretch
acceptance config (≙ the reference's ParallelChannel parameter-server
workload, parallel_channel.h:185, retargeted at the mesh: each data-
parallel worker holds its local gradients for a REAL-sized parameter set
(ResNet-50's actual layer shapes, ~25.5M params) and the ParallelChannel
fan-out + "add" ResponseMerger IS one XLA allreduce riding ICI,
SURVEY §2.9 lowering table).

Prints one JSON line with the measured gradient-allreduce rate and the
synthetic bus-bandwidth probe (collectives.bus_bandwidth_gbps), and
verifies the merged gradients numerically against dense jnp."""
# JAX_PLATFORMS must be set BEFORE _bootstrap: its force_cpu_platform
# hang guard (dead-tunnel protection) only fires when the env says cpu
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "") +
     " --xla_force_host_platform_device_count=8").strip())

import _bootstrap  # noqa: F401,E402

import json
import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "") +
     " --xla_force_host_platform_device_count=8").strip())

import jax  # noqa: E402

if len(jax.devices()) < 8:
    from jax.extend import backend as _jex_backend
    _jex_backend.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from brpc_tpu.parallel.channels import MeshParallelChannel  # noqa: E402
from brpc_tpu.parallel.collectives import bus_bandwidth_gbps  # noqa: E402
from brpc_tpu.parallel.mesh import make_mesh  # noqa: E402


def resnet50_param_shapes():
    """The actual ResNet-50 parameter shapes (conv/BN/fc), ~25.5M params
    — a 'real-sized param set', not a synthetic blob."""
    shapes = [("conv1", (7, 7, 3, 64)), ("bn1_scale", (64,)),
              ("bn1_bias", (64,))]
    in_ch = 64
    stage_planes = [(64, 3), (128, 4), (256, 6), (512, 3)]
    for s, (planes, blocks) in enumerate(stage_planes):
        out_ch = planes * 4
        for b in range(blocks):
            pfx = f"layer{s + 1}.{b}"
            shapes += [
                (f"{pfx}.conv1", (1, 1, in_ch, planes)),
                (f"{pfx}.bn1_scale", (planes,)),
                (f"{pfx}.bn1_bias", (planes,)),
                (f"{pfx}.conv2", (3, 3, planes, planes)),
                (f"{pfx}.bn2_scale", (planes,)),
                (f"{pfx}.bn2_bias", (planes,)),
                (f"{pfx}.conv3", (1, 1, planes, out_ch)),
                (f"{pfx}.bn3_scale", (out_ch,)),
                (f"{pfx}.bn3_bias", (out_ch,)),
            ]
            if b == 0:
                shapes += [(f"{pfx}.downsample", (1, 1, in_ch, out_ch)),
                           (f"{pfx}.bn_ds_scale", (out_ch,)),
                           (f"{pfx}.bn_ds_bias", (out_ch,))]
            in_ch = out_ch
    shapes += [("fc_w", (2048, 1000)), ("fc_b", (1000,))]
    return shapes


def run(iters: int = 3, dtype=jnp.float32, codec: str = "none"):
    mesh = make_mesh({"dp": len(jax.devices())})
    n = mesh.shape["dp"]
    ch = MeshParallelChannel(mesh, "dp", merger="add", codec=codec)

    shapes = resnet50_param_shapes()
    nparams = sum(int(np.prod(s)) for _, s in shapes)
    grad_bytes = nparams * jnp.dtype(dtype).itemsize

    # one flat gradient vector per worker (what the PS ships), worker i
    # holding a deterministic pattern so the merge is checkable
    flat = jnp.arange(nparams, dtype=dtype) % 97
    stacked = jnp.stack([flat * (i + 1) for i in range(n)])  # (n, P)
    from jax.sharding import NamedSharding, PartitionSpec
    stacked = jax.device_put(stacked,
                             NamedSharding(mesh, PartitionSpec("dp")))

    # numeric acceptance against the dense jnp sum
    merged = ch.call_tensor(stacked)
    expect = flat * (n * (n + 1) // 2)
    codec_err = codec_bound = None
    if codec == "none":
        np.testing.assert_allclose(np.asarray(merged[0]),
                                   np.asarray(expect), rtol=1e-5)
    else:
        # lossy-but-BOUNDED leg (ISSUE 8): the dequantize-then-reduce
        # sum's error is at most the per-worker codec bounds added
        # (parallel/quantize.py mirrors native/src/codec.h's formats)
        from brpc_tpu.parallel import quantize
        rows = np.asarray(jax.device_get(stacked))
        if codec == "int8":
            codec_bound = sum(
                quantize.int8_error_bound(jnp.asarray(rows[i]))
                for i in range(n))
        else:  # bf16: 8 explicit mantissa bits -> rel err <= 2^-9+ulp,
            # bounded per worker by max|shard| * 2^-8 (safe factor)
            codec_bound = sum(
                float(np.max(np.abs(rows[i]))) * 2.0 ** -8
                for i in range(n))
        codec_err = float(np.max(np.abs(
            np.asarray(merged[0]) - np.asarray(expect))))
        assert codec_err <= codec_bound, (
            f"{codec} allreduce error {codec_err} exceeds the "
            f"documented bound {codec_bound}")
        # the leg must actually be lossy (0 error would mean the codec
        # silently didn't engage)
        assert codec_err > 0.0, f"{codec} codec did not engage"

    # measured rate of the real gradient allreduce (first call above
    # already compiled + warmed the jit cache)
    ch.call_tensor(stacked)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = ch.call_tensor(stacked)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    algbw = grad_bytes * iters / dt / 1e9
    busbw = algbw * 2 * (n - 1) / n

    return {
        "workload": "param_server_allreduce",
        "params": nparams,
        "grad_mbytes": round(grad_bytes / 1e6, 1),
        "devices": n,
        "platform": jax.devices()[0].platform,
        "numeric_check": "ok",
        "codec": codec,
        "codec_max_abs_err": codec_err,
        "codec_err_bound": codec_bound,
        "allreduce_algbw_gbps": round(algbw, 3),
        "allreduce_busbw_gbps": round(busbw, 3),
        # the driver's synthetic ICI probe (small shard: the number that
        # matters on CPU CI is that it RUNS; the real-chip run uses the
        # same code path at real sizes)
        "probe_busbw_gbps": round(
            bus_bandwidth_gbps(mesh, "dp", mbytes_per_shard=2.0,
                               iters=3), 3),
    }


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--codec", default="none",
                    choices=("none", "int8", "bf16"),
                    help="run the reduce leg through the quantizing "
                         "payload codec (lossy, asserted within its "
                         "documented bound)")
    args = ap.parse_args()
    print(json.dumps(run(codec=args.codec)))


if __name__ == "__main__":
    main()
