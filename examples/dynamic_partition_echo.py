"""DynamicPartitionChannel: 2-way and 4-way schemes live simultaneously;
traffic weights by scheme capacity so migrations drain gradually
(≙ example/dynamic_partition_echo)."""
import _bootstrap  # noqa: F401

import collections
import os
import tempfile
import time

from brpc_tpu.parallel.channels import DynamicPartitionChannel
from brpc_tpu.rpc.server import Server


def make_server(name: bytes):
    s = Server()
    s.add_service("Who", lambda cntl, req, n=name: n)
    s.start("127.0.0.1:0")
    return s


def main():
    old2 = [make_server(b"2way") for _ in range(2)]
    new4 = [make_server(b"4way") for _ in range(4)]
    # file:// naming so membership can change live (≙ file naming service)
    fd, path = tempfile.mkstemp(suffix=".ns")
    os.close(fd)
    with open(path, "w") as f:
        for i, s in enumerate(old2):
            f.write(f"127.0.0.1:{s.port} {i}/2\n")

    dch = DynamicPartitionChannel("file://" + path)
    print("capacities (2-way only):", dch.scheme_capacities())

    # migration: the 4-way scheme appears in naming; both serve until the
    # 2-way set is withdrawn
    with open(path, "w") as f:
        for i, s in enumerate(old2):
            f.write(f"127.0.0.1:{s.port} {i}/2\n")
        for i, s in enumerate(new4):
            f.write(f"127.0.0.1:{s.port} {i}/4\n")
    time.sleep(0.8)  # file naming service re-reads on mtime, 0.5s poll
    print("capacities (both):      ", dch.scheme_capacities())
    hits = collections.Counter(dch.call("Who", b"") for _ in range(30))
    print("mixed traffic:          ", dict(hits))

    with open(path, "w") as f:
        for i, s in enumerate(new4):
            f.write(f"127.0.0.1:{s.port} {i}/4\n")
    time.sleep(0.8)  # file naming service re-reads on mtime, 0.5s poll
    hits = collections.Counter(dch.call("Who", b"") for _ in range(10))
    print("after migration:        ", dict(hits))

    dch.close()
    os.unlink(path)
    for s in old2 + new4:
        s.destroy()


if __name__ == "__main__":
    main()
