#!/usr/bin/env python
"""Driver benchmark: echo QPS over the native loopback transport.

Mirrors the reference's headline benchmark (docs/cn/benchmark.md:7 — echo
QPS on one machine, 1M-5M on 24 HT cores ⇒ ~41.7k QPS/core at the low end).
The whole hot path is native (native/src/rpc.cc run_echo_bench): fibers,
wait-free socket writes, TRPC framing; Python only launches it.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = our QPS/core ÷ reference QPS/core (1M/24).
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # bench is host-side
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import ctypes

    from brpc_tpu._native import lib

    L = lib()
    ncpu = os.cpu_count() or 1
    workers = max(2, min(ncpu, 8))
    L.trpc_init(workers)

    # ring transport when the kernel grants it: multishot accept +
    # provided-buffer recv measured ~19% over epoll on the echo loop
    # (falls back automatically when io_uring is unavailable)
    use_ring = bool(L.trpc_io_uring_available())
    L.trpc_set_io_uring(1 if use_ring else 0)

    # in-process echo server with the native echo handler (no Python in
    # the hot path), then the native multi-fiber client loop against it
    srv = L.trpc_server_create()
    L.trpc_server_add_echo(srv)
    if L.trpc_server_start(srv, b"127.0.0.1", 0) != 0:
        print(json.dumps({"metric": "echo_qps", "value": 0.0,
                          "unit": "qps", "vs_baseline": 0.0,
                          "error": "server start failed"}))
        return 1
    port = L.trpc_server_port(srv)

    out = (ctypes.c_double * 9)()

    def run(nconn: int, conc: int, secs: float):
        rc = L.trpc_run_echo_bench(b"127.0.0.1", port, nconn, conc,
                                   16, 0, secs, out)
        if rc != 0:
            return None
        return out[0], out[1], out[3]  # qps, p50, p99

    # batching amortizes syscalls; surprisingly the multi-connection
    # configs can win EVEN on one core (deeper aggregate pipelining —
    # 8x256 beat 1x128 in the round-4 ring-transport grid), so probe
    # them unconditionally and let the measurements decide
    grid = [(1, 64), (1, 128), (2, 128), (4, 256), (8, 256)]
    best = None
    for nconn, conc in grid:
        r = run(nconn, conc, 1.0)
        if r is not None and (best is None or r[0] > best[1][0]):
            best = ((nconn, conc), r)
    if best is None:
        print(json.dumps({"metric": "echo_qps", "value": 0.0,
                          "unit": "qps", "vs_baseline": 0.0,
                          "error": "bench failed"}))
        return 1
    (nconn, conc), _ = best
    r = run(nconn, conc, 3.0)  # sustained run at the winning config
    qps, p50, p99 = r if r is not None else best[1]
    # unloaded latency: a single synchronous caller (the p99 <50us target
    # in BASELINE.md is a no-queueing number)
    lat = run(1, 1, 1.5)
    ref_qps_per_core = 1_000_000 / 24.0  # docs/cn/benchmark.md:7 low end
    cores_used = min(ncpu, workers)  # bench engages `workers` cores at most
    vs = (qps / cores_used) / ref_qps_per_core
    print(json.dumps({
        "metric": "echo_qps",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(vs, 3),
        "p50_us": round(p50, 1),
        "p99_us": round(p99, 1),
        "unloaded_p50_us": round(lat[1], 1) if lat else None,
        "unloaded_p99_us": round(lat[2], 1) if lat else None,
        "nconn": nconn,
        "concurrency": conc,
        "cores": ncpu,
        "transport": "io_uring" if use_ring else "epoll",
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
