#!/usr/bin/env python
"""Driver benchmark: echo QPS over the native loopback transport.

Mirrors the reference's headline benchmark (docs/cn/benchmark.md:7 — echo
QPS on one machine, 1M-5M on 24 HT cores ⇒ ~41.7k QPS/core at the low end).
The whole hot path is native (native/src/rpc.cc run_echo_bench): fibers,
wait-free socket writes, TRPC framing; Python only launches it.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = our QPS/core ÷ reference QPS/core (1M/24).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def _analyzer_version() -> str:
    """Short content hash of the static-analyzer tree (tools/lint.py
    analyzer_version) — '?' if the tools are unimportable, never a
    bench failure."""
    try:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        from lint import analyzer_version
        return analyzer_version()
    except Exception:  # noqa: BLE001 — bench must not die on tooling
        return "?"


def _scaling_table(cores_avail: int) -> dict:
    """The 1/2/4/8-core table (≙ docs/cn/benchmark.md methodology: same
    binary, pinned to N cores).  Each point is a subprocess because CPU
    affinity must bind before the fiber workers spawn."""
    table = {}
    me = os.path.abspath(__file__)
    for n in (1, 2, 4, 8):
        if n > cores_avail:
            break
        try:
            out = subprocess.run(
                [sys.executable, me, "--cores", str(n), "--brief"],
                capture_output=True, text=True, timeout=120)
            line = out.stdout.strip().splitlines()[-1]
            table[str(n)] = json.loads(line)["value"]
        except Exception:
            table[str(n)] = None
    return table


def _serving_arm(rail: str, codec: str, duration_s: float) -> dict:
    """One serving-bench arm (ISSUE 14): in-process ServingEngine +
    Server driven by rpc_press.press_stream.  Runs in its OWN subprocess
    (the PJRT client and the jit caches are process-global), so env must
    be staged before the jax import chain."""
    os.environ.setdefault(
        "XLA_FLAGS",
        (os.environ.get("XLA_FLAGS", "") +
         " --xla_force_host_platform_device_count=8").strip())
    fake = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "brpc_tpu", "_native", "libpjrt_fake.so")
    if os.path.exists(fake):
        os.environ.setdefault("TRPC_PJRT_PLUGIN", fake)
    from brpc_tpu import tpu_plane
    from brpc_tpu.parallel.mesh import make_mesh
    from brpc_tpu.rpc.channel import Channel, ChannelOptions
    from brpc_tpu.rpc.server import Server, ServerOptions
    from brpc_tpu.serving import ServingEngine
    from brpc_tpu.serving.engine import tiny_config
    from brpc_tpu.serving.kv_cache import KvBlockPlane
    from brpc_tpu.tools.rpc_press import press_stream

    plane = tpu_plane.init()
    engine = ServingEngine(
        cfg=tiny_config(), mesh=make_mesh({"dp": 2, "tp": 4}),
        kv=KvBlockPlane(block_bytes=4096, n_blocks=48,
                        rail=rail, codec=codec),
        n_slots=4, max_waiting=4)
    server = Server(ServerOptions(
        method_max_concurrency={"LLM.Generate": engine.method_cap}))
    engine.register(server)
    port = server.start("127.0.0.1:0")
    engine.start()
    addr = f"127.0.0.1:{port}"
    payload = json.dumps({"prompt_len": 12,
                          "max_new_tokens": 16}).encode()
    # warm the jit caches OFF the clock so the timed TTFT/ITL measure
    # serving, not XLA compilation (admitted-only percentiles stay
    # honest; BENCH_NOTES.md documents the methodology)
    ch = Channel(addr, ChannelOptions(timeout_ms=60000, max_retry=0))
    try:
        _, st = ch.create_stream("LLM.Generate", payload)
        while st.read(timeout_s=120) is not None:
            pass
        st.destroy()
    finally:
        ch.close()
    res = press_stream(addr, "LLM.Generate", payload,
                       concurrency=6, duration_s=duration_s)
    engine.stop()
    engine.assert_drained()          # raises on a block leak
    es = engine.stats()
    server.destroy()
    return {
        "metric": "serving_bench", "rail": rail, "codec": codec,
        "plane": plane, "duration_s": round(res.wall_s, 2),
        "streams": res.streams, "completed": res.completed,
        "shed": res.shed, "resets": res.resets, "errors": res.errors,
        "tokens": res.tokens,
        "tokens_per_s": round(res.tokens_per_s, 1),
        "ttft_p50_us": res._pct(res.ttft_us, .5),
        "ttft_p99_us": res._pct(res.ttft_us, .99),
        "gap_p50_us": res._pct(res.gap_us, .5),
        "gap_p99_us": res._pct(res.gap_us, .99),
        "gap_p999_us": res._pct(res.gap_us, .999),
        "rail_local": es["rail_local"], "rail_host": es["rail_host"],
        "kv_codec_bytes": es["kv_codec_bytes"],
        "preemptions": es["preemptions"],
        "balanced": True,
    }


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # bench is host-side
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import ctypes

    ap = argparse.ArgumentParser()
    ap.add_argument("--cores", type=int, default=0,
                    help="pin to N cores (affinity) and scale workers to N")
    ap.add_argument("--brief", action="store_true",
                    help="shorter probes (used by the scaling table)")
    ap.add_argument("--no-scaling", action="store_true",
                    help="skip the multi-core scaling table")
    ap.add_argument("--repeat", type=int, default=1,
                    help="run the echo grid N times; per-row min/median/"
                         "max, and the driver JSON line reports the "
                         "MEDIAN sustained QPS with a [min, max] band")
    ap.add_argument("--attach-bytes", type=int, default=0,
                    help="run ONLY the large-attachment bench at this "
                         "size and print one JSON line")
    ap.add_argument("--attach-ab", action="store_true",
                    help="back-to-back writev vs SEND_ZC table at "
                         "512KB/1MB/4MB attachments (one subprocess per "
                         "arm: the rail's state is process-global)")
    ap.add_argument("--client-cork-ab", action="store_true",
                    help="back-to-back client-cork A/B at the echo grid's "
                         "concurrency-256 config (one subprocess per arm: "
                         "TRPC_CLIENT_CORK=0 vs 1, --repeat honored)")
    ap.add_argument("--telemetry-ab", action="store_true",
                    help="telemetry-overhead A/B (ISSUE 9): full echo "
                         "grid with TRPC_TELEMETRY=0 vs 1 (one subprocess "
                         "per arm — histogram writes + per-request clock "
                         "reads on vs off), --repeat honored; the bands "
                         "must overlap within the ±20% single-core noise")
    ap.add_argument("--codec-ab", action="store_true",
                    help="payload-codec A/B (ISSUE 8): attachment GB/s "
                         "sweep at 512KB/1MB/4MB per codec "
                         "(none/snappy/bf16/int8; one subprocess per arm "
                         "— TRPC_PAYLOAD_CODEC is resolved per process), "
                         "per-row min/median/max over --repeat runs, "
                         "plus the param-server allreduce example's "
                         "codec legs")
    ap.add_argument("--codec-skip-allreduce", action="store_true",
                    help="with --codec-ab: skip the (slow, JAX) "
                         "allreduce legs and sweep attachments only")
    ap.add_argument("--serving", action="store_true",
                    help="LLM-serving bench (ISSUE 14): tokens/s + "
                         "admitted-only TTFT/ITL from rpc_press "
                         "--stream against the continuous-batching "
                         "engine, with the KV-migration rail/codec A/B "
                         "(auto-rail headline, then host-rail "
                         "none/bf16/int8; one subprocess per arm: the "
                         "PJRT client is process-global)")
    ap.add_argument("--serving-arm", default="",
                    help="internal: run ONE serving arm as "
                         "'rail,codec' and print its JSON line")
    args = ap.parse_args()

    if args.serving_arm:
        rail, codec = args.serving_arm.split(",")
        print(json.dumps(_serving_arm(rail, codec,
                                      4.0 if args.brief else 8.0)))
        return 0

    if args.serving:
        me = os.path.abspath(__file__)
        table = {}
        for rail, codec in (("auto", "none"), ("host", "none"),
                            ("host", "bf16"), ("host", "int8")):
            key = f"{rail}/{codec}"
            try:
                cmd = [sys.executable, me, "--serving-arm",
                       f"{rail},{codec}"]
                if args.brief:
                    cmd.append("--brief")
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=600)
                if r.returncode != 0:
                    raise RuntimeError(f"arm rc={r.returncode}: "
                                       f"{r.stderr[-300:]}")
                table[key] = json.loads(r.stdout.strip().splitlines()[-1])
            except Exception as e:  # noqa: BLE001 — arm -> error cell
                table[key] = {"error": str(e)}
        head = table.get("auto/none", {})
        print(json.dumps({"metric": "serving_ab",
                          "value": head.get("tokens_per_s"),
                          "unit": "tokens/s", "table": table}))
        return 0

    if args.codec_ab:
        me = os.path.abspath(__file__)
        reps = max(1, args.repeat)
        codecs = ("none", "snappy", "bf16", "int8")
        table = {}
        for size in (512 << 10, 1 << 20, 4 << 20):
            row = {}
            for codec in codecs:
                env = dict(os.environ)
                env["TRPC_PAYLOAD_CODEC"] = codec
                samples, good, errs = [], None, []
                for _ in range(reps):
                    try:
                        r = subprocess.run(
                            [sys.executable, me, "--attach-bytes",
                             str(size)], capture_output=True, text=True,
                            timeout=180, env=env)
                        if r.returncode != 0:
                            # a failed arm must NOT contribute a 0.0
                            # sample that drags the band down silently
                            raise RuntimeError(
                                f"arm rc={r.returncode}: "
                                f"{r.stderr[-200:]}")
                        good = json.loads(
                            r.stdout.strip().splitlines()[-1])
                        samples.append(float(good["value"]))
                    except Exception as e:  # noqa: BLE001 — arm -> err
                        errs.append(str(e))
                if samples:
                    samples.sort()
                    good["gbps_band"] = {
                        "min": round(samples[0], 3),
                        "median": round(samples[len(samples) // 2], 3),
                        "max": round(samples[-1], 3)}
                    if errs:
                        good["failed_repeats"] = errs
                    row[codec] = good
                else:
                    row[codec] = {"error": "; ".join(errs) or "no runs"}
            table[str(size)] = row
        out = {"metric": "codec_ab", "repeat": reps, "table": table}
        if not args.codec_skip_allreduce:
            # allreduce shapes (the 25.56M-param ResNet example) per
            # codec: algbw + the asserted numeric error of the lossy leg
            ex = os.path.join(os.path.dirname(me), "examples",
                              "param_server_allreduce.py")
            allreduce = {}
            for codec in ("none", "int8", "bf16"):
                try:
                    r = subprocess.run(
                        [sys.executable, ex, "--codec", codec],
                        capture_output=True, text=True, timeout=600)
                    j = json.loads(r.stdout.strip().splitlines()[-1])
                    allreduce[codec] = {
                        k: j.get(k) for k in
                        ("allreduce_algbw_gbps", "allreduce_busbw_gbps",
                         "codec_max_abs_err", "codec_err_bound")}
                except Exception as e:  # noqa: BLE001 — leg -> error
                    allreduce[codec] = {"error": str(e)}
            out["allreduce"] = allreduce
        print(json.dumps(out))
        return 0

    if args.telemetry_ab:
        me = os.path.abspath(__file__)
        table = {}
        for arm, extra in (("off", {"TRPC_TELEMETRY": "0"}),
                           ("on", {"TRPC_TELEMETRY": "1"})):
            env = dict(os.environ)
            env.update(extra)
            cmd = [sys.executable, me, "--no-scaling",
                   "--repeat", str(max(1, args.repeat))]
            if args.brief:
                cmd.append("--brief")
            try:
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=900, env=env)
                table[arm] = json.loads(r.stdout.strip().splitlines()[-1])
            except Exception as e:  # noqa: BLE001 — arm recorded null
                table[arm] = {"error": str(e)}
        print(json.dumps({"metric": "telemetry_ab", "table": table}))
        return 0

    if args.client_cork_ab:
        me = os.path.abspath(__file__)
        table = {}
        for arm, extra in (("uncorked", {"TRPC_CLIENT_CORK": "0"}),
                           ("corked", {"TRPC_CLIENT_CORK": "1"})):
            env = dict(os.environ)
            env.update(extra)
            cmd = [sys.executable, me, "--no-scaling",
                   "--repeat", str(max(1, args.repeat))]
            if args.brief:
                cmd.append("--brief")
            try:
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=900, env=env)
                table[arm] = json.loads(r.stdout.strip().splitlines()[-1])
            except Exception as e:  # noqa: BLE001 — arm recorded null
                table[arm] = {"error": str(e)}
        print(json.dumps({"metric": "client_cork_ab", "table": table}))
        return 0

    if args.attach_ab:
        me = os.path.abspath(__file__)
        table = {}
        for size in (512 << 10, 1 << 20, 4 << 20):
            row = {}
            for arm, extra in (("writev", {"BENCH_SENDZC": "0"}),
                               ("sendzc", {"BENCH_SENDZC": "1",
                                           "TRPC_SENDZC_FORCE": "1"})):
                env = dict(os.environ)
                env.update(extra)
                try:
                    r = subprocess.run(
                        [sys.executable, me, "--attach-bytes", str(size)],
                        capture_output=True, text=True, timeout=180,
                        env=env)
                    row[arm] = json.loads(
                        r.stdout.strip().splitlines()[-1])
                except Exception as e:  # noqa: BLE001 — arm recorded null
                    row[arm] = {"error": str(e)}
            table[str(size)] = row
        print(json.dumps({"metric": "attach_ab", "table": table}))
        return 0

    if args.cores > 0:
        # bind BEFORE the native init spawns fiber workers/dispatchers
        try:
            os.sched_setaffinity(0, set(range(args.cores)))
        except OSError:
            pass

    from brpc_tpu._native import lib

    L = lib()
    ncpu = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)
    workers = max(2, min(ncpu, 8))
    L.trpc_init(workers)

    # ring transport when the kernel grants it: multishot accept +
    # provided-buffer recv measured ~19% over epoll on the echo loop
    # (falls back automatically when io_uring is unavailable)
    use_ring = bool(L.trpc_io_uring_available())
    L.trpc_set_io_uring(1 if use_ring else 0)
    # egress arm override for the --attach-ab harness
    if os.environ.get("BENCH_SENDZC") == "0":
        L.trpc_set_sendzc(0)
    # ingress fast path A/B switch: TRPC_INLINE_DISPATCH=0 restores the
    # spawned dispatch path (fiber per request, per-response flushes)
    inline_on = os.environ.get("TRPC_INLINE_DISPATCH") != "0"
    L.trpc_set_inline_dispatch(1 if inline_on else 0)
    # client egress fast path A/B switch: TRPC_CLIENT_CORK=0 restores
    # plain per-request writes (no doorbell window on channel_call)
    cork_on = os.environ.get("TRPC_CLIENT_CORK") != "0"
    L.trpc_set_client_cork(1 if cork_on else 0)

    # in-process echo server with the native echo handler (no Python in
    # the hot path), then the native multi-fiber client loop against it
    srv = L.trpc_server_create()
    L.trpc_server_add_echo(srv)
    if L.trpc_server_start(srv, b"127.0.0.1", 0) != 0:
        print(json.dumps({"metric": "echo_qps", "value": 0.0,
                          "unit": "qps", "vs_baseline": 0.0,
                          "error": "server start failed"}))
        return 1
    port = L.trpc_server_port(srv)

    out = (ctypes.c_double * 9)()

    def run(nconn: int, conc: int, secs: float):
        rc = L.trpc_run_echo_bench(b"127.0.0.1", port, nconn, conc,
                                   16, 0, secs, out)
        if rc != 0:
            return None
        return out[0], out[1], out[3]  # qps, p50, p99

    def native_counter(name: str) -> int:
        buf = ctypes.create_string_buffer(1 << 16)
        n = L.trpc_native_metrics_dump(buf, len(buf))
        for line in buf.raw[:n].decode().splitlines():
            if line.startswith(name + " "):
                return int(line.split()[1])
        return 0

    def egress_label() -> str:
        if not use_ring:
            return "writev (epoll transport)"
        if not L.trpc_sendzc_available():
            return "writev (kernel lacks SEND_ZC)"
        if os.environ.get("BENCH_SENDZC") == "0":
            return "writev (rail disabled for A/B)"
        if not L.trpc_sendzc_active():
            return "writev (rail flagged off)"
        if native_counter("native_uring_sendzc_copied") > 0 and \
                os.environ.get("TRPC_SENDZC_FORCE") != "1":
            return ("sendzc->writev (notifications reported kernel "
                    "copies on this route)")
        return "sendzc"

    codec_names = {0: "none", 1: "snappy", 2: "bf16", 3: "int8"}

    if args.attach_bytes > 0:
        # single large-attachment run for the A/B harness: EFFECTIVE GB/s
        # (plain payload bytes moved per second — with a codec on, the
        # wire carries fewer) + which egress rail the bytes took + the
        # codec rail's own accounting (encoder-side bytes in/out = the
        # wire saving)
        rc = L.trpc_run_echo_bench(b"127.0.0.1", port, 2, 16, 16,
                                   args.attach_bytes, 2.0, out)
        print(json.dumps({
            "metric": "attach_gbps",
            "value": round(out[8], 3) if rc == 0 else 0.0,
            "qps": round(out[0], 1) if rc == 0 else 0.0,
            "attach_bytes": args.attach_bytes,
            "egress": egress_label(),
            "payload_codec": codec_names.get(
                int(L.trpc_payload_codec()), "?"),
            "codec_encodes": native_counter("native_codec_encodes"),
            "codec_decodes": native_counter("native_codec_decodes"),
            "codec_bytes_in": native_counter("native_codec_bytes_in"),
            "codec_bytes_out": native_counter("native_codec_bytes_out"),
            "sendzc_submitted": native_counter(
                "native_uring_sendzc_submitted"),
            "sendzc_copied": native_counter("native_uring_sendzc_copied"),
            "sendzc_fixed": native_counter("native_uring_sendzc_fixed"),
            "analyzer": _analyzer_version(),
        }))
        return 0 if rc == 0 else 1

    # batching amortizes syscalls; surprisingly the multi-connection
    # configs can win EVEN on one core (deeper aggregate pipelining —
    # 8x256 beat 1x128 in the round-4 ring-transport grid), so probe
    # them unconditionally and let the measurements decide.  --repeat N
    # walks the whole grid N times: single-core hosts swing ±20% between
    # runs (BENCH_NOTES.md), so one sample per row is noise — the row
    # stats and the reported median make the band explicit.
    grid = [(1, 64), (1, 128), (2, 128), (4, 256), (8, 256)]
    probe_s, sustain_s = (0.5, 1.5) if args.brief else (1.0, 3.0)
    reps = max(1, args.repeat)
    rows = {}  # "NxC" -> [probe qps...]
    for _ in range(reps):
        for nconn, conc in grid:
            r = run(nconn, conc, probe_s)
            if r is not None:
                rows.setdefault(f"{nconn}x{conc}", []).append(r[0])
    if not rows:
        print(json.dumps({"metric": "echo_qps", "value": 0.0,
                          "unit": "qps", "vs_baseline": 0.0,
                          "error": "bench failed"}))
        return 1

    def _stats(vals):
        s = sorted(vals)
        return {"min": round(s[0], 1), "median": round(s[len(s) // 2], 1),
                "max": round(s[-1], 1)}

    row_stats = {k: _stats(v) for k, v in rows.items()}
    best_key = max(row_stats, key=lambda k: row_stats[k]["median"])
    nconn, conc = (int(x) for x in best_key.split("x"))
    # sustained runs at the winning config: report the MEDIAN with the
    # observed [min, max] band
    sustained = []
    for _ in range(reps):
        r = run(nconn, conc, sustain_s)
        if r is not None:
            sustained.append(r)
    if not sustained:
        sustained = [(row_stats[best_key]["median"], 0.0, 0.0)]
    sustained.sort(key=lambda r: r[0])
    qps, p50, p99 = sustained[len(sustained) // 2]
    band = [round(sustained[0][0], 1), round(sustained[-1][0], 1)]
    # unloaded latency: a single synchronous caller (the p99 <50us target
    # in BASELINE.md is a no-queueing number)
    lat = run(1, 1, 0.5 if args.brief else 1.5)

    # large-payload egress: GB/s with a 1MB attachment per call — the
    # path the zero-copy rail (SEND_ZC + registered landing zones) was
    # built for.  `egress` records which rail the bytes actually took.
    large = None
    if not args.brief:
        attach = 1 << 20
        rc = L.trpc_run_echo_bench(b"127.0.0.1", port, 2, 16, 16, attach,
                                   2.0, out)
        if rc == 0 and out[0] > 0:
            large = {"gbps": round(out[8], 3), "qps": round(out[0], 1),
                     "attach_bytes": attach}
    egress = egress_label()

    ref_qps_per_core = 1_000_000 / 24.0  # docs/cn/benchmark.md:7 low end
    cores_used = min(ncpu, workers)  # bench engages `workers` cores at most
    vs = (qps / cores_used) / ref_qps_per_core
    result = {
        "metric": "echo_qps",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(vs, 3),
        "p50_us": round(p50, 1),
        "p99_us": round(p99, 1),
        "unloaded_p50_us": round(lat[1], 1) if lat else None,
        "unloaded_p99_us": round(lat[2], 1) if lat else None,
        "nconn": nconn,
        "concurrency": conc,
        "cores": ncpu,
        "transport": "io_uring" if use_ring else "epoll",
        "egress": egress,
        "repeat": reps,
        "band": band,
        "inline_dispatch": "on" if bool(
            L.trpc_inline_dispatch_active()) else "off",
        "inline_hits": native_counter("native_inline_dispatch_hits"),
        "inline_fallbacks": native_counter(
            "native_inline_dispatch_fallbacks"),
        "cork_responses_per_flush": native_counter(
            "native_batch_cork_responses_per_flush"),
        # hot-path telemetry (ISSUE 9): SERVER-side percentiles from the
        # native histograms beside the client-measured numbers above —
        # inline_echo is what the server saw for the same requests
        # (client_unary is issue->completion including the wait)
        "telemetry": "on" if bool(L.trpc_telemetry_active()) else "off",
        "server_p50_us": native_counter(
            "native_latency_inline_echo_p50_us"),
        "server_p99_us": native_counter(
            "native_latency_inline_echo_p99_us"),
        "server_p999_us": native_counter(
            "native_latency_inline_echo_p999_us"),
        "server_hist_count": native_counter(
            "native_latency_inline_echo_count"),
        "client_hist_p99_us": native_counter(
            "native_latency_client_unary_p99_us"),
        "client_cork": "on" if bool(L.trpc_client_cork_active()) else "off",
        "client_cork_windows": native_counter("native_client_cork_windows"),
        "client_inline_completes": native_counter(
            "native_client_inline_completes"),
        # runtime sharding (ISSUE 7): bench-of-record runs record the
        # active shard count (TRPC_SHARDS, boot-frozen); per-shard
        # accept/dispatch/inline/cork counters prove the partitioning —
        # on a sharded run the work must actually spread
        "shards": int(L.trpc_shard_count()),
        "cross_shard_hops": int(L.trpc_cross_shard_hops()),
        "per_shard": {
            str(k): {
                "accepts": native_counter(f"native_shard{k}_accepts"),
                "dispatches": native_counter(
                    f"native_shard{k}_dispatches"),
                "inline_hits": native_counter(
                    f"native_shard{k}_inline_hits"),
                "cork_flushes": native_counter(
                    f"native_shard{k}_cork_flushes"),
                "ring_cqes": native_counter(
                    f"native_shard{k}_ring_cqes"),
            }
            for k in range(int(L.trpc_shard_count()))
        },
        # overload-control plane (ISSUE 11): bench-of-record runs with
        # the plane OFF (rejects must stay 0 — a bench that shed load
        # would report admitted-only throughput as headline QPS); the
        # rpc_press --ramp cannon owns the overload numbers
        "overload": "on" if bool(L.trpc_overload_active()) else "off",
        "overload_admits": native_counter("native_overload_admits"),
        "overload_rejects": native_counter("native_overload_rejects"),
        # flight recorder (ISSUE 17): bench-of-record runs capture OFF
        # (samples/drops must stay 0 — capture overhead belongs to the
        # BENCH_NOTES "Traffic capture" A/B, not the headline QPS)
        "capture": "on" if bool(L.trpc_dump_active()) else "off",
        "capture_samples": native_counter("native_dump_captured"),
        "capture_drops": native_counter("native_dump_dropped"),
        # payload-codec rail (ISSUE 8): bench-of-record runs none; the
        # --codec-ab harness flips TRPC_PAYLOAD_CODEC per subprocess arm
        "payload_codec": codec_names.get(int(L.trpc_payload_codec()), "?"),
        "codec_encodes": native_counter("native_codec_encodes"),
        "codec_decodes": native_counter("native_codec_decodes"),
        "codec_bytes_in": native_counter("native_codec_bytes_in"),
        "codec_bytes_out": native_counter("native_codec_bytes_out"),
        # schedule perturbation MUST be off (0) for bench-of-record: a
        # nonzero seed means the run measured the fuzzing mode, not the
        # runtime (BENCH_NOTES.md "Schedule replay")
        "sched_seed": int(L.trpc_sched_seed()),
        # ISSUE 10: content hash of tools/lint.py + tools/analyze/* +
        # the manifests — BENCH_NOTES rows name the analyzed tree
        "analyzer": _analyzer_version(),
    }
    if reps > 1:
        result["rows"] = row_stats
    if large is not None:
        result["large_gbps"] = large["gbps"]
        result["large_attach_bytes"] = large["attach_bytes"]
        result["large_qps"] = large["qps"]
        result["sendzc_submitted"] = native_counter(
            "native_uring_sendzc_submitted")
        result["sendzc_copied"] = native_counter(
            "native_uring_sendzc_copied")
    if ncpu >= 2 and not args.brief and args.cores == 0 \
            and not args.no_scaling:
        # multi-core host: emit the per-core scaling table automatically
        # (each point re-runs this script pinned to N cores); a 1-core
        # host degrades to exactly the single-line behavior above
        L.trpc_server_stop(srv)
        result["scaling_qps_by_cores"] = _scaling_table(ncpu)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
