#!/usr/bin/env python
"""Driver benchmark: echo QPS over the native loopback transport.

Mirrors the reference's headline benchmark (docs/cn/benchmark.md:7 — echo
QPS on one machine, 1M-5M on 24 HT cores ⇒ ~41.7k QPS/core at the low end).
The whole hot path is native (native/src/rpc.cc run_echo_bench): fibers,
wait-free socket writes, TRPC framing; Python only launches it.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = our QPS/core ÷ reference QPS/core (1M/24).
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # bench is host-side
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import ctypes

    from brpc_tpu._native import lib

    L = lib()
    ncpu = os.cpu_count() or 1
    workers = max(2, min(ncpu, 8))
    L.trpc_init(workers)

    # in-process echo server with the native echo handler (no Python in
    # the hot path), then the native multi-fiber client loop against it
    srv = L.trpc_server_create()
    L.trpc_server_add_echo(srv)
    if L.trpc_server_start(srv, b"127.0.0.1", 0) != 0:
        print(json.dumps({"metric": "echo_qps", "value": 0.0,
                          "unit": "qps", "vs_baseline": 0.0,
                          "error": "server start failed"}))
        return 1
    port = L.trpc_server_port(srv)

    out = (ctypes.c_double * 9)()
    nconn = max(2, workers)
    concurrency = 4 * nconn
    rc = L.trpc_run_echo_bench(b"127.0.0.1", port, nconn, concurrency,
                               16, 0, 3.0, out)
    if rc != 0:
        print(json.dumps({"metric": "echo_qps", "value": 0.0,
                          "unit": "qps", "vs_baseline": 0.0,
                          "error": f"bench rc={rc}"}))
        return 1
    qps, p50, p90, p99 = out[0], out[1], out[2], out[3]
    ref_qps_per_core = 1_000_000 / 24.0  # docs/cn/benchmark.md:7 low end
    cores_used = min(ncpu, workers)  # bench engages `workers` cores at most
    vs = (qps / cores_used) / ref_qps_per_core
    print(json.dumps({
        "metric": "echo_qps",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(vs, 3),
        "p50_us": round(p50, 1),
        "p99_us": round(p99, 1),
        "cores": ncpu,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
