"""Rule `abi` (ISSUE 10 contract 4): the `trpc_*` C exports in
native/src/capi.cc and the ctypes declarations in
brpc_tpu/_native/__init__.py must agree BOTH ways.

Today a drifted binding is a silent-corruption class: ctypes guesses
int-sized arguments for undeclared functions, truncates 64-bit handles
on LP64, and reads garbage RAX for void returns — none of it crashes at
the call site.  The gate:

  * every `trpc_*` function DEFINED in capi.cc has a Python declaration
    (missing binding) and vice versa (stale binding — the export was
    renamed/removed but the ctypes decl survived);
  * declared argtypes match the C parameter list in arity and WIDTH
    CLASS (I32 / I64 / F64 / PTR — the classes whose confusion corrupts:
    an int binding for a size_t parameter truncates at 4GB, a c_int
    restype for a uint64_t handle drops the top half);
  * every binding with C parameters declares argtypes, and every binding
    whose C return is not plain `int` declares restype (ctypes' implicit
    c_int default is only correct for int).

The Python side is NOT parsed by regex: the module's `_declare(L)` is
executed against a recording stub, so loops/getattr-driven declarations
(`for f in (...): getattr(L, f"trpc_h2_result_{f}")`) are seen exactly
as ctypes sees them.

Escapes: `lint:allow-abi (reason)` on the capi.cc definition line, or a
`# lint:allow-abi trpc_name (reason)` line in the Python file.
"""

from __future__ import annotations

import ctypes
import importlib.util
import itertools
import os
import re
from typing import Dict, List, Optional

from .model import Violation, blank_comments

CAPI_REL = os.path.join("native", "src", "capi.cc")
PY_REL = os.path.join("brpc_tpu", "_native", "__init__.py")

_ESCAPE = "lint:allow-abi"

# width classes
I32, I64, F64, PTR, NONE, UNKNOWN = "i32", "i64", "f64", "ptr", "void", "?"

_C_I32 = {"int", "int32_t", "uint32_t", "unsigned", "unsigned int",
          "bool", "uint8_t", "int8_t", "uint16_t", "int16_t", "char"}
_C_I64 = {"int64_t", "uint64_t", "size_t", "ssize_t", "long",
          "unsigned long", "long long", "unsigned long long",
          "uintptr_t", "intptr_t"}


def _c_class(decl: str, fnptr_typedefs: set) -> str:
    d = decl.strip()
    d = re.sub(r"\bconst\b", "", d).strip()
    if not d or d == "void":
        return NONE
    if "*" in d or "[" in d or "(" in d:
        return PTR
    # strip the trailing parameter name
    m = re.match(r"([A-Za-z_][\w:\s]*?)\s+[A-Za-z_]\w*$", d)
    base = (m.group(1) if m else d).strip()
    if base in fnptr_typedefs:
        return PTR
    if base in _C_I32:
        return I32
    if base in _C_I64:
        return I64
    if base == "double" or base == "float":
        return F64
    return UNKNOWN


def parse_capi(root: str) -> Dict[str, dict]:
    """{name: {ret, params: [class...], line, escaped}} from capi.cc."""
    path = os.path.join(root, CAPI_REL)
    out: Dict[str, dict] = {}
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8", errors="replace") as f:
        text = f.read()
    blanked = blank_comments(text)
    lines = text.splitlines()
    fnptr_typedefs = set(re.findall(
        r"typedef\s+[\w\s\*]+\(\s*\*\s*(\w+)\s*\)", blanked))
    # definitions: ret trpc_name(params) {  — params may span lines and
    # contain function-pointer declarators, so the parameter list is
    # scanned with balanced parens, not a regex
    for m in re.finditer(r"\b(trpc_\w+)\s*\(", blanked):
        name = m.group(1)
        # balanced-paren scan for the closing ')'
        depth = 0
        i = m.end() - 1
        while i < len(blanked):
            if blanked[i] == "(":
                depth += 1
            elif blanked[i] == ")":
                depth -= 1
                if depth == 0:
                    break
            i += 1
        if i >= len(blanked):
            continue
        # a DEFINITION is followed by '{' (declarations/typedefs/calls
        # are followed by ';', ',', ')', operators, ...)
        j = i + 1
        while j < len(blanked) and blanked[j] in " \t\n":
            j += 1
        if j >= len(blanked) or blanked[j] != "{":
            continue
        # return declaration: scan back to the previous ; } { or newline
        # boundary of the previous statement
        k = m.start() - 1
        while k >= 0 and blanked[k] not in ";}{":
            k -= 1
        ret_decl = blanked[k + 1:m.start()].strip()
        if not ret_decl:
            continue  # a call like `trpc_foo(...) {` cannot occur; skip
        params = blanked[m.end():i]
        line1 = blanked.count("\n", 0, m.start()) + 1
        escaped = any(_ESCAPE in lines[x]
                      for x in range(max(0, line1 - 2),
                                     min(line1 + 1, len(lines))))
        if ret_decl.split()[-1] == "void" and "*" not in ret_decl:
            ret = NONE
        else:
            ret = _c_class(ret_decl + " x", fnptr_typedefs)  # fake a name
        plist = []
        params = params.strip()
        if params and params != "void":
            # split top-level commas only (fn-ptr params nest parens)
            depth = 0
            cur = ""
            parts = []
            for ch in params:
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                if ch == "," and depth == 0:
                    parts.append(cur)
                    cur = ""
                else:
                    cur += ch
            parts.append(cur)
            for p in parts:
                if "(" in p or "*" in p or "[" in p:
                    plist.append(PTR)
                else:
                    plist.append(_c_class(p, fnptr_typedefs))
        out[name] = {"ret": ret, "params": plist, "line": line1,
                     "escaped": escaped}
    return out


class _RecFn:
    def __init__(self, name: str):
        self.name = name
        self.argtypes: Optional[list] = None
        self.restype = "UNSET"


class _Recorder:
    def __init__(self):
        self.fns: Dict[str, _RecFn] = {}

    def __getattr__(self, name: str):
        if name.startswith("__"):
            raise AttributeError(name)
        fns = object.__getattribute__(self, "fns")
        if name not in fns:
            fns[name] = _RecFn(name)
        return fns[name]


_probe_counter = itertools.count()


def load_declarations(root: str) -> Optional[Dict[str, _RecFn]]:
    """Import the ctypes loader module from the target repo and run its
    _declare against a recorder.  Returns None when the module or its
    _declare is missing (reported by check())."""
    path = os.path.join(root, PY_REL)
    if not os.path.exists(path):
        return None
    modname = f"_abi_probe_{next(_probe_counter)}"
    spec = importlib.util.spec_from_file_location(modname, path)
    if spec is None or spec.loader is None:
        return None
    mod = importlib.util.module_from_spec(spec)
    try:
        spec.loader.exec_module(mod)
    except Exception:
        return None
    declare = getattr(mod, "_declare", None)
    if declare is None:
        return None
    rec = _Recorder()
    declare(rec)
    return rec.fns


def _py_class(obj) -> str:
    if obj is None:
        return NONE
    if obj is ctypes.c_double or obj is ctypes.c_float:
        return F64
    if obj in (ctypes.c_int, ctypes.c_int32, ctypes.c_uint32,
               ctypes.c_uint, ctypes.c_bool, ctypes.c_uint8,
               ctypes.c_int8, ctypes.c_uint16, ctypes.c_int16):
        return I32
    if obj in (ctypes.c_int64, ctypes.c_uint64, ctypes.c_size_t,
               ctypes.c_ssize_t, ctypes.c_long, ctypes.c_ulong,
               ctypes.c_longlong, ctypes.c_ulonglong):
        # c_long is 64-bit on LP64, which is what the runtime targets
        return I64
    try:
        if obj in (ctypes.c_char_p, ctypes.c_void_p, ctypes.c_wchar_p):
            return PTR
        if isinstance(obj, type) and issubclass(
                obj, (ctypes._Pointer, ctypes._CFuncPtr, ctypes.Array,
                      ctypes.Structure)):
            return PTR
    except TypeError:
        pass
    return UNKNOWN


def _py_escapes(root: str) -> Dict[str, bool]:
    path = os.path.join(root, PY_REL)
    out: Dict[str, bool] = {}
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8", errors="replace") as f:
        for ln in f:
            if _ESCAPE in ln:
                for name in re.findall(r"trpc_\w+", ln):
                    out[name] = True
    return out


def _py_line_of(root: str, name: str) -> int:
    path = os.path.join(root, PY_REL)
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            for i, ln in enumerate(f, 1):
                if name in ln:
                    return i
    except OSError:
        pass
    return 0


def check_root(root: str, violations: List[Violation]) -> None:
    exports = parse_capi(root)
    if not exports:
        return  # no capi.cc in this tree: rule out of scope
    decls = load_declarations(root)
    if decls is None:
        violations.append(Violation(
            "abi", PY_REL, 0,
            "ctypes loader (or its _declare) missing/unimportable — the "
            "C-ABI gate cannot verify the binding surface"))
        return
    py_escaped = _py_escapes(root)

    for name, ex in sorted(exports.items()):
        if ex["escaped"] or py_escaped.get(name):
            continue
        fn = decls.get(name)
        if fn is None:
            violations.append(Violation(
                "abi", CAPI_REL, ex["line"],
                f"{name} is exported by capi.cc but has no ctypes "
                f"declaration in {PY_REL} — an undeclared call lets "
                f"ctypes guess int-sized args (silent corruption); "
                f"declare argtypes/restype or escape with {_ESCAPE}"))
            continue
        # arity + width
        if fn.argtypes is None:
            if ex["params"]:
                violations.append(Violation(
                    "abi", PY_REL, _py_line_of(root, name),
                    f"{name} takes {len(ex['params'])} parameter(s) in "
                    f"capi.cc but declares no argtypes — ctypes will "
                    f"guess widths at every call"))
        else:
            if len(fn.argtypes) != len(ex["params"]):
                violations.append(Violation(
                    "abi", PY_REL, _py_line_of(root, name),
                    f"{name} arity mismatch: capi.cc takes "
                    f"{len(ex['params'])} parameter(s), argtypes "
                    f"declares {len(fn.argtypes)}"))
            else:
                for i, (c_cls, py_t) in enumerate(
                        zip(ex["params"], fn.argtypes)):
                    p_cls = _py_class(py_t)
                    if c_cls == UNKNOWN or p_cls == UNKNOWN:
                        continue
                    if c_cls != p_cls:
                        violations.append(Violation(
                            "abi", PY_REL, _py_line_of(root, name),
                            f"{name} argument {i} width mismatch: "
                            f"capi.cc says {c_cls}, argtypes says "
                            f"{p_cls} ({getattr(py_t, '__name__', py_t)})"))
        # restype
        if fn.restype == "UNSET":
            if ex["ret"] not in (I32,):
                violations.append(Violation(
                    "abi", PY_REL, _py_line_of(root, name),
                    f"{name} returns {ex['ret']} in capi.cc but declares "
                    f"no restype — ctypes' implicit c_int default "
                    f"{'reads garbage for void' if ex['ret'] == NONE else 'truncates the value'}; "
                    f"declare restype "
                    f"({'None' if ex['ret'] == NONE else 'the matching c type'})"))
        else:
            r_cls = _py_class(fn.restype)
            if r_cls != UNKNOWN and ex["ret"] != UNKNOWN \
                    and r_cls != ex["ret"]:
                violations.append(Violation(
                    "abi", PY_REL, _py_line_of(root, name),
                    f"{name} restype width mismatch: capi.cc returns "
                    f"{ex['ret']}, restype declares {r_cls}"))

    for name, fn in sorted(decls.items()):
        if name.startswith("trpc_") and name not in exports \
                and not py_escaped.get(name):
            violations.append(Violation(
                "abi", PY_REL, _py_line_of(root, name),
                f"stale ctypes binding {name}: capi.cc no longer exports "
                f"it (renamed exports must update {PY_REL})"))


def check(model, violations: List[Violation]) -> None:
    check_root(model.root, violations)
