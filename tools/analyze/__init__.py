"""tools/analyze — the concurrency-contract analyzer (ISSUE 10).

Multi-pass static rules over a shared C++ source model (analyze.model):

    lockorder   held-while-acquiring graph over every mutex, cycles fail
    fiberblock  no OS-blocking calls reachable from parse-fiber roots
    atomics     explicit std::memory_order on every gated hot-path op
    abi         capi.cc trpc_* exports <-> ctypes declarations, both ways
    wiretags    meta TLV tags from the one registry, no bare numerics

Entry point: run_rules(root, names) -> List[Violation].  tools/lint.py
folds these into its rule registry (python tools/lint.py --rule ...);
tools/ANALYZE.md documents each contract and its escape hatch.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from . import abi, atomics, fiberblock, lockorder, wiretags
from .model import Model, Violation, build_model

# rule name -> check(model, violations)
ANALYZER_RULES = {
    "lockorder": lockorder.check,
    "fiberblock": fiberblock.check,
    "atomics": atomics.check,
    "abi": abi.check,
    "wiretags": wiretags.check,
}


def run_rules(root: str, names: Optional[List[str]] = None,
              model: Optional[Model] = None) -> List[Violation]:
    picked = list(ANALYZER_RULES) if names is None else list(names)
    unknown = [n for n in picked if n not in ANALYZER_RULES]
    if unknown:
        raise ValueError(f"unknown analyzer rule(s): {unknown} "
                         f"(have: {sorted(ANALYZER_RULES)})")
    if model is None:
        model = build_model(root)
    violations: List[Violation] = []
    for name in picked:
        ANALYZER_RULES[name](model, violations)
    return violations
