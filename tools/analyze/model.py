"""Lightweight C++ source model for the concurrency-contract analyzer
(ISSUE 10).  No libclang — the container has no egress — so this is a
comment/string-aware, brace-tracking heuristic parser, deliberately in
the spirit of tools/lint.py's line-level checks: precise enough to build
function spans, a name-resolved call graph, and lock/atomic site tables
over native/src/, with `lint:allow-*` escape hatches carrying the intent
where the heuristics over-approximate.

What the model extracts per translation unit:

  * function definitions — name (and Class::name when qualified), the
    0-based [start, end] line span of the body, found by matching a
    definition header (identifier + params + `{`, not a control keyword)
    and walking braces;
  * a call graph — identifiers followed by `(` inside a body, resolved
    against the set of defined function names (over-approximate on
    purpose: same-name methods merge, which is the conservative
    direction for reachability rules);
  * mutex declarations — (file, name, kind) for std::mutex /
    ProfiledMutex (OS mutexes) and FiberMutex (fiber-aware), so lock
    sites can classify what they acquire;
  * lock acquisitions — lock_guard/unique_lock/scoped_lock guards with
    their active scope (decl line .. closing brace) plus explicit
    .lock()/.unlock() pairs;
  * atomic declarations — names of std::atomic<...> variables, so the
    atomics rule can flag `++`/`+=` shorthand (defaulted seq_cst).

Comments and string/char literals are blanked (not removed: columns and
line numbers stay stable) before structural parsing; the ORIGINAL lines
are kept for escape-annotation lookups, since the escapes live in
comments by design.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, NamedTuple, Optional, Set, Tuple


class Violation(NamedTuple):
    rule: str
    path: str   # repo-relative
    line: int   # 1-based; 0 = whole file
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


# ---------------------------------------------------------------------------
# comment/string blanking


def blank_comments(text: str) -> str:
    """Replace comment bodies and string/char literal contents with
    spaces, preserving length and newlines so line/column math on the
    result maps 1:1 onto the source."""
    out = list(text)
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            j = text.find("\n", i)
            j = n if j < 0 else j
            for k in range(i, j):
                out[k] = " "
            i = j
        elif c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            j = n - 2 if j < 0 else j
            for k in range(i, j + 2):
                if out[k] != "\n":
                    out[k] = " "
            i = j + 2
        elif c == '"' or c == "'":
            q = c
            j = i + 1
            while j < n and text[j] != q:
                if text[j] == "\\":
                    j += 1
                j += 1
            for k in range(i + 1, min(j, n)):
                if out[k] != "\n":
                    out[k] = " "
            i = j + 1
        else:
            i += 1
    return "".join(out)


# ---------------------------------------------------------------------------
# function extraction

_KEYWORDS = {
    "if", "for", "while", "switch", "catch", "return", "sizeof", "new",
    "delete", "throw", "do", "else", "case", "default", "alignof",
    "static_assert", "decltype", "defined", "alignas", "noexcept",
}

# definition header: ...name(args) [const|noexcept|override]* {
_DEF_TAIL_RE = re.compile(
    r"(?:(\w+)\s*::\s*)?([A-Za-z_]\w*)\s*$")

_CALL_RE = re.compile(r"\b([A-Za-z_]\w*)\s*(?:<[\w\s:,<>*&]*>)?\s*\(")

# method names shared with std containers/sync types: a `.name(` call on
# an unknown receiver must never resolve to one of OUR same-named
# functions (precision guard for the graph rules)
_STD_METHOD_DENY = {
    "lock", "unlock", "try_lock", "wait", "wait_for", "wait_until",
    "notify_one", "notify_all", "push", "pop", "push_back",
    "emplace_back", "emplace", "append", "size", "clear", "reset",
    "get", "release", "swap", "count", "find", "begin", "end", "insert",
    "erase", "data", "empty", "front", "back", "load", "store",
    "exchange", "fetch_add", "fetch_sub", "str", "c_str", "substr",
    "resize", "reserve", "assign", "at", "run", "Run", "join", "detach",
    "open", "close", "read", "write", "abort", "exit", "signal",
    # generic callback-member names: `task.fn(arg)` must not resolve to
    # some unrelated local helper that happens to be named `fn`
    "fn", "cb", "done", "func", "callback",
}


class FuncDef(NamedTuple):
    name: str          # unqualified
    qualified: str     # Class::name or name
    path: str          # repo-relative
    start: int         # 0-based first line of the header
    body_start: int    # 0-based line of the opening brace
    end: int           # 0-based line of the closing brace


class MutexDecl(NamedTuple):
    name: str
    path: str
    line: int          # 1-based
    kind: str          # "os" (std::mutex / ProfiledMutex) | "fiber"


_MUTEX_DECL_RE = re.compile(
    r"\b(std::mutex|ProfiledMutex|FiberMutex)\b(?:\s*&)?\s+"
    r"([A-Za-z_]\w*)\s*[;={(]")
_ATOMIC_DECL_RE = re.compile(
    r"\bstd::atomic(?:<[^;>]*>|_bool|_int|_flag)?\s+([A-Za-z_]\w*)\s*[;={]")
_CONDVAR_DECL_RE = re.compile(
    r"\bstd::condition_variable(?:_any)?\s+([A-Za-z_]\w*)\s*[;={]")

# lock-acquisition shapes, shared by the lockorder and fiberblock rules
# (one definition: the two rules must classify a site identically, and
# the `path::name` identities the escapes key on must never drift)
GUARD_RE = re.compile(
    r"\b(?:std::)?(?:lock_guard|unique_lock|scoped_lock)\s*(?:<[^>]*>)?\s+"
    r"\w+\s*[({]\s*([A-Za-z_][\w.\->]*?)\s*[,)}]")
LOCK_CALL_RE = re.compile(
    r"\b([A-Za-z_][\w.\->]*?)\s*(?:\.|->)\s*lock\s*\(\s*\)")
UNLOCK_CALL_RE = re.compile(
    r"\b([A-Za-z_][\w.\->]*?)\s*(?:\.|->)\s*unlock\s*\(\s*\)")


def lock_field(expr: str) -> str:
    """Last identifier of an access path: `victim->remote_mu` ->
    `remote_mu`, `mu()` -> `mu`, `ps.wmu` -> `wmu`."""
    parts = re.split(r"\.|->", expr)
    return parts[-1].strip().rstrip("()")


def _skip_init_list_back(blob: str, k: int) -> Optional[int]:
    """If the paren group opening at k belongs to a constructor's member
    initializer (`Ctor(params) : a_(x), b_(y) {` brace-walk-back matches
    b_'s parens), return the position of the REAL parameter list's ')';
    None when k is not inside an initializer list.  Without this, the
    constructor registers as a phantom function named after the last
    initializer's member and its body is invisible to the graph rules."""
    j = k - 1
    while j >= 0 and (blob[j].isalnum() or blob[j] == "_"):
        j -= 1  # the initializer's member name
    while j >= 0 and blob[j] in " \t\n":
        j -= 1
    # walk back over preceding `, name(args)` initializer groups
    while j >= 0 and blob[j] == ",":
        j -= 1
        while j >= 0 and blob[j] in " \t\n":
            j -= 1
        if j < 0 or blob[j] != ")":
            return None
        depth = 0
        while j >= 0:
            if blob[j] == ")":
                depth += 1
            elif blob[j] == "(":
                depth -= 1
                if depth == 0:
                    break
            j -= 1
        j -= 1
        while j >= 0 and (blob[j].isalnum() or blob[j] == "_"):
            j -= 1
        while j >= 0 and blob[j] in " \t\n":
            j -= 1
    if j >= 0 and blob[j] == ":" and (j == 0 or blob[j - 1] != ":"):
        j -= 1
        while j >= 0 and blob[j] in " \t\n":
            j -= 1
        if j >= 0 and blob[j] == ")":
            return j
    return None


def _find_matching_brace(blanked: str, open_pos: int) -> int:
    depth = 0
    for i in range(open_pos, len(blanked)):
        c = blanked[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return i
    return len(blanked) - 1


class SourceFile:
    def __init__(self, root: str, rel: str):
        self.rel = rel
        with open(os.path.join(root, rel), encoding="utf-8",
                  errors="replace") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.blanked = blank_comments(self.text)
        self.blanked_lines = self.blanked.splitlines()
        # offset of each line start in the blob
        self.line_off: List[int] = [0]
        for ln in self.blanked_lines:
            self.line_off.append(self.line_off[-1] + len(ln) + 1)

    def line_of(self, pos: int) -> int:
        """0-based line index containing blob offset pos."""
        lo, hi = 0, len(self.line_off) - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if self.line_off[mid + 1] <= pos:
                lo = mid + 1
            else:
                hi = mid
        return lo


def extract_functions(sf: SourceFile) -> List[FuncDef]:
    """All function/method definitions in the file (free functions,
    out-of-line methods, and methods defined inline in class bodies)."""
    out: List[FuncDef] = []
    blob = sf.blanked
    i = 0
    n = len(blob)
    while i < n:
        op = blob.find("{", i)
        if op < 0:
            break
        # walk back over [const|noexcept|override|final|-> type] to ')'
        j = op - 1
        while j >= 0 and blob[j] in " \t\n":
            j -= 1
        tail_end = j + 1
        # tolerate trailing qualifiers between ')' and '{'
        m_qual = re.search(r"\)\s*(?:const|noexcept|override|final|mutable"
                           r"|\s)*$", blob[max(0, op - 200):op])
        if not m_qual:
            i = op + 1
            continue
        close_paren = max(0, op - 200) + m_qual.start()
        # find the matching '(' for that ')'
        depth = 0
        k = close_paren
        while k >= 0:
            if blob[k] == ")":
                depth += 1
            elif blob[k] == "(":
                depth -= 1
                if depth == 0:
                    break
            k -= 1
        if k < 0:
            i = op + 1
            continue
        in_init_list = False
        real_close = _skip_init_list_back(blob, k)
        if real_close is not None:
            # constructor with a member-initializer list: rematch at the
            # actual parameter list
            in_init_list = True
            close_paren = real_close
            depth = 0
            k = close_paren
            while k >= 0:
                if blob[k] == ")":
                    depth += 1
                elif blob[k] == "(":
                    depth -= 1
                    if depth == 0:
                        break
                k -= 1
            if k < 0:
                i = op + 1
                continue
        head = blob[max(0, k - 160):k]
        m = _DEF_TAIL_RE.search(head)
        if not m:
            i = op + 1
            continue
        name = m.group(2)
        if name in _KEYWORDS or name.startswith("TRPC_"):
            i = op + 1
            continue
        # reject initializer/assignment shapes: between the CLOSING paren
        # and '{' a definition carries only qualifiers — a ';' or '='
        # there means this brace opens something else.  (The check must
        # not cover the parameter list itself: default arguments
        # `int x = 3` are legal in definitions — FiberCond::wait et al.
        # A detected member-initializer list sits in that span by
        # construction and may contain '=' inside initializer
        # expressions, so it is exempt.)
        if not in_init_list and (";" in blob[close_paren:op]
                                 or "=" in blob[close_paren:op]):
            i = op + 1
            continue
        qualified = (m.group(1) + "::" + name) if m.group(1) else name
        cp = _find_matching_brace(blob, op)
        start_line = sf.line_of(k)
        out.append(FuncDef(name=name, qualified=qualified, path=sf.rel,
                           start=start_line, body_start=sf.line_of(op),
                           end=sf.line_of(cp)))
        # inline class methods: do NOT skip the whole body — nested
        # definitions (methods inside struct bodies) are found because we
        # keep scanning from just past this opening brace
        i = op + 1
    return out


class Model:
    """Parsed view of every .cc/.h under native/src (minus excluded test
    drivers), shared by the analyzer rules."""

    def __init__(self, root: str,
                 exclude: Tuple[str, ...] = ("test_core.cc",
                                             "test_stress.cc",
                                             "pjrt_fake.cc")):
        self.root = root
        self.files: Dict[str, SourceFile] = {}
        src = os.path.join(root, "native", "src")
        if os.path.isdir(src):
            for name in sorted(os.listdir(src)):
                if not name.endswith((".cc", ".h")):
                    continue
                if name in exclude:
                    continue
                rel = os.path.join("native", "src", name)
                self.files[rel] = SourceFile(root, rel)

        # function table: unqualified name -> defs
        self.functions: Dict[str, List[FuncDef]] = {}
        self.defs_by_file: Dict[str, List[FuncDef]] = {}
        for rel, sf in self.files.items():
            defs = extract_functions(sf)
            self.defs_by_file[rel] = defs
            for d in defs:
                self.functions.setdefault(d.name, []).append(d)

        # mutex + atomic + OS-condvar declarations
        self.mutexes: Dict[str, List[MutexDecl]] = {}
        self.atomics: Dict[str, Set[str]] = {}  # file -> names
        self.os_condvars: Set[str] = set()      # std::condition_variable
        for rel, sf in self.files.items():
            names: Set[str] = set()
            for idx, ln in enumerate(sf.blanked_lines, 1):
                for m in _MUTEX_DECL_RE.finditer(ln):
                    kind = "fiber" if m.group(1) == "FiberMutex" else "os"
                    self.mutexes.setdefault(m.group(2), []).append(
                        MutexDecl(m.group(2), rel, idx, kind))
                for m in _ATOMIC_DECL_RE.finditer(ln):
                    names.add(m.group(1))
                for m in _CONDVAR_DECL_RE.finditer(ln):
                    self.os_condvars.add(m.group(1))
            self.atomics[rel] = names

        self._calls_cache: Dict[Tuple[str, int], Set[str]] = {}
        self._resolved_cache: Dict[Tuple[str, int], Set[str]] = {}

    # -- call graph ---------------------------------------------------------

    def calls_in(self, d: FuncDef) -> Set[str]:
        """Names of defined functions called inside d's body (an
        over-approximation: any identifier followed by '(' that matches
        a definition anywhere in the scanned tree)."""
        key = (d.path, d.start)
        cached = self._calls_cache.get(key)
        if cached is not None:
            return cached
        sf = self.files[d.path]
        body = "\n".join(sf.blanked_lines[d.body_start:d.end + 1])
        out: Set[str] = set()
        for m in _CALL_RE.finditer(body):
            name = m.group(1)
            if name == d.name or name in _KEYWORDS:
                continue
            if name in self.functions:
                out.add(name)
        self._calls_cache[key] = out
        return out

    def resolved_calls(self, d: FuncDef) -> Set[str]:
        """Precision-filtered call set for graph rules: a callee counts
        only when its name resolves to exactly ONE definition in the
        scanned tree and is not a std-container/mutex method name (the
        `x.lock()` / `q.push()` forms would otherwise alias unrelated
        same-named functions and manufacture edges out of nothing)."""
        key = (d.path, d.start)
        cached = self._resolved_cache.get(key)
        if cached is not None:
            return cached
        out = {name for name in self.calls_in(d)
               if name not in _STD_METHOD_DENY
               and len(self.functions.get(name, ())) == 1}
        self._resolved_cache[key] = out
        return out

    def reachable_from(self, roots: List[str]) -> Dict[str, Optional[str]]:
        """BFS over the precision-filtered call graph from root function
        NAMES.  Returns {function name: parent name} (parent None for
        roots) — the parent chain is the witness path for findings.
        Uses resolved_calls (unique-name + denylist) so ambiguous method
        names don't drag unrelated subsystems into the reachable set."""
        parent: Dict[str, Optional[str]] = {}
        queue: List[str] = []
        for r in roots:
            if r in self.functions and r not in parent:
                parent[r] = None
                queue.append(r)
        while queue:
            cur = queue.pop()
            for d in self.functions.get(cur, ()):
                for callee in self.resolved_calls(d):
                    if callee not in parent:
                        parent[callee] = cur
                        queue.append(callee)
        return parent

    def witness_path(self, parent: Dict[str, Optional[str]],
                     name: str) -> str:
        chain = [name]
        seen = {name}
        while parent.get(chain[-1]) is not None:
            nxt = parent[chain[-1]]
            if nxt in seen:
                break
            chain.append(nxt)
            seen.add(nxt)
        return " <- ".join(chain)

    # -- lock identity --------------------------------------------------------

    def resolve_mutex(self, field: str,
                      rel: str) -> Optional[Tuple[str, str]]:
        """(identity "path::name", kind "os"|"fiber") for a lock FIELD
        name used in file rel.  Names unique to one file resolve there;
        a name declared in several files resolves to the USE site's file
        when that file declares one (generic `mu`/`mu_` members), else
        None (cannot tell whose member this is).  Both graph rules key
        their escapes on this identity — one definition, no drift."""
        decls = self.mutexes.get(field)
        if not decls:
            return None
        files = {d.path for d in decls}
        if len(files) == 1:
            path = next(iter(files))
        elif rel in files:
            path = rel
        else:
            return None
        kinds = {d.kind for d in decls if d.path == path}
        kind = "os" if "os" in kinds else "fiber"
        return (f"{path}::{field}", kind)

def build_model(root: str) -> Model:
    return Model(root)
