"""Rule `atomics` (ISSUE 10 contract 3): every atomic operation in the
gated hot-path files states an explicit std::memory_order.

A defaulted seq_cst on the fast path is a silent full fence per
operation (the PR-9 histograms write on every request; the PR-7 mailbox
CAS loop runs per cross-shard post), and a defaulted order also hides
the author's intent — explicit order is the per-site annotation the
reviewer checks against the pairing site.  Two checks per gated file:

  * method-form ops (.load/.store/.fetch_*/.exchange/.compare_exchange_*)
    must pass a memory_order argument (statement-spanning: multi-line
    calls are joined to the terminating ';');
  * `++` / `--` / `+=` / `-=` / `|=` / `&=` shorthand on a variable
    DECLARED std::atomic in the same file is flagged — the shorthand is
    always seq_cst and cannot state an order; spell it fetch_add/fetch_or
    with the intended order.

Gated files: the ISSUE-10 set (metrics, shard, socket, uring — the
relaxed-histogram, mailbox, wait-free-write and ring seams) — grown by
editing GATED_FILES as new hot-path translation units appear.

Escape: `lint:allow-default-order (reason)` on the line (or the line
above) — for deliberate seq_cst sites (e.g. the PR-3 cork park/Uncork
Dekker handshake, which NEEDS the StoreLoad fence).
"""

from __future__ import annotations

import re
from typing import List

from .model import Model, Violation

GATED_FILES = (
    "native/src/metrics.h", "native/src/metrics.cc",
    "native/src/overload.h", "native/src/overload.cc",
    "native/src/shard.h", "native/src/shard.cc",
    "native/src/socket.h", "native/src/socket.cc",
    "native/src/timer_thread.h", "native/src/timer_thread.cc",
    "native/src/uring.h", "native/src/uring.cc",
)

_OP_RE = re.compile(
    r"\.(load|store|fetch_add|fetch_sub|fetch_or|fetch_and|fetch_xor|"
    r"exchange|compare_exchange_weak|compare_exchange_strong)\s*\(")
_INC_RE = re.compile(
    r"(?:\+\+|--)\s*([A-Za-z_]\w*)|([A-Za-z_]\w*)(?:\[[^\]]*\])?\s*"
    r"(?:\+\+|--|\+=|-=|\|=|&=|\^=)")

_ESCAPE = "lint:allow-default-order"


def _call_args(stmt: str) -> str:
    """The argument text of the FIRST call in stmt (which starts at the
    matched `.op(`): the balanced-paren span after the first '('.
    Returns what was scanned even on an unterminated span (joining is
    capped), which errs toward accepting — the op is then re-checked by
    a human, not spuriously flagged."""
    start = stmt.find("(")
    if start < 0:
        return ""
    depth = 0
    for i in range(start, len(stmt)):
        if stmt[i] == "(":
            depth += 1
        elif stmt[i] == ")":
            depth -= 1
            if depth == 0:
                return stmt[start + 1:i]
    return stmt[start + 1:]


def check(model: Model, violations: List[Violation]) -> None:
    for rel in GATED_FILES:
        sf = model.files.get(rel)
        if sf is None:
            continue
        atoms = model.atomics.get(rel, set())
        lines = sf.blanked_lines
        for i, ln in enumerate(lines):
            orig = sf.lines[i]
            escaped = _ESCAPE in orig or (i > 0 and _ESCAPE in sf.lines[i - 1])
            for m in _OP_RE.finditer(ln):
                # the order must appear in THIS call's own argument
                # list: join continuation lines, then walk the balanced
                # parens from the matched '(' — a memory_order on a
                # neighboring op in the same statement must not mask a
                # defaulted one (`a.load() + b.load(relaxed)`)
                stmt = ln[m.start():]
                j = i
                while ";" not in stmt and j + 1 < len(lines) and j - i < 6:
                    j += 1
                    stmt += " " + lines[j]
                if "memory_order" in _call_args(stmt):
                    continue
                if escaped:
                    continue
                violations.append(Violation(
                    "atomics", rel, i + 1,
                    f".{m.group(1)}() without an explicit "
                    f"std::memory_order in a gated hot-path file: state "
                    f"the order (relaxed/acquire/release/acq_rel/seq_cst "
                    f"— the default seq_cst is a full fence AND hides "
                    f"intent), or escape with {_ESCAPE} (reason)"))
            if not atoms or escaped:
                continue
            for m in _INC_RE.finditer(ln):
                name = m.group(1) or m.group(2)
                if name in atoms:
                    violations.append(Violation(
                        "atomics", rel, i + 1,
                        f"increment/compound-assign shorthand on "
                        f"std::atomic {name} is an implicit seq_cst RMW: "
                        f"spell it fetch_add/fetch_sub/fetch_or with an "
                        f"explicit order, or escape with {_ESCAPE} "
                        f"(reason)"))
