"""Rule `fiberblock` (ISSUE 10 contract 2): no OS-blocking calls
reachable from the parse-fiber hot-path roots.

A parse fiber runs on a shard's reactor worker; anything that parks the
OS THREAD (not the fiber) stalls every fiber of that shard — the
whole-reactor head-of-line blocking the PR-3/5 fast paths exist to
avoid.  This rule extends the line-level no-raw-alloc gate to
reachability: from the roots (ServerOnMessages / ChannelOnMessages and
the inline-dispatch seams they run), walk the call graph and flag

  * acquisitions of OS mutexes (std::mutex / ProfiledMutex — FiberMutex
    parks the FIBER and is allowed),
  * sleeps (sleep/usleep/nanosleep/std::this_thread::sleep_*),
  * OS condvar waits and bare blocking syscalls (epoll_wait/poll/select,
    fsync/fdatasync).

The call graph uses the precision-filtered resolution (unique names,
std-method denylist, model.resolved_calls) so a `.push()` on a vector
doesn't drag unrelated code into the reachable set.

Escapes, matching how the tree actually earns its exceptions:

  * `lint:allow-blocking-bounded (reason)` on an OS-mutex DECLARATION
    line marks every acquisition of that mutex as audited-bounded (held
    for O(1) pointer work, never across a park/syscall) — the
    object-pool free lists and the per-socket sequencer are this class;
  * `lint:allow-blocking (reason)` on a call SITE escapes that site
    alone (for sleeps/waits with a real justification).
"""

from __future__ import annotations

import re
from typing import Dict, List, Set

from .model import (GUARD_RE, LOCK_CALL_RE, Model, Violation, lock_field)

ROOTS = [
    # server + client parse fibers (the PR-3/5 run-to-completion paths)
    "ServerOnMessages", "ChannelOnMessages",
    # inline-dispatch seams spawned ON the parse fiber
    "EchoFiber", "HbmEchoFiber", "RedisCacheFiber",
    # telemetry record sites run inside the above (gated separately for
    # allocations; reachability keeps them honest about blocking too)
    "telemetry_record", "rpcz_capture",
]

_SLEEP_RE = re.compile(
    r"\b(?:usleep|nanosleep|sleep)\s*\(|std::this_thread::sleep_")
_SYSCALL_RE = re.compile(r"\b(?:epoll_wait|poll|select|fsync|fdatasync)\s*\(")
_CONDVAR_WAIT_RE = re.compile(
    r"\b([A-Za-z_][\w.\->]*?)\s*(?:\.|->)\s*wait(?:_for|_until)?\s*\(")

_SITE_ESCAPE = "lint:allow-blocking"
_DECL_ESCAPE = "lint:allow-blocking-bounded"


def _decl_escaped(model: Model, rel: str, line0: int) -> bool:
    """The bounded-audit escape counts only on the declaration line or
    in the CONTIGUOUS comment block immediately above it — a fixed
    lookback window would let one mutex's escape silently bless an
    unaudited mutex declared a couple of lines below the same comment."""
    sf = model.files.get(rel)
    if sf is None:
        return False
    if _DECL_ESCAPE in sf.lines[line0]:
        return True
    i = line0 - 1
    while i >= 0 and sf.lines[i].strip().startswith("//"):
        if _DECL_ESCAPE in sf.lines[i]:
            return True
        i -= 1
    return False


def _bounded_os_mutexes(model: Model) -> Set[str]:
    """Identities ("path::name") whose bounded audit covers EVERY
    same-named OS-mutex declaration in that file.  Name-based identity
    cannot tell two same-file `mu` members apart, so the escape is
    fail-closed: one unannotated declaration in the group withholds the
    blessing from all of them — adding an unaudited `std::mutex mu;` to
    a file whose other `mu` is audited re-fails the sites until the new
    declaration is audited too."""
    groups: Dict[str, List[bool]] = {}
    for name, decls in model.mutexes.items():
        for d in decls:
            if d.kind != "os":
                continue
            groups.setdefault(f"{d.path}::{name}", []).append(
                _decl_escaped(model, d.path, d.line - 1))
    return {ident for ident, escs in groups.items() if all(escs)}


def check(model: Model, violations: List[Violation]) -> None:
    parent = model.reachable_from(ROOTS)
    if not parent:
        return
    bounded = _bounded_os_mutexes(model)

    for name in sorted(parent):
        for d in model.functions.get(name, ()):
            sf = model.files[d.path]
            body = sf.blanked_lines[d.body_start:d.end + 1]
            orig = sf.lines[d.body_start:d.end + 1]
            witness = model.witness_path(parent, name)
            for i, ln in enumerate(body):
                line1 = d.body_start + i + 1
                # site escape: the line itself or up to 2 comment lines
                # above (escape reasons often wrap)
                if any(_SITE_ESCAPE in orig[j]
                       for j in range(max(0, i - 2), i + 1)):
                    continue

                m = _SLEEP_RE.search(ln)
                if m:
                    violations.append(Violation(
                        "fiberblock", d.path, line1,
                        f"OS sleep reachable from parse-fiber roots "
                        f"({witness}): use fiber_usleep / a timer, or "
                        f"escape with {_SITE_ESCAPE} (reason)"))
                    continue
                m = _SYSCALL_RE.search(ln)
                if m:
                    violations.append(Violation(
                        "fiberblock", d.path, line1,
                        f"blocking syscall reachable from parse-fiber "
                        f"roots ({witness}): move it off the reactor or "
                        f"escape with {_SITE_ESCAPE} (reason)"))
                    continue
                for g in list(GUARD_RE.finditer(ln)) + \
                        list(LOCK_CALL_RE.finditer(ln)):
                    res = model.resolve_mutex(lock_field(g.group(1)),
                                              d.path)
                    if res is None or res[1] != "os":
                        continue
                    if res[0] in bounded:
                        continue
                    violations.append(Violation(
                        "fiberblock", d.path, line1,
                        f"OS mutex {res[0].split('::')[-1]} acquired on a "
                        f"path reachable from parse-fiber roots "
                        f"({witness}): a contended std::mutex parks the "
                        f"whole reactor thread — use FiberMutex, or audit "
                        f"the critical section as bounded and mark the "
                        f"DECLARATION with {_DECL_ESCAPE} (reason), or "
                        f"escape this site with {_SITE_ESCAPE} (reason)"))
                for w in _CONDVAR_WAIT_RE.finditer(ln):
                    # FiberCond / butex waits park the fiber: allowed.
                    # Flag only receivers declared std::condition_variable
                    # (model.os_condvars — built with the declarations,
                    # so no per-rule cache to go stale)
                    if lock_field(w.group(1)) in model.os_condvars:
                        violations.append(Violation(
                            "fiberblock", d.path, line1,
                            f"OS condition-variable wait reachable from "
                            f"parse-fiber roots ({witness}): park the "
                            f"fiber (butex / FiberCond) instead, or "
                            f"escape with {_SITE_ESCAPE} (reason)"))
