"""Rule `lockorder` (ISSUE 10 contract 1): build the held-while-acquiring
edge set over every mutex acquisition in native/src/ and fail on cycles.

A deadlock needs a cycle in the lock-order graph: thread 1 holds A and
wants B while thread 2 holds B and wants A.  The analyzer extracts every
acquisition site (lock_guard / unique_lock / scoped_lock guards and
explicit .lock() calls), tracks which locks are lexically held at each
point (guards release at their scope's closing brace, .lock() at the
matching .unlock() or end of function), and adds the edge A -> B for
every acquisition of B under A — both directly and through the call
graph (holding A while calling a function that may acquire B).

Lock identity is the declared variable name, classified against the
declaration table (std::mutex / ProfiledMutex / FiberMutex all
participate: FiberMutex can deadlock fibers just as std::mutex deadlocks
threads).  Names declared more than once in DIFFERENT files (the generic
`mu` / `mu_` members) are file-qualified; two same-named instances in one
file share an identity, which is the conservative direction — an
instance-ordering hazard (locking b->mu under a->mu) shows up as a self
edge.

Self edges are reported only when taken DIRECTLY (a nested acquisition
of the same identity inside one function): cross-call self edges are
dominated by re-entrant helpers that the caller locks FOR, and the
direct case is the one that encodes a real two-instance ordering
decision (document it: address-ordered, or single-instance by
construction).

Escapes: `lint:allow-lock-order (reason)` on the acquisition line (or
the line above) removes that SITE's outgoing/incoming edges; the reason
should name the ordering argument (e.g. "address-ordered", "trylock
only", "never taken concurrently with X").
"""

from __future__ import annotations

import re
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

from .model import (GUARD_RE, LOCK_CALL_RE, UNLOCK_CALL_RE,
                    _STD_METHOD_DENY, FuncDef, Model, Violation,
                    lock_field)

_ESCAPE = "lint:allow-lock-order"


class Acq(NamedTuple):
    lock: str          # canonical lock identity
    line0: int         # 0-based line of the acquisition
    release0: int      # 0-based line after which the lock is free
    escaped: bool


class FnLocks(NamedTuple):
    acqs: List[Acq]
    # (callee name, set of lock identities held at the call, call line)
    calls_held: List[Tuple[str, frozenset, int]]


def _canon_at(model: Model, name: str, rel: str) -> Optional[str]:
    res = model.resolve_mutex(name, rel)
    return res[0] if res else None


def _scope_end(depths: List[int], start: int, decl_depth: int) -> int:
    """Last 0-based line (relative index) at which a guard declared at
    line `start` with brace depth `decl_depth` is still held."""
    for i in range(start + 1, len(depths)):
        if depths[i] < decl_depth:
            return i
    return len(depths) - 1


def _analyze_function(model: Model, d: FuncDef) -> FnLocks:
    sf = model.files[d.path]
    body = sf.blanked_lines[d.body_start:d.end + 1]
    orig = sf.lines[d.body_start:d.end + 1]
    # depth AFTER processing each line (guards declared on line i live
    # while depth stays >= depth at declaration)
    depths: List[int] = []
    depth = 0
    entry: List[int] = []
    for ln in body:
        entry.append(depth)
        depth += ln.count("{") - ln.count("}")
        depths.append(depth)

    acqs: List[Acq] = []
    for i, ln in enumerate(body):
        escaped = _ESCAPE in orig[i] or (i > 0 and _ESCAPE in orig[i - 1])
        for m in GUARD_RE.finditer(ln):
            lock = _canon_at(model, lock_field(m.group(1)), d.path)
            if lock is None:
                continue
            acqs.append(Acq(lock, i, _scope_end(depths, i, depths[i]),
                            escaped))
        for m in LOCK_CALL_RE.finditer(ln):
            lock = _canon_at(model, lock_field(m.group(1)), d.path)
            if lock is None:
                continue
            rel_end = len(body) - 1
            field = lock_field(m.group(1))
            for j in range(i + 1, len(body)):
                um = UNLOCK_CALL_RE.search(body[j])
                if um and lock_field(um.group(1)) == field:
                    rel_end = j
                    break
            acqs.append(Acq(lock, i, rel_end, escaped))

    calls_held: List[Tuple[str, frozenset, int]] = []
    for i, ln in enumerate(body):
        held = frozenset(a.lock for a in acqs
                         if a.line0 < i <= a.release0 and not a.escaped)
        if not held:
            continue
        for m in re.finditer(r"\b([A-Za-z_]\w*)\s*\(", ln):
            name = m.group(1)
            if (name != d.name and name not in _STD_METHOD_DENY
                    and len(model.functions.get(name, ())) == 1):
                calls_held.append((name, held, i))
    return FnLocks(acqs, calls_held)


def check(model: Model, violations: List[Violation]) -> None:
    per_fn: Dict[Tuple[str, int], FnLocks] = {}
    fn_locks_summary: Dict[str, Set[str]] = {}  # fn name -> locks it
    # may acquire (directly, unescaped); propagated transitively below
    defs_of: Dict[str, List[FuncDef]] = model.functions

    for name, defs in defs_of.items():
        for d in defs:
            fl = _analyze_function(model, d)
            per_fn[(d.path, d.start)] = fl
            s = fn_locks_summary.setdefault(name, set())
            s.update(a.lock for a in fl.acqs if not a.escaped)

    # transitive closure: a function "may acquire" what its callees may
    changed = True
    guard = 0
    while changed and guard < 50:
        changed = False
        guard += 1
        for name, defs in defs_of.items():
            s = fn_locks_summary[name]
            before = len(s)
            for d in defs:
                for callee in model.resolved_calls(d):
                    s |= fn_locks_summary.get(callee, set())
            if len(s) != before:
                changed = True

    # edge set: lock A -> lock B ("B acquired while A held"), with one
    # witness site per edge
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(a: str, b: str, path: str, line1: int, how: str) -> None:
        if (a, b) not in edges:
            edges[(a, b)] = (path, line1, how)

    for name, defs in defs_of.items():
        for d in defs:
            fl = per_fn[(d.path, d.start)]
            # direct nesting
            for held in fl.acqs:
                if held.escaped:
                    continue
                for inner in fl.acqs:
                    if inner is held or inner.escaped:
                        continue
                    if held.line0 < inner.line0 <= held.release0:
                        if inner.lock == held.lock:
                            violations.append(Violation(
                                "lockorder", d.path,
                                d.body_start + inner.line0 + 1,
                                f"self lock-order edge in {d.name}: "
                                f"{inner.lock} acquired while an instance "
                                f"of the same lock is held — order the "
                                f"instances (e.g. by address) and escape "
                                f"with {_ESCAPE} (reason), or restructure"))
                            continue
                        add_edge(held.lock, inner.lock, d.path,
                                 d.body_start + inner.line0 + 1,
                                 f"nested in {d.name}")
            # through calls
            for callee, held_set, line0 in fl.calls_held:
                for b in fn_locks_summary.get(callee, set()):
                    for a in held_set:
                        if a != b:
                            add_edge(a, b, d.path, d.body_start + line0 + 1,
                                     f"{d.name} calls {callee} holding {a}")

    # cycle detection (iterative DFS over the edge graph)
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    stack_path: List[str] = []
    cycles: List[List[str]] = []

    def dfs(start: str) -> None:
        stack: List[Tuple[str, int]] = [(start, 0)]
        color[start] = GRAY
        stack_path.append(start)
        while stack:
            node, idx = stack[-1]
            nbrs = graph.get(node, [])
            if idx < len(nbrs):
                stack[-1] = (node, idx + 1)
                nxt = nbrs[idx]
                c = color.get(nxt, WHITE)
                if c == GRAY:
                    at = stack_path.index(nxt)
                    cyc = stack_path[at:] + [nxt]
                    if len(cycles) < 8:
                        cycles.append(cyc)
                elif c == WHITE:
                    color[nxt] = GRAY
                    stack_path.append(nxt)
                    stack.append((nxt, 0))
            else:
                stack.pop()
                stack_path.pop()
                color[node] = BLACK

    for node in sorted(graph):
        if color.get(node, WHITE) == WHITE:
            dfs(node)

    reported: Set[frozenset] = set()
    for cyc in cycles:
        key = frozenset(cyc)
        if key in reported:
            continue
        reported.add(key)
        detail = []
        for a, b in zip(cyc, cyc[1:]):
            path, line1, how = edges[(a, b)]
            detail.append(f"{a} -> {b} [{path}:{line1}: {how}]")
        path0, line0, _ = edges[(cyc[0], cyc[1])]
        violations.append(Violation(
            "lockorder", path0, line0,
            "lock-order cycle (deadlock risk): " + "; ".join(detail) +
            f" — fix the acquisition order or escape one edge's site "
            f"with {_ESCAPE} (reason)"))
