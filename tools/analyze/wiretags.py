"""Rule `wiretags` (ISSUE 10 contract 5): TRPC meta TLV tags come from
ONE registry — tools/wire_tags_manifest.txt — mirrored by named
constants on both sides of the wire, with no bare numeric tag literals
at the framing seams.

Tags 6/7/8/13/16/17… were assigned PR by PR as comments in rpc.cc's
EncodeMeta/DecodeMeta; the next codec/trace PR picking "18" by grepping
comments is one collision away from corrupting frames.  The registry:

  * manifest line: `<tag> <name> <description>` (name lower_snake);
  * C++: `kMetaTag<CamelCase(name)> = <tag>` constants (native/src/rpc.h)
    must match the manifest BOTH ways (a constant the manifest doesn't
    know / a manifest entry no constant defines both fail — rename
    detection, like the flags/metrics manifests);
  * Python: brpc_tpu/rpc/wire_tags.py `<NAME_UPPER> = <tag>` mirror,
    both ways again;
  * rpc.cc framing seams: `tlv(`/`tlv_u8(`/`tlv_u32(`/`tlv_u64(` calls
    must not pass a bare integer literal as the tag, and `case <int>:`
    inside DecodeMeta must use the constants.

Escape: `lint:allow-wire-tag (reason)` on the line — for deliberately
raw bytes (e.g. a fuzz fixture building an INVALID tag on purpose).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Tuple

from .model import Model, Violation

MANIFEST_REL = os.path.join("tools", "wire_tags_manifest.txt")
HEADER_REL = os.path.join("native", "src", "rpc.h")
RPCCC_REL = os.path.join("native", "src", "rpc.cc")
PY_REL = os.path.join("brpc_tpu", "rpc", "wire_tags.py")

_ESCAPE = "lint:allow-wire-tag"

_CONST_RE = re.compile(r"\bkMetaTag(\w+)\s*=\s*(\d+)")
_PY_CONST_RE = re.compile(r"^([A-Z][A-Z0-9_]*)\s*=\s*(\d+)", re.M)
_TLV_CALL_RE = re.compile(r"\btlv(?:_u8|_u32|_u64)?\s*\(\s*(\d+)\s*,")
_CASE_RE = re.compile(r"\bcase\s+(\d+)\s*:")


def camel(name: str) -> str:
    return "".join(p.capitalize() for p in name.split("_"))


def _load_manifest(root: str, violations: List[Violation]
                   ) -> Dict[str, int]:
    path = os.path.join(root, MANIFEST_REL)
    out: Dict[str, int] = {}
    by_tag: Dict[int, str] = {}
    if not os.path.exists(path):
        violations.append(Violation(
            "wiretags", MANIFEST_REL, 0,
            "wire-tag manifest missing (every meta TLV tag must be "
            "registered here: `<tag> <name> <description>`)"))
        return out
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(None, 2)
            if len(parts) < 3 or not parts[0].isdigit() \
                    or not re.fullmatch(r"[a-z][a-z0-9_]*", parts[1]):
                violations.append(Violation(
                    "wiretags", MANIFEST_REL, i,
                    f"malformed wire-tag manifest entry {line!r} "
                    f"(want `<tag> <lower_snake_name> <description>`)"))
                continue
            tag, name = int(parts[0]), parts[1]
            if name in out:
                violations.append(Violation(
                    "wiretags", MANIFEST_REL, i,
                    f"duplicate wire-tag name {name}"))
                continue
            if tag in by_tag:
                violations.append(Violation(
                    "wiretags", MANIFEST_REL, i,
                    f"tag {tag} assigned to both {by_tag[tag]} and "
                    f"{name} — a wire collision"))
                continue
            out[name] = tag
            by_tag[tag] = name
    return out


def check(model: Model, violations: List[Violation]) -> None:
    root = model.root
    if not os.path.exists(os.path.join(root, RPCCC_REL)):
        return  # no framing code in this tree: rule out of scope
    manifest = _load_manifest(root, violations)

    # --- C++ constants <-> manifest, both ways -----------------------------
    header = model.files.get(HEADER_REL)
    consts: Dict[str, Tuple[int, int]] = {}  # camel name -> (value, line)
    if header is not None:
        for i, ln in enumerate(header.blanked_lines, 1):
            for m in _CONST_RE.finditer(ln):
                consts[m.group(1)] = (int(m.group(2)), i)
    for name, tag in sorted(manifest.items()):
        c = camel(name)
        if c not in consts:
            violations.append(Violation(
                "wiretags", HEADER_REL, 0,
                f"manifest tag {tag} ({name}) has no kMetaTag{c} "
                f"constant in {HEADER_REL}"))
        elif consts[c][0] != tag:
            violations.append(Violation(
                "wiretags", HEADER_REL, consts[c][1],
                f"kMetaTag{c} = {consts[c][0]} disagrees with the "
                f"manifest ({name} = {tag})"))
    known_camels = {camel(n) for n in manifest}
    for c, (val, line) in sorted(consts.items()):
        if c not in known_camels:
            violations.append(Violation(
                "wiretags", HEADER_REL, line,
                f"kMetaTag{c} = {val} is not registered in "
                f"{MANIFEST_REL} (add `<tag> <name> <description>`)"))

    # --- Python mirror <-> manifest, both ways -----------------------------
    py_path = os.path.join(root, PY_REL)
    if not os.path.exists(py_path):
        violations.append(Violation(
            "wiretags", PY_REL, 0,
            f"Python wire-tag mirror missing ({PY_REL} must define "
            f"<NAME> = <tag> for every manifest entry)"))
    else:
        with open(py_path, encoding="utf-8") as f:
            text = f.read()
        py_consts: Dict[str, int] = {}
        for m in _PY_CONST_RE.finditer(text):
            py_consts[m.group(1)] = int(m.group(2))
        for name, tag in sorted(manifest.items()):
            up = name.upper()
            if up not in py_consts:
                violations.append(Violation(
                    "wiretags", PY_REL, 0,
                    f"manifest tag {tag} ({name}) has no {up} constant "
                    f"in the Python mirror"))
            elif py_consts[up] != tag:
                violations.append(Violation(
                    "wiretags", PY_REL, 0,
                    f"{up} = {py_consts[up]} disagrees with the "
                    f"manifest ({name} = {tag})"))
        known_upper = {n.upper() for n in manifest}
        for up, val in sorted(py_consts.items()):
            if up not in known_upper:
                violations.append(Violation(
                    "wiretags", PY_REL, 0,
                    f"{up} = {val} in the Python mirror is not "
                    f"registered in {MANIFEST_REL}"))

    # --- no bare numeric tags at the framing seams -------------------------
    rpccc = model.files.get(RPCCC_REL)
    if rpccc is None:
        return
    decode_span = None
    for d in model.defs_by_file.get(RPCCC_REL, []):
        if d.name == "DecodeMeta":
            decode_span = (d.body_start, d.end)
    for i, ln in enumerate(rpccc.blanked_lines):
        orig = rpccc.lines[i]
        if _ESCAPE in orig or (i > 0 and _ESCAPE in rpccc.lines[i - 1]):
            continue
        for m in _TLV_CALL_RE.finditer(ln):
            violations.append(Violation(
                "wiretags", RPCCC_REL, i + 1,
                f"bare numeric TLV tag {m.group(1)} at an encode seam: "
                f"use the kMetaTag* constant (registry: "
                f"{MANIFEST_REL}), or escape with {_ESCAPE} (reason)"))
        if decode_span and decode_span[0] <= i <= decode_span[1]:
            for m in _CASE_RE.finditer(ln):
                violations.append(Violation(
                    "wiretags", RPCCC_REL, i + 1,
                    f"bare numeric case {m.group(1)} in DecodeMeta: use "
                    f"the kMetaTag* constant (registry: "
                    f"{MANIFEST_REL}), or escape with {_ESCAPE} "
                    f"(reason)"))
